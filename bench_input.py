"""Host input-pipeline micro-benchmark — the native C++ loader vs numpy.

The in-tree native runtime (``native/dataio.cc`` via ctypes) backs the
host-fed input path (``--device_data off``): IDX/CIFAR byte parsing and
the per-step batch gather + crop/flip augmentation.  This harness measures
both implementations on identical inputs so the native component's worth
is a recorded number, not an assertion.  Pure host CPU — no TPU needed.

Emits one JSON line per stage:
``{"metric": ..., "value": <native rate>, "unit": ...,
   "vs_baseline": <native/numpy speedup>, "detail": {...}}``.

Both paths are bit-identical by construction (the random draws happen
once, outside the timed region — ``data/cifar10.py::_draw``); this harness
asserts that on every run before timing.
"""

from __future__ import annotations

import json
import struct
import time

import numpy as np

REPEATS = 3


def _time(fn, iters: int) -> float:
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _emit(metric: str, value: float, unit: str, speedup: float,
          detail: dict) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 1),
                      "unit": unit, "vs_baseline": round(speedup, 3),
                      "detail": detail}), flush=True)


def bench_cifar_parse(n_records: int = 10000) -> None:
    from distributedtensorflowexample_tpu import native

    rng = np.random.RandomState(0)
    raw = rng.randint(0, 256, size=n_records * 3073,
                      dtype=np.uint8).tobytes()
    rows = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 3073)

    def numpy_parse():
        from distributedtensorflowexample_tpu.data.dequant import (
            U8_UNIT_SCALE)
        nhwc = rows[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        # Multiply by the canonical f32 1/255 (data/dequant.py), matching
        # both the loaders and the native parser — a division rounds
        # differently on 126/256 byte values and breaks the bit-identity
        # assertion below.
        return (nhwc.astype(np.float32) * U8_UNIT_SCALE,
                rows[:, 0].astype(np.int32))

    ni, nl = native.parse_cifar(raw)
    pi, pl = numpy_parse()
    np.testing.assert_array_equal(ni, pi)
    np.testing.assert_array_equal(nl, pl)

    mb = len(raw) / 1e6
    t_native = _time(lambda: native.parse_cifar(raw), 3)
    t_numpy = _time(numpy_parse, 3)
    _emit("cifar_parse_native_mb_per_sec", mb / t_native, "MB/sec",
          t_numpy / t_native,
          {"records": n_records, "numpy_mb_per_sec": round(mb / t_numpy, 1),
           "omp_threads": native.omp_threads()})


def bench_idx_parse(n: int = 60000) -> None:
    from distributedtensorflowexample_tpu import native

    rng = np.random.RandomState(1)
    body = rng.randint(0, 256, size=n * 28 * 28, dtype=np.uint8)
    raw = struct.pack(">IIII", 2051, n, 28, 28) + body.tobytes()

    def numpy_parse():
        from distributedtensorflowexample_tpu.data.dequant import (
            U8_UNIT_SCALE)
        data = np.frombuffer(raw, dtype=np.uint8, count=n * 28 * 28,
                             offset=16)
        # Canonical multiply, not divide — see bench_cifar_parse.
        return data.reshape(n, 28, 28, 1).astype(np.float32) * U8_UNIT_SCALE

    np.testing.assert_array_equal(native.parse_idx_images(raw),
                                  numpy_parse())
    mb = len(raw) / 1e6
    t_native = _time(lambda: native.parse_idx_images(raw), 3)
    t_numpy = _time(numpy_parse, 3)
    _emit("idx_parse_native_mb_per_sec", mb / t_native, "MB/sec",
          t_numpy / t_native,
          {"images": n, "numpy_mb_per_sec": round(mb / t_numpy, 1)})


def bench_gather_augment(n_src: int = 50000, batch: int = 256) -> None:
    """The per-step host work of an augmented CIFAR run (--device_data
    off): gather batch rows + reflect-pad-4 crop + hflip.  Native does it
    in one fused OpenMP pass; numpy gathers then augments."""
    from distributedtensorflowexample_tpu import native
    from distributedtensorflowexample_tpu.data.cifar10 import (
        _augment_numpy, _draw)

    rng = np.random.RandomState(2)
    src = rng.rand(n_src, 32, 32, 3).astype(np.float32)
    idx = rng.randint(0, n_src, size=batch).astype(np.int64)
    ys, xs, flips = _draw(np.random.RandomState(3), batch)

    def native_fused():
        return native.gather_augment(src, idx, ys, xs, flips)

    def numpy_path():
        return _augment_numpy(src[idx], ys, xs, flips)

    np.testing.assert_array_equal(native_fused(), numpy_path())
    t_native = _time(native_fused, 20)
    t_numpy = _time(numpy_path, 20)
    _emit("gather_augment_native_images_per_sec", batch / t_native,
          "images/sec", t_numpy / t_native,
          {"batch": batch, "source_rows": n_src,
           "numpy_images_per_sec": round(batch / t_numpy, 1)})


def bench_gather_augment_u8(n_src: int = 50000, batch: int = 256) -> None:
    """The quantized host path (round 4): the same fused gather+crop+flip
    on a uint8-resident split moves 4x fewer bytes.  The speedup baseline
    is the f32 NATIVE fused path — the line reads as what uint8 storage
    buys ON TOP of the C++ runtime (the upload saving is additional)."""
    from distributedtensorflowexample_tpu import native
    from distributedtensorflowexample_tpu.data.cifar10 import _draw
    from distributedtensorflowexample_tpu.data.device_dataset import (
        _dequant_numpy)

    rng = np.random.RandomState(4)
    src8 = rng.randint(0, 256, size=(n_src, 32, 32, 3), dtype=np.uint8)
    src32 = _dequant_numpy(src8, "unit")
    idx = rng.randint(0, n_src, size=batch).astype(np.int64)
    ys, xs, flips = _draw(np.random.RandomState(5), batch)

    # Commutation check before timing: u8 result dequantizes to exactly
    # the f32 path's output.
    np.testing.assert_array_equal(
        _dequant_numpy(native.gather_augment(src8, idx, ys, xs, flips),
                       "unit"),
        native.gather_augment(src32, idx, ys, xs, flips))
    t_u8 = _time(lambda: native.gather_augment(src8, idx, ys, xs, flips), 20)
    t_f32 = _time(lambda: native.gather_augment(src32, idx, ys, xs, flips),
                  20)
    _emit("gather_augment_native_u8_images_per_sec", batch / t_u8,
          "images/sec", t_f32 / t_u8,
          {"batch": batch, "source_rows": n_src,
           "f32_images_per_sec": round(batch / t_f32, 1),
           "bytes_per_image_u8": 3072, "bytes_per_image_f32": 12288})


def main() -> None:
    from distributedtensorflowexample_tpu import native
    # Run ledger (env-gated; OBS_LEDGER) — same per-run bookkeeping as
    # the rest of the bench family.
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger

    obs_ledger.maybe_begin("bench_input")
    if not native.available():
        print(json.dumps({"metric": "native_loader", "value": 0,
                          "unit": "unavailable", "vs_baseline": 0.0,
                          "detail": {"note": "toolchain/build unavailable; "
                                             "numpy fallback is the only "
                                             "path"}}), flush=True)
        obs_ledger.end_global(rc=0, note="native loader unavailable")
        return
    bench_cifar_parse()
    bench_idx_parse()
    bench_gather_augment()
    bench_gather_augment_u8()
    obs_ledger.end_global(rc=0)


if __name__ == "__main__":
    main()
