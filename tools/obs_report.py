#!/usr/bin/env python
"""obs_report — render flight-recorder telemetry as OUTAGE_r*-style markdown.

  python tools/obs_report.py /tmp/flight_1234.json
  python tools/obs_report.py --dir /tmp/supervise_capture_flight \
      --journal /tmp/supervise_capture.jsonl
  # cross-rank Perfetto trace (load at ui.perfetto.dev or
  # chrome://tracing): one lane per rank, one track per attempt
  python tools/obs_report.py --dir /tmp/fleet/flight \
      --journal /tmp/fleet/fleet.jsonl --format trace > fleet.trace.json
  # machine-readable merge (events + anatomy + health + coverage)
  python tools/obs_report.py --dir /tmp/fleet/flight --format json

Reads the ``flight_<pid>.json`` dumps the obs recorder leaves behind
(one per dead run; see distributedtensorflowexample_tpu/obs/) and
prints, per file: run identity (pid/rank/attempt/phase/reason), the
counter table, gauges, the last spans, and the loss-tape tail.  With
``--journal`` it also renders the supervisor journal's attempt history
— and, for fleet journals (resilience/fleet.py), a per-rank timeline:
which rank died first, what tore the gang down, which step the restart
agreed on — so one page answers the questions rounds 3-5 needed grep
archaeology for: what died, at which step, on which attempt.

Round 10 (obs/timeline.py + obs/anomaly.py): every invocation also
MERGES the sources into one cross-rank wall-clock-aligned timeline —
``--format trace`` exports it as Perfetto/Chrome-trace JSON,
``--format json`` as the raw merge, and the default markdown gains a
coverage section (which ranks are present, which flights are missing
or torn — a fleet postmortem renders the ranks it HAS and lists the
gaps instead of failing), a per-step anatomy table (input / compute /
hook / snapshot / other + the compiled collective schedule), and a
health section from any ``health*.json`` found next to the sources.

Stdlib-only and read-only: safe to run on the box mid-outage.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtensorflowexample_tpu.obs import timeline as obs_timeline  # noqa: E402


def _table(headers: list[str], rows: list[list]) -> list[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    out += ["| " + " | ".join(str(c) for c in row) + " |" for row in rows]
    return out


def _fmt_num(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


# One parser for the collective series-key shape (obs/timeline.py owns
# it — per_rank_collectives parses the same gauges out of flights).
_COLL_SERIES = obs_timeline.COLL_SERIES_RE


def render_collectives(counters: dict, gauges: dict) -> list[str]:
    """Wire-traffic section from the per-step collective inventory the
    trainer armed (OBS_COLLECTIVES=1 — utils/profiling.collective_
    inventory through MetricsHook): the per-op schedule plus cumulative
    totals, so a postmortem answers "what was this run's collective
    schedule" without recompiling anything.  Empty when the run carried
    no collective accounting."""
    per_op: dict[str, dict] = {}
    for key, g in gauges.items():
        m = _COLL_SERIES.match(key)
        if m:
            per_op.setdefault(m.group(2), {})[m.group(1)] = g.get("value")
    out: list[str] = []
    if per_op:
        out += _table(["op", "per step", "bytes/step"],
                      [[f"`{op}`", _fmt_num(d.get("ops", "")),
                        _fmt_num(d.get("bytes", ""))]
                       for op, d in sorted(per_op.items())])
    totals = [(k, counters[k]) for k in
              ("collective_ops_total", "collective_bytes_total")
              if k in counters]
    if totals:
        out += [""] if out else []
        out += [f"- **{k}**: {_fmt_num(v)}" for k, v in totals]
    return out


def render_flight(path: str, flight: dict, max_spans: int = 12,
                  max_loss: int = 8) -> str:
    lines = [f"## Flight — `{os.path.basename(path)}`", ""]
    meta = [("reason", flight.get("reason")),
            ("pid", flight.get("pid")),
            ("rank", flight.get("rank")),
            ("attempt", flight.get("attempt")),
            ("phase", flight.get("phase")),
            ("start_unix", flight.get("start_unix")),
            ("argv", " ".join(flight.get("argv", []) or []) or None)]
    meta += sorted((flight.get("notes") or {}).items())
    lines += [f"- **{k}**: {v}" for k, v in meta if v is not None]

    metrics = flight.get("metrics") or {}
    counters = metrics.get("counters") or {}
    if counters:
        lines += ["", "### Counters", ""]
        lines += _table(["counter", "value"],
                        [[f"`{k}`", _fmt_num(v)]
                         for k, v in sorted(counters.items())])
    gauges = metrics.get("gauges") or {}
    if gauges:
        ts = metrics.get("monotonic_ts")
        lines += ["", "### Gauges", ""]
        rows = []
        for k, g in sorted(gauges.items()):
            age = ("" if ts is None or g.get("monotonic_ts") is None
                   else f"{ts - g['monotonic_ts']:.3f}")
            rows.append([f"`{k}`", _fmt_num(g.get("value")), age])
        lines += _table(["gauge", "value", "age_s"], rows)

    coll = render_collectives(counters, gauges)
    if coll:
        lines += ["", "### Collectives", ""] + coll

    spans = flight.get("spans") or []
    if spans:
        lines += ["", f"### Last spans ({min(len(spans), max_spans)} of "
                      f"{len(spans)} recorded)", ""]
        rows = []
        for ev in spans[-max_spans:]:
            extra = {k: v for k, v in ev.items()
                     if k not in ("name", "t0_s", "dur_s", "depth",
                                  "parent", "attempt", "phase")}
            rows.append([f"`{ev.get('name')}`", ev.get("step", ""),
                         _fmt_num(ev.get("dur_s", "")),
                         ev.get("phase", ""),
                         " ".join(f"{k}={v}" for k, v in sorted(
                             extra.items()) if k != "step")])
        lines += _table(["span", "step", "dur_s", "phase", "attrs"], rows)

    loss = flight.get("loss_tail") or []
    if loss:
        lines += ["", f"### Loss tail (last {min(len(loss), max_loss)} of "
                      f"{len(loss)} recorded)", ""]
        lines += _table(["step", "loss"],
                        [[s, _fmt_num(v)] for s, v in loss[-max_loss:]])
    return "\n".join(lines)


def _journal_records(path: str):
    """(records, torn_count) — torn lines are what replay skips."""
    records, torn = [], 0
    with open(path) as f:
        for line in f:
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                torn += 1
    return records, torn


def render_journal(path: str) -> str:
    lines = [f"## Supervisor journal — `{os.path.basename(path)}`", ""]
    rows = []
    try:
        records, torn = _journal_records(path)
    except OSError as e:
        return "\n".join(lines + [f"- unreadable: {e}"])
    for rec in records:
        rows.append([rec.get("event", ""), rec.get("task", ""),
                     rec.get("rank", ""), rec.get("attempt", ""),
                     rec.get("rc", ""),
                     rec.get("reason", rec.get("why", ""))])
    for _ in range(torn):
        rows.append(["(torn line — skipped on replay)", "", "", "", "", ""])
    lines += _table(["event", "task", "rank", "attempt", "rc", "reason"],
                    rows)
    return "\n".join(lines)


_FLEET_EVENTS = ("gang_start", "rank_exit", "rank_lost", "gang_teardown",
                 "gang_end", "resume_agreement", "fleet_end")


def render_fleet_timeline(path: str) -> str:
    """Per-rank timeline of a fleet run (resilience/fleet.py journal):
    who died first, what tore the gang down, what step the restart
    agreed on — the questions a multi-process postmortem starts with.
    Empty string when the journal has no fleet events (single-child
    supervisor journals skip the section)."""
    try:
        records, _ = _journal_records(path)
    except OSError:
        return ""
    events = [r for r in records if r.get("event") in _FLEET_EVENTS]
    if not events:
        return ""
    t0 = events[0].get("ts") or 0
    rows = []
    for r in events:
        ev = r["event"]
        if ev == "gang_start":
            detail = (f"ranks {r.get('ranks')}, resume_step "
                      f"{r.get('resume_step')}")
        elif ev == "rank_exit":
            detail = f"rc={r.get('rc')}" + (
                f" ({r['reason']})" if r.get("reason") else "")
        elif ev == "rank_lost":
            detail = r.get("error", "")
        elif ev == "gang_teardown":
            detail = r.get("why", "")
        elif ev == "gang_end":
            detail = f"{r.get('outcome')}: {r.get('why')}"
        elif ev == "resume_agreement":
            # journal keys are strings: sort ranks numerically so a
            # 12-rank fleet doesn't render 0, 1, 10, 11, 2, ...
            per = r.get("per_rank") or {}
            detail = ("agreed step " + str(r.get("agreed")) + "; " +
                      ", ".join(f"rank {k}: {v}" for k, v in sorted(
                          per.items(),
                          key=lambda kv: (not str(kv[0]).isdigit(),
                                          int(kv[0])
                                          if str(kv[0]).isdigit()
                                          else str(kv[0])))))
        else:   # fleet_end
            detail = (f"attempts={r.get('attempts')} "
                      f"restarts={r.get('restarts')}")
        ts = r.get("ts")
        rows.append([("" if ts is None else f"{ts - t0:+.3f}"),
                     r.get("rank", ""), r.get("attempt", ""), f"`{ev}`",
                     detail])
    lines = [f"## Per-rank timeline — `{os.path.basename(path)}`", ""]
    lines += _table(["t_s", "rank", "attempt", "event", "detail"], rows)
    return "\n".join(lines)


def render_coverage(merged: dict) -> str:
    """The gap list (the torn-flight satellite): which ranks the merge
    HAS, which it expected but could not read — rendered, never raised."""
    cov = merged["coverage"]
    lines = ["## Merged timeline", "",
             f"- **span events**: {len(merged['events'])} "
             f"(+{len(merged['markers'])} journal markers)",
             f"- **ranks present**: {cov['ranks_present'] or 'none'}"]
    if cov["ranks_missing"]:
        lines.append(f"- **ranks MISSING** (expected from the journal / "
                     f"flight names, nothing readable): "
                     f"{cov['ranks_missing']}")
    for path, err in sorted(cov["unreadable"].items()):
        lines.append(f"- **unreadable**: `{os.path.basename(path)}` — "
                     f"{err}")
    if cov["torn_lines"]:
        lines.append(f"- **torn JSONL lines skipped**: "
                     f"{cov['torn_lines']}")
    if cov["uncalibrated_events"]:
        lines.append(f"- **events without a wall stamp** (pre-round-10 "
                     f"writer, no calibratable sibling): "
                     f"{cov['uncalibrated_events']}")
    return "\n".join(lines)


def render_anatomy(rows: list[dict]) -> str:
    """Per-step anatomy (obs/timeline.step_anatomy): where each logged
    window's wall time went, per rank/attempt."""
    if not rows:
        return ""
    lines = ["## Step anatomy (per logged window)", ""]
    table_rows = []
    for r in rows:
        table_rows.append([
            r.get("rank", ""), r.get("attempt", ""),
            (f"{r['step_from']}..{r['step_to']}"
             if r.get("step_from") is not None else r.get("step_to", "")),
            r.get("n", ""), _fmt_num(r.get("window_s", "")),
            _fmt_num(r.get("input_s") if r.get("input_s") is not None
                     else ""),
            _fmt_num(r.get("compute_s") if r.get("compute_s") is not None
                     else ""),
            _fmt_num(r.get("hook_s") if r.get("hook_s") is not None
                     else ""),
            _fmt_num(r.get("snapshot_s", "")),
            _fmt_num(r.get("other_s") if r.get("other_s") is not None
                     else ""),
            _fmt_num(r.get("collective_ops") or ""),
            _fmt_num(r.get("collective_bytes") or "")])
    lines += _table(["rank", "att", "steps", "n", "window_s", "input_s",
                     "compute_s", "hook_s", "snap_s", "other_s",
                     "coll_ops", "coll_bytes"], table_rows)
    tot = obs_timeline.anatomy_totals(rows)
    lines += ["", "- **totals**: " + ", ".join(
        f"{k}={_fmt_num(v)}" for k, v in sorted(tot.items()))]
    return "\n".join(lines)


def render_ledger(path: str) -> str:
    """Run-ledger section (obs/ledger.py RUNS.jsonl): the run table —
    entrypoint, attempts, outcome, anomalies — next to the flights and
    journal those runs left, plus the fleet's resume agreements.
    Unreadable/missing renders as a note, never a raise (the report
    must come out mid-outage)."""
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    lines = [f"## Run ledger — `{os.path.basename(path)}`", ""]
    if not os.path.exists(path) and not os.path.exists(path + ".1"):
        return "\n".join(lines + [f"- unreadable: {path} does not exist"])
    folded = obs_ledger.runs(path)
    table = obs_ledger.run_table(path, folded=folded)
    rows = [[r["run"], r["entrypoint"], r["rank"], r["attempt"],
             r["outcome"], r["final_step"], r["samples"],
             r["anomalies"] or ""] for r in table]
    lines += _table(["run", "entrypoint", "rank", "att", "outcome",
                     "step", "samples", "anomalies"],
                    [[("" if c is None else c) for c in row]
                     for row in rows])
    agreements = [e for e in folded["events"]
                  if e.get("event") == "resume_agreement"]
    for a in agreements:
        lines.append(f"- **resume agreement**: step {a.get('agreed')} "
                     f"(per-rank {a.get('per_rank')}, discarded "
                     f"{a.get('discarded')})")
    if folded["torn"]:
        lines.append(f"- **torn ledger lines skipped**: {folded['torn']}")
    return "\n".join(lines)


def render_health(payloads: list[dict]) -> str:
    """Health section: fleet aggregates first (stragglers + why), then
    per-rank detector flags that fired."""
    if not payloads:
        return ""
    lines = ["## Health", ""]
    for h in sorted(payloads, key=lambda p: (p.get("kind") != "fleet",
                                             p.get("rank") or 0)):
        src = h.get("src", "")
        if h.get("kind") == "fleet":
            skew = h.get("skew") or {}
            lines.append(f"- **fleet** (`{src}`): stragglers "
                         f"{h.get('stragglers') or 'none'}, max step "
                         f"{skew.get('max_step')}, lag {skew.get('lag_steps')}")
            for r, why in sorted((skew.get("why") or {}).items()):
                lines.append(f"  - rank {r}: {why}")
        else:
            fired = {k: f for k, f in (h.get("flags") or {}).items()
                     if f.get("firing") or f.get("fired_step") is not None}
            lines.append(
                f"- **rank {h.get('rank')}** (`{src}`): step "
                f"{h.get('step')}, "
                + (", ".join(f"{k} fired@{f.get('fired_step')}"
                             for k, f in sorted(fired.items()))
                   or "no flags"))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("flights", nargs="*",
                   help="flight_<pid>.json files to render")
    p.add_argument("--dir", default="",
                   help="also render every flight_*.json in this "
                        "directory (OBS_DIR of the run)")
    p.add_argument("--journal", default="",
                   help="supervisor JSONL journal to render alongside")
    p.add_argument("--format", default="md",
                   choices=["md", "json", "trace"],
                   help="md: OUTAGE-style markdown (default); trace: "
                        "Perfetto/Chrome-trace JSON of the cross-rank "
                        "merge; json: the raw merge + anatomy rows")
    p.add_argument("--trace_glob", default="",
                   help="glob of OBS_TRACE_FILE JSONLs to merge in "
                        "(higher-fidelity than the flights' bounded "
                        "span rings)")
    p.add_argument("--health", action="append", default=[],
                   help="extra health.json files to merge (those next "
                        "to --dir/--journal are discovered)")
    p.add_argument("--ledger", default="",
                   help="run ledger (RUNS.jsonl, obs/ledger.py) to "
                        "render as a run table alongside the flights "
                        "and journal")
    p.add_argument("--max_spans", type=int, default=12)
    p.add_argument("--max_loss", type=int, default=8)
    args = p.parse_args(argv)

    sources = obs_timeline.fleet_dir_sources(
        flight_dir=args.dir, journal=args.journal,
        trace_glob=args.trace_glob)
    sources["flight_paths"] = sorted(set(sources["flight_paths"])
                                     | set(args.flights))
    sources["health_paths"] = sorted(set(sources["health_paths"])
                                     | set(args.health))
    if not sources["flight_paths"] and not sources["health_paths"] \
            and not args.journal and not args.trace_glob \
            and not args.ledger:
        p.error("nothing to render: pass flight files, --dir, "
                "--trace_glob, --health, --ledger, or --journal")
    merged = obs_timeline.merge(**sources)

    if args.format == "trace":
        json.dump(obs_timeline.chrome_trace(merged), sys.stdout)
        print()
        return 0
    anatomy = obs_timeline.step_anatomy(merged)
    if args.format == "json":
        json.dump({"coverage": merged["coverage"],
                   "events": merged["events"],
                   "markers": merged["markers"],
                   "health": merged["health"],
                   "collectives": {str(k): v for k, v in
                                   merged["collectives"].items()},
                   "anatomy": anatomy,
                   "anatomy_totals": obs_timeline.anatomy_totals(anatomy)},
                  sys.stdout, default=str)
        print()
        return 0

    sections = ["# Telemetry report", ""]
    for path in sorted(sources["flight_paths"]):
        try:
            with open(path) as f:
                flight = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            sections.append(f"## Flight — `{os.path.basename(path)}`\n\n"
                            f"- unreadable: {e} (rendered the rest — "
                            f"see Merged timeline for the gap list)")
            continue
        sections.append(render_flight(path, flight,
                                      max_spans=args.max_spans,
                                      max_loss=args.max_loss))
    sections.append(render_coverage(merged))
    for section in (render_anatomy(anatomy),
                    render_health(merged["health"]),
                    render_ledger(args.ledger) if args.ledger else ""):
        if section:
            sections.append(section)
    if args.journal:
        timeline = render_fleet_timeline(args.journal)
        if timeline:
            sections.append(timeline)
        sections.append(render_journal(args.journal))
    print("\n\n".join(sections))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
