#!/usr/bin/env python
"""obs_report — render flight-recorder telemetry as OUTAGE_r*-style markdown.

  python tools/obs_report.py /tmp/flight_1234.json
  python tools/obs_report.py --dir /tmp/supervise_capture_flight \
      --journal /tmp/supervise_capture.jsonl

Reads the ``flight_<pid>.json`` dumps the obs recorder leaves behind
(one per dead run; see distributedtensorflowexample_tpu/obs/) and
prints, per file: run identity (pid/attempt/phase/reason), the counter
table, gauges, the last spans, and the loss-tape tail.  With
``--journal`` it also renders the supervisor journal's attempt history,
so one page answers the questions rounds 3-5 needed grep archaeology
for: what died, at which step, on which attempt, after which phase.

Stdlib-only and read-only: safe to run on the box mid-outage.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _table(headers: list[str], rows: list[list]) -> list[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    out += ["| " + " | ".join(str(c) for c in row) + " |" for row in rows]
    return out


def _fmt_num(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_flight(path: str, flight: dict, max_spans: int = 12,
                  max_loss: int = 8) -> str:
    lines = [f"## Flight — `{os.path.basename(path)}`", ""]
    meta = [("reason", flight.get("reason")),
            ("pid", flight.get("pid")),
            ("attempt", flight.get("attempt")),
            ("phase", flight.get("phase")),
            ("start_unix", flight.get("start_unix")),
            ("argv", " ".join(flight.get("argv", []) or []) or None)]
    meta += sorted((flight.get("notes") or {}).items())
    lines += [f"- **{k}**: {v}" for k, v in meta if v is not None]

    metrics = flight.get("metrics") or {}
    counters = metrics.get("counters") or {}
    if counters:
        lines += ["", "### Counters", ""]
        lines += _table(["counter", "value"],
                        [[f"`{k}`", _fmt_num(v)]
                         for k, v in sorted(counters.items())])
    gauges = metrics.get("gauges") or {}
    if gauges:
        ts = metrics.get("monotonic_ts")
        lines += ["", "### Gauges", ""]
        rows = []
        for k, g in sorted(gauges.items()):
            age = ("" if ts is None or g.get("monotonic_ts") is None
                   else f"{ts - g['monotonic_ts']:.3f}")
            rows.append([f"`{k}`", _fmt_num(g.get("value")), age])
        lines += _table(["gauge", "value", "age_s"], rows)

    spans = flight.get("spans") or []
    if spans:
        lines += ["", f"### Last spans ({min(len(spans), max_spans)} of "
                      f"{len(spans)} recorded)", ""]
        rows = []
        for ev in spans[-max_spans:]:
            extra = {k: v for k, v in ev.items()
                     if k not in ("name", "t0_s", "dur_s", "depth",
                                  "parent", "attempt", "phase")}
            rows.append([f"`{ev.get('name')}`", ev.get("step", ""),
                         _fmt_num(ev.get("dur_s", "")),
                         ev.get("phase", ""),
                         " ".join(f"{k}={v}" for k, v in sorted(
                             extra.items()) if k != "step")])
        lines += _table(["span", "step", "dur_s", "phase", "attrs"], rows)

    loss = flight.get("loss_tail") or []
    if loss:
        lines += ["", f"### Loss tail (last {min(len(loss), max_loss)} of "
                      f"{len(loss)} recorded)", ""]
        lines += _table(["step", "loss"],
                        [[s, _fmt_num(v)] for s, v in loss[-max_loss:]])
    return "\n".join(lines)


def render_journal(path: str) -> str:
    lines = [f"## Supervisor journal — `{os.path.basename(path)}`", ""]
    rows = []
    try:
        with open(path) as f:
            raw = f.readlines()
    except OSError as e:
        return "\n".join(lines + [f"- unreadable: {e}"])
    for line in raw:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            rows.append(["(torn line — skipped on replay)", "", "", "", ""])
            continue
        rows.append([rec.get("event", ""), rec.get("task", ""),
                     rec.get("attempt", ""), rec.get("rc", ""),
                     rec.get("reason", rec.get("why", ""))])
    lines += _table(["event", "task", "attempt", "rc", "reason"], rows)
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("flights", nargs="*",
                   help="flight_<pid>.json files to render")
    p.add_argument("--dir", default="",
                   help="also render every flight_*.json in this "
                        "directory (OBS_DIR of the run)")
    p.add_argument("--journal", default="",
                   help="supervisor JSONL journal to render alongside")
    p.add_argument("--max_spans", type=int, default=12)
    p.add_argument("--max_loss", type=int, default=8)
    args = p.parse_args(argv)

    paths = list(args.flights)
    if args.dir:
        paths += sorted(glob.glob(os.path.join(args.dir, "flight_*.json")))
    if not paths and not args.journal:
        p.error("nothing to render: pass flight files, --dir, or --journal")

    sections = ["# Telemetry report", ""]
    for path in paths:
        try:
            with open(path) as f:
                flight = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            sections.append(f"## Flight — `{os.path.basename(path)}`\n\n"
                            f"- unreadable: {e}")
            continue
        sections.append(render_flight(path, flight,
                                      max_spans=args.max_spans,
                                      max_loss=args.max_loss))
    if args.journal:
        sections.append(render_journal(args.journal))
    print("\n\n".join(sections))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
