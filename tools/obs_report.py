#!/usr/bin/env python
"""obs_report — render flight-recorder telemetry as OUTAGE_r*-style markdown.

  python tools/obs_report.py /tmp/flight_1234.json
  python tools/obs_report.py --dir /tmp/supervise_capture_flight \
      --journal /tmp/supervise_capture.jsonl

Reads the ``flight_<pid>.json`` dumps the obs recorder leaves behind
(one per dead run; see distributedtensorflowexample_tpu/obs/) and
prints, per file: run identity (pid/rank/attempt/phase/reason), the
counter table, gauges, the last spans, and the loss-tape tail.  With
``--journal`` it also renders the supervisor journal's attempt history
— and, for fleet journals (resilience/fleet.py), a per-rank timeline:
which rank died first, what tore the gang down, which step the restart
agreed on — so one page answers the questions rounds 3-5 needed grep
archaeology for: what died, at which step, on which attempt.

Stdlib-only and read-only: safe to run on the box mid-outage.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def _table(headers: list[str], rows: list[list]) -> list[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    out += ["| " + " | ".join(str(c) for c in row) + " |" for row in rows]
    return out


def _fmt_num(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


_COLL_SERIES = re.compile(
    r'^collective_(ops|bytes)_per_step\{op="([^"]+)"\}$')


def render_collectives(counters: dict, gauges: dict) -> list[str]:
    """Wire-traffic section from the per-step collective inventory the
    trainer armed (OBS_COLLECTIVES=1 — utils/profiling.collective_
    inventory through MetricsHook): the per-op schedule plus cumulative
    totals, so a postmortem answers "what was this run's collective
    schedule" without recompiling anything.  Empty when the run carried
    no collective accounting."""
    per_op: dict[str, dict] = {}
    for key, g in gauges.items():
        m = _COLL_SERIES.match(key)
        if m:
            per_op.setdefault(m.group(2), {})[m.group(1)] = g.get("value")
    out: list[str] = []
    if per_op:
        out += _table(["op", "per step", "bytes/step"],
                      [[f"`{op}`", _fmt_num(d.get("ops", "")),
                        _fmt_num(d.get("bytes", ""))]
                       for op, d in sorted(per_op.items())])
    totals = [(k, counters[k]) for k in
              ("collective_ops_total", "collective_bytes_total")
              if k in counters]
    if totals:
        out += [""] if out else []
        out += [f"- **{k}**: {_fmt_num(v)}" for k, v in totals]
    return out


def render_flight(path: str, flight: dict, max_spans: int = 12,
                  max_loss: int = 8) -> str:
    lines = [f"## Flight — `{os.path.basename(path)}`", ""]
    meta = [("reason", flight.get("reason")),
            ("pid", flight.get("pid")),
            ("rank", flight.get("rank")),
            ("attempt", flight.get("attempt")),
            ("phase", flight.get("phase")),
            ("start_unix", flight.get("start_unix")),
            ("argv", " ".join(flight.get("argv", []) or []) or None)]
    meta += sorted((flight.get("notes") or {}).items())
    lines += [f"- **{k}**: {v}" for k, v in meta if v is not None]

    metrics = flight.get("metrics") or {}
    counters = metrics.get("counters") or {}
    if counters:
        lines += ["", "### Counters", ""]
        lines += _table(["counter", "value"],
                        [[f"`{k}`", _fmt_num(v)]
                         for k, v in sorted(counters.items())])
    gauges = metrics.get("gauges") or {}
    if gauges:
        ts = metrics.get("monotonic_ts")
        lines += ["", "### Gauges", ""]
        rows = []
        for k, g in sorted(gauges.items()):
            age = ("" if ts is None or g.get("monotonic_ts") is None
                   else f"{ts - g['monotonic_ts']:.3f}")
            rows.append([f"`{k}`", _fmt_num(g.get("value")), age])
        lines += _table(["gauge", "value", "age_s"], rows)

    coll = render_collectives(counters, gauges)
    if coll:
        lines += ["", "### Collectives", ""] + coll

    spans = flight.get("spans") or []
    if spans:
        lines += ["", f"### Last spans ({min(len(spans), max_spans)} of "
                      f"{len(spans)} recorded)", ""]
        rows = []
        for ev in spans[-max_spans:]:
            extra = {k: v for k, v in ev.items()
                     if k not in ("name", "t0_s", "dur_s", "depth",
                                  "parent", "attempt", "phase")}
            rows.append([f"`{ev.get('name')}`", ev.get("step", ""),
                         _fmt_num(ev.get("dur_s", "")),
                         ev.get("phase", ""),
                         " ".join(f"{k}={v}" for k, v in sorted(
                             extra.items()) if k != "step")])
        lines += _table(["span", "step", "dur_s", "phase", "attrs"], rows)

    loss = flight.get("loss_tail") or []
    if loss:
        lines += ["", f"### Loss tail (last {min(len(loss), max_loss)} of "
                      f"{len(loss)} recorded)", ""]
        lines += _table(["step", "loss"],
                        [[s, _fmt_num(v)] for s, v in loss[-max_loss:]])
    return "\n".join(lines)


def _journal_records(path: str):
    """(records, torn_count) — torn lines are what replay skips."""
    records, torn = [], 0
    with open(path) as f:
        for line in f:
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                torn += 1
    return records, torn


def render_journal(path: str) -> str:
    lines = [f"## Supervisor journal — `{os.path.basename(path)}`", ""]
    rows = []
    try:
        records, torn = _journal_records(path)
    except OSError as e:
        return "\n".join(lines + [f"- unreadable: {e}"])
    for rec in records:
        rows.append([rec.get("event", ""), rec.get("task", ""),
                     rec.get("rank", ""), rec.get("attempt", ""),
                     rec.get("rc", ""),
                     rec.get("reason", rec.get("why", ""))])
    for _ in range(torn):
        rows.append(["(torn line — skipped on replay)", "", "", "", "", ""])
    lines += _table(["event", "task", "rank", "attempt", "rc", "reason"],
                    rows)
    return "\n".join(lines)


_FLEET_EVENTS = ("gang_start", "rank_exit", "rank_lost", "gang_teardown",
                 "gang_end", "resume_agreement", "fleet_end")


def render_fleet_timeline(path: str) -> str:
    """Per-rank timeline of a fleet run (resilience/fleet.py journal):
    who died first, what tore the gang down, what step the restart
    agreed on — the questions a multi-process postmortem starts with.
    Empty string when the journal has no fleet events (single-child
    supervisor journals skip the section)."""
    try:
        records, _ = _journal_records(path)
    except OSError:
        return ""
    events = [r for r in records if r.get("event") in _FLEET_EVENTS]
    if not events:
        return ""
    t0 = events[0].get("ts") or 0
    rows = []
    for r in events:
        ev = r["event"]
        if ev == "gang_start":
            detail = (f"ranks {r.get('ranks')}, resume_step "
                      f"{r.get('resume_step')}")
        elif ev == "rank_exit":
            detail = f"rc={r.get('rc')}" + (
                f" ({r['reason']})" if r.get("reason") else "")
        elif ev == "rank_lost":
            detail = r.get("error", "")
        elif ev == "gang_teardown":
            detail = r.get("why", "")
        elif ev == "gang_end":
            detail = f"{r.get('outcome')}: {r.get('why')}"
        elif ev == "resume_agreement":
            # journal keys are strings: sort ranks numerically so a
            # 12-rank fleet doesn't render 0, 1, 10, 11, 2, ...
            per = r.get("per_rank") or {}
            detail = ("agreed step " + str(r.get("agreed")) + "; " +
                      ", ".join(f"rank {k}: {v}" for k, v in sorted(
                          per.items(),
                          key=lambda kv: (not str(kv[0]).isdigit(),
                                          int(kv[0])
                                          if str(kv[0]).isdigit()
                                          else str(kv[0])))))
        else:   # fleet_end
            detail = (f"attempts={r.get('attempts')} "
                      f"restarts={r.get('restarts')}")
        ts = r.get("ts")
        rows.append([("" if ts is None else f"{ts - t0:+.3f}"),
                     r.get("rank", ""), r.get("attempt", ""), f"`{ev}`",
                     detail])
    lines = [f"## Per-rank timeline — `{os.path.basename(path)}`", ""]
    lines += _table(["t_s", "rank", "attempt", "event", "detail"], rows)
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("flights", nargs="*",
                   help="flight_<pid>.json files to render")
    p.add_argument("--dir", default="",
                   help="also render every flight_*.json in this "
                        "directory (OBS_DIR of the run)")
    p.add_argument("--journal", default="",
                   help="supervisor JSONL journal to render alongside")
    p.add_argument("--max_spans", type=int, default=12)
    p.add_argument("--max_loss", type=int, default=8)
    args = p.parse_args(argv)

    paths = list(args.flights)
    if args.dir:
        paths += sorted(glob.glob(os.path.join(args.dir, "flight_*.json")))
    if not paths and not args.journal:
        p.error("nothing to render: pass flight files, --dir, or --journal")

    sections = ["# Telemetry report", ""]
    for path in paths:
        try:
            with open(path) as f:
                flight = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            sections.append(f"## Flight — `{os.path.basename(path)}`\n\n"
                            f"- unreadable: {e}")
            continue
        sections.append(render_flight(path, flight,
                                      max_spans=args.max_spans,
                                      max_loss=args.max_loss))
    if args.journal:
        timeline = render_fleet_timeline(args.journal)
        if timeline:
            sections.append(timeline)
        sections.append(render_journal(args.journal))
    print("\n\n".join(sections))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
