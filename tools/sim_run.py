#!/usr/bin/env python
"""sim_run — scenario in, evidence out: run the deterministic fleet
simulator (sim/) against the REAL scheduler + remediator and land the
full record kit.

  # one scenario file -> record rows on stdout, artifacts in --workdir:
  python tools/sim_run.py scenario.json --workdir /tmp/sim
  # the built-in 10,000-rank battery -> SIM_fleet_cpu_r18.json:
  python tools/sim_run.py --battery --out SIM_fleet_cpu_r18.json

Outputs per run:

- **record rows** (bench-record dialect, one JSON line per metric) —
  queue-wait percentiles, preemption-storm peak, MTTR tails,
  suppression counts, and the must-be-zero invariants
  (``*_steps_lost``, ``*_violations``) tools/bench_ratchet.py ratchets.
- **the ledger + WAL the real code wrote** (``RUNS.jsonl``,
  ``sched/sched.jsonl``) — query them with ``tools/obs_query.py why
  --job <j>`` exactly like a live run's.
- **a Perfetto/chrome-trace timeline** (``--perfetto``) — one track
  per job from the ledger's own rows, plus the serve replica/load
  staircase.

Every battery scenario runs TWICE with the same seed; a single byte of
drift between the two ledgers or WALs is a determinism violation and
lands as ``sim_<scenario>_determinism_violations`` (must-be-zero).
Stdout is the JSON-lines record; prose on stderr.

The scenario DSL's event kinds (the reader half — the writer table
lives in sim/scenario.py; the digest pair keeps them honest):

# KEEP-IN-SYNC(sim-scenario) digest=caa363679294
SCENARIO_EVENT_HELP = '''
  host_loss         rank's host dies (elastic: shrink; else lost)
  host_recover      lost host answers the recovery probe again
  straggler         rank named straggler; gang slows by factor
  straggler_clear   straggler recovers; gang speed restored
  gang_crash        whole gang crashes (rcs 1 -> budgeted retry)
  gang_wedge        gang reports backend wedged (rc 3 quarantine)
  serve_load        offered serve traffic steps to a new level
  snapshot_loss     rank's snapshot shard lost (mirror or rollback)
'''
# KEEP-IN-SYNC-END(sim-scenario)
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger  # noqa: E402
from distributedtensorflowexample_tpu.resilience import (  # noqa: E402
    remediate as heal_mod)
from distributedtensorflowexample_tpu.sim import (  # noqa: E402
    SimWorld, load_scenario, sim_metrics)

#: The measured serve SLO knee (SERVE_lm_cpu_r15.json,
#: serve_lm_tiny_throughput_vs_slo): best in-SLO per-replica goodput.
SERVE_KNEE_TOK_S = 3779.67

#: The fitted psum collective knee at 8 devices
#: (BENCH_collectives_cpu_r06.json detail.knees.psum["8"]) — prices
#: cross-slice snapshot migration in eviction plans.
COLLECTIVE_FIT = {"alpha_s": 0.00035273878968362894,
                  "beta_bytes_per_s": 692186226.9354594}


def _log(msg: str) -> None:
    print(f"sim_run: {msg}", file=sys.stderr, flush=True)


# --- the built-in battery (the SIM_fleet record's scenarios) ---------------

def battery_scenarios() -> list[dict]:
    """Four storms against 10,000 simulated ranks on a 4-slice mesh:
    a host-loss wave, a straggler epidemic, a serve-traffic spike, and
    a quarantine cascade.  Deterministic by construction — everything
    below is literal except the serve cooldown, which seeds from the
    CHECKED-IN measured-MTTR record (same bytes every run)."""
    slices = {"podA": 2600, "podB": 2600, "podC": 2600, "podD": 2600}
    # Post-action quiet period anchored on the worst measured recovery
    # tail (HEAL_* record) instead of the old hardcoded 60 s — see
    # remediate.mttr_seeded_cooldown_s.
    cooldown_s = heal_mod.mttr_seeded_cooldown_s()

    def fleet_jobs(tag, *, n=24, steps=1200, elastic=True):
        return [
            {"job": f"{tag}{i:02d}", "kind": "train",
             "ranks": 417 if i < 16 else 416,
             "steps": steps + 10 * i, "est_step_time_s": 0.5,
             "elastic": elastic, "retries": 3,
             "state_bytes": 1 << 26,
             "priority": 0 if i % 6 == 0 else 10,
             "sim": {"startup_s": 3.0}}
            for i in range(n)]

    hostloss = {
        "name": "fleet10k", "seed": 0, "tick_s": 0.5,
        "horizon_s": 3600, "slices": slices,
        "collective_fit": COLLECTIVE_FIT,
        "jobs": fleet_jobs("t"),
        "events":
            # three loss waves rolling across the fleet while it runs,
            # recoveries trailing each wave (grow-on-recovery load)
            [{"at": 60 + 5 * i, "kind": "host_loss",
              "job": f"t{i:02d}", "rank": 7} for i in range(12)]
            + [{"at": 200 + 5 * i, "kind": "host_recover",
                "job": f"t{i:02d}", "rank": 7} for i in range(12)]
            + [{"at": 300 + 3 * i, "kind": "host_loss",
                "job": f"t{i:02d}", "rank": 11} for i in range(12, 24)],
    }
    epidemic = {
        "name": "epidemic10k", "seed": 0, "tick_s": 0.5,
        "horizon_s": 3600, "slices": slices,
        "collective_fit": COLLECTIVE_FIT,
        # the fleet fills the mesh; six late waiters queue behind it,
        # so straggler evictions have a beneficiary (the heal policy
        # is detection-only with nothing queued) and MTTR is a real
        # detect -> relaunch tail
        "jobs": fleet_jobs("e")
        + [{"job": f"w{i}", "kind": "train", "ranks": 416,
            "steps": 400, "est_step_time_s": 0.5, "retries": 3,
            "state_bytes": 1 << 26, "start_after_s": 60.0,
            "sim": {"startup_s": 3.0}} for i in range(6)],
        "events":
            # half the fleet straggles within two minutes — the heal
            # policy's flap/cooldown/budget guardrails must BIND, not
            # evict everything at once
            [{"at": 90 + 10 * i, "kind": "straggler",
              "job": f"e{i:02d}", "rank": 3} for i in range(12)]
            + [{"at": 600 + 10 * i, "kind": "straggler_clear",
                "job": f"e{i:02d}", "rank": 3} for i in range(12)],
    }
    spike = {
        "name": "servespike", "seed": 0, "tick_s": 0.5,
        "horizon_s": 2400, "slices": slices,
        "collective_fit": COLLECTIVE_FIT,
        # the serve anchor spans the horizon; background training
        # fills the other slices
        "jobs": [{"job": "lm_serve", "kind": "serve", "ranks": 416,
                  "steps": 4700, "est_step_time_s": 0.5,
                  "priority": 0, "sim": {"startup_s": 3.0}}]
                + fleet_jobs("s", n=23, steps=2000),
        "serve": {"replicas": 2, "knee_per_replica": SERVE_KNEE_TOK_S,
                  "min_replicas": 1, "max_replicas": 8, "poll_s": 5.0,
                  "flap_n": 2, "flap_window_s": 120,
                  "cooldown_s": cooldown_s, "budget": 12},
        "events": [
            {"at": 300, "kind": "serve_load",
             "offered_per_s": 4 * SERVE_KNEE_TOK_S},     # spike: 4 knees
            {"at": 900, "kind": "serve_load",
             "offered_per_s": 12 * SERVE_KNEE_TOK_S},    # past max=8
            {"at": 1500, "kind": "serve_load",
             "offered_per_s": 0.2 * SERVE_KNEE_TOK_S},   # collapse
        ],
    }
    cascade = {
        "name": "cascade10k", "seed": 0, "tick_s": 0.5,
        "horizon_s": 3600, "slices": slices,
        "collective_fit": COLLECTIVE_FIT,
        "jobs": fleet_jobs("q"),
        "events":
            # a wedge cascade: six gangs report the backend wedged in
            # quick succession (quarantine, never requeue), two more
            # crash outright (budgeted retries)
            [{"at": 120 + 8 * i, "kind": "gang_wedge",
              "job": f"q{i:02d}", "rank": 0} for i in range(6)]
            + [{"at": 260, "kind": "gang_crash", "job": "q06"},
               {"at": 268, "kind": "gang_crash", "job": "q07"}],
    }
    return [hostloss, epidemic, spike, cascade]


# --- perfetto ---------------------------------------------------------------

def write_perfetto(ledger_path: str, out_path: str,
                   traffic_timeline=None) -> int:
    """Chrome-trace JSON from the ledger the real code wrote: one tid
    per job (placement spans between sched_place and the next terminal
    row, instants for everything else), plus serve replica counters."""
    rows, _ = obs_ledger.read_rows(ledger_path)
    if not rows:
        return 0
    t0 = min(r["ts"] for r in rows if r.get("ts") is not None)
    us = lambda ts: round((ts - t0) * 1e6)  # noqa: E731
    events = []
    open_place: dict[str, tuple] = {}
    closers = ("sched_done", "sched_evict", "sched_retry",
               "sched_quarantine", "sched_fail", "sched_grow")
    for r in rows:
        ev, job, ts = r.get("event"), r.get("job"), r.get("ts")
        if ts is None or not isinstance(ev, str):
            continue
        tid = job or r.get("src") or "fleet"
        if ev == "sched_place":
            open_place[job] = (ts, r.get("slice") or "")
            continue
        if ev in closers and job in open_place:
            ts0, slice_name = open_place.pop(job)
            events.append({
                "name": (f"run[{slice_name}]" if slice_name
                         else "run"),
                "ph": "X", "ts": us(ts0), "dur": max(1, us(ts) - us(ts0)),
                "pid": "sim", "tid": tid,
                "args": {"ended_by": ev}})
        events.append({"name": ev, "ph": "i", "s": "t",
                       "ts": us(ts), "pid": "sim", "tid": tid,
                       "args": {k: v for k, v in r.items()
                                if k not in ("v", "ts", "event")}})
    for job, (ts0, slice_name) in sorted(open_place.items()):
        events.append({"name": "run(unfinished)", "ph": "i", "s": "t",
                       "ts": us(ts0), "pid": "sim", "tid": job})
    for ts, offered, replicas in (traffic_timeline or []):
        events.append({"name": "serve", "ph": "C", "ts": round(ts * 1e6),
                       "pid": "sim", "tid": "serve",
                       "args": {"offered_per_s": round(offered, 3),
                                "replicas": replicas}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)


# --- running ----------------------------------------------------------------

def _run_once(scenario: dict, workdir: str) -> tuple:
    """(world, ledger bytes, WAL bytes) for one fresh run."""
    if os.path.exists(workdir):
        shutil.rmtree(workdir)
    world = SimWorld(load_scenario(dict(scenario)), workdir)
    world.run()
    with open(world.ledger_path, "rb") as f:
        ledger = f.read()
    wal_path = os.path.join(workdir, "sched", "sched.jsonl")
    with open(wal_path, "rb") as f:
        wal = f.read()
    return world, ledger, wal


def run_scenario(scenario: dict, workdir: str, *,
                 check_determinism: bool) -> list[dict]:
    name = scenario.get("name", "scenario")
    world, ledger, wal = _run_once(
        scenario, os.path.join(workdir, name))
    rows = sim_metrics.distill(world, prefix=f"sim_{name}")
    if check_determinism:
        _, ledger2, wal2 = _run_once(
            scenario, os.path.join(workdir, name + ".rerun"))
        drift = int(ledger != ledger2) + int(wal != wal2)
        rows.append({
            "metric": f"sim_{name}_determinism_violations",
            "value": drift, "unit": "runs", "platform": "cpu",
            "detail": {"ledger_bytes": len(ledger),
                       "wal_bytes": len(wal),
                       "ledger_match": ledger == ledger2,
                       "wal_match": wal == wal2}})
        if drift:
            _log(f"{name}: DETERMINISM VIOLATION — same seed, "
                 f"different bytes")
        shutil.rmtree(os.path.join(workdir, name + ".rerun"))
    s = (world.summary or {}).get("summary") or {}
    _log(f"{name}: {s.get('counts')} evictions={s.get('evictions')} "
         f"virtual={world.summary.get('virtual_s')}s")
    return rows


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog=__doc__[__doc__.index("The scenario DSL"):])
    p.add_argument("scenario", nargs="?", default="",
                   help="scenario JSON file (omit with --battery)")
    p.add_argument("--battery", action="store_true",
                   help="run the built-in 10,000-rank storm battery")
    p.add_argument("--workdir", default="/tmp/sim_run",
                   help="artifact root (ledger/WAL per scenario)")
    p.add_argument("--out", default="",
                   help="also write the record (JSON lines) here")
    p.add_argument("--perfetto", default="",
                   help="write a chrome-trace timeline of the FIRST "
                        "scenario here")
    p.add_argument("--no-determinism-check", action="store_true",
                   help="skip the same-seed rerun comparison")
    args = p.parse_args(argv)
    if bool(args.scenario) == bool(args.battery):
        p.error("exactly one of <scenario> or --battery")
    scenarios = (battery_scenarios() if args.battery
                 else [json.load(open(args.scenario))])
    all_rows: list[dict] = []
    first_world_dir = ""
    for scenario in scenarios:
        if isinstance(args.scenario, str) and args.scenario \
                and not scenario.get("name"):
            scenario["name"] = os.path.splitext(
                os.path.basename(args.scenario))[0]
        all_rows.extend(run_scenario(
            scenario, args.workdir,
            check_determinism=not args.no_determinism_check))
        if not first_world_dir:
            first_world_dir = os.path.join(
                args.workdir, scenario.get("name", "scenario"))
    if args.perfetto:
        n = write_perfetto(
            os.path.join(first_world_dir, "RUNS.jsonl"),
            args.perfetto)
        _log(f"perfetto timeline ({n} events) -> {args.perfetto}")
    for row in all_rows:
        print(json.dumps(row, sort_keys=True))
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            for row in all_rows:
                f.write(json.dumps(row, sort_keys=True) + "\n")
        os.replace(tmp, args.out)
        _log(f"record -> {args.out}")
    bad = [r for r in all_rows
           if r["metric"].endswith(("_lost", "_violations"))
           and r["value"]]
    if bad:
        _log("MUST-BE-ZERO metrics nonzero: "
             + ", ".join(f"{r['metric']}={r['value']}" for r in bad))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
