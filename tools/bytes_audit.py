"""Per-op bytes attribution for a compiled train step (PR-2 tentpole).

Decomposes XLA cost-analysis ``bytes_accessed`` per HLO op for one of the
contract workloads' train steps and prints a ranked table: which ops carry
the bytes, per category (conv / reduce / cast / layout / gather /
elementwise / collective / matmul), raw AND effective (gather operands
re-priced at rows-actually-touched — the cost convention charges an
indexed read for its WHOLE operand, so a device-resident split makes the
aggregate number a fiction; see utils/profiling.py).

Runs standalone on any backend.  The tier-1 methodology is the CPU
backend (``--backend cpu``): attribution there is static compile
analysis — no chip, no tunnel — and the CATEGORY SHARES transfer to TPU
up to two documented backend artifacts (BASELINE.md "bytes-attribution
methodology"): CPU runs convolutions in f32, so the ``cast`` category is
CPU-only convert traffic around the bf16 stream, and CPU layout copies
differ from TPU's.  Also wired into bench_profile.py phase 2, so every
on-chip window archives the on-chip table automatically.

Usage:
  python tools/bytes_audit.py --backend cpu                  # config 4
  python tools/bytes_audit.py --workload mnist_cnn --json out.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKLOADS = {
    # name -> (model, dataset, default augment, lr, momentum)
    "resnet20": ("resnet20", "cifar10", "cifar", 0.1, 0.9),
    "mnist_cnn": ("mnist_cnn", "mnist", "none", 0.05, 0.9),
    "softmax": ("softmax", "mnist", "none", 0.5, 0.0),
}


def build_and_audit(workload: str, batch_per_chip: int, unroll: int,
                    augment: str | None = None, top_k: int = 15) -> dict:
    """Build the named workload's indexed train step exactly as the bench
    does (bench._make — same dataset resolution, same step factory),
    compile it, and return the audit record."""
    import bench
    from distributedtensorflowexample_tpu.parallel import make_mesh
    from distributedtensorflowexample_tpu.utils.profiling import (
        cost_and_bytes_audit)

    model, dataset, default_aug, lr, momentum = WORKLOADS[workload]
    aug = default_aug if augment is None else augment
    mesh = make_mesh()
    with mesh:
        step, ds, state, u = bench._make(
            model, dataset, batch_per_chip, unroll, mesh, augment=aug,
            lr=lr, momentum=momentum)
        cost, audit = cost_and_bytes_audit(step, (state, ds.peek()),
                                           unroll=u, top_k=top_k)
    record = {"workload": workload, "model": model, "dataset": dataset,
              "augment": aug, "batch_per_chip": batch_per_chip,
              "unroll": u, "mesh_size": mesh.size,
              "backend": __import__("jax").default_backend(),
              "dequant": ds.dequant_impl or "none",
              "cost_per_step": cost, "audit": audit}
    flops = cost.get("flops")
    eff = audit.get("bytes_effective_per_step")
    if flops and eff:
        hbm_bw = float(os.environ.get("TPU_HBM_BW", 819e9))
        record["arith_intensity_raw"] = round(
            flops / audit["bytes_per_step"], 3)
        record["arith_intensity_effective"] = round(flops / eff, 3)
        # The bandwidth roofline the NEXT on-chip window should see if the
        # effective bytes (not the gather-inflated aggregate) are the true
        # traffic — the armed prediction BASELINE.md records.
        record["bw_roofline_effective_steps_per_sec"] = round(
            hbm_bw / eff, 1)
    return record


def print_table(record: dict, top_k: int = 15) -> None:
    audit = record["audit"]
    if not audit:
        print("no audit available (backend exposed no HLO text?)")
        return
    tot, eff = audit["bytes_per_step"], audit["bytes_effective_per_step"]
    print(f"# {record['workload']}  batch/chip={record['batch_per_chip']}  "
          f"unroll={record['unroll']}  backend={record['backend']}  "
          f"dequant={record['dequant']}")
    flops = record.get("cost_per_step", {}).get("flops")
    if flops:
        print(f"flops/step            {flops / 1e6:12.1f} MFLOP")
    print(f"bytes/step (raw)      {tot / 1e6:12.2f} MB")
    print(f"bytes/step (effective){eff / 1e6:12.2f} MB   "
          f"(phantom gather operands: "
          f"{audit['phantom_gather_bytes_per_step'] / 1e6:.2f} MB)")
    if "arith_intensity_effective" in record:
        print(f"arith intensity       raw {record['arith_intensity_raw']} "
              f"-> effective {record['arith_intensity_effective']} flop/B; "
              f"bw roofline {record['bw_roofline_effective_steps_per_sec']} "
              f"steps/s at TPU_HBM_BW")
    print("\nby category (effective MB/step, raw in parens):")
    raw_cat = audit["by_category_per_step"]
    for cat, b in audit["by_category_effective_per_step"].items():
        print(f"  {cat:12s} {b / 1e6:10.2f}  ({raw_cat.get(cat, 0) / 1e6:.2f})"
              f"  {100 * b / max(1, eff):5.1f}%")
    print(f"\ntop {min(top_k, len(audit['top_ops']))} ops (raw MB/step):")
    for op in audit["top_ops"][:top_k]:
        tail = op["op_name"].split("/")[-3:]
        print(f"  {op['bytes_per_step'] / 1e6:9.2f}  {op['category']:11s} "
              f"{op['opcode']:14s} {op['out'][:28]:28s} {'/'.join(tail)}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workload", default="resnet20",
                    choices=sorted(WORKLOADS))
    ap.add_argument("--batch_per_chip", type=int, default=256)
    ap.add_argument("--unroll", type=int, default=1,
                    help="fused steps per call; 1 audits the plain step "
                         "(per-step numbers are unroll-normalized either "
                         "way)")
    ap.add_argument("--augment", default=None,
                    help="override the workload's default augment "
                         "(none|cifar)")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--json", default="",
                    help="also write the full record to this path")
    ap.add_argument("--backend", default="default",
                    choices=("default", "cpu"),
                    help="cpu = pin the CPU backend in-process (the tier-1 "
                         "audit methodology; works with the chip down, and "
                         "this image's sitecustomize overrides the "
                         "JAX_PLATFORMS env var, so the pin must happen "
                         "here)")
    args = ap.parse_args()

    if args.backend == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    record = build_and_audit(args.workload, args.batch_per_chip,
                             args.unroll, args.augment, top_k=args.top)
    print_table(record, top_k=args.top)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=1)
        print(f"\nwrote {args.json}")


if __name__ == "__main__":
    main()
