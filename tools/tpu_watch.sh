#!/bin/bash
# TPU backend watcher — the productized recovery loop (VERDICT r2 #1b).
#
# Probes the backend every 5 minutes with bench.py's SIGTERM-safe
# subprocess probe (a hung init costs ~5 min, not 25-45).  Every attempt
# is appended to $WATCH_LOG.  Launches are EDGE-TRIGGERED: a FAIL->OK
# transition marks a fresh recovery window and starts exactly one
# capture (tools/bench_capture.sh); a backend that stays up does not
# re-launch, and each new window after an outage gets its own capture.
#
# On the edge, bench processes OLDER than the window (age > 15 min) are
# killed first: their tunnel connections died with the outage (no
# healthy chip lease to wedge; SIGTERM is the OS-default immediate
# termination for python), and a short window (round 3 measured one at
# ~9 minutes) must go to the current headline-first bench, not a parked
# process's stale order.  A YOUNG bench — one whose own probe-retry
# loop re-acquired the recovered backend — is healthy and left alone
# (no new launch either: it IS the capture).
#
# `prev` starts OK so a watcher (re)started next to a HEALTHY running
# capture never kills it; in an already-healthy window with no capture,
# launch one by hand:  setsid nohup tools/bench_capture.sh &
#
# Operational notes (hard-won, see .claude/skills/verify/SKILL.md):
#   - Run via `setsid nohup tools/tpu_watch.sh &` from the repo root.
#   - Do NOT run the full CPU test suite and rely on probe timing at
#     the same time on a 1-core host; probes create load spikes.
#   - pkill/pgrep -f patterns match the invoking shell's own command
#     line — launch this script as a FILE, never paste its body inline.

cd "$(dirname "$0")/.." || exit 1
WATCH_LOG=${WATCH_LOG:-/tmp/tpu_watch.log}
RECOVERED_MARKER=${RECOVERED_MARKER:-/tmp/tpu_recovered}
PROBE_INTERVAL_S=${PROBE_INTERVAL_S:-300}

prev=OK
while true; do
  ts=$(date -u +%H:%M:%S)
  # -k 10 390: the probe's own worst case is ~335 s (import + 300 s wait
  # + 30 s SIGTERM grace + SIGKILL); the outer timeout must outlast it
  # or it orphans a SIGTERM-ignoring child before the SIGKILL escalation.
  out=$(timeout -k 10 390 python -c "
import bench
ok, info = bench._probe_backend(timeout_s=300)
print('OK' if ok else 'FAIL', info)
" 2>/dev/null | tail -1)
  echo "$ts $out" >> "$WATCH_LOG"
  case "$out" in
    OK*)
      touch "$RECOVERED_MARKER"
      if [ "$prev" != OK ]; then
        # Only processes OLDER than this recovery window are stale: a
        # young bench (its own probe-retry loop re-acquired the backend
        # just before our probe did) is HEALTHY and holds a live chip
        # lease — killing it mid-init is the documented tunnel-wedging
        # action.  Age gate: anything older than 15 min predates the
        # window (outages run hours; windows are minutes old by now).
        young=0
        for pid in $(pgrep -f "python bench"); do
          age=$(ps -o etimes= -p "$pid" | tr -d ' ')
          if [ -n "$age" ] && [ "$age" -gt 900 ]; then
            echo "$ts killing stale bench pid $pid (age ${age}s)" >> "$WATCH_LOG"
            kill -TERM "$pid" 2>/dev/null
            sleep 10
            kill -KILL "$pid" 2>/dev/null
          else
            young=1
          fi
        done
        if [ "$young" -eq 1 ]; then
          echo "$ts young bench already capturing; not launching" >> "$WATCH_LOG"
        elif pgrep -f "bash tools/bench_capture.sh" > /dev/null; then
          echo "$ts capture script already live; not launching" >> "$WATCH_LOG"
        else
          sleep 10
          echo "$ts launching auto-capture" >> "$WATCH_LOG"
          setsid nohup bash tools/bench_capture.sh > /dev/null 2>&1 &
        fi
      fi
      prev=OK
      ;;
    *)
      prev=FAIL
      ;;
  esac
  sleep "$PROBE_INTERVAL_S"
done
