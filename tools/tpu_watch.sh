#!/bin/bash
# TPU backend watcher — the productized recovery loop (VERDICT r2 #1b,
# pidfile coordination r4 per VERDICT r3 #7).
#
# Probes the backend every 5 minutes with bench.py's SIGTERM-safe
# subprocess probe (a hung init costs ~5 min, not 25-45).  Every attempt
# is appended to $WATCH_LOG.  Launches are EDGE-TRIGGERED: a FAIL->OK
# transition marks a fresh recovery window and starts exactly one
# capture (tools/bench_capture.sh); a backend that stays up does not
# re-launch, and each new window after an outage gets its own capture.
#
# Capture liveness is tracked by a PIDFILE written by bench_capture.sh
# ($CAPTURE_PIDFILE), not argv pattern-matching — a capture launched as
# `bash ./tools/bench_capture.sh` or from another cwd is still seen
# (round-3 weak item: `pgrep -f` missed non-canonical spellings).  The
# only remaining pgrep is an ANCHORED orphan sweep over hand-launched
# `python bench.py` AND `python bench_profile.py` (the \.py anchors
# keep the two patterns from cross-matching each other or
# bench_scaling/bench_input; both ARE swept with the same
# confirmed-outage stale gate).
#
# Kill policy (round-4 review hardening): kills are armed ONLY on a
# recovery edge after a CONFIRMED outage (>= 2 consecutive failed
# probes — one FAIL can be a host load spike, and killing the driver's
# own ~23-min bench on a flap would lose the official record), and the
# stale threshold is max($STALE_S, outage duration + 60 s) — nothing
# that started during or after the outage is ever a kill target.  A
# stale CAPTURE is killed as a whole process group (single-pid fallback
# for non-setsid launches) so a half-dead parent can't suppress the
# fresh launch (round-3 ADVICE).  A YOUNG bench/capture re-acquired the
# recovered backend itself: it IS the capture; leave it alone and don't
# double-launch.  The watcher-startup path NEVER kills.
#
# A watcher (re)started inside an ALREADY-HEALTHY window (first probe
# OK, no edge) used to deliberately do nothing — an operator footgun
# (round-3 weak item).  With the pidfile it can tell a healthy capture
# from none: on the FIRST probe, if OK and no capture/bench is live, it
# launches one.  A healthy running capture (or the driver's own bench
# run — a young `python bench.py`) suppresses that, so a restart next
# to live work remains a no-op.
#
# Operational notes (hard-won, see .claude/skills/verify/SKILL.md):
#   - Run via `setsid nohup tools/tpu_watch.sh &` from the repo root.
#   - Do NOT run the full CPU test suite and rely on probe timing at
#     the same time on a 1-core host; probes create load spikes.
#   - pkill/pgrep -f patterns match the invoking shell's own command
#     line — launch this script as a FILE, never paste its body inline.

cd "$(dirname "$0")/.." || exit 1
WATCH_LOG=${WATCH_LOG:-/tmp/tpu_watch.log}
RECOVERED_MARKER=${RECOVERED_MARKER:-/tmp/tpu_recovered}
CAPTURE_PIDFILE=${CAPTURE_PIDFILE:-/tmp/bench_capture.pid}
PROBE_INTERVAL_S=${PROBE_INTERVAL_S:-300}
# Per-probe backend timeout (was hardcoded 300 inline: the round-5 watch
# log burned exactly 300 s on each of 215 consecutive probes).  Exported
# so the python snippet below reads the same value the outer timeout is
# derived from.
PROBE_TIMEOUT_S=${PROBE_TIMEOUT_S:-300}
export PROBE_TIMEOUT_S
STALE_S=${STALE_S:-900}
# Capture launcher on a recovery edge: "supervised" (default) delegates
# the 4-phase sequence to tools/supervise.py — journaled resume across
# windows, wedge-aware phase skipping, bounded phase 4; "bash" is the
# legacy inline tools/bench_capture.sh fallback.
CAPTURE_LAUNCHER=${CAPTURE_LAUNCHER:-supervised}

# Liveness + age via ps (empty output = no such process).
proc_age() { ps -o etimes= -p "$1" 2>/dev/null | tr -d ' '; }

# $1 = ts, $2 = stale threshold in seconds (empty/0 = NEVER kill — the
# startup path and single-flap edges must not touch live work; only a
# confirmed-outage edge passes a threshold).
# 0 = a live capture remains, 1 = none (stale one killed / orphan
# pidfile cleaned / absent).
check_capture() {
  local ts="$1" kill_over="${2:-0}" cap_pid cap_age
  [ -f "$CAPTURE_PIDFILE" ] || return 1
  cap_pid=$(cat "$CAPTURE_PIDFILE" 2>/dev/null)
  [ -n "$cap_pid" ] || { rm -f "$CAPTURE_PIDFILE"; return 1; }
  cap_age=$(proc_age "$cap_pid")
  if [ -z "$cap_age" ]; then
    echo "$ts removing orphan capture pidfile (pid $cap_pid dead)" \
      >> "$WATCH_LOG"
    rm -f "$CAPTURE_PIDFILE"
    return 1
  fi
  if [ "$kill_over" -gt 0 ] && [ "$cap_age" -gt "$kill_over" ]; then
    # Whole group when the capture was setsid'd; for non-group-leader
    # launches (any spelling is legal now) the fallback kills the shell
    # AND its direct children — killing only the parent would orphan a
    # live bench/profile child that then suppresses the fresh launch as
    # a "young bench" with no parent left to promote its .tmp output.
    # TERM->KILL grace must OUTLAST the supervised capture's own child
    # escalation (supervise.py kill_grace_s=30): a SIGTERM'd supervisor
    # forwards TERM to its child group (own session — the watcher's
    # group kill can't reach it) and needs its full grace to escalate a
    # TERM-ignoring child to KILL before we KILL the supervisor itself.
    kids=$(pgrep -P "$cap_pid" 2>/dev/null | tr '\n' ' ')
    echo "$ts killing stale capture group $cap_pid (age ${cap_age}s >" \
         "${kill_over}s; kids: ${kids:-none})" >> "$WATCH_LOG"
    kill -TERM -- "-$cap_pid" 2>/dev/null \
      || kill -TERM "$cap_pid" $kids 2>/dev/null
    sleep "${CAPTURE_KILL_GRACE_S:-35}"
    kill -KILL -- "-$cap_pid" 2>/dev/null \
      || kill -KILL "$cap_pid" $kids 2>/dev/null
    rm -f "$CAPTURE_PIDFILE"
    return 1
  fi
  echo "$ts capture already live (pid $cap_pid, age ${cap_age}s);" \
       "not launching" >> "$WATCH_LOG"
  return 0
}

# $1 = ts, $2 = stale threshold (empty/0 = never kill).  Sweeps
# bench.py, bench_profile.py (anchored — bench_scaling/bench_input
# never hold the chip long) AND the capture's phase-4 trainer run,
# matched by ITS unique --log_dir (a bare trainer pattern would also
# match CPU-only trainer subprocesses from the test suite, and a young
# one at a recovery edge would suppress the window's capture launch).
# If the capture shell dies without its children, the orphaned trainer
# keeps holding the chip and must be sweepable like the bench.
# 0 = a live one remains (it IS the capture), 1 = none.
check_orphan_bench() {
  local ts="$1" kill_over="${2:-0}" young=1 pid age pat
  for pat in "python bench\.py" "python bench_profile\.py" \
             "trainers\.trainer_.*cli_bench_r05"; do
    for pid in $(pgrep -f "$pat"); do
      age=$(proc_age "$pid")
      [ -n "$age" ] || continue
      if [ "$kill_over" -gt 0 ] && [ "$age" -gt "$kill_over" ]; then
        echo "$ts killing stale bench pid $pid (age ${age}s >" \
             "${kill_over}s)" >> "$WATCH_LOG"
        kill -TERM "$pid" 2>/dev/null
        sleep 10
        kill -KILL "$pid" 2>/dev/null
      else
        young=0
      fi
    done
  done
  return $young
}

# $1 = ts, $2 = stale threshold (0 = liveness checks only, no kills).
maybe_launch() {
  local ts="$1" kill_over="${2:-0}"
  if check_capture "$ts" "$kill_over"; then
    return
  fi
  if check_orphan_bench "$ts" "$kill_over"; then
    echo "$ts young bench already capturing; not launching" >> "$WATCH_LOG"
    return
  fi
  sleep 10
  if [ "$CAPTURE_LAUNCHER" = bash ]; then
    echo "$ts launching auto-capture (bash fallback)" >> "$WATCH_LOG"
    setsid nohup bash tools/bench_capture.sh > /dev/null 2>&1 &
  else
    echo "$ts launching auto-capture (supervised)" >> "$WATCH_LOG"
    setsid nohup python tools/supervise.py --capture > /dev/null 2>&1 &
  fi
}

prev=OK
first=1
fails=0
fail_start=0
while true; do
  ts=$(date -u +%H:%M:%S)
  # Outer timeout = PROBE_TIMEOUT_S + 90: the probe's own worst case is
  # ~timeout+35 s (import + wait + 30 s SIGTERM grace + SIGKILL); the
  # outer timeout must outlast it or it orphans a SIGTERM-ignoring child
  # before the SIGKILL escalation.  ${PROBE_TIMEOUT_S%.*}: the python
  # consumer accepts floats, but bash arithmetic would fatally error on
  # one — truncate (the +90 margin dwarfs a lost fraction).
  out=$(timeout -k 10 $((${PROBE_TIMEOUT_S%.*} + 90)) python -c "
import os
import bench
ok, info = bench._probe_backend(
    timeout_s=float(os.environ.get('PROBE_TIMEOUT_S', 300)))
print('OK' if ok else 'FAIL', info)
" 2>/dev/null | tail -1)
  echo "$ts $out" >> "$WATCH_LOG"
  case "$out" in
    OK*)
      touch "$RECOVERED_MARKER"
      if [ "$prev" != OK ]; then
        # Recovery edge.  Kills are armed ONLY after a CONFIRMED outage
        # (>= 2 consecutive failed probes — a single FAIL can be a load
        # spike on this 1-core host, and killing the driver's own
        # 23-min bench on a flap would lose the official record); the
        # threshold is the outage duration + margin, floored at
        # STALE_S, so nothing that started DURING or AFTER the outage
        # window is ever a kill target.
        kill_over=0
        if [ "$fails" -ge 2 ]; then
          outage_s=$(( $(date +%s) - fail_start + 60 ))
          kill_over=$(( outage_s > STALE_S ? outage_s : STALE_S ))
        fi
        maybe_launch "$ts" "$kill_over"
      elif [ "$first" = 1 ]; then
        # Healthy-window (re)start: liveness checks only, NEVER kill —
        # a restart next to healthy running work must stay a no-op.
        maybe_launch "$ts" 0
      fi
      prev=OK
      fails=0
      ;;
    *)
      if [ "$prev" = OK ] || [ "$fail_start" = 0 ]; then
        fail_start=$(date +%s)
      fi
      fails=$((fails + 1))
      prev=FAIL
      ;;
  esac
  first=0
  sleep "$PROBE_INTERVAL_S"
done
