#!/usr/bin/env python
"""faultline — reproduce any injected-fault scenario from the CLI.

Runs a small CPU training loop with a named FaultPlan wired in, speaking
the supervisor's exit-code protocol, so every resilience scenario is one
command (and one tier-1-safe smoke test):

  python tools/faultline.py --plan preempt --steps 8 --workdir /tmp/fl
  # SIGTERM at a seed-drawn mid-run step -> snapshot saved -> exit 143
  python tools/faultline.py --plan preempt --steps 8 --workdir /tmp/fl
  # resumes from the snapshot, finishes, exit 0

Plans (resilience/faults.py NAMED_PLANS): preempt, wedge, nan_loss,
corrupt_batch, torn_snapshot, heartbeat_flap, journal_torn, slow_rank,
shard_loss, bitflip, none — or explicit specs like
``preemption@3`` / ``wedge@2:5.0`` / ``slow_rank@5:0.5%1`` (rank 1
turns persistent straggler at step 5: every later boundary delayed
0.5 s, heartbeats alive, survives resume), comma-separated.  The same
``(--plan, --steps, --seed)`` triple reproduces the same scenario
anywhere.  Under the supervisor, faults are TRANSIENT by default: they
fire on attempt 0 only (SUPERVISE_ATTEMPT), like the real corrupted
batch or torn write they model.

``--layout zero3`` runs the drill on a ``--mesh``-wide virtual CPU
mesh with ZeRO-3 row state and the shard-redundant ShardStore
(resilience/shardstore.py) in place of the monolithic SnapshotStore:
snapshots are per-rank shard files + ring mirrors under a quorum
manifest, resume goes through the engine's elastic regroup (so a
``--mesh 2`` resume of a ``--mesh 4`` run is legal AND bitwise at the
restore boundary), and the ``shard_loss``/``bitflip`` plans delete or
rot exactly one shard after the final save.  ``%RANK`` on those plans
names the MESH-SHARD index inside this process's store, not a fleet
rank.  The emitted ``params_digest`` hashes the MATERIALIZED params —
the width-independent parity handle (the row digest is 1/D-structured
and only comparable at equal width).

Fleet drills (tools/supervise_fleet.py) run one faultline per rank with
the SAME plan text: a ``%rank`` suffix pins a spec to one rank
(``kill@5%1`` = kill rank 1 at step 5), and this process keeps only the
specs for ITS rank (--rank, default OBS_RANK).  When the fleet's
resume-step agreement exported FLEET_RESUME_STEP, the restore targets
exactly that step — never this rank's own newest, which may sit on a
divergent timeline the gang has discarded.

stdout is one JSON line: status, start/end step, a sha256 digest over
every state leaf (params, optimizer state, BN stats, RNG, step — the
cheap cross-process bitwise-parity handle), and the (step, loss) tape.
Everything else goes to stderr.
"""

from __future__ import annotations

import argparse
import contextlib
import hashlib
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _digest(state) -> str:
    import jax
    import numpy as np

    from distributedtensorflowexample_tpu.training.checkpoint import (
        saveable_state_dict)
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(saveable_state_dict(state)):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _params_digest(state, zero3_layout) -> str:
    """sha256 over the MATERIALIZED params: the width-independent half
    of the parity handle (row leaves are 1/D-structured, so the full
    state digest only compares at equal mesh width)."""
    import jax
    import numpy as np
    h = hashlib.sha256()
    params = zero3_layout.materialize(state.params)
    for leaf in jax.tree.leaves(params):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


_LM_DRILL_SEQ = 32      # short sequences keep LM drills tier-1-cheap
_DRILL_BUCKET_BYTES = 1 << 20   # zero3 drills: one-ish bucket per dtype


def _batch_stream(batch_size: int, seed: int, start_step: int,
                  pool_size: int = 4, model: str = "softmax"):
    """Deterministic, step-addressable batches: step s always sees pool
    slot (s-1) % pool_size, so a resumed run replays the identical
    stream from its restored step — the dataset-cursor contract the
    snapshot manifest records (here the cursor IS the step).  LM models
    get int32 token batches (the host-fed integer convention: uint8
    would read as quantized pixels to the dequant seam)."""
    import jax.numpy as jnp

    if model.startswith("lm_"):
        from distributedtensorflowexample_tpu.data.lm import (
            make_synthetic_tokens)
        from distributedtensorflowexample_tpu.models.transformer_lm import (
            LM_VOCAB)
        seq = make_synthetic_tokens(batch_size * pool_size, _LM_DRILL_SEQ,
                                    LM_VOCAB, seed, sample_seed=seed + 1)
        x = seq[:, :-1].astype("int32")
        y = seq[:, 1:].astype("int32")
    else:
        from distributedtensorflowexample_tpu.data.synthetic import (
            make_synthetic)
        x, y = make_synthetic(batch_size * pool_size, (28, 28, 1), 10,
                              seed=seed + 1)
    pool = [{"image": jnp.asarray(x[i * batch_size:(i + 1) * batch_size]),
             "label": jnp.asarray(y[i * batch_size:(i + 1) * batch_size])}
            for i in range(pool_size)]

    def gen():
        s = start_step
        while True:
            yield pool[s % pool_size]
            s += 1

    return gen()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--plan", default="preempt",
                   help="named plan or kind[@step][:arg] specs, "
                        "comma-separated (see resilience/faults.py)")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--workdir", default="/tmp/faultline",
                   help="snapshot directory (shared across attempts — "
                        "this is what resume resumes from)")
    p.add_argument("--model", default="softmax",
                   choices=["softmax", "mnist_cnn", "lm_tiny"],
                   help="lm_tiny drills the transformer-LM trainer: "
                        "corrupt_batch garbage ids land out-of-vocab, "
                        "the model's OOV poison NaNs the loss, and "
                        "NaNGuard + the flight recorder take it from "
                        "there (models/transformer_lm.py)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--snapshot_every", type=int, default=1)
    p.add_argument("--keep", type=int, default=3)
    p.add_argument("--resume", default="true",
                   help="resume from the latest manifest-valid snapshot "
                        "(or from FLEET_RESUME_STEP when a fleet "
                        "agreement pass exported one)")
    p.add_argument("--rank", type=int, default=None,
                   help="this process's rank for %%rank-targeted fault "
                        "specs (default: OBS_RANK, else 0)")
    p.add_argument("--layout", default="tree",
                   choices=["tree", "zero3"],
                   help="zero3: ZeRO-3 row state on a --mesh-wide "
                        "virtual CPU mesh with the shard-redundant "
                        "ShardStore (shard_loss/bitflip plans live "
                        "here; resume is elastic across widths)")
    p.add_argument("--mesh", type=int, default=4,
                   help="virtual CPU mesh width for --layout zero3")
    p.add_argument("--transient", default="true",
                   help="faults fire on SUPERVISE_ATTEMPT=0 only (a "
                        "retry models recovered hardware); false "
                        "re-fires every attempt")
    args = p.parse_args(argv)
    truthy = lambda v: str(v).lower() in ("1", "true", "t", "yes", "y")

    if args.layout == "zero3":
        # Row layouts need a real multi-device mesh; give this process
        # --mesh virtual CPU devices BEFORE the backend spins up.
        from distributedtensorflowexample_tpu.compat import (
            cpu_collective_flags, set_num_cpu_devices)
        set_num_cpu_devices(args.mesh)
        cpu_collective_flags()
    import jax
    # Standalone invocations must pin CPU in-process: this image's
    # sitecustomize force-registers the axon TPU platform and overrides
    # JAX_PLATFORMS from the environment (see tests/conftest.py) — and a
    # fault drill must never touch, or wedge on, the real tunnel.
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_train_step)
    from distributedtensorflowexample_tpu.resilience import (
        FaultInjectionHook, FaultPlan, FaultyBatches, MetricsTapeHook,
        NaNGuardHook, SnapshotHook, SnapshotStore)
    from distributedtensorflowexample_tpu.resilience.faults import (
        tear_journal)
    from distributedtensorflowexample_tpu.training.hooks import (
        AnomalyHook, HeartbeatHook, MetricsHook)
    from distributedtensorflowexample_tpu.training.loop import TrainLoop
    from distributedtensorflowexample_tpu.training.state import TrainState
    from distributedtensorflowexample_tpu.utils.signals import sigterm_flag

    attempt = int(os.environ.get("SUPERVISE_ATTEMPT", "0"))
    # Supervised drills leave a flight_<pid>.json postmortem per attempt
    # (OBS_FLIGHT=1 opts a bare run in) — the cross-check surface for
    # the supervisor journal + snapshot manifest (tests/test_obs.py).
    rank = (args.rank if args.rank is not None
            else int(os.environ.get("OBS_RANK", "0")))
    rec = obs_recorder.maybe_install(sigterm=False)
    if rec is not None:
        rec.note(tool="faultline", plan=args.plan, model=args.model,
                 workdir=args.workdir)
    # Run ledger + live scrape (env-gated): a fleet drill's per-attempt
    # rows land in the RUNS.jsonl the fleet supervisor exported, and
    # OBS_HTTP_PORT answers /metrics///health while the drill runs.
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    obs_ledger.maybe_begin(
        "faultline", config={"plan": args.plan, "steps": args.steps,
                             "model": args.model, "seed": args.seed,
                             "batch": args.batch, "rank": rank})
    obs_serve.maybe_start()
    plan = FaultPlan.parse(args.plan, args.steps, args.seed)
    if any(s.rank is not None for s in plan.specs):
        # Every rank parses the SAME text (same seed anchor), then keeps
        # only its own specs — "kill rank 1 at step 5" is one shared
        # scenario, not per-rank guesswork.
        plan = plan.for_rank(rank)
        print(f"faultline: rank {rank} plan: "
              + (", ".join(f"{s.kind}@{s.step}" for s in plan.specs)
                 or "(no faults target this rank)"),
              file=sys.stderr, flush=True)
    if plan and truthy(args.transient) and attempt > 0:
        print(f"faultline: attempt {attempt}: plan {args.plan!r} already "
              f"fired (transient) — clean run", file=sys.stderr, flush=True)
        plan = FaultPlan([], seed=args.seed, name=f"{args.plan} (cleared)")

    snap_dir = os.path.join(args.workdir, "snapshots")
    store = SnapshotStore(snap_dir, keep=args.keep)
    model = build_model(args.model)
    sample = (jnp.zeros((args.batch, _LM_DRILL_SEQ), jnp.int32)
              if args.model.startswith("lm_") else
              jnp.zeros((args.batch, 28, 28, 1), jnp.float32))
    tx = optax.sgd(0.1, momentum=0.9)
    state = TrainState.create(model, tx, sample, seed=args.seed)
    mesh = None
    zero3_layout = None
    shard_store = None
    if args.layout == "zero3":
        from distributedtensorflowexample_tpu.engine.engine import (
            apply_update_layout)
        from distributedtensorflowexample_tpu.parallel import make_mesh
        from distributedtensorflowexample_tpu.resilience import (
            ShardLayout, ShardSnapshotHook, ShardStore)
        mesh = make_mesh(args.mesh)
        shard_store = ShardStore(
            snap_dir,
            layout=ShardLayout.for_params("zero3_rows",
                                          _DRILL_BUCKET_BYTES,
                                          state.params, args.mesh),
            keep=args.keep)
    agreed_txt = os.environ.get("FLEET_RESUME_STEP", "")
    if truthy(args.resume):
        if agreed_txt:
            # The fleet's agreement pass picked the max common valid
            # step and discarded everything newer; restoring this
            # rank's own newest instead would silently resume a
            # DIFFERENT global step than the other ranks (the
            # divergence the agreement exists to prevent).
            agreed = int(agreed_txt)
            if agreed > 0:
                active = shard_store if shard_store is not None else store
                ok, why = active.validate(agreed)
                if not ok:
                    print(f"faultline: fleet agreed resume step {agreed} "
                          f"is not valid in this rank's store ({why}) — "
                          f"the agreement pass guarantees every rank "
                          f"holds it; refusing to resume from a "
                          f"divergent snapshot", file=sys.stderr,
                          flush=True)
                    obs_ledger.end_global(rc=1)
                    return 1
                if shard_store is not None:
                    state, shard_aux = shard_store.restore_elastic(
                        state, tx, mesh=mesh, step=agreed)
                    zero3_layout = shard_aux["zero3_layout"]
                else:
                    state = store.restore(state, step=agreed)
            # agreed == 0: no common step existed — start fresh.
        elif shard_store is not None:
            if shard_store.latest_valid() is not None:
                # The elastic restore: ANY saved width regroups onto
                # this mesh through the engine's one re-layout pass.
                state, shard_aux = shard_store.restore_elastic(
                    state, tx, mesh=mesh)
                zero3_layout = shard_aux["zero3_layout"]
        else:
            state = store.restore(state)
    if args.layout == "zero3" and zero3_layout is None:
        # Fresh start (nothing restored): lay the tree state out as
        # rows the same way the engine does.
        state, zero3_layout = apply_update_layout(
            state, tx, update_layout="zero3_rows",
            bucket_bytes=_DRILL_BUCKET_BYTES, mesh=mesh)
    start_step = int(state.step)
    if start_step:
        print(f"faultline: resumed from snapshot at step {start_step}",
              file=sys.stderr, flush=True)

    batches = FaultyBatches(
        _batch_stream(args.batch, args.seed, start_step,
                      model=args.model), plan,
        start_step=start_step)
    tape = MetricsTapeHook()
    # Order is load-bearing: MetricsHook first so the flight recorder
    # rings every step's loss INCLUDING a poisoned one (the evidence);
    # then the NaN guard, which must raise BEFORE SnapshotHook sees the
    # poisoned step, so no snapshot of a non-finite state ever reaches
    # disk; FaultInjectionHook goes last so the step that a
    # preemption/wedge covers is already snapshotted.
    # AnomalyHook right after MetricsHook (it reads the loss gauge the
    # latter sets) and BEFORE FaultInjectionHook: an injected slow_rank
    # delay lands in the NEXT boundary's window sample, so the per-rank
    # health.json a fleet drill reads (OBS_HEALTH, exported by the
    # fleet supervisor) flags the straggler while it is still running.
    from distributedtensorflowexample_tpu.obs.anomaly import RunHealth
    hooks = [MetricsHook(every=1),
             AnomalyHook(every=1,
                         health_path=os.environ.get("OBS_HEALTH", ""),
                         health=RunHealth(rank=rank)),
             NaNGuardHook(), tape,
             (ShardSnapshotHook(shard_store, every=args.snapshot_every,
                                cursor={"seed": args.seed})
              if shard_store is not None else
              SnapshotHook(store, every=args.snapshot_every,
                           cursor={"seed": args.seed})),
             FaultInjectionHook(plan)]
    hb = os.environ.get("SUPERVISE_HEARTBEAT", "")
    if hb:
        hooks.append(HeartbeatHook(hb))

    def emit(status: str, digest_state=None, **extra) -> None:
        rec = {"status": status, "plan": args.plan, "seed": args.seed,
               "attempt": attempt, "rank": rank,
               "start_step": start_step,
               "losses": [[s, loss] for s, loss in tape.tape], **extra}
        if digest_state is not None:
            rec["step"] = int(digest_state.step)
            rec["digest"] = _digest(digest_state)
            if zero3_layout is not None:
                rec["params_digest"] = _params_digest(digest_state,
                                                      zero3_layout)
        print(json.dumps(rec, sort_keys=True), flush=True)

    step_fn = (make_train_step(mesh=mesh, zero3_layout=zero3_layout)
               if mesh is not None else make_train_step())
    mesh_ctx = mesh if mesh is not None else contextlib.nullcontext()
    with sigterm_flag() as preempted:
        loop = TrainLoop(step_fn, batches, args.steps,
                         hooks=hooks, should_stop=preempted)
        try:
            with mesh_ctx:
                state = loop.run(state)
        except FloatingPointError as e:
            # The guard fired before the poisoned state could be saved;
            # the newest snapshot on disk is the last healthy step.  No
            # digest: the local state reference was donated into the
            # loop (its buffers are gone), and a poisoned state has no
            # parity claim to attest anyway.
            print(f"faultline: {e}", file=sys.stderr, flush=True)
            emit("fault", error=str(e),
                 step=start_step + len(tape.tape))
            obs_ledger.end_global(rc=1,
                                  final_step=start_step + len(tape.tape))
            return 1
        # Post-exit faults: applied AFTER the final save — the torn
        # snapshot/journal shapes recovery must survive by falling back
        # (previous valid snapshot; journal replay skipping the tail).
        for spec in plan.post_exit_specs:
            if spec.step > int(state.step):
                continue
            if spec.kind == "torn_snapshot":
                torn = store.tear_latest()
                print(f"faultline: tore snapshot {torn} mid-file",
                      file=sys.stderr, flush=True)
            elif spec.kind in ("shard_loss", "bitflip"):
                if shard_store is None:
                    print(f"faultline: {spec.kind} needs the shard "
                          f"store (--layout zero3) — no-op",
                          file=sys.stderr, flush=True)
                elif spec.kind == "shard_loss":
                    hit = shard_store.drop_rank_dir(spec.rank or 0)
                    print(f"faultline: dropped mesh-shard "
                          f"{spec.rank or 0}'s whole directory from "
                          f"shard set {hit}", file=sys.stderr,
                          flush=True)
                else:
                    hit = shard_store.flip_payload_byte(spec.rank or 0)
                    step_hit, off = hit if hit else (None, None)
                    print(f"faultline: flipped payload byte {off} of "
                          f"mesh-shard {spec.rank or 0} in shard set "
                          f"{step_hit} (silent rot)", file=sys.stderr,
                          flush=True)
            elif spec.kind == "journal_torn":
                jp = os.environ.get("SUPERVISE_JOURNAL", "")
                if jp and tear_journal(jp):
                    print(f"faultline: tore journal {jp} mid-line",
                          file=sys.stderr, flush=True)
                else:
                    print("faultline: journal_torn had no journal to "
                          "tear (SUPERVISE_JOURNAL unset or empty) — "
                          "no-op", file=sys.stderr, flush=True)
        if preempted:
            obs_recorder.dump_global("preempted")
            emit("preempted", digest_state=state)
            obs_ledger.end_global(rc=143, final_step=int(state.step))
            return 143
    emit("ok", digest_state=state)
    obs_ledger.end_global(rc=0, final_step=int(state.step))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
