#!/usr/bin/env python
"""graftlint — two-front static analysis: repo-invariant AST rules +
compiled-HLO contract checks (PR 13; rule table in docs/DESIGN.md §20).

Source front (analysis/src_lint.py — stdlib-only, no jax import):
  stdlib-only     obs/ (+ tagged modules) never reach jax/numpy at
                  import time, proven on the whole import graph
  env-registry    every named os.environ read is declared + documented
                  in analysis/env_registry.py (env-dynamic: dynamic
                  reads must resolve; env-dead: no orphan entries)
  named-refusal   mode-legality refusals (messages naming a --flag)
                  raise refusal.ModeRefusal, not bare ValueError
  clock-seam      no bare time.time()/datetime.now() in obs/ outside
                  the obs/metrics.py _now/_wall seam
  keep-in-sync    paired KEEP-IN-SYNC digest markers agree with their
                  regions' current content
  engine-owns-wiring  raw step-wiring names (parallel/ step builders,
                  worker/opt-state re-layout ctors, shard_map) appear
                  only under engine/ and parallel/ — everywhere else
                  a workload is a RunSpec (allowlist in src_lint)

HLO front (analysis/hlo_lint.py — compiles the per-mode softmax suite
on a CPU mesh plus the serving decode step, then checks each module
against the contract declared next to its step builder in
parallel/{sync,bucketing,zero3}.py and serving/engine.py): zero3's
AG-before-RS prefetch with no step-closing AG, zero1's RS+AG pair,
per-mode collective budgets, donation aliasing (incl. the serving
KV-cache's donate-and-reuse step), dtype ceilings.

Findings flow through the checked-in waiver file
(analysis/waivers.json — dated + reasoned, budget 5, stale waivers are
findings).  Exit 0 = no unwaived findings; 1 = unwaived findings;
2 = internal error.

Usage:
  python -m tools.graftlint                 # both fronts, repo root
  python -m tools.graftlint --front src     # AST rules only (fast)
  python -m tools.graftlint --json - --md report.md
  python -m tools.graftlint --fix           # registry stubs + marker
                                            # digest re-stamp, then re-lint
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtensorflowexample_tpu.analysis import (  # noqa: E402
    Finding, apply_waivers, load_waivers, waivers_path)
from distributedtensorflowexample_tpu.analysis import src_lint  # noqa: E402


def _run_hlo_front(bucket_bytes: int) -> list[Finding]:
    """Compile-and-check on the CPU backend.  The pin must happen
    in-process before first backend use (this image's sitecustomize
    overrides JAX_PLATFORMS — the bytes_audit.py lesson) and is
    skipped when a caller already initialized a multi-device backend
    (the in-process tier-1 run under tests/conftest.py)."""
    import jax

    from distributedtensorflowexample_tpu.compat import set_num_cpu_devices
    try:
        jax.config.update("jax_platforms", "cpu")
        set_num_cpu_devices(8)
    except RuntimeError:
        pass    # backend already initialized — use it as configured
    from distributedtensorflowexample_tpu.analysis import hlo_lint
    return hlo_lint.run_hlo_lint(bucket_bytes=bucket_bytes)


def _render_md(unwaived, waived, stale, fixes) -> str:
    lines = ["# graftlint report", ""]
    if fixes:
        lines += ["## fixes applied", ""]
        lines += [f"- {d}" for d in fixes]
        lines.append("")

    def table(title, items):
        if not items:
            return
        lines.append(f"## {title} ({len(items)})")
        lines.append("")
        lines.append("| rule | where | message |")
        lines.append("|---|---|---|")
        for f in items:
            where = f"{f.path}:{f.line}" if f.line else f.path
            msg = f.message.replace("|", "\\|")
            lines.append(f"| {f.rule} | {where} | {msg} |")
        lines.append("")

    table("unwaived findings", unwaived)
    table("waived findings", waived)
    table("stale waivers", stale)
    if not (unwaived or waived or stale):
        lines.append("clean: no findings.")
    else:
        lines.append(f"verdict: {len(unwaived)} unwaived, "
                     f"{len(waived)} waived, {len(stale)} stale "
                     f"waiver(s).")
    lines.append("")
    return "\n".join(lines)


def _emit(text: str, dest: str) -> None:
    if dest == "-":
        sys.stdout.write(text)
    else:
        with open(dest, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {dest}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=_REPO,
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--package", default="distributedtensorflowexample_tpu")
    ap.add_argument("--front", choices=("src", "hlo", "all"),
                    default="all",
                    help="src = AST rules only (fast, no jax); hlo = "
                         "compile the mode suite and check contracts; "
                         "all = both (default)")
    ap.add_argument("--json", dest="json_out", default="", metavar="PATH",
                    help="write the JSON report here ('-' = stdout)")
    ap.add_argument("--md", dest="md_out", default="", metavar="PATH",
                    help="write the markdown report here ('-' = stdout; "
                         "default when no --json/--md given)")
    ap.add_argument("--fix", action="store_true",
                    help="apply the mechanical fixes (env-registry "
                         "stubs, keep-in-sync digest re-stamp), then "
                         "re-lint")
    ap.add_argument("--waivers", default="",
                    help="waiver file (default: "
                         "<root>/<package>/analysis/waivers.json)")
    ap.add_argument("--bucket_bytes", type=int, default=16 << 10,
                    help="bucket cap for the HLO mode suite (default "
                         "16 KiB: softmax splits into a real 2-bucket "
                         "ladder)")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    fixes: list[str] = []
    if args.fix:
        if args.front == "hlo":
            # --fix only mends source-front rules; mutating source
            # files under a front that will not re-lint them would
            # leave the "--fix output re-lints clean" contract
            # unverified (and edit files the user scoped out).
            print("graftlint: --fix applies to source rules only; "
                  "ignored under --front hlo", file=sys.stderr)
        else:
            fixes = src_lint.apply_fixes(root, args.package)

    findings: list[Finding] = []
    ran_rules: set[str] = {"waiver-invalid", "waiver-budget",
                           "waiver-stale"}
    if args.front in ("src", "all"):
        findings += src_lint.run_src_lint(root, args.package)
        ran_rules |= set(src_lint.SRC_RULES)
    if args.front in ("hlo", "all"):
        # _run_hlo_front pins the CPU backend BEFORE importing
        # hlo_lint (which pulls jax via utils/profiling) — keep this
        # ordering: the import must not precede the pin.
        findings += _run_hlo_front(args.bucket_bytes)
        from distributedtensorflowexample_tpu.analysis import hlo_lint
        ran_rules |= set(hlo_lint.HLO_RULES)

    wpath = args.waivers or waivers_path(root, args.package)
    waivers, waiver_findings = load_waivers(wpath)
    unwaived, waived, stale = apply_waivers(
        findings, waivers, ran_rules,
        waiver_file=os.path.relpath(wpath, root))
    unwaived += waiver_findings     # stale waivers gate too, rendered
                                    # as their own table below
    payload = {
        "ok": not (unwaived or stale),
        "front": args.front,
        "unwaived": [f.as_dict() for f in unwaived + stale],
        "waived": [f.as_dict() for f in waived],
        "fixes": fixes,
    }
    if args.json_out:
        _emit(json.dumps(payload, indent=1, sort_keys=True) + "\n",
              args.json_out)
    if args.md_out or not args.json_out:
        _emit(_render_md(unwaived, waived, stale, fixes),
              args.md_out or "-")
    return 0 if not (unwaived or stale) else 1


def _cli() -> int:
    """Exit-code contract: 0 clean, 1 unwaived findings, 2 internal
    error (a crash in the linter/compile suite must never read as
    'findings' to a CI gate)."""
    try:
        return main()
    except SystemExit:
        raise
    except Exception:
        import traceback
        traceback.print_exc()
        return 2


if __name__ == "__main__":
    sys.exit(_cli())
