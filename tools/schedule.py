#!/usr/bin/env python
"""schedule — the ledger-driven control plane: run a queue of
heterogeneous jobs (train / bench / faultline drill / serving load
tests) on one device mesh with elastic autoscaling and loss-free SLO
preemption (resilience/scheduler.py).

  # run a queue file (JSON list of job dicts; see resilience/scheduler.Job):
  python -m tools.schedule --queue jobs.json --workdir /tmp/sched --devices 4
  # the canned acceptance drill: an 8-job mixed queue over the forced
  # 4-device mesh — one injected rank loss (host_loss), one SLO
  # eviction, zero manual intervention:
  python -m tools.schedule --demo --workdir /tmp/sched
  # afterwards, ask the ledger why any job was preempted/shrunk/...:
  python tools/obs_query.py why <job> --ledger /tmp/sched/RUNS.jsonl

A job dict names what to run (`argv`, with ``{rank}``/``{num_ranks}``
substituted per rank), how wide (`ranks`), how urgent (`priority`, or
an SLO class via `kind` — serve=0 < train=10 < bench=20 < drill=30,
overridable with SCHED_SLO_PRIORITIES), and what it costs: `family`
points at a BENCH_trajectory.json bench family whose measured
steps/sec predicts the job's step time (fallback: `est_step_time_s`),
and the prediction prices admission and derives the per-attempt wall
deadline.  Each placement runs under the gang supervisor
(resilience/fleet.py) with the job's `snapshots` template, so
preemption is the TERM→143→snapshot protocol and a relaunch resumes
bitwise from the agreed step.

The scheduler is crash-tolerant: decisions are write-ahead journaled
(<workdir>/sched.jsonl) and a SIGKILLed scheduler resumes by rerunning
the SAME command — terminal decisions replay idempotently, orphaned
rank groups are swept, and unfinished jobs requeue.  Every decision is
also a ``sched_*`` row in <workdir>/RUNS.jsonl (obs/ledger.py) — the
query surface ``tools/obs_query.py why`` reads.

``--record PATH`` writes a queue-completion record (JSON lines, the
bench-record dialect) that tools/bench_ratchet.py folds into the
trajectory as the SCHED_queue family.

Exit codes: 0 every job done (refusals are operator errors, reported
but not fatal), 3 some job quarantined (backend wedged), 1 failures,
143 terminated (SIGTERM — rerun to resume).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtensorflowexample_tpu.obs import recorder as obs_recorder  # noqa: E402
from distributedtensorflowexample_tpu.resilience import scheduler as sched  # noqa: E402
from distributedtensorflowexample_tpu.resilience.supervisor import (  # noqa: E402
    Journal)

FAULTLINE = os.path.join(_REPO, "tools", "faultline.py")


def demo_queue(workdir: str, steps: int = 12,
               slow_s: float = 0.4) -> list[dict]:
    """The acceptance drill's 8-job mixed queue (all faultline jobs —
    CPU-measurable today, chip-exercisable at the next window):

    - 4 quick ``train`` jobs (t1..t4) filling the mesh in priority
      order;
    - ``elastic2`` — a 2-rank train job whose rank 1 HOST dies mid-run
      (``host_loss``): the gang tears down, the respawn fails like a
      dead host, the survivors continue elastically, and the recovery
      re-probe grows the gang back when the tombstone expires;
    - ``wedge1`` — exits rc 3 (backend wedged): quarantined, never
      requeued;
    - ``bench1`` — a slow bench job (persistent ``slow_rank`` delay =
      a real bench's pace) that a late-arriving
    - ``serve1`` — a REAL serving fleet (PR 15): 4 ranks of
      ``tools/serve_lm.py``, each promoting a snapshot and driving its
      closed loop (priority 0, ready once bench1 proves mid-run
      progress via its step-6 snapshot — late enough that elastic2's
      shrink/grow cycle has already run) EVICTS bench1:
      TERM→143→snapshot, then bench1 resumes with zero lost steps.
      An evicted serving rank drains its in-flight requests before its
      own 143 — the trainer protocol, re-read for serving.
    """
    py = sys.executable
    serve_lm = os.path.join(_REPO, "tools", "serve_lm.py")
    serve_dir = os.path.join(workdir, "jobs", "serve1", "rank{rank}")

    def fl(job, plan, job_steps=steps, ranks=1, **kw):
        base = {"job": job, "ranks": ranks,
                "argv": [py, FAULTLINE, "--plan", plan,
                         "--steps", str(job_steps),
                         "--workdir", os.path.join(workdir, "jobs", job,
                                                   "rank{rank}"),
                         "--keep", "20", "--seed", "0"],
                "snapshots": os.path.join(workdir, "jobs", job,
                                          "rank{rank}", "snapshots"),
                "steps": job_steps, "est_step_time_s": 0.5}
        base.update(kw)
        return base

    return [
        fl("t1", "none", 4, kind="train"),
        fl("t2", "none", 4, kind="train"),
        fl("t3", "none", 4, kind="train"),
        fl("t4", "none", 4, kind="train"),
        # rank 1's host dies at step 2 and answers again 2 s later —
        # the elastic shrink + grow-on-recovery path, end to end.  The
        # unpinned slow_rank paces BOTH ranks so the survivor is still
        # mid-run when the tombstone expires (otherwise sub-ms steps
        # finish the job shrunken before the host can come back).
        fl("elastic2", f"host_loss@2:2.0%1,slow_rank@1:{slow_s}", steps,
           ranks=2, kind="train", fleet_retries=4, elastic=True),
        {"job": "wedge1", "kind": "drill", "ranks": 1, "retries": 0,
         "argv": [py, "-c", "import sys; sys.exit(3)"],
         "est_step_time_s": 0.1, "steps": 1},
        # the victim: slow enough (slow_rank from step 1) that serve1's
        # arrival finds it mid-run; snapshots every step make the
        # eviction loss-free.
        fl("bench1", f"slow_rank@1:{slow_s}", steps, kind="bench"),
        # ready the moment bench1's step-6 snapshot commits (no
        # wall-clock guessing): a full-mesh, priority-0 REAL serving
        # fleet that cannot fit without evicting someone.
        {"job": "serve1", "kind": "serve", "ranks": 4,
         "argv": [py, serve_lm,
                  "--snapshot", os.path.join(serve_dir, "snaps"),
                  "--size", "lm_tiny", "--init_if_missing",
                  "--slots", "2", "--max_len", "32",
                  "--drive", "24", "--clients", "2",
                  "--drive_max_new", "6",
                  "--results", os.path.join(serve_dir, "results.jsonl"),
                  "--stats", os.path.join(serve_dir, "stats.json")],
         "steps": 24, "est_step_time_s": 1.0,
         "after_file": os.path.join(workdir, "jobs", "bench1", "rank0",
                                    "snapshots", "snap_00000006.npz")},
    ]


def write_record(path: str, summary: dict, devices: int) -> None:
    """Queue-completion record, bench-record dialect: one JSON line per
    metric so tools/bench_ratchet.py's load_records/trajectory builder
    reads it like any other family (SCHED_queue_*)."""
    detail = {"platform": "cpu", "devices": devices,
              "status": summary["status"], "counts": summary["counts"],
              "makespan_s": summary["makespan_s"],
              "evictions": summary["evictions"],
              "shrinks": summary["shrinks"], "grows": summary["grows"],
              "retries": summary["retries"], "jobs": summary["jobs"]}
    done = summary["counts"].get("done", 0)
    rows = [
        {"metric": "sched_queue_jobs_done", "value": done,
         "unit": "jobs", "platform": "cpu", "detail": detail},
        {"metric": "sched_queue_jobs_per_min",
         "value": (round(60.0 * done / summary["makespan_s"], 3)
                   if summary["makespan_s"] else 0.0),
         "unit": "jobs/min", "platform": "cpu", "detail": detail},
    ]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for row in rows:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    os.replace(tmp, path)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--queue", default="",
                   help="queue file: JSON list of job dicts (or "
                        "{'jobs': [...]}); default $SCHED_QUEUE")
    p.add_argument("--demo", action="store_true",
                   help="write + run the canned 8-job mixed acceptance "
                        "queue (faultline jobs: one host_loss rank "
                        "kill, one SLO eviction) instead of --queue")
    p.add_argument("--devices", type=int, default=4,
                   help="mesh capacity in devices (the forced 4-device "
                        "CPU mesh today; a real slice at the next "
                        "window)")
    p.add_argument("--workdir", default="/tmp/sched",
                   help="scheduler scratch: sched.jsonl journal, "
                        "RUNS.jsonl ledger, per-job fleet workdirs")
    p.add_argument("--tick_s", type=float, default=None,
                   help="policy-loop cadence (default $SCHED_TICK_S, "
                        f"else {sched.DEFAULT_TICK_S}s)")
    p.add_argument("--ledger", default="",
                   help="run-ledger path (default <workdir>/RUNS.jsonl; "
                        "'none' disables)")
    p.add_argument("--journal", default="",
                   help="scheduler write-ahead journal (default "
                        "<workdir>/sched.jsonl)")
    p.add_argument("--max_job_s", type=float, default=0.0,
                   help="refuse jobs whose predicted cost exceeds this "
                        "(0 = no ceiling)")
    p.add_argument("--cost_margin", type=float, default=16.0,
                   help="per-attempt wall deadline = margin x predicted "
                        "cost, when the job pins no wall_timeout_s")
    p.add_argument("--trajectory",
                   default=os.path.join(_REPO, "BENCH_trajectory.json"),
                   help="BENCH_trajectory.json for measured step-time "
                        "predictions ('' = declared estimates only)")
    p.add_argument("--record", default="",
                   help="write the queue-completion record (JSON lines, "
                        "SCHED_queue family) here")
    p.add_argument("--seed", type=int, default=0,
                   help="backoff-jitter seed (tests)")
    args = p.parse_args(argv)

    workdir = os.path.abspath(args.workdir)
    os.makedirs(workdir, exist_ok=True)
    if args.demo:
        queue_path = os.path.join(workdir, "demo_queue.json")
        with open(queue_path, "w") as f:
            json.dump({"jobs": demo_queue(workdir)}, f, indent=1)
        print(f"schedule: demo queue written to {queue_path}",
              file=sys.stderr, flush=True)
    else:
        queue_path = args.queue or sched.queue_path_default()
        if not queue_path:
            p.error("no queue: pass --queue FILE (or export "
                    "SCHED_QUEUE), or use --demo")
    jobs = sched.load_queue(queue_path)

    # Flight recorder for the scheduler itself (an operator's OBS_DIR
    # export wins), like the other long-running CLIs.
    os.environ.setdefault("OBS_DIR", os.path.join(workdir, "flight"))
    os.makedirs(os.environ["OBS_DIR"], exist_ok=True)
    obs_recorder.install(sigterm=False)

    s = sched.Scheduler(
        jobs, devices=args.devices, workdir=workdir,
        journal=Journal(args.journal
                        or os.path.join(workdir, "sched.jsonl")),
        ledger_path=("" if args.ledger == "none"
                     else args.ledger or None),
        tick_s=args.tick_s, seed=args.seed,
        cost_margin=args.cost_margin, max_job_s=args.max_job_s,
        trajectory_path=args.trajectory)
    summary = s.run()
    print(f"schedule: {summary['status']}: "
          + " ".join(f"{k}={v}" for k, v in summary["counts"].items()
                     if v)
          + f" makespan={summary['makespan_s']:.1f}s "
            f"evictions={summary['evictions']} "
            f"shrinks={summary['shrinks']} grows={summary['grows']} "
            f"retries={summary['retries']}",
          file=sys.stderr, flush=True)
    for jid, why in sorted(summary.get("why", {}).items()):
        if summary["jobs"][jid] in ("failed", "quarantined", "refused"):
            print(f"schedule:   {jid}: {summary['jobs'][jid]} — {why}",
                  file=sys.stderr, flush=True)
    if args.record and summary["status"] != "terminated":
        write_record(args.record, summary, args.devices)
        print(f"schedule: queue-completion record -> {args.record}",
              file=sys.stderr, flush=True)
    if summary["status"] == "terminated":
        return 143
    if summary["counts"].get("quarantined"):
        return 3
    if summary["counts"].get("failed"):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
