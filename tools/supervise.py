#!/usr/bin/env python
"""Run any entrypoint — or the whole on-chip capture sequence — under the
resilience supervisor (heartbeat watchdog, jittered backoff, bounded
retries, journaled resume).

Two modes:

  # one supervised command (trainer, bench, anything):
  python tools/supervise.py --retries 5 --heartbeat_timeout_s 600 \
      -- python -m distributedtensorflowexample_tpu.trainers.trainer_sync_mnist \
         --dataset synthetic --train_steps 5000
  # exit code mirrors the child's final verdict (0 ok, 3 wedged, else rc)

  # the 4-phase capture window (the supervised replacement for
  # tools/bench_capture.sh's inline bash phases — same artifact-value
  # order, same env knobs, same keep() semantics), journaled so a second
  # recovery window resumes exactly where the first died:
  python tools/supervise.py --capture

Capture mode honors bench_capture.sh's env surface (OUT, OUT_HEADLINE,
PROFILE_OUT, BYTES_OUT, COLLECTIVES_OUT, LM_OUT, TRACE_TGZ, CLI_OUT,
TRACE_DIR, LOG, CAPTURE_PIDFILE, BENCH_RETRY_BUDGET_S, BYTES_ARGS —
the graftlint keep-in-sync digest pins the two phase tables to each
other) and writes the SAME
pidfile, so tools/tpu_watch.sh's liveness/stale-kill machinery sees a
supervised capture exactly like a bash one.  The journal
(SUPERVISE_JOURNAL, default alongside the log) is what the bash path
never had: phases already recorded done are skipped on relaunch, and a
wedge verdict (rc=3) persists across supervisor restarts so chip-bound
phases stay skipped while the CPU-only bytes audit still lands.

Either mode: exporting OBS_PROM_DIR makes every completed task refresh
<OBS_PROM_DIR>/supervise.prom (node-exporter textfile-collector
dialect) with the live attempt/kill/heartbeat counters.  For N-process
gangs, see tools/supervise_fleet.py.
"""

from __future__ import annotations

import argparse
import atexit
import glob
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtensorflowexample_tpu.obs import recorder as obs_recorder  # noqa: E402
from distributedtensorflowexample_tpu.resilience.supervisor import (  # noqa: E402
    Journal, RetryPolicy, Supervisor, Task, TaskQueue)


def _write_pidfile(path: str) -> None:
    """bench_capture.sh's pidfile contract: the watcher reads it for
    liveness, and the EXIT cleanup removes it only if still ours."""
    with open(path, "w") as f:
        f.write(str(os.getpid()))

    def _cleanup():
        try:
            with open(path) as f:
                mine = f.read().strip() == str(os.getpid())
        except OSError:
            return
        if mine:
            os.remove(path)

    atexit.register(_cleanup)


def _capture_tasks(start_ts: float,
                   full_bench_done_prior: bool = False) -> list[Task]:
    # Mirrored in tools/bench_capture.sh (the flagged bash fallback):
    # phase set, artifact filenames, env knobs, gate strings.  Any
    # phase change must land in BOTH until the bash path is retired —
    # enforced by graftlint's keep-in-sync rule (the digest below
    # covers both regions; `python -m tools.graftlint --fix` re-stamps
    # after a deliberate re-sync).  tests/test_resilience.py::
    # test_supervise_capture_queue_shape pins this queue's shape.
    # KEEP-IN-SYNC(capture-phases) digest=1921cee5f541
    env = os.environ
    py = sys.executable
    log = env.get("LOG", "/tmp/bench_capture.log")
    out = env.get("OUT", "BENCH_auto_r05.json")
    out_headline = env.get("OUT_HEADLINE", "BENCH_headline_r05.json")
    profile_out = env.get("PROFILE_OUT", "PROFILE_auto_r05.json")
    bytes_out = env.get("BYTES_OUT", "BYTES_AUDIT_r05.json")
    collectives_out = env.get("COLLECTIVES_OUT", "BENCH_collectives_r06.json")
    lm_out = env.get("LM_OUT", "BENCH_lm_r08.json")
    trace_tgz = env.get("TRACE_TGZ", "resnet_trace_r05.tgz")
    cli_out = env.get("CLI_OUT", "CLI_r05.log")
    trace_dir = env.get("TRACE_DIR", "/tmp/resnet_trace")
    # Detached capture: the full retry budget is affordable here (the
    # 900-s default exists for the DRIVER's ~23-25-min kill window).
    retry_budget = env.get("BENCH_RETRY_BUDGET_S", "2400")
    bench_env = {"BENCH_RETRY_BUDGET_S": retry_budget}
    bytes_args = env.get("BYTES_ARGS",
                         "--batch_per_chip 256 --unroll 1").split()

    def tar_trace() -> None:
        if not os.path.isdir(trace_dir):
            return
        size_mb = sum(
            os.path.getsize(os.path.join(r, f))
            for r, _, fs in os.walk(trace_dir) for f in fs) // 2**20
        if size_mb <= 25:
            subprocess.run(["tar", "czf", trace_tgz,
                            "-C", os.path.dirname(trace_dir),
                            os.path.basename(trace_dir)], check=False)

    def keep_json(tmp: str, final: str):
        """keep() semantics for --json artifacts: promote a non-empty
        tmp, drop an empty one (a killed attempt never clobbers a
        previous window's artifact)."""
        def _keep() -> None:
            if os.path.exists(tmp):
                if os.path.getsize(tmp):
                    os.replace(tmp, final)
                else:
                    os.remove(tmp)
        return _keep

    keep_bytes_json = keep_json(bytes_out + ".tmp", bytes_out)
    keep_collectives_json = keep_json(collectives_out + ".tmp",
                                      collectives_out)
    keep_lm_json = keep_json(lm_out + ".tmp", lm_out)

    def fresh_measured() -> bool:
        """Phase-4 gate from bench_capture.sh: the trainer has no
        probe/watchdog layer, so it only runs once a full bench this
        CAPTURE produced a measured line (not a leftover file, not a
        sentinel).  'This capture' is the journal's notion, not this
        process's: on a resumed window full_bench is skipped as
        done_prior and OUT's mtime predates start_ts, yet it IS this
        capture's artifact — the journaled completion is exactly the
        provenance the bash mtime check could only approximate."""
        try:
            if (os.path.getmtime(out) < start_ts
                    and not full_bench_done_prior):
                return False
            with open(out) as f:
                return '"unit": "steps/sec/chip"' in f.read()
        except OSError:
            return False

    def rm_trace_dir() -> None:
        # A stale trace from an earlier run must not get tarred as THIS
        # window's artifact.
        subprocess.run(["rm", "-rf", trace_dir], check=False)

    return [
        # phase 1: the contract metric, fastest possible — a ~9-minute
        # window must convert the headline before anything else.
        Task("headline_bench", [py, "bench.py"], priority=10,
             stdout_path=out_headline, stderr_path=log,
             env={**bench_env, "BENCH_HEADLINE_ONLY": "1"}),
        # phase 2: ResNet attribution + trace (never yet landed on chip).
        Task("profile", [py, "bench_profile.py", "--trace_dir", trace_dir],
             priority=20, stdout_path=profile_out, stderr_path=log,
             pre=rm_trace_dir,
             env=bench_env, post=tar_trace),
        # phase 2b: CPU bytes table — needs_chip=False is what keeps it
        # alive through a wedge verdict (the one artifact a dead chip
        # can't block).
        Task("bytes_audit_cpu",
             [py, "tools/bytes_audit.py", "--backend", "cpu",
              "--workload", "resnet20", *bytes_args,
              "--json", bytes_out + ".tmp"],
             priority=25, needs_chip=False, stderr_path=log,
             post=keep_bytes_json),
        # phase 2c: collective latency/bandwidth curves + knee re-fit on
        # the live backend (bench_collectives.py --real).  Probes with
        # bench.py's env knobs and emits a sentinel record when the
        # backend is down, so the queue keeps moving; with the shell
        # profile's JAX_PLATFORMS=cpu export still in force the record
        # self-labels platform=cpu (never mistakable for chip curves).
        Task("collectives",
             [py, "bench_collectives.py", "--real",
              "--json", collectives_out + ".tmp"],
             priority=27, stderr_path=log,
             env=bench_env, post=keep_collectives_json),
        # phase 2d: the graft-LM family (bench_lm.py --real): tokens/sec
        # + MFU + the lm_base knob A/B matrix on the live backend.  Same
        # sentinel/platform-labeling discipline as 2c — probes with the
        # bench env knobs, emits a sentinel when the backend is down,
        # and under an exported JAX_PLATFORMS=cpu the record self-labels
        # platform=cpu so CPU numbers never read as chip numbers.
        Task("lm",
             [py, "bench_lm.py", "--real", "--json", lm_out + ".tmp"],
             priority=28, stderr_path=log,
             env=bench_env, post=keep_lm_json),
        # phase 3: the full six-workload record.
        Task("full_bench", [py, "bench.py"], priority=30, stdout_path=out,
             stderr_path=log, env=bench_env),
        # phase 4: out-of-box CLI throughput.  Unlike bash (which could
        # only refuse to start it), the supervisor bounds it: SIGTERM +
        # grace first — the trainer saves and exits 143 — KILL only as
        # the last resort.
        Task("cli_trainer",
             [py, "-m",
              "distributedtensorflowexample_tpu.trainers."
              "trainer_sync_mnist",
              "--dataset", "synthetic", "--train_steps", "5000",
              "--batch_size", "64", "--log_every", "1000",
              "--log_dir", "/tmp/cli_bench_r05", "--resume", "false"],
             priority=40, stdout_path=cli_out, stderr_path=log,
             wall_timeout_s=1800.0,
             gate=fresh_measured),
    ]
    # KEEP-IN-SYNC-END(capture-phases)


def _capture_ended(journal_path: str) -> bool:
    """True if the journal's capture RUN already ended (capture_end
    journaled) — the resume semantics exist for a supervisor that DIED
    mid-run, not for suppressing the next recovery window's capture."""
    try:
        with open(journal_path) as f:
            return any('"event": "capture_end"' in line for line in f)
    except OSError:
        return False


def run_capture(args) -> int:
    os.chdir(_REPO)
    pidfile = os.environ.get("CAPTURE_PIDFILE", "/tmp/bench_capture.pid")
    _write_pidfile(pidfile)
    journal_path = os.environ.get("SUPERVISE_JOURNAL",
                                  "/tmp/supervise_capture.jsonl")
    # Flight files (the supervisor's own + every phase child's) land in
    # one directory NEXT TO the journal: postmortems archived beside the
    # provenance record they cross-reference.  Children inherit OBS_DIR;
    # an operator export of OBS_DIR wins.
    obs_dir_preset = "OBS_DIR" in os.environ
    flight_dir = os.environ.setdefault(
        "OBS_DIR",
        os.path.splitext(journal_path)[0] + "_flight")
    if _capture_ended(journal_path):
        # Previous window's capture ran to its end (complete OR wedged
        # verdict): rotate it away so THIS edge captures fresh, like the
        # bash path always did — otherwise every later window replays
        # all phases as done_prior and the watcher's once-per-window
        # capture silently becomes a no-op.  The flight dir rotates WITH
        # the journal (only the default dir — an operator's OBS_DIR is
        # theirs to manage): stale postmortems must not be rendered, or
        # counted, as this window's, and PID reuse across windows could
        # even overwrite them.
        os.replace(journal_path, journal_path + ".prev")
        if not obs_dir_preset and os.path.isdir(flight_dir):
            shutil.rmtree(flight_dir + ".prev", ignore_errors=True)
            os.replace(flight_dir, flight_dir + ".prev")
        print(f"supervise: previous capture ended — journal rotated to "
              f"{journal_path}.prev (flight dir alongside)",
              file=sys.stderr, flush=True)
    os.makedirs(flight_dir, exist_ok=True)
    obs_recorder.install(sigterm=False)
    start_ts = time.time()
    journal = Journal(journal_path)
    sup = Supervisor(policy=RetryPolicy(retries=0),  # bench self-retries
                     journal=journal, kill_grace_s=30.0, seed=args.seed)
    prior_done = journal.replay()["done"]
    queue = TaskQueue(_capture_tasks(
        start_ts, full_bench_done_prior="full_bench" in prior_done), sup)
    results = queue.run()
    if "terminated" not in results.values():
        # A terminated run (watcher killed us) must NOT journal an end:
        # the next window resumes from the first unfinished phase.
        journal.write("capture_end", results=results)
    print(f"supervise: capture done: {results}", file=sys.stderr, flush=True)
    # The supervisor's own flight is written NOW (not left to atexit)
    # so the inventory line below counts every file the advertised
    # obs_report invocation will render.
    obs_recorder.dump_global("capture_end")
    flights = sorted(glob.glob(os.path.join(flight_dir, "flight_*.json")))
    print(f"supervise: {len(flights)} flight file(s) in {flight_dir} — "
          f"render with: python tools/obs_report.py --dir {flight_dir} "
          f"--journal {journal_path}", file=sys.stderr, flush=True)
    return 3 if "wedged" in results.values() else 0


def run_command(args, argv: list[str]) -> int:
    # The supervisor's own flight (attempt counters, heartbeat-age
    # gauge, escalation reason) — written on watchdog kills and exit.
    obs_recorder.install(sigterm=False)
    sup = Supervisor(
        policy=RetryPolicy(retries=args.retries,
                           backoff_base_s=args.backoff_base_s,
                           backoff_max_s=args.backoff_max_s),
        journal=Journal(args.journal),
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        wall_timeout_s=args.timeout_s,
        kill_grace_s=args.kill_grace_s,
        seed=args.seed)
    res = sup.run(argv, name=args.name, stdout_path=args.stdout,
                  heartbeat_path=args.heartbeat)
    if res.status == "ok":
        return 0
    if res.status == "terminated":
        # We were SIGTERM'd and forwarded it (child group killed with
        # grace): report 143 so a wrapper honoring the 0/143/3 protocol
        # sees a clean termination, not a crash to backoff-retry.
        return 143
    return res.returncode if res.returncode is not None else 1


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    child: list[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, child = argv[:split], argv[split + 1:]
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--capture", action="store_true",
                   help="run the journaled 4-phase capture queue "
                        "(bench_capture.sh's supervised replacement)")
    p.add_argument("--retries", type=int, default=3)
    p.add_argument("--backoff_base_s", type=float, default=1.0)
    p.add_argument("--backoff_max_s", type=float, default=60.0)
    p.add_argument("--timeout_s", type=float, default=0.0,
                   help="wall deadline per attempt (0 = none)")
    p.add_argument("--heartbeat_timeout_s", type=float, default=0.0,
                   help="kill when the heartbeat file goes stale this "
                        "long (0 = no heartbeat watchdog)")
    p.add_argument("--heartbeat", default="",
                   help="heartbeat file path (exported to the child as "
                        "SUPERVISE_HEARTBEAT; trainers touch it at step "
                        "boundaries)")
    p.add_argument("--kill_grace_s", type=float, default=10.0,
                   help="SIGTERM-to-SIGKILL grace (covers the child's "
                        "save-on-exit)")
    p.add_argument("--journal", default="", help="JSON-lines journal path")
    p.add_argument("--stdout", default="",
                   help="child stdout file (keep() semantics: an empty "
                        "attempt never clobbers a previous one)")
    p.add_argument("--name", default="", help="task name for the journal")
    p.add_argument("--seed", type=int, default=None,
                   help="backoff-jitter seed (tests)")
    args = p.parse_args(argv)
    args.journal = args.journal or None
    args.stdout = args.stdout or None
    args.heartbeat = args.heartbeat or None
    if args.heartbeat_timeout_s and not args.heartbeat:
        # The advertised one-liner passes only the timeout; without a
        # derived path the watchdog would silently arm against NOTHING
        # (no SUPERVISE_HEARTBEAT exported, no beats, no kills) — the
        # flagship protection reduced to a no-op.
        args.heartbeat = os.path.join(
            tempfile.gettempdir(), f"supervise_hb_{os.getpid()}")
        print(f"supervise: heartbeat file defaulted to {args.heartbeat}",
              file=sys.stderr, flush=True)

    if args.capture:
        return run_capture(args)
    if not child:
        p.error("nothing to run: pass --capture, or -- CMD ARGS...")
    return run_command(args, child)


if __name__ == "__main__":
    raise SystemExit(main())
