"""A/B the device augment's crop implementation on chip (VERDICT r4 #2/#3).

The round-5 trace (PROFILE_auto_r05.json window, /tmp/resnet_trace)
shows the vmap'd per-image ``dynamic_slice`` crop in
``augment_device.cifar_augment_device`` lowering to a SERIAL
256-iteration while loop (~4.4 ms/step of the ResNet-20 step's ~14.9),
and the per-channel LUT dequant gather costing another ~8.2 ms.  This
harness times the INPUT PATH ALONE (resident-split gather + augment +
dequant over a scanned window, no model) for crop/dequant variants:

  base      current code: vmap dynamic_slice crop + LUT-gather dequant
  selmm     selector-matmul crop+flip (one-hot row/col matrices, MXU)
            + LUT-gather dequant
  selmm_oh  selector-matmul crop + one-hot-matmul dequant (full MXU
            input path)
  noaug     gather + LUT dequant only (bounds what augment can save)

All selector/one-hot forms are exact pixel routing (single nonzero term
per output element), so a win here carries over bitwise.

Run detached, never under a harness timeout:
  setsid nohup python tools/ab_augment.py > AB_augment_r05.json 2>/tmp/ab_augment.log &
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPEATS = 3


def _emit(obj) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def selector_crop_flip(images, key):
    """cifar_augment_device's transform (same RNG draws, same reflect
    pad) with the per-image crop+flip expressed as two one-hot selector
    batched matmuls instead of vmap(dynamic_slice) — pure MXU work, no
    serial per-image loop.  Exact: every output pixel is 1.0 * one input
    pixel (uint8 values <= 255 are exact in bfloat16)."""
    import jax
    import jax.numpy as jnp

    from distributedtensorflowexample_tpu.data.augment_device import PAD

    b, h, w, c = images.shape
    ky, kx, kf = jax.random.split(key, 3)
    ys = jax.random.randint(ky, (b,), 0, 2 * PAD + 1)
    xs = jax.random.randint(kx, (b,), 0, 2 * PAD + 1)
    flips = jax.random.bernoulli(kf, 0.5, (b,))
    padded = jnp.pad(images, ((0, 0), (PAD, PAD), (PAD, PAD), (0, 0)),
                     mode="reflect")
    hp = h + 2 * PAD
    # R[b, r, hh] = (hh == ys[b] + r): picks output row r from padded
    # row ys[b]+r.
    rows = ys[:, None, None] + jnp.arange(h)[None, :, None]
    R = (jnp.arange(hp)[None, None, :] == rows).astype(jnp.bfloat16)
    # Cc[b, ww, k] = (ww == xs[b] + (flip ? w-1-k : k)): column pick and
    # horizontal flip folded into one selector.
    k = jnp.arange(w)[None, None, :]
    src = jnp.where(flips[:, None, None], w - 1 - k, k) + xs[:, None, None]
    Cc = (jnp.arange(hp)[None, :, None] == src).astype(jnp.bfloat16)
    x = padded.astype(jnp.bfloat16)
    out = jnp.einsum("brh,bhwc->brwc", R, x,
                     preferred_element_type=jnp.float32)
    out = jnp.einsum("brwc,bwk->brkc", out.astype(jnp.bfloat16), Cc,
                     preferred_element_type=jnp.float32)
    return out.astype(images.dtype)


def apply_dequant_onehot(u8, lut):
    import jax
    import jax.numpy as jnp
    oh = jax.nn.one_hot(u8, 256, dtype=jnp.bfloat16)
    if lut.ndim == 1:
        return jnp.einsum("...k,k->...", oh, lut.astype(jnp.float32),
                          preferred_element_type=jnp.float32)
    return jnp.einsum("...ck,kc->...c", oh, lut.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def make_input_only(variant: str, mesh, batch: int, unroll: int):
    """A jitted (step0, rng, data) -> f32 checksum running `unroll`
    gather+augment+dequant iterations, no model."""
    import jax
    import jax.numpy as jnp

    from distributedtensorflowexample_tpu.data import device_dataset as dd
    from distributedtensorflowexample_tpu.data.cifar10 import load_cifar10
    from distributedtensorflowexample_tpu.parallel import sync as psync

    train_x, train_y = load_cifar10("/tmp/data", "train", source="fallback")
    ds = dd.DeviceDataset(train_x, train_y, batch, mesh=mesh, seed=0,
                          steps_per_next=unroll)
    augment = "none" if variant == "noaug" else "cifar"
    gather = psync.make_device_gather(batch, ds.steps_per_epoch,
                                      augment=augment, mesh=mesh,
                                      num_slots=ds.num_slots)

    @jax.jit
    def run(rng, data):
        def body(carry, step):
            b = gather(step, rng, data)
            return carry + jnp.sum(b["image"][0, 0, 0].astype(
                jnp.float32)), None
        out, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(unroll))
        return out

    data = next(ds)
    rng = jax.random.PRNGKey(0)
    return functools.partial(run, rng, data)


def main() -> None:
    import jax

    from distributedtensorflowexample_tpu.parallel import make_mesh

    smoke = os.environ.get("AB_SMOKE") == "1"
    batch = 64 if smoke else 256
    unroll = 8 if smoke else 195

    from distributedtensorflowexample_tpu.data import augment_device
    from distributedtensorflowexample_tpu.data import device_dataset as dd

    orig_crop = augment_device.cifar_augment_device
    orig_lut = dd.apply_dequant_lut
    mesh = make_mesh()
    for variant in ("base", "selmm", "selmm_oh", "noaug"):
        # Patches must span build AND the first (tracing) call: the
        # gather resolves these module attrs at trace time.
        if variant in ("selmm", "selmm_oh"):
            augment_device.cifar_augment_device = selector_crop_flip
        if variant == "selmm_oh":
            dd.apply_dequant_lut = apply_dequant_onehot
        try:
            run = make_input_only(variant, mesh, batch, unroll)
            jax.block_until_ready(run())  # compile + warmup
            rates = []
            for _ in range(REPEATS):
                t0 = time.perf_counter()
                jax.block_until_ready(run())
                rates.append(unroll / (time.perf_counter() - t0))
            _emit({"metric": f"input_path_{variant}_steps_per_sec",
                   "value": round(max(rates), 2), "unit": "steps/sec",
                   "detail": {"repeats": [round(r, 1) for r in rates],
                              "batch": batch, "unroll": unroll}})
        except Exception as e:
            _emit({"metric": f"input_path_{variant}_steps_per_sec",
                   "value": 0.0, "unit": "error",
                   "detail": {"error": repr(e)}})
        finally:
            augment_device.cifar_augment_device = orig_crop
            dd.apply_dequant_lut = orig_lut


if __name__ == "__main__":
    main()
