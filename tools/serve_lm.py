#!/usr/bin/env python
"""serve_lm — the graft-LM serving worker: snapshot → continuous-
batching KV-cache decode under the standard supervision machinery.

  # serve a snapshot over HTTP until TERM (SERVE_PORT or --http):
  python tools/serve_lm.py --snapshot /tmp/lm_snaps --size lm_small --http 8811

  # self-contained demo: init a snapshot if absent, drive 32 requests
  # through the in-process closed loop, write stats, exit 0:
  python tools/serve_lm.py --snapshot /tmp/lm_snaps --init_if_missing \\
      --drive 32 --stats /tmp/serve_stats.json

The worker speaks every operational protocol the training entrypoints
speak, so the fleet/scheduler machinery supervises it unchanged:

- **TERM → drain → 143**: SIGTERM stops admission, decodes every
  in-flight request to completion, rejects the queued tail loudly
  (outcome ``drained``), writes stats, exits 143 — the trainer's
  loss-free preemption protocol with "state saved" re-read as "every
  admitted request answered".  An evicted serving worker relaunches and
  (in --drive mode) re-issues exactly the unfinished request ids from
  its results tape.
- **heartbeat**: touches ``SUPERVISE_HEARTBEAT`` every loop boundary
  (busy or idle), so the supervisor watchdog can tell a wedged decode
  dispatch from a quiet queue.
- **obs**: flight recorder (``OBS_FLIGHT``), run ledger rows
  (``OBS_LEDGER``: run_start with the resolved config + promoted
  snapshot step, bounded samples, run_end with rc), live scrape
  (``OBS_HTTP_PORT`` — /metrics carries the serve_* series: p50/p99
  gauges, queue depth, slot occupancy, tokens/steps counters).

Default backend is a pinned CPU (the drill/test posture — a serving
smoke must never wedge on a dead tunnel); ``--real`` serves on the
configured backend at a chip window.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RC_PREEMPTED = 143


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--snapshot", default="",
                   help="SnapshotStore directory to promote (default "
                        "$SERVE_SNAPSHOT)")
    p.add_argument("--size", default="lm_tiny",
                   help="graft-LM size the snapshot holds (LM_SIZES)")
    p.add_argument("--slots", type=int, default=0,
                   help="concurrent decode slots (default $SERVE_SLOTS "
                        "or 4)")
    p.add_argument("--slo_ms", type=float, default=-1.0,
                   help="end-to-end latency SLO driving admission "
                        "(default $SERVE_SLO_MS; 0 = admit everything)")
    p.add_argument("--max_len", type=int, default=64,
                   help="KV-cache rows per slot (prompt + generated)")
    p.add_argument("--http", type=int, default=-1,
                   help="request-front port (default $SERVE_PORT; 0 = "
                        "in-process only)")
    p.add_argument("--init_if_missing", action="store_true",
                   help="write a demo-grade (untrained, seeded) snapshot "
                        "when the store holds no valid one")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--real", action="store_true",
                   help="serve on the configured backend (default pins "
                        "the CPU platform in-process)")
    p.add_argument("--sharded_mesh", type=int, default=0,
                   help="params-stay-sharded decode over a D-device "
                        "mesh (serving/sharded.py): params stay zero3 "
                        "bucket rows at 1/D, gathered per block inside "
                        "the compiled step (0 = replicated engine; on "
                        "CPU without --real this forces D host devices)")
    p.add_argument("--spec_draft", default="",
                   help="speculative decoding: LM_SIZES size that "
                        "DRAFTS (e.g. lm_tiny); the served model "
                        "verifies — output stays bitwise greedy")
    p.add_argument("--spec_draft_snapshot", default="",
                   help="snapshot dir for the draft model (default: "
                        "the served --snapshot dir)")
    p.add_argument("--spec_k", type=int, default=4,
                   help="draft window: tokens drafted per verify round")
    p.add_argument("--sample_temp", type=float, default=0.0,
                   help="sampling temperature (0 = greedy decode; "
                        "sampled tokens draw on per-request RNG lanes, "
                        "deterministic per request id)")
    p.add_argument("--sample_top_k", type=int, default=0,
                   help="restrict sampling to the k most likely tokens "
                        "(0 = full softmax; arms the sampler even at "
                        "default temperature)")
    p.add_argument("--sample_seed", type=int, default=0,
                   help="worker-level seed the per-request RNG lanes "
                        "derive from")
    p.add_argument("--prefix_cache", type=int, default=0,
                   help="share K/V rows across requests with equal "
                        "prompt prefixes (value = resident prompt "
                        "capacity; 0 = off)")
    # The in-process closed-loop drive (demo / drills / bench).
    p.add_argument("--drive", type=int, default=0,
                   help="drive N deterministic requests through the "
                        "in-process closed loop, then exit 0 (0 = serve "
                        "until TERM)")
    p.add_argument("--clients", type=int, default=0,
                   help="closed-loop client threads for --drive "
                        "(default $SERVE_LOAD_CLIENTS or 2)")
    p.add_argument("--drive_max_new", type=int, default=8,
                   help="generated tokens per driven request")
    p.add_argument("--drive_think_ms", type=float, default=0.0,
                   help="closed-loop client think time between "
                        "completions (holds offered load below "
                        "saturation)")
    p.add_argument("--results", default="",
                   help="--drive completion tape (JSONL; re-issues only "
                        "unfinished ids on relaunch)")
    p.add_argument("--stats", default="",
                   help="write the final stats JSON here")
    p.add_argument("--ready_file", default="",
                   help="touch this path once the worker is serving")
    args = p.parse_args(argv)

    if args.sharded_mesh > 1 and not args.real:
        # The pinned-CPU posture needs a mesh to shard over; forcing
        # host devices must happen before the first jax import.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.sharded_mesh}").strip()

    import jax

    from distributedtensorflowexample_tpu.compat import (
        enable_persistent_compilation_cache)
    if not args.real:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass    # backend already initialized — use it as configured
    # Serving restarts are the POINT (eviction → relaunch), so the
    # compile cache matters operationally, not just in tests: a
    # relaunched worker re-serves in milliseconds instead of repaying
    # the decode/prefill compiles.  Version-gated through compat.
    enable_persistent_compilation_cache(
        os.environ.get("DISTTF_JAX_CACHE", "/tmp/jax_cache_serve"))

    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    from distributedtensorflowexample_tpu.obs import (
        recorder as obs_recorder)
    from distributedtensorflowexample_tpu.obs import serve as obs_serve
    from distributedtensorflowexample_tpu.serving.engine import (
        DecodeEngine, serve_slots_default)
    from distributedtensorflowexample_tpu.serving.frontend import (
        RequestFront, serve_port_default)
    from distributedtensorflowexample_tpu.serving.loadgen import (
        ClosedLoopLoadGen, DriveFile, load_clients_default)
    from distributedtensorflowexample_tpu.serving.promote import (
        init_lm_snapshot, promote, serve_snapshot_default)
    from distributedtensorflowexample_tpu.serving.queue import (
        ContinuousBatcher, RequestQueue, serve_slo_ms_default)
    from distributedtensorflowexample_tpu.training.hooks import (
        touch_heartbeat)
    from distributedtensorflowexample_tpu.utils.signals import (
        sigterm_flag)

    snapshot = args.snapshot or serve_snapshot_default()
    if not snapshot:
        p.error("--snapshot (or SERVE_SNAPSHOT) is required")
    slots = args.slots or serve_slots_default()
    slo_ms = serve_slo_ms_default() if args.slo_ms < 0 else args.slo_ms
    port = serve_port_default() if args.http < 0 else args.http

    rec = obs_recorder.maybe_install()
    if rec is not None:
        rec.note(tool="serve_lm", snapshot=snapshot, size=args.size,
                 slots=slots, slo_ms=slo_ms)
    obs_ledger.maybe_begin(
        "serve_lm", config={"snapshot": snapshot, "size": args.size,
                            "slots": slots, "slo_ms": slo_ms,
                            "max_len": args.max_len, "drive": args.drive,
                            "seed": args.seed,
                            "sharded_mesh": args.sharded_mesh,
                            "spec_draft": args.spec_draft,
                            "spec_k": args.spec_k,
                            "sample_temp": args.sample_temp,
                            "sample_top_k": args.sample_top_k,
                            "prefix_cache": args.prefix_cache})
    obs_serve.maybe_start()
    ledger = obs_ledger.get()

    if args.init_if_missing:
        from distributedtensorflowexample_tpu.resilience.snapshot import (
            SnapshotStore)
        if SnapshotStore(snapshot).latest_valid() is None:
            init_lm_snapshot(snapshot, args.size, seed=args.seed)
            print(f"serve_lm: initialized demo snapshot in {snapshot}",
                  file=sys.stderr, flush=True)

    t0 = time.monotonic()
    from distributedtensorflowexample_tpu.refusal import ModeRefusal
    try:
        if args.sharded_mesh > 0:
            from distributedtensorflowexample_tpu.serving.promote import (
                promote_sharded)
            from distributedtensorflowexample_tpu.serving.sharded import (
                ShardedDecodeEngine)
            pm = promote_sharded(snapshot, args.size,
                                 mesh_size=args.sharded_mesh)
            engine = ShardedDecodeEngine(pm.model, pm.rows, pm.layout,
                                         slots=slots,
                                         cache_len=args.max_len)
            snap_layout = pm.source_layout
            mode_desc = f", sharded D={pm.layout.num_devices} (params " \
                        f"resident at 1/{pm.layout.num_devices})"
        else:
            pm = promote(snapshot, args.size)
            engine = DecodeEngine(pm.model, pm.params, slots=slots,
                                  cache_len=args.max_len)
            snap_layout = pm.layout
            mode_desc = ""
        spec = sampler = prefix = None
        if args.spec_draft:
            from distributedtensorflowexample_tpu.serving.spec import (
                SpecDecoder)
            dsnap = args.spec_draft_snapshot or snapshot
            if args.init_if_missing and dsnap != snapshot:
                from distributedtensorflowexample_tpu.resilience. \
                    snapshot import SnapshotStore
                if SnapshotStore(dsnap).latest_valid() is None:
                    init_lm_snapshot(dsnap, args.spec_draft,
                                     seed=args.seed)
            dpm = promote(dsnap, args.spec_draft)
            draft_engine = DecodeEngine(dpm.model, dpm.params,
                                        slots=slots,
                                        cache_len=args.max_len)
            spec = SpecDecoder(engine, draft_engine, k=args.spec_k)
            mode_desc += (f", spec k={args.spec_k} (draft "
                          f"{args.spec_draft} step {dpm.step})")
        if args.sample_temp > 0 or args.sample_top_k > 0:
            from distributedtensorflowexample_tpu.serving.sampling \
                import Sampler
            sampler = Sampler(
                temperature=(args.sample_temp if args.sample_temp > 0
                             else 1.0),
                top_k=args.sample_top_k, seed=args.sample_seed)
            mode_desc += f", sampler {sampler.describe()}"
        if args.prefix_cache > 0:
            from distributedtensorflowexample_tpu.serving.prefix import (
                PrefixCache)
            prefix = PrefixCache(engine, capacity=args.prefix_cache)
            mode_desc += f", prefix cache {args.prefix_cache}"
        queue = RequestQueue(engine.vocab)
        hb_path = os.environ.get("SUPERVISE_HEARTBEAT", "")

        def on_step(batcher) -> None:
            # Heartbeat lives in should_stop below (every loop
            # boundary, busy AND idle) — not here too: at ~0.2 ms/step
            # a second touch per decode step would be thousands of
            # redundant open+utime syscalls a second on the hot loop.
            if ledger is not None:
                ledger.sample(step=engine.decode_steps)

        batcher = ContinuousBatcher(engine, queue, slo_ms=slo_ms,
                                    on_step=on_step, spec=spec,
                                    sampler=sampler,
                                    prefix_cache=prefix)
    except ModeRefusal as e:
        # Impossible flag combinations are refused BY NAME before any
        # request could be admitted into them — exit 2, argparse's own
        # bad-usage code, so the supervisor never retries a config
        # that can only refuse again.
        print(f"serve_lm: refused: {e}", file=sys.stderr, flush=True)
        obs_ledger.end_global(rc=2, errors={"refused": str(e)})
        return 2
    front = RequestFront(queue, batcher, port).start() if port else None
    print(f"serve_lm: serving {args.size} snapshot step {pm.step} "
          f"({snap_layout}) — {slots} slot(s), cache {args.max_len} "
          f"rows/slot ({engine.cache_bytes >> 10} KiB), SLO "
          f"{slo_ms or 'off'} ms, load time "
          f"{time.monotonic() - t0:.2f}s" + mode_desc
          + (f", HTTP :{front.port}" if front else ""),
          file=sys.stderr, flush=True)
    if args.ready_file:
        touch_heartbeat(args.ready_file)

    drive_done = threading.Event()
    gen = None
    gen_summary: dict = {}
    if args.drive > 0:
        gen = ClosedLoopLoadGen(
            queue, total=args.drive,
            clients=args.clients or load_clients_default(),
            max_new=args.drive_max_new, vocab=engine.vocab,
            seed=args.seed, think_ms=args.drive_think_ms,
            drive_file=DriveFile(args.results) if args.results
            else None)

        def _drive():
            gen_summary.update(gen.run())
            drive_done.set()

        threading.Thread(target=_drive, daemon=True,
                         name="serve-drive").start()

    with sigterm_flag() as term:
        last_beat = [0.0]

        def should_stop() -> bool:
            if hb_path:
                # Beat on idle boundaries too (a quiet queue is
                # healthy; a silent worker is indistinguishable from a
                # wedged dispatch) — but rate-limited: at ~0.2 ms/step
                # an every-boundary touch is thousands of open+utime
                # syscalls a second on the hot loop, and the watchdog
                # only needs seconds-scale freshness.
                now = time.monotonic()
                if now - last_beat[0] >= 0.5:
                    last_beat[0] = now
                    touch_heartbeat(hb_path)
            return bool(term) or drive_done.is_set()

        batcher.run(should_stop=should_stop)
        preempted = bool(term)
    if gen is not None:
        gen.stop.set()
        drive_done.wait(timeout=30)

    if front is not None:
        front.stop()
    stats = batcher.stats()
    stats.update(snapshot_step=pm.step, snapshot_layout=snap_layout,
                 size=args.size, preempted=preempted,
                 drive=gen_summary or None,
                 platform=jax.default_backend())
    if hasattr(engine, "params_residency"):
        stats["params_residency"] = engine.params_residency()
    if args.stats:
        tmp = args.stats + ".tmp"
        with open(tmp, "w") as f:
            json.dump(stats, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.stats)
    print(json.dumps(stats, sort_keys=True), flush=True)
    rc = RC_PREEMPTED if preempted else 0
    obs_ledger.end_global(rc=rc, final_step=engine.decode_steps)
    if preempted:
        print(f"serve_lm: TERM — drained {stats['completed']} "
              f"completed request(s), rejected tail "
              f"{stats['rejected']['drained']}; exit {rc}",
              file=sys.stderr, flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
