#!/usr/bin/env python
"""obs_query — cross-run queries over the run ledger and the bench
record families: list runs, diff two runs, render metric trajectories.

  # what ran (and how it ended), newest last:
  python tools/obs_query.py list --ledger /tmp/fleet/RUNS.jsonl
  # only trainer runs that crashed:
  python tools/obs_query.py list --ledger RUNS.jsonl \
      --entrypoint trainer --outcome rc
  # everything the ledger knows about one run (start/samples/end):
  python tools/obs_query.py show --ledger RUNS.jsonl 19fc2-1234
  # config + metric deltas between two runs (id prefixes resolve):
  python tools/obs_query.py diff --ledger RUNS.jsonl 19fc2 19fd8
  # the bench trajectory, per family per round:
  python tools/obs_query.py trajectory --format md

Rows come from ``obs/ledger.py``'s RUNS.jsonl (``OBS_LEDGER``; the
fleet supervisor writes <workdir>/RUNS.jsonl by default): ``run_start``
/ ``sample`` / ``run_end`` per run plus the fleet's gang rows and
``resume_agreement`` annotations.  ``diff`` answers the question the
pile of per-run files never could — "these two runs differ HOW": the
config keys that changed (run_start carries the resolved config), the
final-counter deltas (run_end carries cumulative counters), loss-tail
digests (same trajectory or not), outcome and anomaly flags.
``trajectory`` pivots the ``BENCH_*``/``SCALING_*``/``BASELINE_SELF``
records through tools/bench_ratchet.py's builder — the same rows the
checked-in ``BENCH_trajectory.json`` artifact holds.

Stdlib-only and read-only (like obs_report): safe mid-outage, and
``--format json`` makes every view machine-consumable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _p in (_REPO, os.path.dirname(os.path.abspath(__file__))):
    if _p not in sys.path:
        sys.path.insert(0, _p)

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger  # noqa: E402
from obs_report import _table as _table_lines  # noqa: E402  (tools/)


def _table(headers: list[str], rows: list[list]) -> str:
    """obs_report's markdown table builder, joined, with Nones blanked
    — ONE table dialect across the two query/report CLIs."""
    return "\n".join(_table_lines(
        headers, [["" if c is None else c for c in row] for row in rows]))


def _emit(payload, md: str, fmt: str) -> None:
    if fmt == "json":
        json.dump(payload, sys.stdout, indent=1, default=str)
        print()
    else:
        print(md)


# --- list ------------------------------------------------------------------

def cmd_list(args) -> int:
    folded = obs_ledger.runs(args.ledger)
    table = obs_ledger.run_table(args.ledger, folded=folded)
    if args.entrypoint:
        table = [r for r in table
                 if args.entrypoint in str(r.get("entrypoint") or "")]
    if args.outcome:
        table = [r for r in table
                 if args.outcome in str(r.get("outcome") or "")]
    agreements = [e for e in folded["events"]
                  if e.get("event") == "resume_agreement"]
    md_rows = [[r["run"], r["entrypoint"], r["rank"], r["attempt"],
                r["outcome"], r["final_step"], r["samples"],
                r["anomalies"] or "",
                "" if r["duration_s"] is None else f"{r['duration_s']:g}"]
               for r in table]
    md = [f"# Runs — `{os.path.basename(args.ledger)}` "
          f"({len(table)} run(s)"
          + (f", {folded['torn']} torn line(s) skipped"
             if folded["torn"] else "") + ")", "",
          _table(["run", "entrypoint", "rank", "att", "outcome", "step",
                  "samples", "anom", "dur_s"], md_rows)]
    if agreements:
        md += ["", "## Resume agreements", ""]
        md += [f"- agreed step **{a.get('agreed')}** "
               f"(task {a.get('task')}): per-rank "
               f"{a.get('per_rank')}, discarded {a.get('discarded')}"
               for a in agreements]
    _emit({"runs": table, "agreements": agreements,
           "torn": folded["torn"]}, "\n".join(md), args.format)
    return 0


# --- show ------------------------------------------------------------------

def _resolve_run(folded: dict, token: str) -> str:
    """Exact id or unique prefix — eight hex chars beat pasting the
    whole id into a terminal."""
    if token in folded["runs"]:
        return token
    matches = [r for r in folded["order"] if r.startswith(token)]
    if len(matches) == 1:
        return matches[0]
    raise SystemExit(
        f"obs_query: run {token!r} "
        + ("is ambiguous: " + ", ".join(matches) if matches
           else "not found — `obs_query list` shows the ids"))


def cmd_show(args) -> int:
    folded = obs_ledger.runs(args.ledger)
    run_id = _resolve_run(folded, args.run)
    group = folded["runs"][run_id]
    md = [f"# Run `{run_id}`", ""]
    for name, row in (("run_start", group["start"]),
                      ("run_end", group["end"])):
        if row:
            md += [f"## {name}", "", "```json",
                   json.dumps(row, indent=1, sort_keys=True), "```", ""]
    if group["samples"]:
        md += [f"## samples ({len(group['samples'])})", ""]
        rows = [[s.get("step"),
                 (s.get("delta") or {}).get("span_s"),
                 json.dumps((s.get("delta") or {}).get("counters") or {},
                            sort_keys=True)]
                for s in group["samples"]]
        md += [_table(["step", "span_s", "counter deltas"], rows)]
    _emit(group, "\n".join(md), args.format)
    return 0


# --- diff ------------------------------------------------------------------

def diff_runs(folded: dict, id_a: str, id_b: str) -> dict:
    a, b = folded["runs"][id_a], folded["runs"][id_b]

    def cfg(g):
        return ((g["start"] or {}).get("config") or {})

    keys = sorted(set(cfg(a)) | set(cfg(b)))
    config_diff = {k: {"a": cfg(a).get(k), "b": cfg(b).get(k)}
                   for k in keys if cfg(a).get(k) != cfg(b).get(k)}

    def counters(g):
        return ((g["end"] or {}).get("counters") or {})

    ckeys = sorted(set(counters(a)) | set(counters(b)))
    metric_delta = {}
    for k in ckeys:
        va, vb = counters(a).get(k), counters(b).get(k)
        if va != vb:
            metric_delta[k] = {
                "a": va, "b": vb,
                "delta": (None if not isinstance(va, (int, float))
                          or not isinstance(vb, (int, float))
                          else round(vb - va, 6))}

    def end_field(g, f):
        return (g["end"] or {}).get(f)

    tails = {which: end_field(g, "loss_tail")
             for which, g in (("a", a), ("b", b))}
    return {
        "a": {"run": id_a, **{f: (a["start"] or {}).get(f)
                              for f in ("entrypoint", "config_digest",
                                        "rank", "attempt")}},
        "b": {"run": id_b, **{f: (b["start"] or {}).get(f)
                              for f in ("entrypoint", "config_digest",
                                        "rank", "attempt")}},
        "config_diff": config_diff,
        "outcome": {"a": {"rc": end_field(a, "rc"),
                          "final_step": end_field(a, "final_step")},
                    "b": {"rc": end_field(b, "rc"),
                          "final_step": end_field(b, "final_step")}},
        "loss_tail": {**tails,
                      "same_trajectory": (
                          None if not tails["a"] or not tails["b"]
                          else tails["a"].get("sha256")
                          == tails["b"].get("sha256"))},
        "anomaly_flags": {"a": end_field(a, "anomaly_flags"),
                          "b": end_field(b, "anomaly_flags")},
        "counter_deltas": metric_delta}


def cmd_diff(args) -> int:
    folded = obs_ledger.runs(args.ledger)
    id_a = _resolve_run(folded, args.run_a)
    id_b = _resolve_run(folded, args.run_b)
    d = diff_runs(folded, id_a, id_b)
    md = [f"# Run diff — `{id_a}` (a) vs `{id_b}` (b)", "",
          f"- **a**: {d['a']['entrypoint']} "
          f"(config {d['a']['config_digest']}, rank {d['a']['rank']}, "
          f"attempt {d['a']['attempt']}) → rc={d['outcome']['a']['rc']} "
          f"@ step {d['outcome']['a']['final_step']}",
          f"- **b**: {d['b']['entrypoint']} "
          f"(config {d['b']['config_digest']}, rank {d['b']['rank']}, "
          f"attempt {d['b']['attempt']}) → rc={d['outcome']['b']['rc']} "
          f"@ step {d['outcome']['b']['final_step']}"]
    same = d["loss_tail"]["same_trajectory"]
    if same is not None:
        md.append(f"- **loss trajectory**: "
                  + ("IDENTICAL (tail digests match)" if same
                     else "differs (tail digests disagree)"))
    md += ["", "## Config diff", ""]
    if d["config_diff"]:
        md.append(_table(["key", "a", "b"],
                         [[k, v["a"], v["b"]]
                          for k, v in sorted(d["config_diff"].items())]))
    else:
        md.append("- identical resolved configs "
                  f"(digest {d['a']['config_digest']})")
    md += ["", "## Counter deltas (b - a)", ""]
    if d["counter_deltas"]:
        md.append(_table(
            ["counter", "a", "b", "delta"],
            [[f"`{k}`", v["a"], v["b"], v["delta"]]
             for k, v in sorted(d["counter_deltas"].items())]))
    else:
        md.append("- no counter differences")
    _emit(d, "\n".join(md), args.format)
    return 0


# --- trajectory ------------------------------------------------------------

def cmd_trajectory(args) -> int:
    import bench_ratchet
    rows = bench_ratchet.build_trajectory(args.records_dir)
    if args.family:
        rows = [r for r in rows if args.family in r["family"]]
    md = [f"# Bench trajectory — {len(rows)} family-round row(s)", ""]
    for row in rows:
        rnd = "—" if row["round"] is None else f"r{row['round']:02d}"
        md += [f"## {row['family']} {rnd} (`{row['file']}`, "
               f"{'/'.join(row['platforms'])})", "",
               _table(["metric", "value"],
                      [[f"`{k}`", v]
                       for k, v in sorted(row["metrics"].items())]), ""]
    _emit(rows, "\n".join(md), args.format)
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    def add_common(sp, ledger: bool = True):
        sp.add_argument("--format", default="md", choices=["md", "json"])
        if ledger:
            # `or`: a present-but-EMPTY export means "ledger disabled"
            # everywhere else (fleet, maybe_begin) — fall through to
            # the ./RUNS.jsonl default the help text promises.
            sp.add_argument("--ledger", default=os.environ.get(
                "OBS_LEDGER") or "RUNS.jsonl",
                help="RUNS.jsonl path (default: $OBS_LEDGER, else "
                     "./RUNS.jsonl)")

    sp = sub.add_parser("list", help="run table + agreements")
    add_common(sp)
    sp.add_argument("--entrypoint", default="",
                    help="substring filter on the entrypoint")
    sp.add_argument("--outcome", default="",
                    help="substring filter on the outcome "
                         "(ok/preempted/rc=.../running)")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("show", help="one run's rows in full")
    add_common(sp)
    sp.add_argument("run", help="run id (or unique prefix)")
    sp.set_defaults(fn=cmd_show)

    sp = sub.add_parser("diff", help="config + metric deltas between "
                                     "two runs")
    add_common(sp)
    sp.add_argument("run_a")
    sp.add_argument("run_b")
    sp.set_defaults(fn=cmd_diff)

    sp = sub.add_parser("trajectory", help="per-family per-round bench "
                                           "metric trajectories")
    add_common(sp, ledger=False)
    sp.add_argument("--records_dir", default=_REPO)
    sp.add_argument("--family", default="",
                    help="substring filter on the family")
    sp.set_defaults(fn=cmd_trajectory)

    args = p.parse_args(argv)
    if getattr(args, "ledger", None) is not None \
            and args.cmd != "trajectory" \
            and not os.path.exists(args.ledger) \
            and not os.path.exists(args.ledger + ".1"):
        p.error(f"ledger {args.ledger} does not exist (pass --ledger or "
                f"export OBS_LEDGER)")
    return args.fn(args)


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `obs_query list | head` closing the pipe early is a normal
        # way to read a long table, not an error worth a traceback.
        os._exit(0)
