#!/usr/bin/env python
"""obs_query — cross-run queries over the run ledger and the bench
record families: list runs, diff two runs, render metric trajectories.

  # what ran (and how it ended), newest last:
  python tools/obs_query.py list --ledger /tmp/fleet/RUNS.jsonl
  # only trainer runs that crashed:
  python tools/obs_query.py list --ledger RUNS.jsonl \
      --entrypoint trainer --outcome rc
  # everything the ledger knows about one run (start/samples/end):
  python tools/obs_query.py show --ledger RUNS.jsonl 19fc2-1234
  # config + metric deltas between two runs (id prefixes resolve):
  python tools/obs_query.py diff --ledger RUNS.jsonl 19fc2 19fd8
  # why did the scheduler preempt/shrink/quarantine this job
  # (tools/schedule.py's sched_* decision rows, ledger-only):
  python tools/obs_query.py why bench1 --ledger /tmp/sched/RUNS.jsonl
  # the bench trajectory, per family per round:
  python tools/obs_query.py trajectory --format md

Rows come from ``obs/ledger.py``'s RUNS.jsonl (``OBS_LEDGER``; the
fleet supervisor writes <workdir>/RUNS.jsonl by default): ``run_start``
/ ``sample`` / ``run_end`` per run plus the fleet's gang rows and
``resume_agreement`` annotations.  ``diff`` answers the question the
pile of per-run files never could — "these two runs differ HOW": the
config keys that changed (run_start carries the resolved config), the
final-counter deltas (run_end carries cumulative counters), loss-tail
digests (same trajectory or not), outcome and anomaly flags.
``trajectory`` pivots the ``BENCH_*``/``SCALING_*``/``BASELINE_SELF``
records through tools/bench_ratchet.py's builder — the same rows the
checked-in ``BENCH_trajectory.json`` artifact holds.

Stdlib-only and read-only (like obs_report): safe mid-outage, and
``--format json`` makes every view machine-consumable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _p in (_REPO, os.path.dirname(os.path.abspath(__file__))):
    if _p not in sys.path:
        sys.path.insert(0, _p)

from distributedtensorflowexample_tpu.engine import (  # noqa: E402
    resolve_update_layout)     # stdlib-only half of engine/ (spec.py)
from distributedtensorflowexample_tpu.obs import ledger as obs_ledger  # noqa: E402
from obs_report import _table as _table_lines  # noqa: E402  (tools/)


def _table(headers: list[str], rows: list[list]) -> str:
    """obs_report's markdown table builder, joined, with Nones blanked
    — ONE table dialect across the two query/report CLIs."""
    return "\n".join(_table_lines(
        headers, [["" if c is None else c for c in row] for row in rows]))


def _emit(payload, md: str, fmt: str) -> None:
    if fmt == "json":
        json.dump(payload, sys.stdout, indent=1, default=str)
        print()
    else:
        print(md)


# --- list ------------------------------------------------------------------

def cmd_list(args) -> int:
    folded = obs_ledger.runs(args.ledger)
    table = obs_ledger.run_table(args.ledger, folded=folded)
    if args.entrypoint:
        table = [r for r in table
                 if args.entrypoint in str(r.get("entrypoint") or "")]
    if args.outcome:
        table = [r for r in table
                 if args.outcome in str(r.get("outcome") or "")]
    agreements = [e for e in folded["events"]
                  if e.get("event") == "resume_agreement"]
    md_rows = [[r["run"], r["entrypoint"], r["rank"], r["attempt"],
                r["outcome"], r["final_step"], r["samples"],
                r["anomalies"] or "",
                "" if r["duration_s"] is None else f"{r['duration_s']:g}"]
               for r in table]
    md = [f"# Runs — `{os.path.basename(args.ledger)}` "
          f"({len(table)} run(s)"
          + (f", {folded['torn']} torn line(s) skipped"
             if folded["torn"] else "") + ")", "",
          _table(["run", "entrypoint", "rank", "att", "outcome", "step",
                  "samples", "anom", "dur_s"], md_rows)]
    if agreements:
        md += ["", "## Resume agreements", ""]
        md += [f"- agreed step **{a.get('agreed')}** "
               f"(task {a.get('task')}): per-rank "
               f"{a.get('per_rank')}, discarded {a.get('discarded')}"
               for a in agreements]
    _emit({"runs": table, "agreements": agreements,
           "torn": folded["torn"]}, "\n".join(md), args.format)
    return 0


# --- show ------------------------------------------------------------------

def _resolve_run(folded: dict, token: str) -> str:
    """Exact id or unique prefix — eight hex chars beat pasting the
    whole id into a terminal."""
    if token in folded["runs"]:
        return token
    matches = [r for r in folded["order"] if r.startswith(token)]
    if len(matches) == 1:
        return matches[0]
    raise SystemExit(
        f"obs_query: run {token!r} "
        + ("is ambiguous: " + ", ".join(matches) if matches
           else "not found — `obs_query list` shows the ids"))


def cmd_show(args) -> int:
    folded = obs_ledger.runs(args.ledger)
    run_id = _resolve_run(folded, args.run)
    group = folded["runs"][run_id]
    md = [f"# Run `{run_id}`", ""]
    for name, row in (("run_start", group["start"]),
                      ("run_end", group["end"])):
        if row:
            md += [f"## {name}", "", "```json",
                   json.dumps(row, indent=1, sort_keys=True), "```", ""]
    if group["samples"]:
        md += [f"## samples ({len(group['samples'])})", ""]
        rows = [[s.get("step"),
                 (s.get("delta") or {}).get("span_s"),
                 json.dumps((s.get("delta") or {}).get("counters") or {},
                            sort_keys=True)]
                for s in group["samples"]]
        md += [_table(["step", "span_s", "counter deltas"], rows)]
    _emit(group, "\n".join(md), args.format)
    return 0


# --- diff ------------------------------------------------------------------

def diff_runs(folded: dict, id_a: str, id_b: str) -> dict:
    a, b = folded["runs"][id_a], folded["runs"][id_b]

    def cfg(g):
        return ((g["start"] or {}).get("config") or {})

    keys = sorted(set(cfg(a)) | set(cfg(b)))
    config_diff = {k: {"a": cfg(a).get(k), "b": cfg(b).get(k)}
                   for k in keys if cfg(a).get(k) != cfg(b).get(k)}

    def layout(g):
        # The DERIVED working layout (tree / bucket_rows / zero3_rows)
        # — the resume-contract fact the raw knob columns only imply:
        # two runs can differ in bucket_grads/shard_* strings yet land
        # in the same layout, or agree on most knobs and still be
        # checkpoint-incompatible.  Same resolution the Engine runs
        # (engine/spec.py), from the run's resolved config + mesh_size.
        start = g["start"] or {}
        if not start.get("config"):
            return None
        try:
            return resolve_update_layout(start["config"],
                                         int(start.get("mesh_size") or 1))
        except Exception:       # noqa: BLE001 — a foreign config shape
            return None         # must read as "underivable", never die

    def counters(g):
        return ((g["end"] or {}).get("counters") or {})

    ckeys = sorted(set(counters(a)) | set(counters(b)))
    metric_delta = {}
    for k in ckeys:
        va, vb = counters(a).get(k), counters(b).get(k)
        if va != vb:
            metric_delta[k] = {
                "a": va, "b": vb,
                "delta": (None if not isinstance(va, (int, float))
                          or not isinstance(vb, (int, float))
                          else round(vb - va, 6))}

    def end_field(g, f):
        return (g["end"] or {}).get(f)

    tails = {which: end_field(g, "loss_tail")
             for which, g in (("a", a), ("b", b))}
    return {
        "a": {"run": id_a, **{f: (a["start"] or {}).get(f)
                              for f in ("entrypoint", "config_digest",
                                        "rank", "attempt")}},
        "b": {"run": id_b, **{f: (b["start"] or {}).get(f)
                              for f in ("entrypoint", "config_digest",
                                        "rank", "attempt")}},
        "config_diff": config_diff,
        "update_layout": {"a": layout(a), "b": layout(b)},
        "outcome": {"a": {"rc": end_field(a, "rc"),
                          "final_step": end_field(a, "final_step")},
                    "b": {"rc": end_field(b, "rc"),
                          "final_step": end_field(b, "final_step")}},
        "loss_tail": {**tails,
                      "same_trajectory": (
                          None if not tails["a"] or not tails["b"]
                          else tails["a"].get("sha256")
                          == tails["b"].get("sha256"))},
        "anomaly_flags": {"a": end_field(a, "anomaly_flags"),
                          "b": end_field(b, "anomaly_flags")},
        "counter_deltas": metric_delta}


def cmd_diff(args) -> int:
    folded = obs_ledger.runs(args.ledger)
    id_a = _resolve_run(folded, args.run_a)
    id_b = _resolve_run(folded, args.run_b)
    d = diff_runs(folded, id_a, id_b)
    md = [f"# Run diff — `{id_a}` (a) vs `{id_b}` (b)", "",
          f"- **a**: {d['a']['entrypoint']} "
          f"(config {d['a']['config_digest']}, rank {d['a']['rank']}, "
          f"attempt {d['a']['attempt']}) → rc={d['outcome']['a']['rc']} "
          f"@ step {d['outcome']['a']['final_step']}",
          f"- **b**: {d['b']['entrypoint']} "
          f"(config {d['b']['config_digest']}, rank {d['b']['rank']}, "
          f"attempt {d['b']['attempt']}) → rc={d['outcome']['b']['rc']} "
          f"@ step {d['outcome']['b']['final_step']}"]
    same = d["loss_tail"]["same_trajectory"]
    if same is not None:
        md.append(f"- **loss trajectory**: "
                  + ("IDENTICAL (tail digests match)" if same
                     else "differs (tail digests disagree)"))
    md += ["", "## Config diff", ""]
    # The derived working layout leads the table for both runs even
    # when equal: it is the checkpoint-resume contract, and "both
    # zero3_rows" vs "both tree" changes how every knob row below
    # reads.
    lay = d["update_layout"]
    layout_rows = ([["update_layout (derived)", lay["a"], lay["b"]]]
                   if lay["a"] or lay["b"] else [])
    if d["config_diff"] or layout_rows:
        md.append(_table(["key", "a", "b"],
                         layout_rows
                         + [[k, v["a"], v["b"]]
                            for k, v in sorted(d["config_diff"].items())]))
    if not d["config_diff"]:
        md.append(("" if not layout_rows else "\n")
                  + "- identical resolved configs "
                  f"(digest {d['a']['config_digest']})")
    md += ["", "## Counter deltas (b - a)", ""]
    if d["counter_deltas"]:
        md.append(_table(
            ["counter", "a", "b", "delta"],
            [[f"`{k}`", v["a"], v["b"], v["delta"]]
             for k, v in sorted(d["counter_deltas"].items())]))
    else:
        md.append("- no counter differences")
    _emit(d, "\n".join(md), args.format)
    return 0


# --- why (scheduler decisions) ---------------------------------------------

# Renderers for the scheduler's sched_* ledger rows — one entry per
# decision class resilience/scheduler.py can write; unknown sched_*
# rows render generically rather than being dropped, so a reader never
# loses a decision to version skew.
# KEEP-IN-SYNC(sched-events) digest=d37469a5064a
_WHY_RENDER = {
    "sched_submit": lambda r: (
        f"submitted: kind={r.get('kind')}, priority={r.get('priority')}, "
        f"{r.get('ranks')} rank(s), retry budget {r.get('retries')}"),
    "sched_admit": lambda r: (
        "admitted — "
        + (f"predicted cost {r.get('predicted_s')}s "
           f"({r.get('step_time_s')}s/step, source {r.get('source')})"
           if r.get("predicted_s") is not None else
           f"step time {r.get('step_time_s')}s/step (source "
           f"{r.get('source')}), total unknown (no steps declared)"
           if r.get("source") else
           "cost unknown (no trajectory family, no declared estimate)")),
    "sched_refuse": lambda r: f"REFUSED at admission: {r.get('why')}",
    "sched_place": lambda r: (
        f"placed on {r.get('ranks')} of {r.get('devices')} device(s) "
        f"(attempt {r.get('attempt')}"
        + (", resuming from snapshots" if r.get("resumed") else "")
        + (f", wall deadline {r.get('wall_timeout_s')}s"
           if r.get("wall_timeout_s") else "") + ")"),
    "sched_shrink": lambda r: (
        f"elastic SHRINK to {r.get('ranks')} rank(s) (was "
        f"{r.get('was')}; lost rank(s) {r.get('lost')} — host down)"),
    "sched_grow": lambda r: (
        f"GROW back to full width: "
        + (f"rank(s) {r.get('recovered')} answered the recovery "
           f"re-probe — stopped cleanly (rcs {r.get('rcs')}) and "
           f"requeued at full width" if r.get("recovered") is not None
           else f"{r.get('ranks')} rank(s) (was {r.get('was')}, "
                f"fleet-internal re-probe)")),
    "sched_evict": lambda r: (
        f"EVICTED: {r.get('why')} — TERM→143→snapshot "
        f"(rcs {r.get('rcs')}, clean={r.get('clean')}); requeued, "
        f"not charged to the retry budget"),
    "sched_retry": lambda r: (
        f"retry {r.get('retry')}/{r.get('of')} with "
        f"{r.get('backoff_s')}s backoff: {r.get('why')}"),
    "sched_quarantine": lambda r: (
        f"QUARANTINED (rcs {r.get('rcs')}): {r.get('why')}"),
    "sched_fail": lambda r: (
        f"FAILED after {r.get('retries')} retr(ies): {r.get('why')}"),
    "sched_done": lambda r: (
        f"done: rcs {r.get('rcs')} over {r.get('gang_attempts')} gang "
        f"attempt(s), {r.get('restarts')} gang restart(s), "
        f"{r.get('preempt_resumes')} scheduler preemption-resume(s)"),
    "sched_orphan_killed": lambda r: (
        f"restart swept orphaned rank {r.get('rank')} group (pid "
        f"{r.get('pid')}) left by a dead scheduler incarnation"),
    "sched_queue_done": lambda r: (
        f"queue drained: {r.get('status')} {r.get('counts')}"),
}
# KEEP-IN-SYNC-END(sched-events)

_TERMINAL_WHY = {"sched_done": "completed", "sched_fail": "failed",
                 "sched_quarantine": "quarantined",
                 "sched_refuse": "refused"}

# Renderers for the remediation engine's heal_* ledger rows — one entry
# per decision class resilience/remediate.py can write; unknown heal_*
# rows render generically (same contract as the sched_* table above).
# KEEP-IN-SYNC(heal-events) digest=28d0c1dcec37
_HEAL_RENDER = {
    "heal_detect": lambda r: (
        f"anomaly detected: {r.get('kind')}"
        + (f" on rank {r.get('rank')}" if r.get("rank") is not None
           else "")
        + (f" at step {r.get('step')}" if r.get("step") is not None
           else "") + f" (source {r.get('source')})"),
    "heal_evict": lambda r: (
        f"HEALED by eviction ({r.get('kind')}): loss-free gang stop — "
        f"TERM→143→snapshot, resumed bitwise ({r.get('detail')})"),
    "heal_rollback": lambda r: (
        f"HEALED by rollback ({r.get('kind')}): gang rolled back to "
        f"pinned last-good snapshot ({r.get('detail')})"),
    "heal_slo_tighten": lambda r: (
        f"HEALED by admission tightening ({r.get('kind')}): "
        f"{r.get('detail')}"),
    "heal_quarantine": lambda r: (
        f"QUARANTINED rank {r.get('rank')} (repeated offender): "
        f"{r.get('detail')}"),
    "heal_canary_promote": lambda r: (
        f"canary PROMOTED: {r.get('detail')}"),
    "heal_canary_rollback": lambda r: (
        f"canary ROLLED BACK ({r.get('kind')}): {r.get('detail')}"),
    "heal_scale_up": lambda r: (
        f"SCALED UP ({r.get('kind')}): serve fleet grown against the "
        f"measured SLO knee ({r.get('detail')})"),
    "heal_scale_down": lambda r: (
        f"SCALED DOWN ({r.get('kind')}): serve fleet shrunk — "
        f"sustained underload ({r.get('detail')})"),
    "heal_lr_drop": lambda r: (
        f"LR-DROP advisory written ({r.get('kind')}): plateau asks for "
        f"a smaller LR before a rollback — stub behind HEAL_LR_DROP "
        f"({r.get('detail')})"),
    "heal_suppressed": lambda r: (
        f"action {r.get('action')} on {r.get('kind')} SUPPRESSED by "
        f"guardrail: {r.get('reason')}"),
    "heal_dry_run": lambda r: (
        f"DRY RUN: {r.get('action')} on {r.get('kind')} would have "
        f"fired (HEAL_DRY_RUN armed — nothing ran)"),
    "heal_budget_exhausted": lambda r: (
        f"action budget {r.get('budget')} EXHAUSTED — remediation "
        f"degraded to detection-only"),
}
# KEEP-IN-SYNC-END(heal-events)

# Renderers for the shard-redundant snapshot store's ckpt_* ledger rows
# (resilience/shardstore.py) — the checkpoint half of a job's timeline:
# saves, elastic restores, mirror reconstructions, digest-caught rot,
# and the loud over-redundancy refusal.  Unknown ckpt_* rows render
# generically, same contract as the tables above.
_CKPT_RENDER = {
    "ckpt_save": lambda r: (
        f"shard set saved at step {r.get('step')}: {r.get('ranks')} "
        f"shard(s) x R={r.get('redundancy')} copies, "
        f"{r.get('nbytes')} payload byte(s)"),
    "ckpt_restore": lambda r: (
        (f"ELASTIC restore at step {r.get('step')}: "
         f"D={r.get('from_ranks')} shard set regrouped onto "
         f"D={r.get('to_ranks')} through the engine layout pass"
         if r.get("elastic") else
         f"restored shard set at step {r.get('step')} "
         f"(D={r.get('to_ranks')})")
        + (f"; reconstructed shard(s) {r.get('reconstructed')} from "
           f"ring mirrors" if r.get("reconstructed") else "")),
    "ckpt_reconstruct": lambda r: (
        f"shard {r.get('shard')} of step {r.get('step')} rebuilt from "
        f"rank {r.get('source_rank')}'s ring mirror"),
    "ckpt_digest_mismatch": lambda r: (
        f"BIT ROT caught: {r.get('file')} (shard {r.get('shard')}, "
        f"step {r.get('step')}) failed its sha256 — copy refused, "
        f"never restored"),
    "ckpt_copy_unreadable": lambda r: (
        f"copy unreadable: {r.get('file')} (shard {r.get('shard')}, "
        f"step {r.get('step')}) — trying the next ring copy"),
    "ckpt_refused": lambda r: (
        f"restore REFUSED at step {r.get('step')}: shard "
        f"{r.get('shard')} has no intact copy (census "
        f"{r.get('census')}, R={r.get('redundancy')}) — loss exceeds "
        f"redundancy"),
}


def why_rows(rows: list[dict], token: str) -> tuple[str, list[dict]]:
    """Resolve ``token`` (exact id or unique prefix) against the
    distinct job ids in the ledger's sched_*, heal_* AND ckpt_* rows;
    return (job_id, that job's rows in ledger order) — one timeline
    holding the scheduler's decisions, the remediation engine's, and
    the shard store's checkpoint events."""
    sched = [r for r in rows
             if str(r.get("event", "")).startswith(("sched_", "heal_",
                                                    "ckpt_"))
             and r.get("job")]
    jobs = []
    for r in sched:
        if r["job"] not in jobs:
            jobs.append(r["job"])
    if token in jobs:
        job = token
    else:
        matches = [j for j in jobs if str(j).startswith(token)]
        if len(matches) != 1:
            raise SystemExit(
                f"obs_query: job {token!r} "
                + ("is ambiguous: " + ", ".join(map(str, matches))
                   if matches else
                   f"not found — jobs with scheduler rows: "
                   f"{', '.join(map(str, jobs)) or '(none)'}"))
        job = matches[0]
    return job, [r for r in sched if r["job"] == job]


def cmd_why(args) -> int:
    rows, torn = obs_ledger.read_rows(args.ledger)
    job, mine = why_rows(rows, args.job)
    lines = []
    for r in mine:
        ev_name = str(r.get("event", ""))
        if ev_name.startswith("heal_") and r.get("error"):
            # An applied row carrying error= balances the remediator's
            # WAL but the actuator CRASHED — rendering it through the
            # HEALED renderer would tell the operator a heal happened.
            text = (f"action {ev_name.removeprefix('heal_')} FAILED "
                    f"({r.get('kind')}): {r.get('error')}")
        else:
            render = _WHY_RENDER.get(r.get("event")) \
                or _HEAL_RENDER.get(r.get("event")) \
                or _CKPT_RENDER.get(r.get("event"))
            text = (render(r) if render else
                    f"{r.get('event')}: " + json.dumps(
                        {k: v for k, v in r.items()
                         if k not in ("v", "ts", "event", "src", "job")},
                        sort_keys=True, default=str))
        lines.append({"ts": r.get("ts"), "event": r.get("event"),
                      "text": text})
    evictions = sum(1 for r in mine if r.get("event") == "sched_evict")
    shrinks = sum(1 for r in mine if r.get("event") == "sched_shrink")
    grows = sum(1 for r in mine if r.get("event") == "sched_grow")
    heals = [r for r in mine
             if str(r.get("event", "")).startswith("heal_")
             and not r.get("error")
             and r.get("event") not in ("heal_detect", "heal_suppressed",
                                        "heal_dry_run",
                                        "heal_budget_exhausted")]
    last_terminal = next(
        (r for r in reversed(mine) if r.get("event") in _TERMINAL_WHY),
        None)
    verdict = []
    if evictions:
        for_jobs = sorted({str(r.get("for_job")) for r in mine
                           if r.get("event") == "sched_evict"})
        verdict.append(f"preempted {evictions}x (for "
                       + ", ".join(f"`{j}`" for j in for_jobs) + ")")
    if shrinks:
        verdict.append(f"shrank {shrinks}x on rank loss")
    if grows:
        verdict.append(f"grew back {grows}x on recovery")
    if heals:
        kinds = sorted({str(r["event"]).removeprefix("heal_")
                        for r in heals})
        verdict.append(f"self-healed {len(heals)}x "
                       f"({', '.join(kinds)})")
    verdict.append(
        f"finally {_TERMINAL_WHY[last_terminal['event']]}"
        if last_terminal else "no terminal decision on record "
                              "(still queued/running, or the ledger "
                              "predates the end)")
    md = [f"# Why — job `{job}`", ""]
    md += [f"- [{l['ts']}] {l['text']}" for l in lines]
    md += ["", f"**Verdict**: {'; '.join(verdict)}."]
    _emit({"job": job, "timeline": lines,
           "verdict": "; ".join(verdict), "torn": torn},
          "\n".join(md), args.format)
    return 0


# --- trajectory ------------------------------------------------------------

def cmd_trajectory(args) -> int:
    import bench_ratchet
    rows = bench_ratchet.build_trajectory(args.records_dir)
    if args.family:
        rows = [r for r in rows if args.family in r["family"]]
    md = [f"# Bench trajectory — {len(rows)} family-round row(s)", ""]
    for row in rows:
        rnd = "—" if row["round"] is None else f"r{row['round']:02d}"
        md += [f"## {row['family']} {rnd} (`{row['file']}`, "
               f"{'/'.join(row['platforms'])})", "",
               _table(["metric", "value"],
                      [[f"`{k}`", v]
                       for k, v in sorted(row["metrics"].items())]), ""]
    _emit(rows, "\n".join(md), args.format)
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    def add_common(sp, ledger: bool = True):
        sp.add_argument("--format", default="md", choices=["md", "json"])
        if ledger:
            # `or`: a present-but-EMPTY export means "ledger disabled"
            # everywhere else (fleet, maybe_begin) — fall through to
            # the ./RUNS.jsonl default the help text promises.
            sp.add_argument("--ledger", default=os.environ.get(
                "OBS_LEDGER") or "RUNS.jsonl",
                help="RUNS.jsonl path (default: $OBS_LEDGER, else "
                     "./RUNS.jsonl)")

    sp = sub.add_parser("list", help="run table + agreements")
    add_common(sp)
    sp.add_argument("--entrypoint", default="",
                    help="substring filter on the entrypoint")
    sp.add_argument("--outcome", default="",
                    help="substring filter on the outcome "
                         "(ok/preempted/rc=.../running)")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("show", help="one run's rows in full")
    add_common(sp)
    sp.add_argument("run", help="run id (or unique prefix)")
    sp.set_defaults(fn=cmd_show)

    sp = sub.add_parser("diff", help="config + metric deltas between "
                                     "two runs")
    add_common(sp)
    sp.add_argument("run_a")
    sp.add_argument("run_b")
    sp.set_defaults(fn=cmd_diff)

    sp = sub.add_parser("why", help="one job's scheduler decision "
                                    "timeline: why was it preempted / "
                                    "shrunk / quarantined")
    add_common(sp)
    sp.add_argument("job", help="job id (or unique prefix) from "
                                "tools/schedule.py's queue")
    sp.set_defaults(fn=cmd_why)

    sp = sub.add_parser("trajectory", help="per-family per-round bench "
                                           "metric trajectories")
    add_common(sp, ledger=False)
    sp.add_argument("--records_dir", default=_REPO)
    sp.add_argument("--family", default="",
                    help="substring filter on the family")
    sp.set_defaults(fn=cmd_trajectory)

    args = p.parse_args(argv)
    if getattr(args, "ledger", None) is not None \
            and args.cmd != "trajectory" \
            and not os.path.exists(args.ledger) \
            and not os.path.exists(args.ledger + ".1"):
        p.error(f"ledger {args.ledger} does not exist (pass --ledger or "
                f"export OBS_LEDGER)")
    return args.fn(args)


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `obs_query list | head` closing the pipe early is a normal
        # way to read a long table, not an error worth a traceback.
        os._exit(0)
