#!/bin/bash
# One full on-chip capture: bench.py (headline measured first,
# watchdogged - see docs/DESIGN.md §10), then bench_profile.py (ResNet
# attribution + jax.profiler trace), then the trace tarred into the repo
# if it is small enough to commit.  Launched by tools/tpu_watch.sh on
# backend recovery, or by hand:  setsid nohup tools/bench_capture.sh &
#
# Detached on purpose: a tool-timeout SIGKILL on a chip-holding process
# wedges the shared tunnel (verify skill), so captures must never run
# under a harness timeout.

cd "$(dirname "$0")/.." || exit 1
OUT=${OUT:-BENCH_auto_r04.json}
PROFILE_OUT=${PROFILE_OUT:-PROFILE_r04.json}
TRACE_TGZ=${TRACE_TGZ:-resnet_trace_r04.tgz}
TRACE_DIR=${TRACE_DIR:-/tmp/resnet_trace}
LOG=${LOG:-/tmp/bench_capture.log}
CAPTURE_PIDFILE=${CAPTURE_PIDFILE:-/tmp/bench_capture.pid}

# Pidfile = the watcher's liveness signal (tools/tpu_watch.sh reads it
# instead of pgrep argv-matching, so any launch spelling works).  EXIT
# trap removes it only if it is still OURS — a stale-killed capture must
# not race a fresh one's pidfile away.
echo $$ > "$CAPTURE_PIDFILE"
cleanup_pidfile() {
  [ "$(cat "$CAPTURE_PIDFILE" 2>/dev/null)" = "$$" ] \
    && rm -f "$CAPTURE_PIDFILE"
}
trap cleanup_pidfile EXIT

# Detached capture: no outer harness timeout, so the full 40-min retry
# budget is affordable here (bench.py's default shrank to 900 s to fit
# under the DRIVER's ~23-25-min kill — that constraint does not apply
# to this path).  Exported so bench_profile.py (same module constant)
# gets it too.
export BENCH_RETRY_BUDGET_S=${BENCH_RETRY_BUDGET_S:-2400}

date -u >> "$LOG"
python bench.py > "$OUT.tmp" 2>> "$LOG"
rc=$?
# Keep whatever landed even on failure: each line is flushed as it
# completes, so a partial file is a valid partial capture.
if [ -s "$OUT.tmp" ]; then mv "$OUT.tmp" "$OUT"; else rm -f "$OUT.tmp"; fi
echo "bench rc=$rc" >> "$LOG"

if [ "$rc" -eq 3 ]; then
  # bench's watchdog fired: the backend is provably wedged.  Running the
  # profile against it would burn another BENCH_TOTAL_BUDGET_S while
  # this live process suppresses nothing useful — stop here; the next
  # recovery window relaunches the whole capture.
  echo "profile skipped: bench watchdog fired (backend wedged)" >> "$LOG"
else
  # A stale trace from an earlier run must not get tarred as THIS
  # window's artifact.
  rm -rf "$TRACE_DIR"
  python bench_profile.py --trace_dir "$TRACE_DIR" > "$PROFILE_OUT.tmp" 2>> "$LOG"
  rc2=$?
  if [ -s "$PROFILE_OUT.tmp" ]; then
    mv "$PROFILE_OUT.tmp" "$PROFILE_OUT"
  else
    rm -f "$PROFILE_OUT.tmp"
  fi
  echo "profile rc=$rc2" >> "$LOG"
  if [ "$rc2" -eq 0 ] && [ -d "$TRACE_DIR" ]; then
    sz=$(du -sm "$TRACE_DIR" | cut -f1)
    if [ "$sz" -le 25 ]; then
      tar czf "$TRACE_TGZ" -C "$(dirname "$TRACE_DIR")" "$(basename "$TRACE_DIR")"
      echo "trace tarred (${sz}MB) -> $TRACE_TGZ" >> "$LOG"
    else
      echo "trace too big to commit (${sz}MB), left in $TRACE_DIR" >> "$LOG"
    fi
  fi
fi
date -u >> "$LOG"
