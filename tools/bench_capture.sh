#!/bin/bash
# One on-chip capture window, ordered by artifact value (round-3 data:
# windows between outages ran as short as ~9 minutes, and the ResNet
# attribution has never yet executed on hardware):
#   phase 1  bench.py BENCH_HEADLINE_ONLY=1  -> the contract metric +
#            same-window roofline, fastest possible ($OUT_HEADLINE)
#   phase 2  bench_profile.py                -> ResNet attribution +
#            jax.profiler trace ($PROFILE_OUT, trace tarred if small)
#   phase 3  bench.py (full)                 -> all six workload lines
#            ($OUT) — spends whatever window remains
#   phase 4  trainer CLI at its defaults     -> out-of-box auto-unroll
#            throughput ($CLI_OUT, bounded 5000 steps) — confirms the
#            round-5 BASELINE.md prediction
# Each phase's output is kept even if a later phase dies; a watchdog
# exit (rc=3: backend provably wedged) stops the remaining phases.
# Launched by tools/tpu_watch.sh on backend recovery, or by hand:
#   setsid nohup tools/bench_capture.sh &
#
# Detached on purpose: a tool-timeout SIGKILL on a chip-holding process
# wedges the shared tunnel (verify skill), so captures must never run
# under a harness timeout.
#
# The phase table below is mirrored in tools/supervise.py
# _capture_tasks (the supervised default path): phase set, artifact
# filenames, env knobs, gates.  Any phase change must land in BOTH
# until this bash path is retired — enforced by graftlint's
# keep-in-sync rule: the digest on the marker a few lines down covers
# both regions' content, so editing either side stales both digests
# until you re-sync and `python -m tools.graftlint --fix` re-stamps.

cd "$(dirname "$0")/.." || exit 1

# CAPTURE_SUPERVISED=1 delegates the whole sequence to the journaled
# supervisor (tools/supervise.py --capture): same phases, same env knobs,
# same pidfile — plus resume-across-windows and wedge-aware skipping.
# tools/tpu_watch.sh launches supervise.py directly on a recovery edge
# (CAPTURE_LAUNCHER=supervised, its default); this guard gives hand
# launches of THIS script the same path, with the inline bash phases
# below kept as the flagged fallback (CAPTURE_SUPERVISED=0, the default
# here, preserves the battle-tested behavior for `bash tools/bench_capture.sh`).
if [ "${CAPTURE_SUPERVISED:-0}" = 1 ]; then
  exec python tools/supervise.py --capture
fi

# KEEP-IN-SYNC(capture-phases) digest=1921cee5f541
OUT=${OUT:-BENCH_auto_r05.json}
OUT_HEADLINE=${OUT_HEADLINE:-BENCH_headline_r05.json}
PROFILE_OUT=${PROFILE_OUT:-PROFILE_auto_r05.json}
BYTES_OUT=${BYTES_OUT:-BYTES_AUDIT_r05.json}
COLLECTIVES_OUT=${COLLECTIVES_OUT:-BENCH_collectives_r06.json}
LM_OUT=${LM_OUT:-BENCH_lm_r08.json}
TRACE_TGZ=${TRACE_TGZ:-resnet_trace_r05.tgz}
CLI_OUT=${CLI_OUT:-CLI_r05.log}
TRACE_DIR=${TRACE_DIR:-/tmp/resnet_trace}
LOG=${LOG:-/tmp/bench_capture.log}
CAPTURE_PIDFILE=${CAPTURE_PIDFILE:-/tmp/bench_capture.pid}

# Pidfile = the watcher's liveness signal (tools/tpu_watch.sh reads it
# instead of pgrep argv-matching, so any launch spelling works).  EXIT
# trap removes it only if it is still OURS — a stale-killed capture must
# not race a fresh one's pidfile away.
echo $$ > "$CAPTURE_PIDFILE"
cleanup_pidfile() {
  [ "$(cat "$CAPTURE_PIDFILE" 2>/dev/null)" = "$$" ] \
    && rm -f "$CAPTURE_PIDFILE"
}
trap cleanup_pidfile EXIT

# Detached capture: no outer harness timeout, so the full 40-min retry
# budget is affordable here (bench.py's default shrank to 900 s to fit
# under the DRIVER's ~23-25-min kill — that constraint does not apply
# to this path).  Exported so every phase gets it.
export BENCH_RETRY_BUDGET_S=${BENCH_RETRY_BUDGET_S:-2400}

# Keep whatever landed even on a failed phase: every line is flushed as
# it completes, so a partial file is a valid partial capture.
keep() { # $1=tmp $2=final
  if [ -s "$1" ]; then mv "$1" "$2"; else rm -f "$1"; fi
}

# Phase 2b body, callable from two places: the normal phase-2b slot AND
# every wedge bail.  The CPU audit is tunnel-free, so a wedged chip must
# never cost us the one artifact that doesn't need the chip — but it
# must not run BEFORE the on-chip phases either (it burns real window
# wall time on this shared host).  Guarded by an in-process flag: at
# most once per capture RUN (a $BYTES_OUT left by a PREVIOUS window
# must not suppress this window's fresh table — the phase-4
# fresh_measured stale-file lesson).
BYTES_AUDIT_RAN=0
run_bytes_audit() {
  [ "$BYTES_AUDIT_RAN" = 1 ] && return 0
  BYTES_AUDIT_RAN=1
  python tools/bytes_audit.py --backend cpu --workload resnet20 \
    ${BYTES_ARGS:---batch_per_chip 256 --unroll 1} \
    --json "$BYTES_OUT.tmp" >> "$LOG" 2>&1
  echo "bytes audit (cpu) rc=$?" >> "$LOG"
  # keep() checks -s on the JSON; the tool writes it only on success.
  keep "$BYTES_OUT.tmp" "$BYTES_OUT"
}

# $1=rc $2=msg — a watchdog exit (rc=3) means the backend is provably
# wedged; stop burning the window on the remaining ON-CHIP phases (the
# CPU-only audit still lands first — it cannot wedge on the tunnel).
bail_if_wedged() {
  [ "$1" -eq 3 ] || return 0
  echo "$2" >> "$LOG"
  run_bytes_audit
  date -u >> "$LOG"
  exit 3
}

START_TS=$(date +%s)
date -u >> "$LOG"

# --- phase 1: headline only -----------------------------------------------
BENCH_HEADLINE_ONLY=1 python bench.py > "$OUT_HEADLINE.tmp" 2>> "$LOG"
rc1=$?
keep "$OUT_HEADLINE.tmp" "$OUT_HEADLINE"
echo "headline-only bench rc=$rc1" >> "$LOG"
bail_if_wedged "$rc1" "remaining phases skipped: watchdog fired (backend wedged)"

# --- phase 2: ResNet attribution + trace ----------------------------------
# A stale trace from an earlier run must not get tarred as THIS window's
# artifact.
rm -rf "$TRACE_DIR"
python bench_profile.py --trace_dir "$TRACE_DIR" > "$PROFILE_OUT.tmp" 2>> "$LOG"
rc2=$?
keep "$PROFILE_OUT.tmp" "$PROFILE_OUT"
echo "profile rc=$rc2" >> "$LOG"
if [ "$rc2" -eq 0 ] && [ -d "$TRACE_DIR" ]; then
  sz=$(du -sm "$TRACE_DIR" | cut -f1)
  if [ "$sz" -le 25 ]; then
    tar czf "$TRACE_TGZ" -C "$(dirname "$TRACE_DIR")" "$(basename "$TRACE_DIR")"
    echo "trace tarred (${sz}MB) -> $TRACE_TGZ" >> "$LOG"
  else
    echo "trace too big to commit (${sz}MB), left in $TRACE_DIR" >> "$LOG"
  fi
fi
# --- phase 2b: per-op bytes attribution (CPU backend, tunnel-free) --------
# The on-chip per-op table rides inside $PROFILE_OUT (bench_profile emits
# detail.bytes_audit per variant); this archives the CPU-methodology
# table alongside it for the A/B BASELINE.md documents.  Runs on the CPU
# backend IN-PROCESS (--backend cpu: sitecustomize overrides the
# JAX_PLATFORMS env var, so the pin must happen inside the tool); a
# wedge bail in ANY phase also runs it on the way out (see
# run_bytes_audit), so a dead chip cannot block it — re-driven
# end-to-end against the down backend, PR 2: phases 1-3 sentinel, the
# audit JSON still lands.
run_bytes_audit
bail_if_wedged "$rc2" "full bench skipped: profile watchdog fired (backend wedged)"

# --- phase 2c: collective latency/bandwidth curves + knee -----------------
# bench_collectives.py --real: probes with the bench env knobs and emits
# a sentinel record when the backend is down (never hangs the window);
# under an exported JAX_PLATFORMS=cpu the record self-labels
# platform=cpu so CPU curves are never mistaken for chip numbers.
python bench_collectives.py --real --json "$COLLECTIVES_OUT.tmp" \
  >> "$LOG" 2>> "$LOG"
rc2c=$?
keep "$COLLECTIVES_OUT.tmp" "$COLLECTIVES_OUT"
echo "collectives rc=$rc2c" >> "$LOG"

# --- phase 2d: graft-LM family (bench_lm.py --real) -----------------------
# tokens/sec + MFU + the lm_base knob A/B matrix on the live backend;
# same sentinel/platform-labeling discipline as phase 2c.
python bench_lm.py --real --json "$LM_OUT.tmp" \
  >> "$LOG" 2>> "$LOG"
rc2d=$?
keep "$LM_OUT.tmp" "$LM_OUT"
echo "lm rc=$rc2d" >> "$LOG"

# --- phase 3: full bench --------------------------------------------------
python bench.py > "$OUT.tmp" 2>> "$LOG"
rc3=$?
keep "$OUT.tmp" "$OUT"
echo "full bench rc=$rc3" >> "$LOG"
bail_if_wedged "$rc3" "cli phase skipped: full-bench watchdog fired (backend wedged)"

# --- phase 4: out-of-box CLI throughput (round-5 auto-unroll claim) --------
# Only when THIS WINDOW's latest evidence ($OUT — phase 3, not phase 1,
# whose measurement may predate a mid-window death; the mtime check
# excludes a prior window's leftover file) contains a MEASURED line: the
# trainer has no probe/watchdog layer, so against a dead backend (bench
# exits 0 with unavailability sentinels, not rc=3) it would hang at
# init holding the pidfile until the watcher's next stale-kill edge.
fresh_measured() {
  [ -s "$OUT" ] || return 1
  [ "$(stat -c %Y "$OUT" 2>/dev/null || echo 0)" -ge "$START_TS" ] || return 1
  grep -q '"unit": "steps/sec/chip"' "$OUT"
}
if ! fresh_measured; then
  echo "cli phase skipped: no fresh measured line in $OUT this window" >> "$LOG"
  date -u >> "$LOG"
  exit 0
fi
# BASELINE.md round-5 prediction: the shipped trainer CLI at its defaults
# (auto steps_per_loop) should land near the bench's fused path instead
# of the ~1.4 ms/step dispatch tax.  Bounded step count, no outer
# timeout (a SIGKILL on a chip-holding process wedges the tunnel).
python -m distributedtensorflowexample_tpu.trainers.trainer_sync_mnist \
  --dataset synthetic --train_steps 5000 --batch_size 64 \
  --log_every 1000 --log_dir /tmp/cli_bench_r05 --resume false \
  > "$CLI_OUT.tmp" 2>> "$LOG"
rc4=$?
keep "$CLI_OUT.tmp" "$CLI_OUT"
echo "cli out-of-box rc=$rc4 last=$(grep -o 'steps_per_sec_per_chip=[0-9.]*' \
  "$CLI_OUT" 2>/dev/null | tail -1)" >> "$LOG"
date -u >> "$LOG"
# KEEP-IN-SYNC-END(capture-phases)
