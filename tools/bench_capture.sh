#!/bin/bash
# One on-chip capture window, ordered by artifact value (round-3 data:
# windows between outages ran as short as ~9 minutes, and the ResNet
# attribution has never yet executed on hardware):
#   phase 1  bench.py BENCH_HEADLINE_ONLY=1  -> the contract metric +
#            same-window roofline, fastest possible ($OUT_HEADLINE)
#   phase 2  bench_profile.py                -> ResNet attribution +
#            jax.profiler trace ($PROFILE_OUT, trace tarred if small)
#   phase 3  bench.py (full)                 -> all six workload lines
#            ($OUT) — spends whatever window remains
# Each phase's output is kept even if a later phase dies; a watchdog
# exit (rc=3: backend provably wedged) stops the remaining phases.
# Launched by tools/tpu_watch.sh on backend recovery, or by hand:
#   setsid nohup tools/bench_capture.sh &
#
# Detached on purpose: a tool-timeout SIGKILL on a chip-holding process
# wedges the shared tunnel (verify skill), so captures must never run
# under a harness timeout.

cd "$(dirname "$0")/.." || exit 1
OUT=${OUT:-BENCH_auto_r04.json}
OUT_HEADLINE=${OUT_HEADLINE:-BENCH_headline_r04.json}
PROFILE_OUT=${PROFILE_OUT:-PROFILE_r04.json}
TRACE_TGZ=${TRACE_TGZ:-resnet_trace_r04.tgz}
TRACE_DIR=${TRACE_DIR:-/tmp/resnet_trace}
LOG=${LOG:-/tmp/bench_capture.log}
CAPTURE_PIDFILE=${CAPTURE_PIDFILE:-/tmp/bench_capture.pid}

# Pidfile = the watcher's liveness signal (tools/tpu_watch.sh reads it
# instead of pgrep argv-matching, so any launch spelling works).  EXIT
# trap removes it only if it is still OURS — a stale-killed capture must
# not race a fresh one's pidfile away.
echo $$ > "$CAPTURE_PIDFILE"
cleanup_pidfile() {
  [ "$(cat "$CAPTURE_PIDFILE" 2>/dev/null)" = "$$" ] \
    && rm -f "$CAPTURE_PIDFILE"
}
trap cleanup_pidfile EXIT

# Detached capture: no outer harness timeout, so the full 40-min retry
# budget is affordable here (bench.py's default shrank to 900 s to fit
# under the DRIVER's ~23-25-min kill — that constraint does not apply
# to this path).  Exported so every phase gets it.
export BENCH_RETRY_BUDGET_S=${BENCH_RETRY_BUDGET_S:-2400}

# Keep whatever landed even on a failed phase: every line is flushed as
# it completes, so a partial file is a valid partial capture.
keep() { # $1=tmp $2=final
  if [ -s "$1" ]; then mv "$1" "$2"; else rm -f "$1"; fi
}

date -u >> "$LOG"

# --- phase 1: headline only -----------------------------------------------
BENCH_HEADLINE_ONLY=1 python bench.py > "$OUT_HEADLINE.tmp" 2>> "$LOG"
rc1=$?
keep "$OUT_HEADLINE.tmp" "$OUT_HEADLINE"
echo "headline-only bench rc=$rc1" >> "$LOG"
if [ "$rc1" -eq 3 ]; then
  echo "remaining phases skipped: watchdog fired (backend wedged)" >> "$LOG"
  date -u >> "$LOG"
  exit 3
fi

# --- phase 2: ResNet attribution + trace ----------------------------------
# A stale trace from an earlier run must not get tarred as THIS window's
# artifact.
rm -rf "$TRACE_DIR"
python bench_profile.py --trace_dir "$TRACE_DIR" > "$PROFILE_OUT.tmp" 2>> "$LOG"
rc2=$?
keep "$PROFILE_OUT.tmp" "$PROFILE_OUT"
echo "profile rc=$rc2" >> "$LOG"
if [ "$rc2" -eq 0 ] && [ -d "$TRACE_DIR" ]; then
  sz=$(du -sm "$TRACE_DIR" | cut -f1)
  if [ "$sz" -le 25 ]; then
    tar czf "$TRACE_TGZ" -C "$(dirname "$TRACE_DIR")" "$(basename "$TRACE_DIR")"
    echo "trace tarred (${sz}MB) -> $TRACE_TGZ" >> "$LOG"
  else
    echo "trace too big to commit (${sz}MB), left in $TRACE_DIR" >> "$LOG"
  fi
fi
if [ "$rc2" -eq 3 ]; then
  echo "full bench skipped: profile watchdog fired (backend wedged)" >> "$LOG"
  date -u >> "$LOG"
  exit 3
fi

# --- phase 3: full bench --------------------------------------------------
python bench.py > "$OUT.tmp" 2>> "$LOG"
rc3=$?
keep "$OUT.tmp" "$OUT"
echo "full bench rc=$rc3" >> "$LOG"
date -u >> "$LOG"
