#!/bin/bash
# One full on-chip capture: bench.py (headline measured first,
# watchdogged - see docs/DESIGN.md §10), then bench_profile.py (ResNet
# attribution + jax.profiler trace), then the trace tarred into the repo
# if it is small enough to commit.  Launched by tools/tpu_watch.sh on
# backend recovery, or by hand:  setsid nohup tools/bench_capture.sh &
#
# Detached on purpose: a tool-timeout SIGKILL on a chip-holding process
# wedges the shared tunnel (verify skill), so captures must never run
# under a harness timeout.

cd "$(dirname "$0")/.." || exit 1
OUT=${OUT:-BENCH_auto_r03.json}
PROFILE_OUT=${PROFILE_OUT:-PROFILE_r03.json}
TRACE_TGZ=${TRACE_TGZ:-resnet_trace_r03.tgz}
LOG=${LOG:-/tmp/bench_capture.log}

date -u >> "$LOG"
python bench.py > "$OUT.tmp" 2>> "$LOG"
rc=$?
# Keep whatever landed even on failure: each line is flushed as it
# completes, so a partial file is a valid partial capture.
if [ -s "$OUT.tmp" ]; then mv "$OUT.tmp" "$OUT"; else rm -f "$OUT.tmp"; fi
echo "bench rc=$rc" >> "$LOG"

if [ "$rc" -eq 3 ]; then
  # bench's watchdog fired: the backend is provably wedged.  Running the
  # profile against it would burn another BENCH_TOTAL_BUDGET_S while
  # this live process suppresses nothing useful — stop here; the next
  # recovery window relaunches the whole capture.
  echo "profile skipped: bench watchdog fired (backend wedged)" >> "$LOG"
else
  # A stale trace from an earlier run must not get tarred as THIS
  # window's artifact.
  rm -rf /tmp/resnet_trace
  python bench_profile.py > "$PROFILE_OUT.tmp" 2>> "$LOG"
  rc2=$?
  if [ -s "$PROFILE_OUT.tmp" ]; then
    mv "$PROFILE_OUT.tmp" "$PROFILE_OUT"
  else
    rm -f "$PROFILE_OUT.tmp"
  fi
  echo "profile rc=$rc2" >> "$LOG"
  if [ "$rc2" -eq 0 ] && [ -d /tmp/resnet_trace ]; then
    sz=$(du -sm /tmp/resnet_trace | cut -f1)
    if [ "$sz" -le 25 ]; then
      tar czf "$TRACE_TGZ" -C /tmp resnet_trace
      echo "trace tarred (${sz}MB) -> $TRACE_TGZ" >> "$LOG"
    else
      echo "trace too big to commit (${sz}MB), left in /tmp/resnet_trace" >> "$LOG"
    fi
  fi
fi
date -u >> "$LOG"
