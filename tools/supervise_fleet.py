#!/usr/bin/env python
"""Run an N-rank gang under the fleet supervisor (resilience/fleet.py):
per-rank heartbeats, whole-gang teardown on any rank loss, gang restart
from the agreed (maximum common valid) snapshot step.

  # the ACCEPTANCE drill: 2-rank sync mnist_cnn, rank 1 killed mid-run
  # by a rank-targeted FaultPlan -> gang teardown -> gang restart from
  # the agreed step -> bitwise-identical to an uninterrupted run:
  python tools/supervise_fleet.py --num_ranks 2 --workdir /tmp/fleet -- \\
      python tools/faultline.py --plan 'kill@5%1' --steps 10 \\
          --model mnist_cnn --workdir '/tmp/fleet/rank{rank}' --keep 10

  # real trainers get the same env surface the paper's ClusterSpec
  # launch used (TF_CONFIG per rank; cluster.resolve reads it):
  python tools/supervise_fleet.py --num_ranks 2 --heartbeat_timeout_s 600 \\
      --workdir /tmp/fleet -- \\
      python -m distributedtensorflowexample_tpu.trainers.trainer_sync_mnist \\
          --dataset synthetic --train_steps 5000 --log_dir /tmp/fleet/shared

Every ``{rank}`` (and ``{num_ranks}``) in the child argv is substituted
per rank, so one command line fans out to per-rank workdirs.  Exported
per rank: TF_CONFIG (task index = rank), OBS_RANK, FLEET_NUM_RANKS,
SUPERVISE_ATTEMPT (the gang attempt), SUPERVISE_HEARTBEAT (+ the
timeout edge), and — after any restart — FLEET_RESUME_STEP, the agreed
resume step every rank must restore (0 = start fresh).

Exit codes extend the supervisor protocol: 0 ok, 143 terminated
(SIGTERM forwarded to every rank group), 3 wedged (some rank reported
the backend provably gone), 4 rank lost + worker-tiled state (restart
with fewer workers is structurally illegal), 5 rank lost + refused
without --elastic, 1 crash budget exhausted.  With --elastic a lost
rank shrinks the gang; the recovery re-probe before every relaunch
grows it back to full width once the host answers again — drill the
whole cycle with the host_loss fault (``--plan 'host_loss@5:30%1'``:
rank 1's host dies at step 5, answers 30 s later; the fleet exports
the FLEET_HOST_DOWN_FILE tombstone seam per rank).  The multi-job
layer above this — queueing, SLO preemption, cost-priced admission —
is ``python -m tools.schedule`` (resilience/scheduler.py).  OBS_PROM_DIR (optional)
receives a fleet.prom textfile-collector export after every gang
attempt; per-rank flight files land in OBS_DIR (default
<workdir>/flight) as flight_<rank>_<pid>.json — render with
``python tools/obs_report.py --dir <workdir>/flight --journal
<workdir>/fleet.jsonl`` (add ``--format trace > fleet.trace.json`` for
a Perfetto-loadable cross-rank timeline).

Round 16 (`--heal`): detection closes the loop.  The remediation
policy engine (resilience/remediate.py, DESIGN.md §23) watches the
same health files + ledger rows and acts through guardrailed policies:
straggler/regression → loss-free stop + bitwise resume, NaN/plateau →
rollback to the pinned last-good snapshot, repeated host loss → rank
quarantine.  Every decision is a ``heal_*`` ledger row
(``obs_query why <name>`` renders the timeline); HEAL_DRY_RUN=1
journals without acting.  Without --heal the round-10 stance below is
unchanged.

Online health (detection only without --heal): every rank gets OBS_HEALTH exported, so
its AnomalyHook writes <workdir>/health_rank<r>.json; the fleet's
monitor loop reads those, flags stragglers/skew
(obs/anomaly.detect_skew), annotates the journal with ``anomaly``
events, and maintains the aggregate <workdir>/health.json.

Round 12 (run ledger + live scrape): every rank AND the fleet append to
the run ledger <workdir>/RUNS.jsonl (exported as OBS_LEDGER; --ledger
overrides, 'none' disables) — per-attempt run rows, bounded metric
samples, gang rows, and the resume_agreement annotation, queryable with
``python tools/obs_query.py list|diff --ledger <workdir>/RUNS.jsonl``.
With ``--http`` each rank gets an OBS_HTTP_PORT export and serves
/metrics, /health, /flight, /ledger/tail live (obs/serve.py); the
monitor pass then scrapes /health over HTTP and falls back to the
per-rank file (the journal's ``health_scrape`` events name the
transport used).

Interrupted-AGREEMENT drill (PR 12, the fault library's supervisor-side
scenario): the agreement pass journals its ``resume_agreement`` record
WRITE-AHEAD, so a supervisor that dies mid-``discard_newer`` (drill it
with ``FLEET_DRILL_DIE_IN_DISCARD=<k>`` — raises after the k-th rank's
store is swept) leaves an intent a restarted invocation replays before
its first launch: the remaining ranks' divergent snapshots are
discarded (idempotently) and FLEET_RESUME_STEP pins the first gang to
the already-agreed step.  A ``resume_discard_done`` record marks
completion; only an unmatched intent replays.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtensorflowexample_tpu.obs import recorder as obs_recorder  # noqa: E402
from distributedtensorflowexample_tpu.resilience import (  # noqa: E402
    remediate)
from distributedtensorflowexample_tpu.resilience.fleet import (  # noqa: E402
    FleetSupervisor, RankLossRefused, RankLossStructurallyIllegal,
    resolve_ledger_dest)
from distributedtensorflowexample_tpu.resilience.supervisor import (  # noqa: E402
    Journal, RetryPolicy)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    child: list[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, child = argv[:split], argv[split + 1:]
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--num_ranks", type=int, default=2)
    p.add_argument("--retries", type=int, default=3,
                   help="gang restarts after crashes (clean unanimous "
                        "preemptions are exempt)")
    p.add_argument("--backoff_base_s", type=float, default=1.0)
    p.add_argument("--backoff_max_s", type=float, default=60.0)
    p.add_argument("--timeout_s", type=float, default=0.0,
                   help="wall deadline per gang attempt (0 = none)")
    p.add_argument("--heartbeat_timeout_s", type=float, default=0.0,
                   help="tear the gang down when ANY rank's heartbeat "
                        "goes stale this long (0 = no heartbeat "
                        "watchdog)")
    p.add_argument("--kill_grace_s", type=float, default=10.0,
                   help="TERM-to-KILL grace per teardown (covers the "
                        "ranks' save-on-exit)")
    p.add_argument("--preempt_grace_s", type=float, default=30.0,
                   help="how long a partial 143 may wait for the rest "
                        "of the gang before it counts as divergence")
    p.add_argument("--workdir", default="/tmp/fleet",
                   help="fleet scratch: heartbeats, per-rank logs, "
                        "journal, flight dir")
    p.add_argument("--snapshots", default="",
                   help="per-rank SnapshotStore directory template "
                        "({rank} substituted) for the resume-step "
                        "agreement; default <workdir>/rank{rank}/"
                        "snapshots; pass 'none' to disable")
    p.add_argument("--journal", default="",
                   help="fleet journal path (default <workdir>/"
                        "fleet.jsonl)")
    p.add_argument("--stdout_dir", default="",
                   help="per-rank per-attempt child stdout files "
                        "(default <workdir>)")
    p.add_argument("--elastic", action="store_true",
                   help="on a permanently lost rank, continue with the "
                        "survivors (sync/replicated state only)")
    p.add_argument("--sync_mode", default="sync", choices=["sync", "async"],
                   help="what the ranks train: async means worker-tiled "
                        "state, where restarting with fewer workers is "
                        "structurally illegal")
    p.add_argument("--name", default="", help="task name for the journal")
    p.add_argument("--health", default="",
                   help="aggregate fleet health.json path (default "
                        "<workdir>/health.json; 'none' disables the "
                        "aggregate write — per-rank health_rank<r>.json "
                        "files land in the workdir either way)")
    p.add_argument("--skew_lag_steps", type=int, default=3,
                   help="step lag behind the front rank before a rank "
                        "counts as lagging")
    p.add_argument("--skew_time_ratio", type=float, default=4.0,
                   help="step-time multiple of the other ranks' median "
                        "that marks a laggard as a straggler (its own "
                        "regression flag also qualifies)")
    p.add_argument("--http", action="store_true",
                   help="export a per-rank OBS_HTTP_PORT so every rank "
                        "serves /metrics, /health, /flight, /ledger/tail "
                        "live (obs/serve.py); the fleet monitor then "
                        "prefers HTTP /health scrapes over the per-rank "
                        "file (journal shows which transport it used)")
    p.add_argument("--ledger", default="",
                   help="run ledger path exported to every rank as "
                        "OBS_LEDGER (default <workdir>/RUNS.jsonl; "
                        "'none' disables the default — an operator's "
                        "own OBS_LEDGER export still wins, for ranks "
                        "AND fleet rows alike) — query with "
                        "tools/obs_query.py list/diff --ledger <path>")
    p.add_argument("--heal", action="store_true",
                   help="self-healing mode (resilience/remediate.py): "
                        "watch the per-rank health files + ledger "
                        "anomaly rows while the gang runs, and close "
                        "the loop — straggler/regression → loss-free "
                        "stop + bitwise resume, NaN/plateau → rollback "
                        "to the pinned last-good snapshot, repeated "
                        "host loss → rank quarantine.  Guardrailed "
                        "(HEAL_FLAP_N/HEAL_COOLDOWN_S/"
                        "HEAL_ACTION_BUDGET) and HEAL_DRY_RUN=1 "
                        "journals decisions without acting")
    p.add_argument("--heal_poll_s", type=float, default=0.25,
                   help="remediation watcher cadence under --heal")
    p.add_argument("--max_heals", type=int, default=4,
                   help="heal-driven relaunches before giving up")
    p.add_argument("--seed", type=int, default=None,
                   help="backoff-jitter seed (tests)")
    args = p.parse_args(argv)
    if not child:
        p.error("nothing to run: pass -- CMD ARGS... "
                "({rank} substituted per rank)")

    workdir = os.path.abspath(args.workdir)
    snapshots = args.snapshots or os.path.join(workdir,
                                               "rank{rank}", "snapshots")
    if snapshots == "none":
        snapshots = ""
    # Flight files from every rank (and the fleet's own) in one place,
    # named flight_<rank>_<pid>.json; an operator export of OBS_DIR wins.
    os.environ.setdefault("OBS_DIR", os.path.join(workdir, "flight"))
    os.makedirs(os.environ["OBS_DIR"], exist_ok=True)
    obs_recorder.install(sigterm=False)

    journal = Journal(args.journal
                      or os.path.join(workdir, "fleet.jsonl"))

    def make_fleet() -> FleetSupervisor:
        return FleetSupervisor(
            args.num_ranks,
            policy=RetryPolicy(retries=args.retries,
                               backoff_base_s=args.backoff_base_s,
                               backoff_max_s=args.backoff_max_s),
            journal=journal,
            heartbeat_timeout_s=args.heartbeat_timeout_s,
            wall_timeout_s=args.timeout_s,
            kill_grace_s=args.kill_grace_s,
            preempt_grace_s=args.preempt_grace_s,
            seed=args.seed,
            elastic=args.elastic,
            worker_tiled=(args.sync_mode == "async"),
            workdir=workdir,
            health_path=("" if args.health == "none"
                         else args.health or None),
            skew_lag_steps=args.skew_lag_steps,
            skew_time_ratio=args.skew_time_ratio,
            ledger_path=("" if args.ledger == "none"
                         else args.ledger or None),
            http=args.http)

    try:
        if args.heal:
            # Self-healing mode: the policy engine watches the same
            # telemetry the monitor writes and drives the actuators the
            # fleet already has — one journal holds the fleet's AND the
            # remediator's WAL, one ledger both row families.  The
            # shared resolution rule (fleet.resolve_ledger_dest) keeps
            # the remediator bound to the SAME file the fleet's
            # anomaly/rank_lost rows land in.
            ledger_path = resolve_ledger_dest(
                "" if args.ledger == "none"
                else args.ledger or os.path.join(workdir, "RUNS.jsonl"))
            target = remediate.FleetTarget()
            actuators = {
                "evict": remediate.make_evict_actuator(target),
                "quarantine": remediate.make_quarantine_actuator(target),
            }
            if snapshots:
                actuators["rollback"] = remediate.make_rollback_actuator(
                    snapshots, target=target)
            rem = remediate.Remediator(
                journal=journal, ledger_path=ledger_path,
                actuators=actuators, scope=args.name or "fleet")
            watchers = [
                remediate.HealthWatcher(
                    os.path.join(workdir, "health_rank*.json"),
                    fleet_health=("" if args.health == "none"
                                  else args.health
                                  or os.path.join(workdir,
                                                  "health.json")),
                    scope=args.name or "fleet"),
            ]
            if ledger_path:
                # rank_lost ONLY: the ledger's `anomaly` rows mirror
                # the same conditions the health files already deliver
                # — tailing both would double-count one condition into
                # one guardrail key and cross the flap bar in a single
                # poll cycle.
                watchers.append(remediate.LedgerWatcher(
                    ledger_path, kinds=("rank_lost",),
                    scope=args.name or "fleet"))
            out = remediate.run_remediated(
                make_fleet, child, rem, watchers, target=target,
                name=args.name, snapshot_dir_template=snapshots,
                stdout_dir=args.stdout_dir or workdir,
                poll_s=args.heal_poll_s, max_heals=args.max_heals)
            res = out["results"][-1]
            print(f"supervise_fleet: heal: {out['healed']} relaunch(es), "
                  f"{rem.guardrails.actions_used} action(s), final "
                  f"status {out['status']}", file=sys.stderr, flush=True)
        else:
            res = make_fleet().run(child, name=args.name,
                                   snapshot_dir_template=snapshots,
                                   stdout_dir=args.stdout_dir or workdir)
    except RankLossStructurallyIllegal as e:
        print(f"supervise_fleet: {e}", file=sys.stderr, flush=True)
        return 4
    except RankLossRefused as e:
        print(f"supervise_fleet: {e}", file=sys.stderr, flush=True)
        return 5
    print(f"supervise_fleet: {res.status}: gang_attempts="
          f"{res.gang_attempts} restarts={res.restarts} "
          f"preemptions={res.preemptions} agreed_steps={res.agreed_steps} "
          f"ranks={res.ranks} rcs={res.last_rcs}",
          file=sys.stderr, flush=True)
    if res.status == "ok":
        return 0
    if res.status == "terminated":
        return 143
    if res.status == "wedged":
        return 3
    # Exhausted: forward a rank's own positive rc where one exists.
    # 143 is excluded — that code means "terminated/preempted cleanly"
    # to any outer supervisor honoring the protocol, and an EXHAUSTED
    # fleet whose last attempt happened to contain a preempted rank
    # must not masquerade as one (it would be restarted budget-free
    # forever).  Signal deaths are negative (waitpid convention) and
    # would wrap mod 256 — those, 143s, and an empty rc map all report
    # as a plain crash.
    bad = [rc for rc in res.last_rcs.values()
           if rc is not None and 0 < rc < 256 and rc != 143]
    return bad[0] if bad else 1


if __name__ == "__main__":
    raise SystemExit(main())
