#!/usr/bin/env python
"""heal_drill — measured self-healing drills: inject each fault class,
let the remediation policy engine (resilience/remediate.py) detect and
heal it, and record time-to-detect / time-to-heal / work-lost (must be
zero) as a HEAL_* bench-record family.

  # the full drill battery -> HEAL_lm_cpu_r16.json:
  python tools/heal_drill.py --out HEAL_lm_cpu_r16.json
  # one drill, fast model (CI-sized):
  python tools/heal_drill.py --drill slow_rank --model softmax --out /tmp/h.json

Drills (each a real end-to-end run, CPU-pinned, supervised):

- **slow_rank**: a 2-rank faultline fleet where rank 1 turns persistent
  straggler mid-run; the per-rank EWMA regression + the fleet's
  straggler naming feed the engine, which EVICTS loss-free
  (request_stop → TERM→143→snapshot) and relaunches; the resumed run
  is bitwise the uninterrupted one.
- **nan**: a poisoned batch NaNs the loss (OOV ids for LM models); the
  gang dies (fleet retries=0 — the REMEDIATOR owns the restart
  decision), the post-mortem health file still carries the flag, and
  the engine ROLLS BACK to the pinned last-good snapshot (< fired_step,
  validity-checked) before relaunching.
- **host_loss**: rank 1's host dies (tombstone + SIGKILL); the elastic
  fleet shrinks and completes — the engine's role here is detection
  (ledger ``rank_lost`` rows; quarantine is flap-gated for REPEATED
  offenders) and verifying the survivor lost zero steps.
- **serve_slo**: a burst floods a live lm serving worker past its
  latency target; the engine TIGHTENS admission (``set_slo_ms``) and
  the accepted-work p99 recovers — with every admitted request
  answered.
- **canary**: a candidate snapshot serves a slot fraction
  (serving/promote.Canary) with an injected latency regression; the
  window verdicts ROLLBACK, the canary arm drains to completion, and
  every request id lands exactly once.
- **ckpt**: a D=4 ZeRO-3 run is preempted and its shard-redundant
  snapshot set is damaged post-exit — one mesh-shard's whole directory
  deleted, then separately one payload byte flipped (silent rot); the
  fleet's resume agreement still votes for that step (R=2 quorum
  holds), the relaunch RECONSTRUCTS the shard from its ring mirror —
  the rot is caught by sha256, never restored silently — and the
  finished run is bitwise the uninterrupted one.  Rides along:
  ``ckpt_shard_restore_failures`` / ``ckpt_digest_mismatch_unrecovered``
  must-be-zero rows.

``steps_lost`` is exact: the count of (step, loss) pairs from the
uninterrupted reference run that no healed attempt reproduced bit-for-
bit (a poisoned step's tape entry is superseded by its healthy replay).
MTTD = first ``heal_detect`` ledger row vs the detector's own onset
stamp; MTTR = detect → the healed run's completion.  The serve_slo
drill's MTTD is poll-granularity BY CONSTRUCTION (a scrape-based
detector's onset IS the first breaching observation, so the row reads
~0 — the serving detection latency lives in the scrape cadence, not
this metric; its MTTR line carries the real claim: detect →
accepted-work p99 measurably back under the breach line).  Stdout is
the JSON-lines record; prose on stderr (the bench-record discipline).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

FAULTLINE = os.path.join(_REPO, "tools", "faultline.py")


def _log(msg: str) -> None:
    print(f"heal_drill: {msg}", file=sys.stderr, flush=True)


def _fresh(workdir: str) -> str:
    """Wipe-and-recreate a drill's own subdirectory.  Every drill is a
    MEASUREMENT: a reused workdir would replay the previous run's WAL
    into the guardrail budget, date MTTD from the previous run's
    heal_detect row, resume from its snapshots, and union its JSON
    tails into the steps_lost proof — all silent staleness."""
    import shutil
    if os.path.exists(workdir):
        shutil.rmtree(workdir)
    os.makedirs(workdir)
    return workdir


def _wall() -> float:
    from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
    return obs_metrics._wall()


# --- shared measurement plumbing -------------------------------------------

def _ledger_rows(path: str) -> list[dict]:
    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    rows, _ = obs_ledger.read_rows(path)
    return rows


def _mttd_mttr(ledger_path: str, kinds: tuple, t_healed: float,
               action_events: tuple) -> dict:
    """Timings from the ledger alone (the same rows ``obs_query why``
    renders): onset from the detector's own stamp carried on the
    heal_detect row, detect from that row's write time, heal from the
    drill-observed completion wall time."""
    rows = _ledger_rows(ledger_path)
    detect = next((r for r in rows if r.get("event") == "heal_detect"
                   and r.get("kind") in kinds), None)
    action = next((r for r in rows if r.get("event") in action_events),
                  None)
    if detect is None:
        return {"mttd_ms": None, "mttr_ms": None, "detect_row": None}
    detail = detect.get("detail") or {}
    onset = detail.get("updated_unix") or detail.get("ts") \
        or detect.get("ts")
    mttd = max(0.0, float(detect["ts"]) - float(onset))
    mttr = max(0.0, t_healed - float(detect["ts"]))
    return {"mttd_ms": round(mttd * 1000.0, 1),
            "mttr_ms": round(mttr * 1000.0, 1),
            "detect_kind": detect.get("kind"),
            "action": (action or {}).get("event")}


def steps_lost(straight_losses: list, healed_tapes: list) -> int:
    """(step, loss) pairs of the uninterrupted reference that no healed
    attempt reproduced exactly.  NaN entries never match anything (a
    poisoned step only counts as recovered via its healthy replay)."""
    produced = {(s, l) for tape in healed_tapes for s, l in tape}
    return sum(1 for s, l in straight_losses if (s, l) not in produced)


def _straight_run(workdir: str, model: str, steps: int,
                  seed: int = 0) -> dict:
    """The uninterrupted reference, in-process (warm jit cache)."""
    import contextlib
    import io

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import faultline
    finally:
        sys.path.pop(0)
    _fresh(workdir)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = faultline.main(["--plan", "none", "--steps", str(steps),
                             "--model", model, "--workdir", workdir,
                             "--keep", "50", "--seed", str(seed)])
    assert rc == 0, f"straight reference run failed rc={rc}"
    lines = [l for l in buf.getvalue().splitlines() if l.strip()]
    return json.loads(lines[-1])


def _outs(workdir: str) -> list[dict]:
    """Every rank/attempt JSON tail the drill's placements left."""
    recs = []
    for path in sorted(glob.glob(os.path.join(
            workdir, "out", "launch*", "rank*_attempt*.out"))):
        with open(path) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
        if lines:
            try:
                recs.append(json.loads(lines[-1]))
            except json.JSONDecodeError:
                continue
    return recs


# --- the fleet drill harness -----------------------------------------------

def _fleet_drill(workdir: str, plan: str, steps: int, model: str, *,
                 ranks: int = 2, elastic: bool = False,
                 fleet_retries: int = 0, seed: int = 0,
                 poll_s: float = 0.2, max_heals: int = 2,
                 anomaly_env: dict | None = None,
                 extra_argv: list | None = None) -> dict:
    """Run one faultline gang under full remediation; return the drill
    report (status, heals, ledger path, per-attempt tails)."""
    from distributedtensorflowexample_tpu.resilience import remediate
    from distributedtensorflowexample_tpu.resilience.fleet import (
        FleetSupervisor)
    from distributedtensorflowexample_tpu.resilience.supervisor import (
        Journal, RetryPolicy)

    _fresh(workdir)
    journal = Journal(os.path.join(workdir, "fleet.jsonl"))
    ledger = os.path.join(workdir, "RUNS.jsonl")
    snapshots = os.path.join(workdir, "rank{rank}", "snapshots")
    argv = [sys.executable, FAULTLINE, "--plan", plan,
            "--steps", str(steps), "--model", model,
            "--workdir", os.path.join(workdir, "rank{rank}"),
            "--keep", "50", "--seed", str(seed)] + list(extra_argv or [])

    def make_fleet() -> FleetSupervisor:
        return FleetSupervisor(
            ranks,
            policy=RetryPolicy(retries=fleet_retries,
                               backoff_base_s=0.1, backoff_max_s=0.5),
            journal=journal, kill_grace_s=30.0, poll_s=0.05, seed=seed,
            elastic=elastic, workdir=workdir, ledger_path=ledger)

    target = remediate.FleetTarget()
    rem = remediate.Remediator(
        journal=journal, ledger_path=ledger, scope="drill",
        actuators={
            "evict": remediate.make_evict_actuator(target),
            "rollback": remediate.make_rollback_actuator(
                snapshots, target=target),
            "quarantine": remediate.make_quarantine_actuator(target)},
        guardrails=remediate.Guardrails(flap_n=2, flap_window_s=30.0,
                                        cooldown_s=10.0, budget=4))
    watchers = [
        remediate.HealthWatcher(
            os.path.join(workdir, "health_rank*.json"),
            fleet_health=os.path.join(workdir, "health.json"),
            scope="drill"),
        # rank_lost only — the anomaly mirror rows would double-count
        # the health files' conditions into the flap guardrail.
        remediate.LedgerWatcher(ledger, kinds=("rank_lost",),
                                scope="drill"),
    ]
    env = {"OBS_ANOMALY_WARMUP": "4", "OBS_ANOMALY_Z": "8"}
    env.update(anomaly_env or {})
    t0 = _wall()
    out = remediate.run_remediated(
        make_fleet, argv, rem, watchers, target=target, name="drill",
        snapshot_dir_template=snapshots,
        stdout_dir=os.path.join(workdir, "out"), env_extra=env,
        poll_s=poll_s, max_heals=max_heals)
    out.update(ledger=ledger, t0=t0, t_healed=_wall(),
               actions=rem.guardrails.actions_used,
               outs=_outs(workdir))
    return out


def _fleet_rows(name: str, report: dict, straight: dict, *,
                kinds: tuple, action_events: tuple, model: str,
                final_ranks=None) -> list[dict]:
    timings = _mttd_mttr(report["ledger"], kinds, report["t_healed"],
                         action_events)
    tapes = [[(s, l) for s, l in rec.get("losses", [])]
             for rec in report["outs"]]
    lost = steps_lost(straight["losses"], tapes)
    finals = [rec for rec in report["outs"]
              if rec.get("status") == "ok"
              and rec.get("step") == straight["step"]
              and (final_ranks is None or rec.get("rank") in final_ranks)]
    bitwise = bool(finals) and all(
        rec["digest"] == straight["digest"] for rec in finals)
    if not bitwise:
        _log(f"{name}: WARNING — final digests do not all match the "
             f"straight run ({len(finals)} final record(s))")
    detail = {"platform": "cpu", "model": model, "drill": name,
              "status": report["status"], "heals": report["healed"],
              "actions": report["actions"],
              "bitwise_resume": bitwise,
              "final_records": len(finals), **timings}
    rows = []
    for metric, value, unit in (
            (f"heal_{name}_mttd_ms", timings["mttd_ms"], "ms"),
            (f"heal_{name}_mttr_ms", timings["mttr_ms"], "ms"),
            (f"heal_{name}_steps_lost",
             lost if bitwise else max(lost, 1), "steps")):
        rows.append({"metric": metric, "value": value, "unit": unit,
                     "platform": "cpu", "detail": detail})
    return rows


# --- the five drills -------------------------------------------------------

def drill_slow_rank(base: str, model: str, steps: int = 24,
                    delay_s: float = 2.0) -> list[dict]:
    """Straggler → evict → bitwise resume."""
    _log(f"slow_rank: 2-rank {model}, rank 1 straggles "
         f"{delay_s}s/step from step 8")
    wd = os.path.join(base, "slow_rank")
    report = _fleet_drill(wd, f"slow_rank@8:{delay_s}%1", steps, model,
                          ranks=2)
    straight = _straight_run(os.path.join(base, "straight_slow"),
                             model, steps)
    return _fleet_rows("slow_rank", report, straight,
                       kinds=("step_time_regression", "straggler"),
                       action_events=("heal_evict",), model=model)


def drill_nan(base: str, model: str, steps: int = 12) -> list[dict]:
    """NaN-poison → rollback to pinned last-good → bitwise resume.
    LM models take the corrupt-batch road (garbage ids → OOV poison →
    NaN); float models take nan_loss directly."""
    plan = "corrupt_batch@6" if model.startswith("lm_") else "nan_loss@6"
    _log(f"nan: 1-rank {model}, {plan}; fleet retries=0 — the "
         f"remediator owns the restart decision")
    wd = os.path.join(base, "nan")
    report = _fleet_drill(wd, plan, steps, model, ranks=1)
    straight = _straight_run(os.path.join(base, "straight_nan"),
                             model, steps)
    return _fleet_rows("nan", report, straight,
                       kinds=("nan_loss",),
                       action_events=("heal_rollback",), model=model)


def drill_host_loss(base: str, model: str, steps: int = 16) -> list[dict]:
    """Host loss → elastic shrink (fleet policy) + remediation-layer
    detection; the survivor loses zero steps."""
    _log(f"host_loss: 2-rank elastic {model}, rank 1's host dies at "
         f"step 5 (down forever)")
    wd = os.path.join(base, "host_loss")
    report = _fleet_drill(wd, "host_loss@5:0%1", steps, model,
                          ranks=2, elastic=True, fleet_retries=4)
    straight = _straight_run(os.path.join(base, "straight_host"),
                             model, steps)
    return _fleet_rows("host_loss", report, straight,
                       kinds=("rank_lost",),
                       action_events=("heal_quarantine",), model=model,
                       final_ranks=(0,))


def drill_serve_slo(base: str, size: str = "lm_tiny",
                    breach_ms: float = 250.0,
                    target_ms: float = 150.0) -> list[dict]:
    """Serving p99 breach → admission tightened → accepted-work p99
    recovers, zero admitted requests dropped."""
    from distributedtensorflowexample_tpu.resilience import remediate
    from distributedtensorflowexample_tpu.resilience.supervisor import (
        Journal)
    from distributedtensorflowexample_tpu.serving.engine import (
        DecodeEngine)
    from distributedtensorflowexample_tpu.serving.promote import (
        init_lm_snapshot, promote)
    from distributedtensorflowexample_tpu.serving.queue import (
        ContinuousBatcher, RequestQueue, recent_p99_ms)

    _log(f"serve_slo: {size} burst past p99 {breach_ms}ms → tighten "
         f"admission to {target_ms}ms")
    wd = _fresh(os.path.join(base, "serve_slo"))
    snaps = os.path.join(wd, "snaps")
    init_lm_snapshot(snaps, size)
    pm = promote(snaps, size)
    engine = DecodeEngine(pm.model, pm.params, slots=2, cache_len=48)
    queue = RequestQueue(engine.vocab)
    batcher = ContinuousBatcher(engine, queue, slo_ms=0.0)
    ledger = os.path.join(wd, "RUNS.jsonl")
    rem = remediate.Remediator(
        journal=Journal(os.path.join(wd, "heal.jsonl")),
        ledger_path=ledger, scope="serve",
        actuators={"slo_tighten": remediate.make_slo_actuator(
            lambda: batcher.slo_ms, batcher.set_slo_ms, target_ms)},
        guardrails=remediate.Guardrails(flap_n=2, cooldown_s=5.0,
                                        budget=4))
    watcher = remediate.ServeWatcher(
        lambda: {"p99_ms": recent_p99_ms(batcher.completed, 32),
                 "completed": len(batcher.completed)},
        breach_ms=breach_ms)
    stop = threading.Event()
    t = threading.Thread(target=lambda: batcher.run(stop.is_set),
                         daemon=True)
    t.start()
    reqs = []
    # Phase A: the burst — queue wait drives end-to-end latency over
    # the breach line (admit-everything: slo starts at 0).
    for i in range(48):
        reqs.append(queue.submit([1 + i % 32, 2, 3], max_new=24,
                                 rid=f"burst{i}"))
    healed_at = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        for ev in watcher.poll():
            if rem.observe(ev) == "acted":
                healed_at = _wall()
        if healed_at is not None:
            break
        time.sleep(0.05)
    assert healed_at is not None, "serve_slo drill never breached/healed"
    # Phase B: paced traffic after the heal — the recovery measurement.
    for i in range(16):
        reqs.append(queue.submit([5 + i % 32, 6], max_new=4,
                                 rid=f"paced{i}"))
        time.sleep(0.05)
    for r in reqs:
        r.done.wait(timeout=120)
    stop.set()
    t.join(timeout=60)
    paced = [r for r in batcher.completed if r.rid.startswith("paced")]
    recovered_p99 = recent_p99_ms(paced, 16) or 0.0
    t_recovered = max((r.done_t for r in paced), default=None)
    # Zero admitted-and-lost: every request either completed or was
    # rejected loudly at admission; an admitted one with no outcome is
    # a loss.
    lost = sum(1 for r in reqs
               if r.admit_t is not None and r.outcome != "ok")
    timings = _mttd_mttr(ledger, ("serve_p99_breach",), healed_at,
                         ("heal_slo_tighten",))
    # MTTR for serving = detect → accepted-work p99 measurably back
    # under the breach line (the paced tape), not just the knob flip.
    rows_r = _ledger_rows(ledger)
    detect = next((r for r in rows_r
                   if r.get("event") == "heal_detect"), None)
    mttr = None
    if detect is not None and t_recovered is not None \
            and recovered_p99 <= breach_ms:
        # done_t is monotonic; convert via the shared offset now.
        mttr = round((time.time() - (time.monotonic() - t_recovered)
                      - float(detect["ts"])) * 1000.0, 1)
    detail = {"platform": "cpu", "model": size, "drill": "serve_slo",
              "breach_ms": breach_ms, "target_ms": target_ms,
              "recovered_p99_ms": recovered_p99,
              "completed": len(batcher.completed),
              "slo_rejected": sum(1 for r in batcher.rejected
                                  if r.outcome == "slo_rejected"),
              **timings}
    return [
        {"metric": "heal_serve_slo_mttd_ms", "value": timings["mttd_ms"],
         "unit": "ms", "platform": "cpu", "detail": detail},
        {"metric": "heal_serve_slo_mttr_ms",
         "value": mttr if mttr is not None else timings["mttr_ms"],
         "unit": "ms", "platform": "cpu", "detail": detail},
        {"metric": "heal_serve_slo_requests_lost", "value": lost,
         "unit": "requests", "platform": "cpu", "detail": detail},
    ]


def drill_canary(base: str, size: str = "lm_tiny",
                 n_requests: int = 24) -> list[dict]:
    """Canary promotion with an injected latency regression → window
    verdict ROLLBACK → canary arm drains; every id lands exactly once."""
    from distributedtensorflowexample_tpu.resilience import remediate
    from distributedtensorflowexample_tpu.resilience.supervisor import (
        Journal)
    from distributedtensorflowexample_tpu.serving.engine import (
        DecodeEngine)
    from distributedtensorflowexample_tpu.serving.promote import (
        Canary, init_lm_snapshot, promote)
    from distributedtensorflowexample_tpu.serving.queue import (
        ContinuousBatcher, RequestQueue)

    _log(f"canary: {size} candidate serves a slot fraction with an "
         f"injected latency regression — must roll back without "
         f"dropping a request")
    wd = _fresh(os.path.join(base, "canary"))
    base_snaps = os.path.join(wd, "baseline")
    cand_snaps = os.path.join(wd, "candidate")
    init_lm_snapshot(base_snaps, size, seed=0)
    init_lm_snapshot(cand_snaps, size, seed=1)
    pm_b = promote(base_snaps, size)
    pm_c = promote(cand_snaps, size)

    arms = {}
    for arm, pm, slow in (("baseline", pm_b, 0.0),
                          ("canary", pm_c, 0.15)):
        engine = DecodeEngine(pm.model, pm.params, slots=2, cache_len=32)
        q = RequestQueue(engine.vocab)
        # The injected fault: the candidate's decode boundary pays a
        # delay (a bad quantization, a layout regression) — the
        # slow_rank idiom, serving-side.
        b = ContinuousBatcher(
            engine, q, slo_ms=0.0,
            on_step=(lambda _b: time.sleep(slow)) if slow else None)
        arms[arm] = (q, b)

    canary = Canary(pm_b.step, pm_c.step, fraction=0.5, window=6,
                    p99_ratio=2.0)
    assert canary.admit_candidate(pm_c.params)
    ledger = os.path.join(wd, "RUNS.jsonl")
    rolled: dict = {}
    prompts: dict = {}
    final_reqs: dict = {}

    def canary_rollback(ev):
        """Revert: stop routing to the candidate, RE-ROUTE its queued
        (not-yet-admitted) requests to the baseline arm, and stop the
        canary batcher — its run loop's own drain decodes the in-flight
        slots to completion, so rollback drops nothing: admitted work
        finishes on the canary, queued work re-lands on the baseline."""
        rolled["at"] = _wall()
        pending = arms["canary"][0].drain_pending()
        for req in pending:
            final_reqs[req.rid] = arms["baseline"][0].submit(
                prompts[req.rid], max_new=req.max_new, rid=req.rid)
        stops["canary"].set()
        return {"rerouted": len(pending), **canary.payload()}

    rem = remediate.Remediator(
        journal=Journal(os.path.join(wd, "heal.jsonl")),
        ledger_path=ledger, scope="serve",
        actuators={"canary_rollback": canary_rollback},
        guardrails=remediate.Guardrails(flap_n=1, cooldown_s=5.0,
                                        budget=2))
    stops = {arm: threading.Event() for arm in arms}
    threads = {}
    for arm, (q, b) in arms.items():
        threads[arm] = threading.Thread(
            target=lambda b=b, arm=arm: b.run(stops[arm].is_set),
            daemon=True)
        threads[arm].start()

    # Warm both arms first (one unobserved request each): the first
    # request pays the prefill+decode compiles — seconds against ~ms
    # steady state — and a compile-inflated baseline p99 would mask
    # any canary regression inside the verdict window.
    for arm, (q, _b) in arms.items():
        q.submit([1, 2, 3], max_new=4, rid=f"warm_{arm}").done.wait(
            timeout=120)
    t_first_canary = None
    routed = {}
    for i in range(n_requests):
        rid = f"req{i}"
        arm = canary.route(rid)
        if arm == "canary" and t_first_canary is None:
            t_first_canary = _wall()
        prompts[rid] = [1 + i % 24, 2, 3]
        routed[rid] = arm
        final_reqs[rid] = arms[arm][0].submit(prompts[rid], max_new=4,
                                              rid=rid)
        # Paced offered load: the comparison must measure the ARMS,
        # not self-inflicted queue wait on the healthy baseline.
        time.sleep(0.03)
    verdict = None
    observed: set = set()
    deadline = time.monotonic() + 180
    while verdict is None and time.monotonic() < deadline:
        for rid, r in list(final_reqs.items()):
            if r.done.is_set() and rid not in observed:
                observed.add(rid)
                canary.observe(routed[rid], r.latency_s or 0.0,
                               ok=r.outcome == "ok")
        verdict = canary.verdict()
        time.sleep(0.02)
    assert verdict == "rollback", f"canary verdict {verdict!r}"
    rem.observe(remediate.AnomalyEvent(
        kind="canary_regression", key="canary:rollback", scope="serve",
        source="canary", detail=canary.payload()))
    for rid, r in list(final_reqs.items()):
        r.done.wait(timeout=120)
    for arm in arms:
        stops[arm].set()
        threads[arm].join(timeout=60)
    # Exactly-once: every id's FINAL request object completed ok —
    # canary in-flight finished on the canary arm, re-routed queued
    # ids finished on the baseline.
    lost = sum(1 for r in final_reqs.values() if r.outcome != "ok")
    mttd = (None if t_first_canary is None
            else round((rolled.get("at", t_first_canary)
                        - t_first_canary) * 1000.0, 1))
    t_drained = _wall()
    mttr = (None if "at" not in rolled
            else round((t_drained - rolled["at"]) * 1000.0, 1))
    detail = {"platform": "cpu", "model": size, "drill": "canary",
              "verdict": verdict, "canary": canary.payload(),
              "requests": n_requests}
    return [
        {"metric": "heal_canary_mttd_ms", "value": mttd, "unit": "ms",
         "platform": "cpu", "detail": detail},
        {"metric": "heal_canary_mttr_ms", "value": mttr, "unit": "ms",
         "platform": "cpu", "detail": detail},
        {"metric": "heal_canary_requests_lost", "value": lost,
         "unit": "requests", "platform": "cpu", "detail": detail},
    ]


def _straight_zero3(workdir: str, model: str, steps: int, mesh: int,
                    seed: int = 0) -> dict:
    """The uninterrupted ZeRO-3 reference — a SUBPROCESS, not
    in-process like :func:`_straight_run`: the row layout needs its own
    --mesh virtual CPU devices, pinned before a backend spins up, and
    this process's backend is already a 1-device CPU."""
    import subprocess
    _fresh(workdir)
    out = subprocess.run(
        [sys.executable, FAULTLINE, "--plan", "none",
         "--steps", str(steps), "--model", model, "--workdir", workdir,
         "--keep", "50", "--seed", str(seed),
         "--layout", "zero3", "--mesh", str(mesh)],
        capture_output=True, text=True)
    assert out.returncode == 0, (
        f"straight zero3 reference failed rc={out.returncode}: "
        f"{out.stderr[-800:]}")
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    return json.loads(lines[-1])


def _ckpt_rows(name: str, report: dict, straight: dict, *,
               detect_event: str, model: str) -> list[dict]:
    """Rows for one shard-fault drill.  Detection here is the shard
    store's OWN (the sha256/census check at restore), not a watcher
    poll: onset is the faulted attempt's 143 exit (the post-exit fault
    lands at exit), detect is the first ``detect_event`` ledger row the
    reconstruction wrote, heal is the drill-observed completion."""
    rows_l = _ledger_rows(report["ledger"])
    onset = next((r.get("ts") for r in rows_l
                  if r.get("event") == "run_end"
                  and r.get("rc") == 143), None)
    detect = next((r for r in rows_l
                   if r.get("event") == detect_event), None)
    mttd = mttr = None
    if detect is not None:
        if onset is not None:
            mttd = round(max(0.0, float(detect["ts"]) - float(onset))
                         * 1000.0, 1)
        mttr = round(max(0.0, report["t_healed"] - float(detect["ts"]))
                     * 1000.0, 1)
    tapes = [[(s, l) for s, l in rec.get("losses", [])]
             for rec in report["outs"]]
    lost = steps_lost(straight["losses"], tapes)
    finals = [rec for rec in report["outs"]
              if rec.get("status") == "ok"
              and rec.get("step") == straight["step"]]
    # Same width saver->restorer, so BOTH digests must match: the full
    # row-state one and the width-independent materialized-params one.
    bitwise = bool(finals) and all(
        rec["digest"] == straight["digest"]
        and rec.get("params_digest") == straight.get("params_digest")
        for rec in finals)
    if not bitwise:
        _log(f"{name}: WARNING — final digests do not all match the "
             f"straight run ({len(finals)} final record(s))")
    restore_failures = sum(1 for r in rows_l
                           if r.get("event") == "ckpt_refused")
    mismatches = [r for r in rows_l
                  if r.get("event") == "ckpt_digest_mismatch"]
    rebuilt = {(r.get("step"), r.get("shard")) for r in rows_l
               if r.get("event") == "ckpt_reconstruct"}
    unrecovered = sum(1 for r in mismatches
                      if (r.get("step"), r.get("shard")) not in rebuilt)
    detail = {"platform": "cpu", "model": model, "drill": name,
              "status": report["status"],
              "detect_event": (detect or {}).get("event"),
              "reconstructs": len(rebuilt),
              "bitwise_resume": bitwise,
              "final_records": len(finals),
              "mttd_ms": mttd, "mttr_ms": mttr}
    rows = []
    for metric, value, unit in (
            (f"heal_{name}_mttd_ms", mttd, "ms"),
            (f"heal_{name}_mttr_ms", mttr, "ms"),
            (f"heal_{name}_steps_lost",
             lost if bitwise else max(lost, 1), "steps"),
            ("ckpt_shard_restore_failures", restore_failures, "count"),
            ("ckpt_digest_mismatch_unrecovered", unrecovered, "count")):
        rows.append({"metric": metric, "value": value, "unit": unit,
                     "platform": "cpu", "detail": detail})
    return rows


def drill_ckpt(base: str, model: str = "softmax", steps: int = 12,
               mesh: int = 4) -> list[dict]:
    """Shard-redundant checkpointing: a D=4 ZeRO-3 gang is preempted
    and, after its final save, (a) one mesh-shard's whole snapshot
    directory is deleted, then separately (b) one payload byte of one
    shard is flipped in place.  The fleet's resume agreement still
    votes for that step (quorum holds at R=2), the relaunch
    reconstructs the shard from its ring mirror — detecting the rot by
    sha256, never silently restoring it — and the finished run is
    BITWISE the uninterrupted one.  softmax by default: the row layout
    doesn't care about model size, and the drill stays tier-1 cheap."""
    rows: list[dict] = []
    straight = _straight_zero3(os.path.join(base, "straight_ckpt"),
                               model, steps, mesh)
    zero3 = ["--layout", "zero3", "--mesh", str(mesh)]
    for plan, detect_event in (
            ("shard_loss", "ckpt_reconstruct"),
            ("bitflip", "ckpt_digest_mismatch")):
        _log(f"ckpt: 1-process D={mesh} zero3 {model}, {plan} after "
             f"the final save — mirror reconstruction must be bitwise")
        wd = os.path.join(base, f"ckpt_{plan}")
        report = _fleet_drill(wd, plan, steps, model, ranks=1,
                              extra_argv=zero3)
        rows += _ckpt_rows(f"ckpt_{plan}", report, straight,
                           detect_event=detect_event, model=model)
    return rows


DRILLS = ("slow_rank", "nan", "host_loss", "serve_slo", "canary",
          "ckpt")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--drill", default="all",
                   help=f"one of {DRILLS} or 'all'")
    p.add_argument("--model", default="lm_tiny",
                   choices=["softmax", "mnist_cnn", "lm_tiny"],
                   help="workload for the fleet drills (the serving "
                        "drills always use the lm engine)")
    p.add_argument("--workdir", default="/tmp/heal_drill")
    p.add_argument("--out", default="",
                   help="append the record rows here (JSON lines); "
                        "default stdout only")
    args = p.parse_args(argv)

    import jax
    # Drills must never touch (or wedge on) a real tunnel — same pin as
    # faultline.
    jax.config.update("jax_platforms", "cpu")

    from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
    obs_ledger.maybe_begin("heal_drill", config={"drill": args.drill,
                                                 "model": args.model})
    wanted = DRILLS if args.drill == "all" else tuple(
        d.strip() for d in args.drill.split(","))
    unknown = [d for d in wanted if d not in DRILLS]
    if unknown:
        p.error(f"unknown drill(s) {unknown}; known: {DRILLS}")
    rows: list[dict] = []
    for d in wanted:
        t0 = time.monotonic()
        if d == "slow_rank":
            rows += drill_slow_rank(args.workdir, args.model)
        elif d == "nan":
            rows += drill_nan(args.workdir, args.model)
        elif d == "host_loss":
            rows += drill_host_loss(args.workdir, args.model)
        elif d == "serve_slo":
            rows += drill_serve_slo(args.workdir)
        elif d == "canary":
            rows += drill_canary(args.workdir)
        elif d == "ckpt":
            rows += drill_ckpt(args.workdir)
        _log(f"{d}: done in {time.monotonic() - t0:.1f}s")
    for row in rows:
        print(json.dumps(row, sort_keys=True), flush=True)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            for row in rows:
                f.write(json.dumps(row, sort_keys=True) + "\n")
        os.replace(tmp, args.out)
        _log(f"record written to {args.out}")
    bad = [r for r in rows
           if r["metric"].endswith(("_lost", "_restore_failures",
                                    "_unrecovered"))
           and r["value"] not in (0, 0.0)]
    obs_ledger.end_global(rc=1 if bad else 0)
    if bad:
        _log(f"FAILED must-be-zero invariants: "
             f"{[r['metric'] for r in bad]}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
