#!/usr/bin/env python
"""bench_ratchet — guard the bench trajectory: newest records vs prior
records, self-baselines, armed predictions, and the tier-1 dots floor.

  python tools/bench_ratchet.py                    # scan + verdict
  python tools/bench_ratchet.py --dots 224         # also gate tier-1
  python tools/bench_ratchet.py --raise_floor 224  # ratchet the floor UP

Every round leaves JSON-lines records (``BENCH_*.json``) and ratcheting
self-baselines (``BASELINE_SELF.json``), but until round 10 nothing
COMPARED them: a regression had to be noticed by a human re-reading the
trajectory.  This tool is the missing comparator, with the repo's own
measurement methodology built in (BASELINE_SELF note, DESIGN.md §10):

- **prior-record ratchet** — per (metric, platform), the newest
  non-provisional record against the best prior one.  The shared chip's
  cross-window throughput variance (~10-20x measured in rounds 2-5)
  means a RAW value drop proves nothing, so a drop is only UNEXPLAINED
  (exit 1) when the window-normalized ``vs_roofline`` ratio — the one
  number that survives chip sharing — also regressed, or when neither
  record carries one; never when either measurement is self-noisy
  (``spread_frac`` over its repeats exceeds ``--noise``, the
  obs/anomaly.spread_fraction sentinel bench.py now embeds); and never
  when the newest record's round has a checked-in ``OUTAGE_r<N>.md`` —
  an outage postmortem IS the explanation, already adjudicated (the
  rounds-3-5 degraded-tunnel records stay red forever otherwise).
- **self-baseline check** — newest chip records against the
  BASELINE_SELF per-metric denominators.  Warn-only by default
  (``--strict`` gates): vs_baseline carries window luck by design.
- **armed predictions** — ``armed_predictions_*`` blocks in
  BASELINE_SELF are next-live-window expectations; reported (with any
  matching newer record) so a window that lands without confirming its
  predictions is visible, never silently forgotten.
- **tier-1 dots floor** — ``--dots N`` (the DOTS_PASSED count of the
  current tier-1 run) must not drop below the checked-in floor
  (tests/tier1_floor.json).  ``--raise_floor`` is the only sanctioned
  writer and refuses to lower it — the floor ratchets like the
  baselines do.

Exit codes: 0 ok / explained-only, 1 unexplained regression or floor
violation, 2 usage.  Stdlib-only (plus obs/, itself stdlib-only).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtensorflowexample_tpu.obs.anomaly import (  # noqa: E402
    spread_fraction)

_ROUND_RE = re.compile(r"_r(\d+)")


def _round_of(path: str) -> int:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else -1


def load_records(paths: list[str]) -> list[dict]:
    """All non-provisional record lines, oldest round first.  Torn or
    non-JSON lines are skipped (a SIGKILLed bench leaves them; the
    ratchet reads what survived, like every other postmortem reader).
    A file that yields NO per-line records is retried as one
    pretty-printed JSON document — bench_collectives writes its record
    with ``indent=1``, and a per-line-only parser silently dropped that
    whole family from both the ratchet and the trajectory."""
    records = []

    def _keep(rec, path) -> bool:
        if not isinstance(rec, dict) or "metric" not in rec:
            return False
        detail = rec.get("detail") or {}
        if rec.get("unit") == "unavailable" or detail.get("provisional"):
            return False        # sentinel, not a measurement
        rec["_file"] = os.path.basename(path)
        rec["_round"] = _round_of(path)
        records.append(rec)
        return True

    for path in sorted(paths, key=lambda p: (_round_of(p),
                                             os.path.basename(p))):
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        kept = 0
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            kept += _keep(rec, path)
        if not kept:
            try:
                _keep(json.loads(text), path)
            except json.JSONDecodeError:
                pass
    return records


def _platform(rec: dict) -> str:
    detail = rec.get("detail") or {}
    return str(rec.get("platform") or detail.get("platform") or "chip")


def _spread(rec: dict) -> float:
    detail = rec.get("detail") or {}
    if detail.get("spread_frac") is not None:
        return float(detail["spread_frac"])
    return spread_fraction(detail.get("repeats") or [])


def _vs_roofline(rec: dict) -> float | None:
    v = (rec.get("detail") or {}).get("vs_roofline")
    return float(v) if v is not None else None


def outage_rounds(records_dir: str) -> set:
    """Rounds with a checked-in OUTAGE_r<N>.md postmortem — windows the
    repo has already adjudicated as degraded."""
    return {_round_of(p) for p in
            glob.glob(os.path.join(records_dir, "OUTAGE_r*.md"))} - {-1}


def _lower_is_better(metric: str) -> bool:
    """Latency-family metrics (the serving p50/p99 ``*_ms`` lines, the
    heal family's mttd/mttr) regress UPWARD — the throughput rule
    inverted, or a 26% latency improvement would gate as an
    'unexplained drop' while a real regression sailed through."""
    return metric.endswith("_ms")


def check_zero_invariants(records: list[dict],
                          outages: set = frozenset()) -> list[dict]:
    """Must-be-zero metrics: the heal family's ``*_lost`` lines
    (steps_lost, requests_lost), the serving family's
    ``*_mismatch`` lines (speculative-decode tokens diverging from
    plain greedy), and the checkpoint family's ``*_restore_failures`` /
    ``*_unrecovered`` lines (a shard restore that failed, or rot the
    digest caught but the mirror could not repair).  A nonzero value
    is an UNEXPLAINED finding
    regardless of tolerance or noise — a remediation drill that lost a
    step is a broken resume protocol, and a spec-decode mismatch is a
    broken acceptance rule, not a slow one.  Gated on the NEWEST
    record per (metric, platform) only, with the same OUTAGE_r<N>.md
    adjudication the throughput ratchet honors: a historical nonzero
    that a later round fixed (or a documented degraded window) must
    not stay red forever."""
    series: dict = {}
    for rec in records:
        metric = rec.get("metric", "")
        if metric.endswith(("_lost", "_mismatch", "_violations",
                            "_restore_failures", "_unrecovered")):
            series.setdefault((metric, _platform(rec)), []).append(rec)
    findings = []
    for (metric, platform), recs in sorted(series.items()):
        rec = recs[-1]
        v = rec.get("value")
        if v in (0, 0.0):
            continue
        base = {"metric": metric, "platform": platform,
                "newest": v, "newest_file": rec["_file"],
                "prior": 0, "prior_file": "(invariant)",
                "drop_frac": None}
        if rec["_round"] in outages:
            findings.append({**base, "severity": "explained",
                             "why": f"round {rec['_round']} window is a "
                                    f"documented outage (see OUTAGE_r"
                                    f"{rec['_round']:02d}.md)"})
            continue
        findings.append({**base, "severity": "regression",
                         "why": "must-be-zero invariant: a heal drill "
                                "losing work means the resume protocol "
                                "broke, not that the window was slow"})
    return findings


def compare_records(records: list[dict], tolerance: float,
                    noise: float, outages: set = frozenset()) -> list[dict]:
    """Per (metric, platform): newest record vs the best prior (best =
    highest value, or LOWEST for ``*_ms`` latency metrics).  Returns
    finding dicts with ``severity`` 'regression' (unexplained) or
    'explained' (window variance / noisy measurement) — see module
    docstring for the rule.  ``drop_frac`` is always the worsening
    magnitude, whichever direction that metric worsens in."""
    series: dict = {}
    for rec in records:
        if rec.get("metric", "").endswith(
                ("_lost", "_mismatch", "_violations",
                 "_restore_failures", "_unrecovered")):
            # check_zero_invariants owns the must-be-zero family: here
            # a fixed loss (1 -> 0) would read as a 100% "drop".
            continue
        series.setdefault((rec["metric"], _platform(rec)), []).append(rec)
    findings = []
    for (metric, platform), recs in sorted(series.items()):
        if len(recs) < 2:
            continue
        newest = recs[-1]
        if _lower_is_better(metric):
            prior = min(recs[:-1],
                        key=lambda r: r.get("value") or float("inf"))
            new_v = newest.get("value") or 0.0
            old_v = prior.get("value") or 0.0
            if old_v <= 0 or new_v <= (1.0 + tolerance) * old_v:
                continue
            drop = new_v / old_v - 1.0
        else:
            prior = max(recs[:-1], key=lambda r: r.get("value") or 0.0)
            new_v = newest.get("value") or 0.0
            old_v = prior.get("value") or 0.0
            if old_v <= 0 or new_v >= (1.0 - tolerance) * old_v:
                continue
            drop = 1.0 - new_v / old_v
        base = {"metric": metric, "platform": platform,
                "newest": new_v, "newest_file": newest["_file"],
                "prior": old_v, "prior_file": prior["_file"],
                "drop_frac": round(drop, 4)}
        noisy = [which for which, rec in (("newest", newest),
                                          ("prior", prior))
                 if _spread(rec) > noise]
        vr_new, vr_old = _vs_roofline(newest), _vs_roofline(prior)
        if newest["_round"] in outages:
            findings.append({**base, "severity": "explained",
                             "why": f"round {newest['_round']} window is "
                                    f"a documented outage (see OUTAGE_r"
                                    f"{newest['_round']:02d}.md)"})
        elif noisy:
            findings.append({**base, "severity": "explained",
                             "why": f"{'/'.join(noisy)} measurement "
                                    f"self-noisy (spread > {noise:g}) — "
                                    f"not comparable"})
        elif (vr_new is not None and vr_old is not None
                and vr_new >= (1.0 - tolerance) * vr_old):
            findings.append({**base, "severity": "explained",
                             "why": f"vs_roofline held ({vr_old:g} -> "
                                    f"{vr_new:g}): the raw drop is "
                                    f"cross-window chip variance, not a "
                                    f"code regression"})
        else:
            findings.append({**base, "severity": "regression",
                             "why": ("vs_roofline also regressed "
                                     f"({vr_old:g} -> {vr_new:g})"
                                     if vr_new is not None
                                     and vr_old is not None else
                                     "no same-window roofline on record "
                                     "to explain it")})
    return findings


def compare_baseline(records: list[dict], baselines: dict,
                     tolerance: float,
                     outages: set = frozenset()) -> list[dict]:
    """Newest chip record per metric vs its BASELINE_SELF denominator."""
    newest: dict = {}
    for rec in records:
        if _platform(rec) == "chip":
            newest[rec["metric"]] = rec
    findings = []
    for metric, base in sorted(baselines.items()):
        if not isinstance(base, (int, float)) or metric not in newest:
            continue
        rec = newest[metric]
        if rec["_round"] in outages:
            continue            # adjudicated window; nothing to re-judge
        v = rec.get("value") or 0.0
        if v < (1.0 - tolerance) * base:
            findings.append({
                "metric": metric, "platform": "chip", "severity": "baseline",
                "newest": v, "newest_file": rec["_file"], "prior": base,
                "prior_file": "BASELINE_SELF.json",
                "drop_frac": round(1.0 - v / base, 4),
                "why": "below the ratcheted self-baseline (vs_baseline "
                       "carries window luck — gate with --strict only "
                       "when the window is known-comparable)"})
    return findings


def armed_predictions(baselines: dict, records: list[dict]) -> list[dict]:
    """Report armed_predictions_* blocks with any newer matching record
    — armed expectations stay visible until a window confirms them."""
    by_metric: dict = {}
    for rec in records:
        by_metric[rec["metric"]] = rec             # newest wins
    out = []
    for key, block in sorted(baselines.items()):
        if not key.startswith("armed_predictions"):
            continue
        m = re.search(r"round(\d+)", key)
        armed_round = int(m.group(1)) if m else -1
        confirmations = {
            metric: {"value": rec.get("value"), "file": rec["_file"]}
            for metric, rec in by_metric.items()
            if rec["_round"] > armed_round}
        out.append({"key": key, "armed_round": armed_round,
                    "note": (block or {}).get("note", "")
                    if isinstance(block, dict) else str(block)[:200],
                    "newer_records": confirmations})
    return out


_TRAJECTORY_NAME = "BENCH_trajectory.json"


def _family_of(path: str) -> str:
    """Family = the record filename with round and extension stripped:
    BENCH_lm_cpu_r08.json -> BENCH_lm_cpu, SCALING_r05_sync.json ->
    SCALING_sync, BENCH_r01.json -> BENCH — the stable axis the
    trajectory pivots on."""
    base = os.path.basename(path)
    if base.endswith(".json"):
        base = base[:-5]
    return _ROUND_RE.sub("", base)


def _scaling_metrics(path: str) -> dict:
    """SCALING_* files are per-devices rows, not "metric" records:
    flatten each to ``<n>dev_steps_per_sec`` (plus any real metric
    lines, e.g. the weak-scaling efficiency tail)."""
    metrics: dict = {}
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return metrics
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict):
            continue
        if (rec.get("detail") or {}).get("provisional"):
            continue        # same sentinel rejection as load_records
        if rec.get("metric") and rec.get("unit") != "unavailable":
            metrics[rec["metric"]] = rec.get("value")
        elif rec.get("devices") is not None \
                and rec.get("steps_per_sec") is not None:
            metrics[f"{rec['devices']}dev_steps_per_sec"] = \
                rec["steps_per_sec"]
    return metrics


def build_trajectory(records_dir: str) -> list[dict]:
    """One row per bench family per round — the canonical cross-round
    view of the whole perf trajectory, pivoted out of the 20+ record
    files external tooling otherwise sees as an unreadable pile.
    Deterministic (sorted rows, sorted metric keys, no timestamps): a
    regeneration with unchanged records is byte-identical, so the
    checked-in artifact diffs like code."""
    rows: list[dict] = []
    # SCHED_* is the scheduler's queue-completion record family
    # (tools/schedule.py --record), SERVE_* the serving bench family
    # (bench_serving.py throughput-vs-SLO curves), and HEAL_* the
    # remediation-drill family (tools/heal_drill.py mttd/mttr/
    # steps-lost), and SIM_* the fleet-simulator battery
    # (tools/sim_run.py --battery: queue waits, MTTR tails, and the
    # determinism/steps-lost/WAL must-be-zero invariants at 10k
    # simulated ranks): the same metric-row dialect as the bench
    # families,
    # so the control plane's, the serving path's, and the self-healing
    # layer's numbers ride the same trajectory/ratchet surface as
    # every other measured thing.
    for pattern in ("BENCH_*.json", "SCHED_*.json", "SERVE_*.json",
                    "HEAL_*.json", "SIM_*.json"):
        for path in sorted(glob.glob(os.path.join(records_dir,
                                                  pattern))):
            if os.path.basename(path) == _TRAJECTORY_NAME:
                continue        # never its own source
            recs = load_records([path])
            if not recs:
                continue
            metrics: dict = {}
            platforms: set = set()
            for rec in recs:
                metrics[rec["metric"]] = rec.get("value")
                platforms.add(_platform(rec))
            rows.append({"family": _family_of(path),
                         "round": _round_of(path),
                         "file": os.path.basename(path),
                         "platforms": sorted(platforms),
                         "n_records": len(recs),
                         "metrics": {k: metrics[k]
                                     for k in sorted(metrics)}})
    for path in sorted(glob.glob(os.path.join(records_dir,
                                              "SCALING_*.json"))):
        metrics = _scaling_metrics(path)
        if not metrics:
            continue
        rows.append({"family": _family_of(path),
                     "round": _round_of(path),
                     "file": os.path.basename(path),
                     "platforms": ["cpu"],      # every SCALING record
                     "n_records": len(metrics),
                     "metrics": {k: metrics[k] for k in sorted(metrics)}})
    base_path = os.path.join(records_dir, "BASELINE_SELF.json")
    try:
        with open(base_path) as f:
            baselines = json.load(f)
    except (OSError, json.JSONDecodeError):
        baselines = {}
    numeric = {k: v for k, v in baselines.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    if numeric:
        rows.append({"family": "BASELINE_SELF", "round": None,
                     "file": "BASELINE_SELF.json", "platforms": ["chip"],
                     "n_records": len(numeric),
                     "metrics": {k: numeric[k] for k in sorted(numeric)}})
    rows.sort(key=lambda r: (r["family"],
                             -1 if r["round"] is None else r["round"],
                             r["file"]))
    return rows


def write_trajectory(records_dir: str, out_path: str) -> int:
    rows = build_trajectory(records_dir)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        for row in rows:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    os.replace(tmp, out_path)
    return len(rows)


def check_floor(floor_path: str, dots: int | None,
                raise_to: int | None) -> tuple[list[str], list[str]]:
    """(errors, info).  The floor file is the ratchet's only writable
    artifact, and only UPWARD."""
    errors, info = [], []
    try:
        with open(floor_path) as f:
            payload = json.load(f)
        floor = int(payload["dots_passed_floor"])
    except (OSError, json.JSONDecodeError, KeyError, ValueError) as e:
        return [f"floor file {floor_path} unreadable: {e}"], []
    info.append(f"tier-1 floor: DOTS_PASSED >= {floor} ({floor_path})")
    if dots is not None:
        if dots < floor:
            errors.append(f"tier-1 DOTS_PASSED {dots} dropped below the "
                          f"checked-in floor {floor} — the suite lost "
                          f"tests (or the run lost time); neither is a "
                          f"legal ratchet direction")
        else:
            info.append(f"tier-1 DOTS_PASSED {dots} >= floor {floor}: ok")
    if raise_to is not None:
        if raise_to < floor:
            errors.append(f"--raise_floor {raise_to} < current floor "
                          f"{floor}: the floor only ratchets UP")
        else:
            payload["dots_passed_floor"] = raise_to
            tmp = floor_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1)
                f.write("\n")
            os.replace(tmp, floor_path)
            info.append(f"floor raised {floor} -> {raise_to}")
    return errors, info


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--records_dir", default=_REPO,
                   help="where the BENCH_*.json records live")
    p.add_argument("--glob", default="BENCH_*.json,SERVE_*.json,"
                                     "HEAL_*.json,SIM_*.json",
                   help="comma-separated record patterns the prior-"
                        "record ratchet scans (the serving and heal "
                        "families regress like any bench family; heal "
                        "*_ms metrics gate lower-is-better and *_lost / "
                        "*_mismatch / *_violations / *_restore_failures "
                        "/ *_unrecovered must stay zero)")
    p.add_argument("--baseline", default="",
                   help="BASELINE_SELF.json (default: in records_dir)")
    p.add_argument("--tolerance", type=float, default=0.10,
                   help="fractional drop below which nothing is flagged")
    p.add_argument("--noise", type=float, default=0.25,
                   help="spread_frac above which a measurement is too "
                        "self-noisy to call a regression from")
    p.add_argument("--dots", type=int, default=None,
                   help="this run's tier-1 DOTS_PASSED, gated against "
                        "the floor file")
    p.add_argument("--floor_file",
                   default=os.path.join(_REPO, "tests", "tier1_floor.json"))
    p.add_argument("--raise_floor", type=int, default=None,
                   help="ratchet the floor UP to this value (refuses to "
                        "lower)")
    p.add_argument("--strict", action="store_true",
                   help="self-baseline drops gate too (same-window-"
                        "comparable runs only)")
    p.add_argument("--trajectory", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="also (re)generate the canonical cross-round "
                        "trajectory artifact — one JSON line per bench "
                        "family per round, pivoted from the BENCH_*/"
                        "SCALING_*/BASELINE_SELF records (default PATH: "
                        f"<records_dir>/{_TRAJECTORY_NAME})")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable verdict on stdout")
    args = p.parse_args(argv)

    paths = sorted(p for pat in args.glob.split(",") if pat
                   for p in glob.glob(os.path.join(args.records_dir,
                                                   pat.strip()))
                   if os.path.basename(p) != _TRAJECTORY_NAME)
    records = load_records(paths)
    baseline_path = args.baseline or os.path.join(args.records_dir,
                                                  "BASELINE_SELF.json")
    try:
        with open(baseline_path) as f:
            baselines = json.load(f)
    except (OSError, json.JSONDecodeError):
        baselines = {}

    trajectory_rows = None
    if args.trajectory is not None:
        out_path = args.trajectory or os.path.join(args.records_dir,
                                                   _TRAJECTORY_NAME)
        trajectory_rows = write_trajectory(args.records_dir, out_path)

    outages = outage_rounds(args.records_dir)
    findings = compare_records(records, args.tolerance, args.noise,
                               outages)
    findings += check_zero_invariants(records, outages)
    findings += compare_baseline(records, baselines, args.tolerance,
                                 outages)
    armed = armed_predictions(baselines, records)
    floor_errors, floor_info = check_floor(args.floor_file, args.dots,
                                           args.raise_floor)

    gate = [f for f in findings if f["severity"] == "regression"
            or (args.strict and f["severity"] == "baseline")]
    verdict = {"records": len(records), "files": len(paths),
               "findings": findings, "armed_predictions": armed,
               "floor": {"errors": floor_errors, "info": floor_info},
               "unexplained": len(gate) + len(floor_errors)}
    if trajectory_rows is not None:
        verdict["trajectory_rows"] = trajectory_rows
    if args.as_json:
        json.dump(verdict, sys.stdout, indent=1, default=str)
        print()
    else:
        print(f"bench_ratchet: {len(records)} records in {len(paths)} "
              f"files")
        if trajectory_rows is not None:
            print(f"  [trajectory] {trajectory_rows} family-round rows "
                  f"written")
        for f_ in findings:
            worse = ("invariant violated"
                     if f_["drop_frac"] is None
                     else f"worse by {f_['drop_frac']:.1%}")
            print(f"  [{f_['severity']}] {f_['metric']} ({f_['platform']}):"
                  f" {f_['prior']:g} ({f_['prior_file']}) -> "
                  f"{f_['newest']:g} ({f_['newest_file']}), "
                  f"{worse} — {f_['why']}")
        if not findings:
            print("  no drops beyond tolerance")
        for a in armed:
            newer = (f"{len(a['newer_records'])} newer record(s)"
                     if a["newer_records"] else
                     "NO newer records yet — prediction still open")
            print(f"  [armed] {a['key']} (round {a['armed_round']}): "
                  f"{newer}")
        for line in floor_info:
            print(f"  [floor] {line}")
        for line in floor_errors:
            print(f"  [FLOOR VIOLATION] {line}")
        print(f"bench_ratchet: "
              + ("OK" if not gate and not floor_errors else
                 f"{len(gate) + len(floor_errors)} UNEXPLAINED"))
    return 1 if gate or floor_errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
