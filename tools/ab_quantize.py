"""A/B the headline's resident-split storage on chip (VERDICT r4 #1/#3).

Round 5's first recovery window measured the headline at 477.9
steps/s/chip against a same-window roofline probe of ~1,870 — a ~3.5x
gap the round-2 record (1,681, vs_roofline ~0.94) did not have.  The
ONE headline-path change since that record is the round-4 uint8-resident
split (BASELINE.md "Round-4 core change"), whose predicted win was never
measured.  This harness separates the suspects in a single window:

  off       float32-resident split           (the round-2 path)
  auto      uint8 + LUT gather dequant       (the current default)
  u8_mul    uint8 + convert*(1/255)          (NOT bitwise; isolates the
                                              LUT gather from the u8 row
                                              gather)
  u8_onehot uint8 + one-hot @ LUT matmul     (bitwise-exact: the sum has
                                              exactly one nonzero term;
                                              MXU-friendly gather)

Each variant is the exact headline configuration (mnist_cnn sync, batch
256/chip, deepest unroll) timed with bench.py's own _measure, plus one
shared same-window roofline probe for cross-window calibration.  One
JSON line per variant, flushed as it lands.

Run detached, never under a harness timeout (tools/bench_capture.sh
header explains why):  setsid nohup python tools/ab_quantize.py > AB_quantize_r05.json 2>/tmp/ab_quantize.log &
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # repo-root bench.py: _measure, _roofline_probe, REPEATS


def _emit(obj) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def apply_dequant_onehot(u8, lut):
    """Bitwise-exact LUT lookup as a one-hot matmul: the dot's sum has
    exactly one nonzero term per output element, so the float result is
    the LUT entry itself (no rounding).  Trades the elementwise dynamic
    gather (a shape TPUs lower poorly) for an MXU contraction."""
    import jax
    import jax.numpy as jnp
    oh = jax.nn.one_hot(u8, 256, dtype=lut.dtype)
    if lut.ndim == 1:
        return oh @ lut
    return jnp.einsum("...ck,kc->...c", oh, lut)


def apply_dequant_multiply(u8, lut):
    """NOT bitwise-exact (XLA's reciprocal multiply is ~1 ulp off on
    ~40% of values — device_dataset.make_dequant_lut).  Diagnostic only:
    bounds what exactness costs vs a plain convert+scale."""
    del lut
    import jax.numpy as jnp
    return u8.astype(jnp.float32) / 255.0


def main() -> None:
    unroll_epochs = int(os.environ.get("AB_UNROLL_EPOCHS", "16"))
    calls_per_repeat = int(os.environ.get("AB_CALLS", "2"))
    smoke = os.environ.get("AB_SMOKE") == "1"

    from distributedtensorflowexample_tpu.data import device_dataset as dd
    from distributedtensorflowexample_tpu.parallel import make_mesh

    mesh = make_mesh()
    b = bench.BATCH["cnn"]
    spe = bench.TRAIN_N["mnist"] // (b * mesh.size)
    unroll = unroll_epochs * spe
    if smoke:
        # Wiring check (CPU: JAX_PLATFORMS=cpu): shallow unroll so all
        # four variants trace/execute in minutes.  Rates are
        # meaningless; the point is that every variant builds and runs
        # end to end through the monkeypatch plumbing.
        unroll = 16
    else:
        cost = {}
        probe_rates = bench._roofline_probe(
            mesh, b, length=bench.ROOFLINE_LEN["headline"], cost_out=cost)
        _emit({"metric": "roofline_probe", "repeats": probe_rates,
               "cost_per_step": cost})

    orig_lut = dd.apply_dequant_lut
    variants = {
        "off": ("off", orig_lut),
        "auto": ("auto", orig_lut),
        "u8_mul": ("auto", apply_dequant_multiply),
        "u8_onehot": ("auto", apply_dequant_onehot),
    }
    for name, (qmode, dequant) in variants.items():
        dd.apply_dequant_lut = dequant
        try:
            real_init = dd.DeviceDataset.__init__

            def patched_init(self, *a, **kw):
                kw["quantize"] = qmode
                real_init(self, *a, **kw)

            dd.DeviceDataset.__init__ = patched_init
            try:
                step, ds, state, u = bench._make("mnist_cnn", "mnist", b,
                                                 unroll, mesh)
            finally:
                dd.DeviceDataset.__init__ = real_init
            best, rates, _ = bench._measure(step, ds, state,
                                            calls_per_repeat * unroll, u)
            _emit({"metric": f"headline_{name}_steps_per_sec_per_chip",
                   "value": round(best, 2), "unit": "steps/sec/chip",
                   "detail": {"repeats": rates, "unroll": u,
                              "batch_per_chip": b, "quantize": qmode,
                              "dequant": dequant.__name__}})
        except Exception as e:  # fault-isolate: later variants still run
            _emit({"metric": f"headline_{name}_steps_per_sec_per_chip",
                   "value": 0.0, "unit": "error", "detail": {"error": repr(e)}})
        finally:
            dd.apply_dequant_lut = orig_lut


if __name__ == "__main__":
    main()
