"""The Engine: one replicated-execution front-end (ROADMAP direction 4,
arXiv:1902.00465).

``Engine(spec).run()`` is what every reference trainer's shared runner
used to be — resolve cluster flags → (maybe) jax.distributed.initialize
→ build the mesh → data → model/optimizer/state (sharded at init) →
replication-mode layout passes → hooks → loop → final eval — now owned
by ONE object driven by a declarative
:class:`~distributedtensorflowexample_tpu.engine.spec.RunSpec`.
``Engine(spec).build()`` is the same construction stack cut down to the
bench surface: dataset + state + compiled step, no hooks, no eval, no
checkpoint — what bench.py/bench_lm.py used to hand-wire per knob
config.  Both paths MOVED here from trainers/common.py and the bench
builders with operation order preserved (seed usage, state-creation
order, layout passes), so loss tapes and collective multisets are
bitwise-identical to the pre-engine wiring (tests/test_engine.py pins
this per mode).

The replication strategies themselves still live in parallel/ — the
Engine selects and composes them (spec.MODES declares each mode's
update layout + graftlint HLO contract); the ``engine-owns-wiring``
source rule (analysis/src_lint.py) keeps raw step construction from
leaking back outside these two packages.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from distributedtensorflowexample_tpu import cluster
from distributedtensorflowexample_tpu.config import RunConfig
from distributedtensorflowexample_tpu.data import (
    Batcher, DeviceDataset, DevicePrefetcher, load_cifar10, load_lm,
    load_mnist)
from distributedtensorflowexample_tpu.data.cifar10 import (
    augment as cifar_augment)
from distributedtensorflowexample_tpu.engine.spec import (
    RunSpec, resolve_mode)
from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.parallel import (
    batch_sharding, make_mesh, replicated_sharding)
from distributedtensorflowexample_tpu.parallel.async_ps import (
    consolidate, make_async_train_step, make_indexed_async_train_step,
    make_worker_state)
from distributedtensorflowexample_tpu.parallel.sync import (
    evaluate, make_indexed_train_step, make_resident_eval, make_train_step)
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.training.checkpoint import (
    CheckpointManager)
from distributedtensorflowexample_tpu.training.hooks import (
    CheckpointHook, EvalHook)
from distributedtensorflowexample_tpu.training.loop import TrainLoop
from distributedtensorflowexample_tpu.training.metrics import MetricsLogger
from distributedtensorflowexample_tpu.training.optimizers import (
    build_optimizer)
from distributedtensorflowexample_tpu.training.state import TrainState
from distributedtensorflowexample_tpu.utils.profiling import ProfilerHook

_SAMPLE_SHAPES = {"mnist": (28, 28, 1), "cifar10": (32, 32, 3)}

# Auto --steps_per_loop unroll ceiling.  64 amortizes the ~1.4 ms tunnel
# dispatch latency to <2% of even MNIST-scale step times while keeping
# compiled programs small and hook/log boundaries responsive; the bench's
# much larger sweeps (unroll in the thousands) stay a bench concern.
_AUTO_UNROLL_CAP = 64

# Multi-host preemption consensus cadence in GLOBAL steps: how stale the
# unanimous-stop decision may be.  Tens of steps of detection latency is
# negligible against a preemption grace period, and polling every
# boundary at unroll 1 would add a cross-host sync to every step.
_CONSENSUS_POLL_STEPS = 64


def auto_steps_per_loop(remaining: int, steps_per_epoch: int,
                        cap: int = _AUTO_UNROLL_CAP,
                        intervals: tuple = (), start: int = 0) -> int:
    """The unroll --steps_per_loop=0 selects (VERDICT r4 #4): the largest
    value <= min(cap, steps_per_epoch, remaining) that divides the
    remaining step count, every positive interval in ``intervals``
    (log/eval/checkpoint periods), AND the resumed ``start`` step.
    Dividing the remainder means the default CLI can never trip the
    steps-must-be-a-multiple error a hand-picked value is validated
    against below; dividing the intervals (and the start, since call
    boundaries are ``start + k*d``) means periodic hooks fire ON their
    exact interval marks rather than drifting to the next boundary after
    each mark.  A user asking for --log_every 1 therefore gets genuine
    per-step logging."""
    import math
    g = math.gcd(remaining, start)      # gcd(x, 0) == x: fresh runs free
    for iv in intervals:
        if iv and iv > 0:
            g = math.gcd(g, iv)
    hi = min(cap, steps_per_epoch, remaining)
    for d in range(min(hi, g), 1, -1):
        if g % d == 0:
            return d
    return 1


def _load_dataset(cfg: RunConfig, name: str, split: str):
    """``name`` is the workload's dataset family (shapes, model);
    ``cfg.dataset`` selects the SOURCE: the real bytes (default — missing
    files are a crisp error), or ``synthetic`` as the explicit opt-in to
    the deterministic synthetic split (VERDICT r4 #5: no silent
    substitution on the trainer surface)."""
    if cfg.dataset not in (name, "synthetic"):
        raise ModeRefusal(
            f"--dataset {cfg.dataset!r} does not match this trainer's "
            f"dataset {name!r}; pass --dataset {name} (real bytes in "
            f"--data_dir) or --dataset synthetic")
    source = "synthetic" if cfg.dataset == "synthetic" else "real"
    if name == "mnist":
        return load_mnist(cfg.data_dir, split, seed=cfg.seed, source=source)
    if name == "cifar10":
        return load_cifar10(cfg.data_dir, split, seed=cfg.seed,
                            source=source)
    if name == "lm":
        # Token corpus for the transformer-LM family: both sources
        # resolve to the deterministic synthetic chain (no real-corpus
        # format exists yet — data/lm.py), so no fallback warning fires.
        return load_lm(cfg.data_dir, split, seed=cfg.seed, source=source)
    raise ValueError(f"unknown dataset {name!r}")


def _refuse_incompatible_restore(saved: dict | None, current: dict,
                                 log_dir: str, is_chief: bool) -> None:
    """Named refusal for structurally-incompatible restores (reference
    parity: a Saver restore into a mismatched graph also failed — ours
    names the topology fact instead of an Orbax shape error).  ``saved``
    is None for pre-metadata checkpoints: restore proceeds, Orbax itself
    still catches true layout mismatches."""
    if not saved:
        return
    if saved.get("sync_mode", current["sync_mode"]) != current["sync_mode"]:
        raise ModeRefusal(
            f"checkpoint in {log_dir}/checkpoints was written by a "
            f"sync_mode={saved['sync_mode']!r} run; restoring it into "
            f"sync_mode={current['sync_mode']!r} would mismatch the state "
            f"layout (worker-tiled vs replicated). Use a fresh --log_dir "
            f"or rerun with --sync_mode={saved['sync_mode']}")
    # Pre-PR-6 checkpoints carry no update_layout key; the only layout
    # they can hold is the params-shaped tree — default to that, never
    # to the CURRENT run's layout (which would wave a legacy checkpoint
    # into a bucket_rows run and die on an unnamed Orbax mismatch).
    saved_layout = saved.get("update_layout", "tree")
    if saved_layout != current.get("update_layout"):
        raise ModeRefusal(
            f"checkpoint in {log_dir}/checkpoints holds "
            f"{saved_layout!r} optimizer state; this run uses "
            f"{current['update_layout']!r} (--bucket_grads with "
            f"--shard_update stores per-bucket flat rows instead of the "
            f"params-shaped tree; --shard_params stores the PARAMS as "
            f"rows too — zero3_rows). Resume with the writing run's "
            f"knobs or start fresh with a new --log_dir")
    if (saved_layout.endswith("_rows")
            and saved.get("mesh_size") is not None
            and saved["mesh_size"] != current["mesh_size"]):
        # Bucket rows are a function of D ([D, ceil(n/D)] layout +
        # padding): a different mesh size is at best an unnamed Orbax
        # shape error and at worst — when the padded totals happen to
        # match — a silently PERMUTED momentum (or, for zero3_rows,
        # PARAM) restore.
        raise ModeRefusal(
            f"checkpoint in {log_dir}/checkpoints holds {saved_layout} "
            f"state laid out for mesh_size="
            f"{saved['mesh_size']}; this run has mesh_size="
            f"{current['mesh_size']} — the 1/D row layout is structural. "
            f"Resume on {saved['mesh_size']} devices or start fresh "
            f"with a new --log_dir")
    if (saved.get("num_workers") is not None
            and saved["num_workers"] != current["num_workers"]):
        raise ModeRefusal(
            f"checkpoint in {log_dir}/checkpoints holds async worker-tiled "
            f"state for num_workers={saved['num_workers']}; this run has "
            f"num_workers={current['num_workers']} (mesh size "
            f"{current['mesh_size']}). The leading worker axis is "
            f"structural — resume on {saved['num_workers']} devices or "
            f"start fresh with a new --log_dir")
    if (is_chief and saved.get("mesh_size") is not None
            and saved["mesh_size"] != current["mesh_size"]):
        print(f"note: resuming a mesh_size={saved['mesh_size']} checkpoint "
              f"on mesh_size={current['mesh_size']} (fine for sync mode: "
              f"state is replicated)", flush=True)


def apply_update_layout(state, tx, *, update_layout: str,
                        bucket_bytes=None, mesh=None,
                        shard_update: bool = False):
    """The ONE state re-layout pass every construction path shares
    (trainers, bench builders, serving promotion): take the
    replicated-tree state ``create_sharded`` laid out and re-lay it into
    the mode's working layout, so the step's donation aliases from call
    one.  Returns ``(state, zero3_layout_or_None)``.

    * ``zero3_rows`` — optimizer state FIRST (it reads the full params),
      then the params themselves become 1/D bucket rows; init_rows
      DONATES the replicated tree, so full params stop being resident
      right here.
    * ``bucket_rows`` — optimizer state as per-bucket flat rows (the
      bucketed ZeRO-1 schedule); params stay replicated.
    * ``tree`` + ``shard_update`` — re-lay the optimizer state into the
      GSPMD constraint form's 1/D-per-device sharding (no
      replicated->sharded recompile on call two).
    """
    if update_layout == "zero3_rows":
        from distributedtensorflowexample_tpu.parallel.bucketing import (
            init_bucketed_opt_state)
        from distributedtensorflowexample_tpu.parallel.zero3 import (
            Zero3Layout)
        zero3_layout = Zero3Layout(state.params, bucket_bytes, mesh)
        state = state.replace(opt_state=init_bucketed_opt_state(
            tx, state.params, bucket_bytes, mesh))
        state = state.replace(params=zero3_layout.init_rows(state.params))
        return state, zero3_layout
    if update_layout == "bucket_rows":
        from distributedtensorflowexample_tpu.parallel.bucketing import (
            init_bucketed_opt_state)
        state = state.replace(opt_state=init_bucketed_opt_state(
            tx, state.params, bucket_bytes, mesh))
        return state, None
    if shard_update:
        from distributedtensorflowexample_tpu.training.optimizers import (
            update_shardings)
        state = state.replace(opt_state=jax.device_put(
            state.opt_state, update_shardings(state.opt_state, mesh)))
    return state, None


@dataclasses.dataclass
class EngineBuild:
    """What ``Engine.build`` hands the bench surface: the compiled step
    + its dataset + the laid-out state, plus the resolution facts the
    caller used to recompute by hand."""

    step: object
    ds: object
    state: object
    mesh: object
    unroll: int
    global_batch: int
    num_replicas: int
    mode: str
    bucket_bytes: object = None
    zero3_layout: object = None


class Engine:
    """Runs a :class:`RunSpec`.  ``run()`` is the full supervised
    training surface (hooks, checkpoints, telemetry, preemption);
    ``build()`` is the bench surface (step + data + state only);
    ``describe()`` resolves the declaration without compiling anything.
    """

    def __init__(self, spec: RunSpec):
        self.spec = spec

    # --- the declarative seams (RunSpec callables or the registries) ---

    def _model(self, cfg: RunConfig):
        if self.spec.model_fn is not None:
            return self.spec.model_fn(cfg)
        return build_model(self.spec.model, dropout=cfg.dropout,
                           dtype=jnp.dtype(cfg.dtype), remat=cfg.remat)

    def _optimizer(self, cfg: RunConfig, mesh, wrap_shard_update: bool):
        if self.spec.optimizer_fn is not None:
            return self.spec.optimizer_fn(cfg, mesh, wrap_shard_update)
        return build_optimizer(cfg, mesh=mesh,
                               wrap_shard_update=wrap_shard_update)

    def _input(self, cfg: RunConfig, split: str):
        if self.spec.input_fn is not None:
            return self.spec.input_fn(cfg, split)
        return _load_dataset(cfg, self.spec.dataset, split)

    # --- knob resolution (the exact cascade run_training applied) ------

    def _resolve_flags(self, cfg: RunConfig, num_replicas: int):
        """Pure flag validation BEFORE data loading: a bogus flag should
        fail by name, not after (or instead of) a multi-second dataset
        read.  Returns ``(bucket_bytes, zero3_on, bucket_zero1)``."""
        if cfg.sync_mode == "async" and cfg.fused_optimizer:
            # The async step vmaps the optimizer apply over virtual
            # workers; a pallas_call has no batching rule XLA can
            # partition over the worker-sharded axis. (The Pallas CE
            # head IS supported in async — it runs on the flattened
            # batch outside the vmap.)
            raise ModeRefusal(
                "--fused_optimizer is not supported with sync_mode=async")
        if cfg.device_data not in ("auto", "on", "off"):
            raise ValueError(f"unknown device_data {cfg.device_data!r}")
        # Token datasets (the transformer-LM family) are integer splits:
        # the host Batcher/prefetch path is a float-image pipeline whose
        # uint8 convention means "quantized pixels" — dequantizing ids
        # to floats would silently train on garbage, so the off-path is
        # refused by name instead.
        if self.spec.resolved_token_data() and cfg.device_data == "off":
            raise ModeRefusal(
                "the lm dataset is an integer token split and runs on the "
                "device-resident input path only; --device_data off selects "
                "the host float-image Batcher, which would dequantize token "
                "ids into pixels. Drop --device_data off")
        if cfg.sync_mode not in ("sync", "async"):
            raise ValueError(f"unknown sync_mode {cfg.sync_mode!r}")
        if cfg.data_sharding not in ("replicated", "sharded"):
            raise ValueError(f"unknown data_sharding {cfg.data_sharding!r}")
        if cfg.data_sharding == "sharded" and cfg.device_data == "off":
            raise ModeRefusal("--data_sharding sharded requires the "
                             "device-resident input path (device_data)")
        from distributedtensorflowexample_tpu.data.device_dataset import (
            DEQUANT_IMPLS)
        if cfg.dequant_impl not in DEQUANT_IMPLS:
            raise ValueError(f"unknown dequant_impl {cfg.dequant_impl!r} "
                             f"(one of {DEQUANT_IMPLS})")
        if cfg.dequant_impl == "pallas" and (cfg.device_data == "off"
                                             or cfg.data_sharding
                                             == "sharded"):
            raise ModeRefusal("--dequant_impl pallas fuses the on-device "
                             "row gather with the dequant; it requires the "
                             "replicated device-resident input path")
        if cfg.shard_update and cfg.sync_mode == "async":
            raise ModeRefusal(
                "--shard_update shards ONE replicated update across the "
                "mesh; async mode's state is already worker-tiled (each "
                "device owns its workers' whole update) — there is no "
                "cross-replica redundancy to shard away")
        from distributedtensorflowexample_tpu.parallel.bucketing import (
            resolve_bucket_bytes)
        bucket_bytes = resolve_bucket_bytes(cfg.bucket_grads)  # by name
        if bucket_bytes and cfg.fused_optimizer:
            raise ModeRefusal(
                "--bucket_grads restructures the gradient reduction around "
                "the optimizer apply; the Pallas fused apply is a custom "
                "call with its own layout contract — use one or the other")
        if cfg.shard_params and cfg.sync_mode != "sync":
            raise ModeRefusal(
                "--shard_params shards the sync data-parallel step's "
                "params across the mesh; async mode's state is "
                "worker-tiled (each device already owns its workers' "
                "whole copy) — there is no cross-replica redundancy to "
                "shard away")
        if cfg.shard_params and not bucket_bytes:
            raise ModeRefusal(
                "--shard_params lays params out in the knee-sized "
                "dtype-homogeneous bucket rows; pass --bucket_grads (auto, "
                "or a byte cap) to size them")
        # ZeRO-3 (--shard_params, parallel/zero3.py) subsumes the ZeRO-1
        # bucket schedule: params, grads AND optimizer state all live as
        # 1/D bucket rows.  On a 1-device mesh there is nothing to shard
        # and the plain step is used as-is (same fall-through as ZeRO-1
        # below).
        zero3_on = cfg.shard_params and bool(bucket_bytes) \
            and num_replicas > 1 and cfg.sync_mode == "sync"
        # The explicit per-bucket ZeRO-1 schedule replaces the GSPMD
        # constraint form of --shard_update (see parallel/bucketing.py);
        # on a 1-device mesh there is nothing to reduce and the plain
        # step (with the constraint wrapper's 1-extent no-op) is used
        # as-is.
        bucket_zero1 = bool(bucket_bytes) and cfg.shard_update \
            and num_replicas > 1 and cfg.sync_mode == "sync" \
            and not zero3_on
        return bucket_bytes, zero3_on, bucket_zero1

    # --- the declaration, resolved without compiling anything ----------

    def describe(self, sample_shape: tuple | None = None) -> dict:
        """What this spec RESOLVES to — mode, update layout, declared
        HLO contract, hook stack — without building a mesh or compiling
        a step.  With ``sample_shape``, also the abstract TrainState
        (``jax.eval_shape`` over state creation: zero FLOPs), which is
        what tests pin a workload's full surface against."""
        cfg = self.spec.config
        num_replicas = cfg.num_devices or jax.device_count()
        bucket_bytes, zero3_on, bucket_zero1 = self._resolve_flags(
            cfg, num_replicas)
        decl = resolve_mode(cfg, num_replicas)
        hooks = []
        if cfg.checkpoint_every > 0 or cfg.resume:
            if cfg.checkpoint_every > 0:
                hooks.append("CheckpointHook")
        if os.environ.get("SNAPSHOT_DIR", "") and (zero3_on or bucket_zero1):
            hooks.append("ShardSnapshotHook")
        if cfg.eval_every > 0:
            hooks.append("EvalHook")
        if cfg.profile_dir:
            hooks.append("ProfilerHook")
        if os.environ.get("SUPERVISE_HEARTBEAT", ""):
            hooks.append("HeartbeatHook")
        hooks += ["MetricsHook", "AnomalyHook"]
        out = {
            "entrypoint": f"trainer:{self.spec.model}",
            "mode": decl.name,
            "update_layout": ("zero3_rows" if zero3_on else
                              "bucket_rows" if bucket_zero1 else "tree"),
            "contract": decl.contract,
            "bucket_bytes": bucket_bytes,
            "mesh_size": num_replicas,
            "token_data": self.spec.resolved_token_data(),
            "checkpointing": cfg.checkpoint_every > 0 or cfg.resume,
            "hooks": hooks,
        }
        if sample_shape is not None:
            model = self._model(cfg)
            tx = self._optimizer(cfg, None, wrap_shard_update=False)
            dtype = jnp.int32 if out["token_data"] else jnp.float32
            out["abstract_state"] = jax.eval_shape(
                functools.partial(TrainState.create, model, tx,
                                  seed=cfg.seed),
                jax.ShapeDtypeStruct(tuple(sample_shape), dtype))
        return out

    # --- the bench surface ---------------------------------------------

    def build(self, mesh=None, unroll: int = 1) -> EngineBuild:
        """Dataset + laid-out state + compiled step for one knob config
        — the construction stack bench.py/bench_lm.py used to hand-wire,
        with no hooks, no eval, no checkpointing (the harness measures
        the step, the trainer surface supervises it).  Train split only;
        ``unroll`` is the lax.scan fusion the bench sweeps."""
        cfg = self.spec.config
        if mesh is None:
            mesh = make_mesh(cfg.num_devices)
        num_replicas = mesh.size
        bucket_bytes, zero3_on, bucket_zero1 = self._resolve_flags(
            cfg, num_replicas)
        global_batch = (cfg.batch_size if cfg.global_batch
                        else cfg.batch_size * num_replicas)
        if global_batch % num_replicas:
            raise ValueError(f"global batch {global_batch} not divisible "
                             f"by {num_replicas} replicas")
        token_data = self.spec.resolved_token_data()
        train_x, train_y = self._input(cfg, "train")
        ds = DeviceDataset(train_x, train_y, global_batch, mesh=mesh,
                           seed=cfg.seed, steps_per_next=unroll,
                           quantize=cfg.quantize,
                           dequant_impl=cfg.dequant_impl,
                           data_sharding=cfg.data_sharding,
                           token_data=token_data)
        model = self._model(cfg)
        tx = self._optimizer(cfg, mesh,
                             wrap_shard_update=not (bucket_zero1
                                                    or zero3_on))
        sample_shape = (global_batch,) + tuple(train_x.shape[1:])
        state = TrainState.create_sharded(model, tx, sample_shape,
                                          cfg.seed,
                                          replicated_sharding(mesh))
        state, zero3_layout = apply_update_layout(
            state, tx,
            update_layout=("zero3_rows" if zero3_on else
                           "bucket_rows" if bucket_zero1 else "tree"),
            bucket_bytes=bucket_bytes, mesh=mesh,
            shard_update=cfg.shard_update)
        ce_impl = "pallas" if cfg.pallas_ce else "xla"
        device_augment = "cifar" if self.spec.augment else "none"
        if cfg.sync_mode == "async":
            state = make_worker_state(state, num_replicas, mesh)
            step = make_indexed_async_train_step(
                num_replicas, cfg.async_period, global_batch,
                ds.steps_per_epoch, cfg.label_smoothing, ce_impl=ce_impl,
                mesh=mesh, unroll_steps=unroll, augment=device_augment,
                num_slots=ds.num_slots, data_sharding=cfg.data_sharding,
                dequant_impl=cfg.dequant_impl, bucket_bytes=bucket_bytes)
        else:
            step = make_indexed_train_step(
                global_batch, ds.steps_per_epoch, cfg.label_smoothing,
                ce_impl=ce_impl, mesh=mesh, unroll_steps=unroll,
                augment=device_augment, num_replicas=num_replicas,
                replicas_to_aggregate=cfg.replicas_to_aggregate,
                num_slots=ds.num_slots, data_sharding=cfg.data_sharding,
                dequant_impl=cfg.dequant_impl, bucket_bytes=bucket_bytes,
                bucket_shard_update=bucket_zero1,
                zero3_layout=zero3_layout,
                zero3_overlap=cfg.zero3_overlap)
        return EngineBuild(
            step=step, ds=ds, state=state, mesh=mesh, unroll=unroll,
            global_batch=global_batch, num_replicas=num_replicas,
            mode=resolve_mode(cfg, num_replicas).name,
            bucket_bytes=bucket_bytes, zero3_layout=zero3_layout)

    # --- the full trainer surface --------------------------------------

    def run(self) -> dict:
        """Train per the spec; returns a summary dict (used by tests and
        bench).  This IS the shared trainer runner's flow, moved — every
        operation in its original order."""
        spec = self.spec
        cfg: RunConfig = spec.config
        model_name, dataset_name = spec.model, spec.dataset
        augment = spec.augment
        info = cluster.resolve(cfg)
        if info.role == "ps":
            print(cluster.PS_NOTICE, flush=True)
            return {"role": "ps", "exited": True}
        cluster.maybe_initialize_distributed(info)
        if info.is_distributed:
            # Rank-labeled telemetry: every obs surface (flight filename,
            # span context — obs/recorder.py, obs/trace.py) reads
            # OBS_RANK.  The fleet supervisor exports it at spawn; a
            # hand-launched worker gets it here from its resolved cluster
            # identity, so two ranks' flight files can never collide on
            # pid alone.
            os.environ.setdefault("OBS_RANK", str(info.process_id))

        mesh = make_mesh(cfg.num_devices)
        if jax.process_count() > 1:
            # Every later decision with a collective in it — loop length,
            # unroll, eval/checkpoint cadence, the SHARED checkpoint
            # directory (divergent paths split-brain Orbax's
            # collective-save barriers and WEDGE the first save —
            # observed), the stop consensus — assumes the processes were
            # launched with the same flags.  Verify once, up front,
            # unconditionally (a guard gated on per-process config would
            # itself be a mismatched collective), and fail by name
            # instead of hanging later.  Per-process-legitimate fields
            # (cluster identity, local data / profile paths) are
            # excluded.
            import zlib

            from jax.experimental import multihost_utils
            per_process = {"job_name", "task_index", "process_id",
                           "ps_hosts", "worker_hosts",
                           "coordinator_address", "num_processes",
                           "data_dir", "profile_dir"}
            if not (cfg.checkpoint_every > 0 or cfg.resume):
                # Without checkpointing there is no collective touching
                # the path — per-worker scratch log dirs are legitimate
                # (the reference's workers logged locally).  Enablement
                # itself is in the digest, so divergent enablement still
                # errors.
                per_process = per_process | {"log_dir"}
            blob = repr(sorted(
                (k, v) for k, v in dataclasses.asdict(cfg).items()
                if k not in per_process)).encode()
            digests = multihost_utils.process_allgather(
                np.uint32(zlib.crc32(blob)))
            if len({int(d) for d in digests}) > 1:
                raise ModeRefusal(
                    f"run configuration differs across the "
                    f"{jax.process_count()} processes (config digests "
                    f"{sorted({int(d) for d in digests})}). Collective "
                    "decisions (train_steps, steps_per_loop, "
                    "eval/checkpoint cadence, the shared --log_dir) must "
                    "agree on every process — launch all workers with "
                    "identical flags (only cluster identity, --data_dir "
                    "and --profile_dir may differ)")
        num_replicas = mesh.size
        global_batch = (cfg.batch_size if cfg.global_batch
                        else cfg.batch_size * num_replicas)
        if global_batch % num_replicas:
            raise ValueError(f"global batch {global_batch} not divisible "
                             f"by {num_replicas} replicas")

        token_data = spec.resolved_token_data()
        bucket_bytes, zero3_on, bucket_zero1 = self._resolve_flags(
            cfg, num_replicas)

        train_x, train_y = self._input(cfg, "train")
        test_x, test_y = self._input(cfg, "test")
        data_shard = batch_sharding(mesh)
        repl = replicated_sharding(mesh)

        # Device-resident input path (data/device_dataset.py): the split
        # lives in HBM and batches are gathered on device — no per-step
        # H2D copy.  "auto" (the default) uses it in both sync and async
        # modes; augmentation runs on device (data/augment_device.py).
        use_device_data = cfg.device_data != "off"
        if not use_device_data:
            batcher = Batcher(train_x, train_y, global_batch,
                              seed=cfg.seed,
                              process_index=jax.process_index(),
                              process_count=jax.process_count(),
                              augment_fn=cifar_augment if augment else None,
                              quantize=cfg.quantize)
            # eval/train symmetry: the resident eval below resolves the
            # SAME --dequant_impl; the host-fed steps resolve it in
            # dequant_host_batch.
            batches = DevicePrefetcher(batcher, sharding=data_shard)

        model = self._model(cfg)
        tx = self._optimizer(cfg, mesh,
                             wrap_shard_update=not (bucket_zero1
                                                    or zero3_on))
        # Sample shape comes from the loaded split itself (images:
        # [N,H,W,C], tokens: [N,T]) — _SAMPLE_SHAPES stays as
        # documentation of the image families' shapes.
        sample_shape = (global_batch,) + tuple(train_x.shape[1:])
        state = TrainState.create_sharded(model, tx, sample_shape,
                                          cfg.seed, repl)
        if bucket_bytes and cfg.sync_mode == "sync" and num_replicas > 1 \
                and state.batch_stats:
            raise ModeRefusal(
                f"--bucket_grads cannot run {model_name!r}: its BatchNorm "
                f"computes global-batch statistics, which the bucketed "
                f"per-shard gradient region would silently turn into "
                f"per-shard statistics (a different model, not a "
                f"different collective schedule). Use the default fused "
                f"all-reduce for BatchNorm models")
        update_layout = ("zero3_rows" if zero3_on else
                         "bucket_rows" if bucket_zero1 else "tree")
        snap_dir = os.environ.get("SNAPSHOT_DIR", "")
        shard_store = None
        if snap_dir and update_layout != "tree":
            # Shard-redundant row-layout snapshots (resilience/
            # shardstore.py): per-rank 1/D shard files + ring mirrors
            # under a sha256 quorum manifest.  The layout facts come
            # from the TREE params — they are what the manifest records,
            # and they are D-independent, which is what makes the
            # elastic restore below legal.
            from distributedtensorflowexample_tpu.resilience.shardstore \
                import ShardLayout, ShardSnapshotHook, ShardStore
            shard_store = ShardStore(
                snap_dir,
                layout=ShardLayout.for_params(update_layout, bucket_bytes,
                                              state.params, num_replicas),
                keep=cfg.keep_checkpoints)
        restored_from_shards = False
        if shard_store is not None and cfg.resume \
                and shard_store.latest_valid() is not None:
            # The engine-integrated ELASTIC restore: a quorum-valid
            # shard set written at ANY mesh width regroups onto this
            # one THROUGH the same apply_update_layout pass the
            # non-resume path runs — bitwise (tests/test_checkpoint.py).
            # The by-name cross-width refusal in
            # _refuse_incompatible_restore still guards the Orbax path,
            # where no regroup exists.
            state, shard_aux = shard_store.restore_elastic(
                state, tx, mesh=mesh)
            zero3_layout = shard_aux["zero3_layout"]
            restored_from_shards = True
            if jax.process_index() == 0:
                print(f"resumed from shard set at step "
                      f"{shard_aux['step']} (written at "
                      f"D={shard_aux['from_ranks']}, this mesh is "
                      f"D={num_replicas})", flush=True)
        else:
            state, zero3_layout = apply_update_layout(
                state, tx, update_layout=update_layout,
                bucket_bytes=bucket_bytes, mesh=mesh,
                shard_update=cfg.shard_update)

        is_async = cfg.sync_mode == "async"
        if is_async and cfg.replicas_to_aggregate:
            raise ModeRefusal(
                "--replicas_to_aggregate is a SyncReplicasOptimizer "
                "(sync-mode) concept; async mode has no aggregation "
                "barrier to relax")
        if is_async:
            # Local-SGD emulation of the reference's async-PS staleness:
            # one virtual worker per device, averaged every
            # --async_period steps.
            state = make_worker_state(state, num_replicas, mesh)

        is_chief = info.is_chief and jax.process_index() == 0
        logger = MetricsLogger(cfg.log_dir, num_chips=num_replicas,
                               is_chief=is_chief, log_every=cfg.log_every)
        hooks = []
        manager = None
        # Topology facts of THIS run, persisted next to the checkpoints
        # so a later resume can be refused by name instead of dying on an
        # Orbax shape mismatch (async state is worker-tiled: leading axis
        # = num_workers, so worker count is structural; sync state is
        # replicated and restores fine across mesh sizes — recorded but
        # not refused).
        run_meta = {"sync_mode": cfg.sync_mode, "mesh_size": num_replicas,
                    "num_workers": num_replicas if is_async else None,
                    # bucket_rows: optimizer state stored as per-bucket
                    # flat 1/D rows (the bucketed ZeRO-1 schedule);
                    # zero3_rows: params AND optimizer state stored as
                    # rows (ZeRO-3) — both structurally different from
                    # the params-shaped tree layout, so a cross-layout
                    # resume must be refused by name.
                    "update_layout": ("zero3_rows" if zero3_on else
                                      "bucket_rows" if bucket_zero1 else
                                      "tree")}
        if cfg.checkpoint_every > 0 or cfg.resume:
            manager = CheckpointManager(f"{cfg.log_dir}/checkpoints",
                                        max_to_keep=cfg.keep_checkpoints,
                                        async_save=cfg.async_checkpoint,
                                        run_metadata=run_meta)
            if cfg.resume and not restored_from_shards \
                    and manager.latest_step() is not None:
                _refuse_incompatible_restore(manager.saved_run_metadata(),
                                             run_meta, cfg.log_dir,
                                             is_chief)
                state = manager.restore(state)
                if is_chief:
                    print(f"resumed from checkpoint at step "
                          f"{int(state.step)}", flush=True)
            if cfg.checkpoint_every > 0:
                hooks.append(CheckpointHook(manager, cfg.checkpoint_every))
        if shard_store is not None:
            # Rides next to (not instead of) the Orbax hook: the shard
            # set is what the fleet's resume agreement and the elastic
            # restore read.
            hooks.append(ShardSnapshotHook(shard_store,
                                           every=max(1,
                                                     cfg.checkpoint_every),
                                           cursor={"seed": cfg.seed}))

        # Eval batch must divide across the mesh like the train batch
        # does.
        eval_batch = max(global_batch,
                         (1000 // num_replicas) * num_replicas
                         or num_replicas)
        if use_device_data:
            # Test split resident in HBM too: one dispatch per eval, and
            # eval wall time stops polluting the training window.
            _evaluate = make_resident_eval(test_x, test_y,
                                           batch_size=eval_batch,
                                           mesh=mesh, quantize=cfg.quantize,
                                           dequant_impl=cfg.dequant_impl,
                                           token_data=token_data)
        else:
            _evaluate = functools.partial(evaluate, images=test_x,
                                          labels=test_y,
                                          batch_size=eval_batch,
                                          sharding=data_shard)
        if zero3_on:
            # Eval consumes the full tree; gather the 1/D rows back once
            # per eval (jitted+cached per layout — a transient full copy,
            # like the forward's own gathered temporaries).
            _row_eval = _evaluate
            _evaluate = lambda s: _row_eval(
                s.replace(params=zero3_layout.materialize(s.params)))
        # Async state carries per-worker copies; eval on their average.
        eval_fn = ((lambda s: _evaluate(consolidate(s)))
                   if is_async else _evaluate)
        if cfg.eval_every > 0:
            hooks.append(EvalHook(eval_fn, cfg.eval_every, logger))
        if cfg.profile_dir:
            hooks.append(ProfilerHook(cfg.profile_dir,
                                      cfg.profile_start_step,
                                      cfg.profile_num_steps))

        ce_impl = "pallas" if cfg.pallas_ce else "xla"
        device_augment = "cifar" if augment else "none"
        steps_per_call = 1
        ds = None
        if use_device_data:
            remaining = cfg.train_steps - int(state.step)
            if cfg.steps_per_loop == 0:
                # Auto (the default): out of the box the shipped CLI
                # fuses multiple steps per dispatch like the bench does,
                # instead of paying the ~1.4 ms/step dispatch tax at
                # unroll 1.
                steps_per_call = (auto_steps_per_loop(
                    remaining, len(train_x) // global_batch,
                    intervals=(cfg.log_every, cfg.eval_every,
                               cfg.checkpoint_every),
                    start=int(state.step))
                    if remaining > 0 else 1)
                if steps_per_call > 1 and is_chief:
                    # Say what the default chose: the user sees logs
                    # arrive in strides and should know why (and how to
                    # opt out).
                    print(f"steps_per_loop auto: fusing {steps_per_call} "
                          f"steps per dispatch (--steps_per_loop 1 for "
                          f"per-step dispatch)", flush=True)
            else:
                steps_per_call = max(1, cfg.steps_per_loop)
                if remaining > 0 and remaining % steps_per_call:
                    # The loop advances in steps_per_call strides; a
                    # non-multiple remainder would silently under-run the
                    # target step count.
                    raise ModeRefusal(
                        f"remaining steps {remaining} (train_steps "
                        f"{cfg.train_steps} - resumed step "
                        f"{int(state.step)}) must be a multiple of "
                        f"--steps_per_loop {steps_per_call}")
            # Constructed after a possible resume so epoch slots line up
            # with the restored global step.
            ds = DeviceDataset(train_x, train_y, global_batch, mesh=mesh,
                               seed=cfg.seed, start_step=int(state.step),
                               steps_per_next=steps_per_call,
                               quantize=cfg.quantize,
                               dequant_impl=cfg.dequant_impl,
                               data_sharding=cfg.data_sharding,
                               token_data=token_data)
            batches = ds
        elif cfg.steps_per_loop > 1:
            raise ModeRefusal("--steps_per_loop > 1 requires the "
                             "device-resident input path (device_data)")

        if is_async and use_device_data:
            train_step = make_indexed_async_train_step(
                num_replicas, cfg.async_period, global_batch,
                ds.steps_per_epoch, cfg.label_smoothing, ce_impl=ce_impl,
                mesh=mesh, unroll_steps=steps_per_call,
                augment=device_augment, num_slots=ds.num_slots,
                data_sharding=cfg.data_sharding,
                dequant_impl=cfg.dequant_impl, bucket_bytes=bucket_bytes)
        elif is_async:
            train_step = make_async_train_step(num_replicas,
                                               cfg.async_period,
                                               cfg.label_smoothing,
                                               ce_impl=ce_impl, mesh=mesh,
                                               dequant=batcher.dequant,
                                               dequant_impl=cfg.dequant_impl,
                                               quantize=cfg.quantize,
                                               bucket_bytes=bucket_bytes)
        elif use_device_data:
            train_step = make_indexed_train_step(
                global_batch, ds.steps_per_epoch, cfg.label_smoothing,
                ce_impl=ce_impl, mesh=mesh, unroll_steps=steps_per_call,
                augment=device_augment, num_replicas=num_replicas,
                replicas_to_aggregate=cfg.replicas_to_aggregate,
                num_slots=ds.num_slots, data_sharding=cfg.data_sharding,
                dequant_impl=cfg.dequant_impl, bucket_bytes=bucket_bytes,
                bucket_shard_update=bucket_zero1,
                zero3_layout=zero3_layout,
                zero3_overlap=cfg.zero3_overlap)
        else:
            train_step = make_train_step(
                cfg.label_smoothing, ce_impl=ce_impl, mesh=mesh,
                num_replicas=num_replicas,
                replicas_to_aggregate=cfg.replicas_to_aggregate,
                dequant=batcher.dequant, dequant_impl=cfg.dequant_impl,
                quantize=cfg.quantize, bucket_bytes=bucket_bytes,
                bucket_shard_update=bucket_zero1,
                zero3_layout=zero3_layout,
                zero3_overlap=cfg.zero3_overlap)
        # Preemption safety (TPU-first failure recovery, SURVEY §5): the
        # platform sends SIGTERM before reclaiming a slice/VM.  The
        # handler only SETS A FLAG — the loop polls it at call boundaries
        # and stops cleanly (end hooks run, final checkpoint written),
        # then the process exits 143 so a restarted job auto-resumes
        # (--resume default) from the last completed step.  Raising from
        # the handler instead is unsafe: the step donates its input
        # state, and an exception landing mid-call leaves deleted buffers
        # (see TrainLoop).
        from distributedtensorflowexample_tpu.utils.signals import (
            sigterm_flag)

        stop_agreed = []
        preempted = None    # bound by the sigterm_flag context below

        if jax.process_count() > 1:
            # Multi-host: the stop decision must be UNANIMOUS at the SAME
            # call boundary — a lone process breaking out would leave the
            # others blocked in the next step's gradient psum until the
            # SIGKILL, and the collective Orbax save requires every
            # process to call it with the same step.  process_allgather
            # at a boundary is itself a collective all processes reach in
            # lockstep.  Polled roughly every _CONSENSUS_POLL_STEPS
            # global steps (every boundary for fused windows that big): a
            # per-call cross-host sync at unroll 1 would tax every step
            # to detect a rare event, and tens of steps of detection
            # latency is nothing against a preemption grace period.
            from jax.experimental import multihost_utils

            poll_every = max(1, _CONSENSUS_POLL_STEPS // steps_per_call)
            boundary = [0]

            def _consensus():
                agreed = bool(multihost_utils.process_allgather(
                    np.int32(bool(preempted))).max())
                if agreed:
                    stop_agreed.append(True)
                return agreed

            def _should_stop():
                i = boundary[0]
                boundary[0] += 1
                if i % poll_every:
                    return False    # uniform skip: same count everywhere
                return _consensus()
        else:
            def _consensus():
                if preempted:
                    stop_agreed.append(True)
                return bool(preempted)

            _should_stop = _consensus

        # Supervised runs (tools/supervise.py) export
        # SUPERVISE_HEARTBEAT; the boundary touches are what let the
        # watchdog distinguish a wedged dispatch from a long quiet
        # stretch of healthy fused steps.
        hb_path = os.environ.get("SUPERVISE_HEARTBEAT", "")
        if hb_path:
            from distributedtensorflowexample_tpu.training.hooks import (
                HeartbeatHook)
            hooks.append(HeartbeatHook(hb_path,
                                       every=_CONSENSUS_POLL_STEPS))
        # Telemetry (obs/): the registry feed is always on — its boundary
        # cost is the lock-free path, microbench-guarded in
        # tests/test_obs.py — while the flight recorder (a
        # flight_<pid>.json postmortem on every death) arms for
        # supervised runs automatically and for anything else via
        # OBS_FLIGHT=1.
        from distributedtensorflowexample_tpu.obs import (
            recorder as obs_recorder)
        from distributedtensorflowexample_tpu.training.hooks import (
            MetricsHook)
        # Per-step collective accounting (OBS_COLLECTIVES=1): inventory
        # the compiled step's collectives once and feed the registry
        # counters per boundary.  Opt-in because the AOT
        # lower().compile() does NOT share the jit executable cache on
        # this jax pin — arming it costs one extra compile of the train
        # step (device-resident path only: it has a peekable batch to
        # lower against).
        collectives = None
        if os.environ.get("OBS_COLLECTIVES") == "1" and use_device_data:
            from distributedtensorflowexample_tpu.utils.profiling import (
                collective_inventory_of)
            inv = collective_inventory_of(train_step, (state, ds.peek()),
                                          unroll=steps_per_call)
            if inv and inv.get("multiset"):
                collectives = inv
                note = ""
                if is_async and cfg.async_period > 1:
                    # The worker-average psums are cond-gated on the
                    # period: the module-weight inventory counts them at
                    # every step, so SUSTAINED wire traffic is the totals
                    # divided by the period (bench_scaling's
                    # amortized_bytes_per_step approximation, documented
                    # there: the every-step scalar-metrics psum pair —
                    # 8 B — is amortized along with it).  The per-op
                    # gauges keep the raw compiled schedule; only the
                    # cumulative counters amortize.
                    collectives = dict(
                        inv,
                        total_count_per_step=(inv["total_count_per_step"]
                                              / cfg.async_period),
                        total_out_bytes_per_step=(
                            inv["total_out_bytes_per_step"]
                            / cfg.async_period))
                    note = (f", sustained /{cfg.async_period} (cond-gated "
                            f"worker average): "
                            f"{collectives['total_out_bytes_per_step']:.0f}"
                            f" B")
                if is_chief:
                    print(f"collectives per step: {inv['multiset']} "
                          f"({inv['total_out_bytes_per_step']} B out in "
                          f"the compiled schedule{note})", flush=True)
        hooks.append(MetricsHook(every=cfg.log_every,
                                 collectives=collectives))
        # Online anomaly detection (obs/anomaly.py): always-on — the
        # per-boundary cost is a few float ops, guarded with MetricsHook's
        # budget — AFTER MetricsHook so the loss sentinels read the gauge
        # it just set instead of paying a second device fetch.  Detection
        # only: a firing bumps counters, dumps a flight, and (under a
        # supervisor that exported OBS_HEALTH) refreshes the health.json
        # the fleet reads for its skew/straggler pass.
        from distributedtensorflowexample_tpu.training.hooks import (
            AnomalyHook)
        hooks.append(AnomalyHook(every=cfg.log_every,
                                 health_path=os.environ.get("OBS_HEALTH",
                                                            "")))
        rec = obs_recorder.maybe_install()
        if rec is not None:
            # (rank, attempt, phase land in the flight payload itself —
            # the recorder reads OBS_RANK/SUPERVISE_ATTEMPT/OBS_PHASE.)
            rec.note(trainer=model_name, dataset=dataset_name,
                     sync_mode=cfg.sync_mode, log_dir=cfg.log_dir)
            if collectives is not None:
                rec.note(collectives_per_step=collectives["multiset"],
                         collective_bytes_per_step=collectives[
                             "total_out_bytes_per_step"])
        # Cross-run ledger (OBS_LEDGER) + live scrape surface
        # (OBS_HTTP_PORT): the run_start row carries the RESOLVED config
        # — what obs_query diffs two runs by — and MetricsHook feeds the
        # bounded samples; /metrics and /health answer while training.
        from distributedtensorflowexample_tpu.obs import (
            ledger as obs_ledger)
        from distributedtensorflowexample_tpu.obs import serve as obs_serve
        obs_ledger.maybe_begin(
            entrypoint=f"trainer:{model_name}",
            config=dataclasses.asdict(cfg),
            platform=jax.default_backend(), mesh_size=num_replicas,
            num_processes=jax.process_count(), dataset=dataset_name)
        obs_serve.maybe_start()

        with sigterm_flag() as preempted:
            with mesh:
                loop = TrainLoop(train_step, batches, cfg.train_steps,
                                 hooks, logger,
                                 steps_per_call=steps_per_call,
                                 should_stop=_should_stop)
                state = loop.run(state)
                if not stop_agreed:
                    # One more uniform consensus poll (every process
                    # reaches this point in lockstep): a signal that
                    # landed after the last boundary poll — or during the
                    # loop's final steps — still saves BEFORE the final
                    # eval spends grace time.  A signal landing inside
                    # the eval dispatch itself remains unhonorable
                    # mid-collective.
                    _consensus()
                if stop_agreed:
                    # End hooks already force-saved (CheckpointHook.end);
                    # a manager without the periodic hook (resume-only
                    # run) still gets the final save.  Skip the final
                    # eval — the slice is being reclaimed.
                    if manager is not None and cfg.checkpoint_every == 0:
                        manager.save(int(state.step), state, force=True)
                        manager.wait()
                    if is_chief:
                        saved = ("checkpoint saved, restart auto-resumes"
                                 if manager is not None else
                                 "NO checkpoint manager "
                                 "(--checkpoint_every 0 --resume false) "
                                 "— NOTHING SAVED")
                        print(f"SIGTERM at step {int(state.step)}: "
                              f"{saved}; exiting 143", flush=True)
                    logger.close()
                    # Explicit dump (not just atexit): the postmortem
                    # should say PREEMPTED, with the final step/loss
                    # already rung.
                    obs_recorder.dump_global("preempted")
                    # The ledger row too — atexit would close it rc=None
                    # ("never reported"), but this exit DID report.
                    obs_ledger.end_global(rc=143,
                                          final_step=int(state.step))
                    raise SystemExit(143)
                final_acc = eval_fn(state)

        if manager is not None and cfg.checkpoint_every == 0:
            manager.save(int(state.step), state, force=True)
            manager.wait()
        logger.scalar(int(state.step), "final_accuracy", final_acc)
        steps_per_sec = logger.last_steps_per_sec
        logger.close()
        obs_ledger.end_global(rc=0, final_step=int(state.step),
                              final_accuracy=round(float(final_acc), 6))
        return {"final_accuracy": final_acc,
                "steps": int(state.step),
                "steps_per_sec": steps_per_sec,
                "steps_per_sec_per_chip": steps_per_sec / max(1,
                                                              num_replicas),
                "num_replicas": num_replicas,
                "global_batch": global_batch}
