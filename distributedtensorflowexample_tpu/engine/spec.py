# graftlint: stdlib-only
"""The declarative half of engine/ — what a workload SAYS, with no jax
in sight (arXiv:1902.00465's input_fn/model_fn split, grown a knob
surface).

A :class:`RunSpec` is the whole declaration: a model (registry name or
``model_fn``), a dataset family (or ``input_fn``), the parsed
:class:`~distributedtensorflowexample_tpu.config.RunConfig`, and
nothing else.  Everything that used to be hand-forked per trainer —
mesh construction, replication-mode selection, collective insertion,
the rows/constraint state layouts, the checkpoint/obs/ledger/heal/
heartbeat hook stack — is the Engine's job (engine/engine.py).

The MODES table is the registry the tentpole exists for: each
replication strategy DECLARES its update layout and its graftlint HLO
contract here, so "add a mode" means "add a row + a contract", not
"fork the wiring a seventh time".  ``resolve_mode`` /
``resolve_update_layout`` are pure functions of (config, mesh_size) —
the same resolution run_training always applied, now callable from
stdlib-only tools (tools/obs_query.py renders a ledger row's layout
through them without importing jax).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional


@dataclasses.dataclass(frozen=True)
class ModeDecl:
    """One replication strategy: its checkpoint-layout contract and the
    HLO contract (``module:ATTR``) graftlint holds its compiled step
    to.  ``contract`` is a dotted reference, not the dict itself — spec
    stays importable without jax; analysis/hlo_lint.py resolves it."""

    name: str
    update_layout: str              # tree | bucket_rows | zero3_rows
    contract: Optional[str]         # "pkg.module:ATTR" or None (async:
                                    # the cond-gated worker average has
                                    # no fixed per-step multiset to pin)
    summary: str


_P = "distributedtensorflowexample_tpu.parallel"

#: The mode registry — ordered from plainest to most sharded; the
#: resolution below picks the FIRST row whose knobs are live.
MODES = {
    "sync_dp": ModeDecl(
        "sync_dp", "tree", f"{_P}.sync:HLO_CONTRACT",
        "sync data-parallel: per-parameter gradient psum each step "
        "(covers --shard_update's GSPMD constraint form: same program "
        "shape, optimizer state laid out 1/D)"),
    "async_ps": ModeDecl(
        "async_ps", "tree", None,
        "async-PS emulation: worker-tiled state, local SGD, "
        "cond-gated parameter average every --async_period steps"),
    "bucketed": ModeDecl(
        "bucketed", "tree", f"{_P}.bucketing:BUCKETED_HLO_CONTRACT",
        "--bucket_grads: per-parameter all-reduces fused into "
        "knee-sized dtype-homogeneous buckets"),
    "zero1": ModeDecl(
        "zero1", "bucket_rows", f"{_P}.bucketing:ZERO1_HLO_CONTRACT",
        "--bucket_grads + --shard_update: explicit per-bucket "
        "reduce-scatter -> sharded update -> all-gather; optimizer "
        "state resident as 1/D bucket rows"),
    "zero3": ModeDecl(
        "zero3", "zero3_rows", f"{_P}.zero3:HLO_CONTRACT",
        "--shard_params (ZeRO-3/FSDP): params, grads AND optimizer "
        "state as 1/D bucket rows; per-bucket all-gather just before "
        "use"),
}


def _get(config, key: str, default=None):
    """Read a knob off a RunConfig OR a plain dict (ledger run_start
    rows carry the config as a dict)."""
    if isinstance(config, dict):
        return config.get(key, default)
    return getattr(config, key, default)


def resolve_mode(config, mesh_size: int) -> ModeDecl:
    """The one mode-selection function (the exact cascade run_training
    applied inline): which MODES row this (config, mesh) resolves to.
    Pure and stdlib-only — no validation here (the Engine refuses bad
    knob combinations by name before ever calling this)."""
    bucket_on = bool(_get(config, "bucket_grads", ""))
    sync = _get(config, "sync_mode", "sync") == "sync"
    if not sync:
        return MODES["async_ps"]
    if mesh_size > 1 and bucket_on and _get(config, "shard_params", False):
        return MODES["zero3"]
    if mesh_size > 1 and bucket_on and _get(config, "shard_update", False):
        return MODES["zero1"]
    if mesh_size > 1 and bucket_on:
        return MODES["bucketed"]
    return MODES["sync_dp"]


def resolve_update_layout(config, mesh_size: int) -> str:
    """The checkpoint layout contract of a (config, mesh) pair — what
    run_meta["update_layout"] records and cross-layout resume refusals
    compare.  Callable on a raw ledger config dict (tools/obs_query.py
    diff renders it per run)."""
    return resolve_mode(config, mesh_size).update_layout


@dataclasses.dataclass
class RunSpec:
    """A workload, declared.  ``model``/``dataset`` are the registry
    names every reference trainer already used; the three optional
    callables are the TF-Replicator seams for workloads the registries
    don't know:

    * ``model_fn(cfg) -> flax module`` — replaces the models registry
      lookup (the ~50-line demo ships its own module inline).
    * ``input_fn(cfg, split) -> (x, y)`` — replaces the dataset-family
      loader (and its --dataset source matching), e.g. the bench's
      fallback-source loads or the demo's toy blobs.
    * ``optimizer_fn(cfg, mesh, wrap_shard_update) -> optax tx`` —
      replaces build_optimizer for callers whose optimizer is not the
      flag surface's (the bench pins a bare float-LR optax.sgd: a
      schedule-wrapped twin has a DIFFERENT opt_state pytree, and the
      bench's parity contract is bitwise).

    ``token_data=None`` derives the integer-split contract from the
    family name (the lm corpus), exactly as run_training did.
    """

    model: str
    dataset: str
    config: Any
    augment: bool = False
    model_fn: Optional[Callable] = None
    input_fn: Optional[Callable] = None
    optimizer_fn: Optional[Callable] = None
    token_data: Optional[bool] = None

    def resolved_token_data(self) -> bool:
        if self.token_data is not None:
            return bool(self.token_data)
        return self.dataset == "lm"
