"""engine/ — the one replicated-execution front-end (ROADMAP direction
4; arXiv:1902.00465).  ``spec`` is the declarative half (RunSpec, the
MODES registry, the pure mode/layout resolvers — stdlib-only, importable
from jax-free tools); ``engine`` is the executing half (the Engine
itself).  The Engine is exported lazily so ``engine.spec`` consumers
(tools/obs_query.py) never pay — or break on — a jax import.
"""

from distributedtensorflowexample_tpu.engine.spec import (
    MODES, ModeDecl, RunSpec, resolve_mode, resolve_update_layout)

__all__ = ["MODES", "ModeDecl", "RunSpec", "resolve_mode",
           "resolve_update_layout", "Engine", "EngineBuild",
           "apply_update_layout", "auto_steps_per_loop"]


def __getattr__(name):
    if name in ("Engine", "EngineBuild", "apply_update_layout",
                "auto_steps_per_loop"):
        from distributedtensorflowexample_tpu.engine import engine as _eng
        return getattr(_eng, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
