# graftlint: stdlib-only
"""The one shape every mode-legality refusal goes through.

The repo's correctness story leans on *refusal by name*: an illegal knob
combination (``--shard_params`` under async, ``--bucket_grads`` on a
BatchNorm model, a cross-layout resume) fails at flag-validation time
with a message that names the flag and says why the combination is a
different model or a different program — never a silent fallback.  Until
PR 13 that convention lived in reviewer memory: refusals were bare
``ValueError``\\ s, greppable only by knowing each message.

:class:`ModeRefusal` is the machine-checked form.  It subclasses
``ValueError`` so every existing ``except ValueError`` /
``pytest.raises(ValueError)`` site keeps working, and it is the ONE
class ``grep -rn ModeRefusal`` needs to enumerate the repo's whole
mode-legality surface.  The contract is enforced statically:
``analysis/src_lint.py``'s ``named-refusal`` rule flags any package
``raise ValueError`` whose message names a CLI flag (a ``--token``) —
that message is a mode-legality refusal and must be a ModeRefusal.
"""

from __future__ import annotations


class ModeRefusal(ValueError):
    """A named refusal of an illegal mode/knob combination.

    Raise with a message that (a) names the flag(s) by their CLI
    spelling and (b) says why the combination is refused rather than
    degraded — the existing refusal messages are the style guide.
    """
