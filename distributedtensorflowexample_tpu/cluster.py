"""Cluster-flag resolution — ClusterSpec/TF_CONFIG compatibility onto SPMD.

The reference bootstrapped ``tf.train.ClusterSpec`` + ``tf.train.Server`` per
process and parked PS roles in ``server.join()`` (SURVEY.md §3b, component
C7).  Under the SPMD rebuild there are no parameter-server processes at all
(BASELINE.json north star: "no gRPC PS processes ... in the loop"), so this
module maps the old topology flags onto the one concept that remains — how
many JAX processes exist and which one is this:

* ``--worker_hosts``/``--task_index`` or a ``TF_CONFIG`` env var resolve to
  (num_processes, process_id, coordinator_address) for
  ``jax.distributed.initialize``.
* ``--job_name=ps`` is accepted and exits immediately with a notice: PS
  capability is subsumed by replicated NamedSharding (documented semantic
  change, SURVEY.md §7 step 6).
* chief == process 0 (the reference's is_chief == task_index 0).
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax

from distributedtensorflowexample_tpu.config import RunConfig


@dataclasses.dataclass
class ClusterInfo:
    num_processes: int = 1
    process_id: int = 0
    coordinator_address: str = ""
    is_chief: bool = True
    role: str = "worker"            # "worker" | "ps" (ps = exit-with-notice)

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def tf_config_env(workers: list[str], index: int,
                  task_type: str = "worker") -> str:
    """Serialize the reference-style ``TF_CONFIG`` for worker ``index``
    — the inverse of :func:`_from_tf_config`, kept in this module so
    the writer and the parser can't drift.  The fleet supervisor
    (resilience/fleet.py) exports exactly this to every rank it
    launches, so a child trainer resolves the same ``ClusterInfo`` a
    hand-launched worker with the documented env surface would."""
    return json.dumps({"cluster": {"worker": list(workers)},
                       "task": {"type": task_type, "index": index}})


def _from_tf_config() -> ClusterInfo | None:
    raw = os.environ.get("TF_CONFIG", "")
    if not raw:
        return None
    try:
        tf_config = json.loads(raw)
        clus = tf_config["cluster"]
        task = tf_config.get("task", {})
        task_type = str(task.get("type", "worker"))
        idx = int(task.get("index", 0))
    except (json.JSONDecodeError, KeyError, TypeError, ValueError):
        return None
    if task_type == "ps":
        return ClusterInfo(role="ps", is_chief=False)
    # TF task ordering: an optional single-entry "chief" job precedes the
    # "worker" job; both participate in training.  (An "evaluator" never
    # joins the training cluster — treat like ps: nothing to serve here.)
    if task_type == "evaluator":
        return ClusterInfo(role="ps", is_chief=False)
    chief = list(clus.get("chief", []))
    workers = chief + list(clus.get("worker", []))
    if not workers:
        return None
    pid = idx if task_type == "chief" else len(chief) + idx
    return ClusterInfo(num_processes=len(workers), process_id=pid,
                       coordinator_address=workers[0], is_chief=(pid == 0))


def resolve(cfg: RunConfig) -> ClusterInfo:
    """Resolve cluster flags + env into a ClusterInfo (no side effects)."""
    if cfg.job_name == "ps":
        return ClusterInfo(role="ps", is_chief=False)
    info = _from_tf_config()
    if info is not None:
        return info
    if cfg.coordinator_address:
        pid = cfg.process_id if cfg.process_id >= 0 else cfg.task_index
        return ClusterInfo(num_processes=cfg.num_processes, process_id=pid,
                           coordinator_address=cfg.coordinator_address,
                           is_chief=(pid == 0))
    workers = cfg.worker_host_list
    if len(workers) > 1 and cfg.job_name == "worker":
        pid = cfg.process_id if cfg.process_id >= 0 else cfg.task_index
        return ClusterInfo(num_processes=len(workers), process_id=pid,
                           coordinator_address=workers[0],
                           is_chief=(pid == 0))
    return ClusterInfo()


def maybe_initialize_distributed(info: ClusterInfo) -> None:
    """``jax.distributed.initialize`` — the tf.train.Server replacement.

    Idempotent: a second trainer run in the same process (tests, notebooks,
    back-to-back ``main()`` calls) must reuse the live runtime — a repeat
    ``initialize`` raises once the XLA backend exists."""
    from distributedtensorflowexample_tpu.compat import (
        distributed_is_initialized)
    if info.is_distributed and not distributed_is_initialized():
        jax.distributed.initialize(
            coordinator_address=info.coordinator_address,
            num_processes=info.num_processes,
            process_id=info.process_id)


PS_NOTICE = (
    "[distributedtensorflowexample_tpu] --job_name=ps: parameter-server "
    "processes are obsolete in the TPU-native SPMD runtime — variables live "
    "replicated/sharded on the device mesh and gradient aggregation is an "
    "XLA collective. This process has nothing to serve and will exit. "
    "Launch only worker roles.")
