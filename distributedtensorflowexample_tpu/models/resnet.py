"""CIFAR-10 ResNet (component C9′, SURVEY.md §2).

Reference behavior [RECONSTRUCTED from BASELINE.json configs 4-5]: ResNet-20
— 3 stages × 3 basic residual blocks at widths 16/32/64, batch norm, global
average pool, 10-way head (He et al. CIFAR variant).

TPU notes: NHWC + bfloat16 compute keeps convs on the MXU; BN statistics are
computed over the *sharded global* batch dim inside the jitted step, so under
data parallelism XLA inserts the cross-replica reduction — giving sync-BN
semantics deterministically (the reference's per-replica BN is a GPU-strategy
artifact, not a capability we must preserve).
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, strides=self.strides, name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, name="conv2")(y)
        y = norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, kernel_size=(1, 1),
                            strides=self.strides, name="proj")(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(y + residual)


class ResNetCIFAR(nn.Module):
    """He-style CIFAR ResNet: depth = 6n+2 with n blocks per stage.

    ``remat="block"`` checkpoints each residual block: the backward pass
    recomputes the block's forward instead of keeping its activations
    resident — activation HBM footprint drops from the whole 6n+2 stack
    to one block's worth (plus the n+1 inter-block residuals), at the
    price of roughly one extra forward pass of flops.  Same math, same
    values (recomputation replays identical ops — parity is pinned
    bitwise in tests/test_bytes.py); worth it when activations, not
    weights, are what overflows HBM (deep stacks, large batch).
    """
    blocks_per_stage: int = 3
    widths: tuple[int, ...] = (16, 32, 64)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16
    remat: str = "none"           # none | block

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.remat not in ("none", "block"):
            raise ValueError(f"unknown remat policy {self.remat!r} "
                             "(one of none, block)")
        block_cls = BasicBlock
        if self.remat == "block":
            # static_argnums counts __call__'s args with self at 0: the
            # train flag (2) selects BN's running-average branch and must
            # stay a python bool under the remat trace.
            block_cls = nn.remat(BasicBlock, static_argnums=(2,))
        x = x.astype(self.dtype)
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype, name="bn_init")(x)
        x = nn.relu(x)
        for stage, width in enumerate(self.widths):
            for block in range(self.blocks_per_stage):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = block_cls(width, strides, self.dtype,
                              name=f"stage{stage}_block{block}")(x, train)
        # Pooling stays in f32 ONLY inside the reduction (jnp.mean's f32
        # accumulator — fused into the reduce, verified by the PR-2 bytes
        # audit); the first materialized f32 tensor is the [B, classes]
        # logits below.
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)


def ResNet20(num_classes: int = 10, dtype: jnp.dtype = jnp.bfloat16,
             remat: str = "none") -> ResNetCIFAR:
    return ResNetCIFAR(blocks_per_stage=3, num_classes=num_classes,
                       dtype=dtype, remat=remat)
