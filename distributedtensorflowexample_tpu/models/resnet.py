"""CIFAR-10 ResNet (component C9′, SURVEY.md §2).

Reference behavior [RECONSTRUCTED from BASELINE.json configs 4-5]: ResNet-20
— 3 stages × 3 basic residual blocks at widths 16/32/64, batch norm, global
average pool, 10-way head (He et al. CIFAR variant).

TPU notes: NHWC + bfloat16 compute keeps convs on the MXU; BN statistics are
computed over the *sharded global* batch dim inside the jitted step, so under
data parallelism XLA inserts the cross-replica reduction — giving sync-BN
semantics deterministically (the reference's per-replica BN is a GPU-strategy
artifact, not a capability we must preserve).
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, strides=self.strides, name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, name="conv2")(y)
        y = norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, kernel_size=(1, 1),
                            strides=self.strides, name="proj")(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(y + residual)


class ResNetCIFAR(nn.Module):
    """He-style CIFAR ResNet: depth = 6n+2 with n blocks per stage."""
    blocks_per_stage: int = 3
    widths: tuple[int, ...] = (16, 32, 64)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype, name="bn_init")(x)
        x = nn.relu(x)
        for stage, width in enumerate(self.widths):
            for block in range(self.blocks_per_stage):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = BasicBlock(width, strides, self.dtype,
                               name=f"stage{stage}_block{block}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)


def ResNet20(num_classes: int = 10, dtype: jnp.dtype = jnp.bfloat16) -> ResNetCIFAR:
    return ResNetCIFAR(blocks_per_stage=3, num_classes=num_classes, dtype=dtype)
