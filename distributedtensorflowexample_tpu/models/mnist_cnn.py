"""MNIST CNN (component C9, SURVEY.md §2) — the canonical deep-MNIST net.

Reference behavior [RECONSTRUCTED from BASELINE.json configs 2-3]: two
conv+maxpool stages, a 1024-wide FC layer with dropout, and a 10-way head.
TPU notes: NHWC layout, bfloat16 compute with float32 params (MXU-friendly),
dropout only when ``train=True`` so the eval graph stays deterministic.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    dropout_rate: float = 0.5
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(1024, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)
