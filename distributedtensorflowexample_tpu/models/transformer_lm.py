"""Decoder-only transformer LM — the flagship workload where the perf
knobs finally bind (ROADMAP "New directions" #5).

Every scaling feature since PR 2 (``--remat block``, ZeRO-1
``--shard_update``, knee-sized ``--bucket_grads``) is parity-tested but
HBM-noise at ResNet-20/0.27M params.  This model supplies the scale those
features were built for: a pre-LN, causal, weight-tied decoder with a
config-selectable size ladder (``LM_SIZES``) from ``lm_tiny`` (tier-1
parity tests, ~0.1M params) to ``lm_base`` (~57M params — optimizer
state + activations pressure real memory, arXiv:2004.13336's own
evaluation regime).

Design notes:

* **BN-free by construction** — every normalization is LayerNorm (a
  per-row op with no cross-batch statistics), so the ``--bucket_grads``
  / ZeRO-1 refusals for BatchNorm models never trigger and the bucketed
  per-shard gradient region computes the identical model.
* **Weight-tied embedding** — the output head is ``embed.attend``
  (logits = x @ E^T), halving head params and making the vocab matmul
  the same dot-general family the MFU audit prices.
* **``remat="block"``** — same policy surface as ResNet: each decoder
  block is ``nn.remat``-wrapped so the backward pass recomputes the
  block's forward instead of keeping its activations resident.  At
  lm_base the resident set is dominated by per-block attention
  probabilities ([B, H, T, T]) and MLP activations ([B, T, 4d]) — the
  bytes the PR-2 knob was built to trade for one extra forward.
  Same math bitwise (recomputation replays identical ops).
* **Out-of-vocab poison, not silent clamp** — XLA gathers CLAMP
  out-of-range indices, so a corrupted token batch (the
  ``corrupt_batch`` fault: garbage bytes off the wire) would silently
  train on wrong-but-legal embeddings forever.  Instead the logits are
  poisoned to NaN when any token id falls outside ``[0, vocab)``:
  NaNGuardHook fails fast, the flight recorder dumps the postmortem,
  and a supervised restart resumes from the last healthy snapshot —
  the same refuse-loudly discipline as the uint8 ``nan_loss`` refusal
  (resilience/faults.py).

Compute dtype is ``dtype`` (bfloat16 default) with f32 params and f32
softmax/logits, matching the other models' MXU discipline.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

#: Default vocabulary — deliberately < 256 so (a) token splits store as
#: uint8 in HBM (the quantized-data-path win: 4x less gather traffic
#: than int32) and (b) random garbage bytes are detectably out-of-vocab
#: (the corrupt_batch -> OOV-poison -> NaNGuard path has real teeth).
LM_VOCAB = 250

#: The size ladder.  lm_tiny is the tier-1 parity workload; lm_base is
#: sized so f32 params + momentum alone are ~0.5 GB replicated (~57M
#: params) — the scale where --remat/--shard_update/--bucket_grads stop
#: being HBM-noise.  lm_small is the throughput rung in between (CPU-
#: measurable step times at real-ish shapes).
LM_SIZES = {
    "lm_tiny": dict(n_layers=2, d_model=64, n_heads=2, d_ff=256),
    "lm_small": dict(n_layers=4, d_model=256, n_heads=4, d_ff=1024),
    "lm_base": dict(n_layers=8, d_model=768, n_heads=12, d_ff=3072),
}


class DecoderBlock(nn.Module):
    """Pre-LN decoder block: LN -> causal MHA -> residual, LN -> MLP ->
    residual.  Attention is written as explicit batched einsums (two
    dot-generals with batch dims) — the exact HLO shape the MFU flops
    audit (utils/profiling.hlo_flops_by_op) must price correctly."""
    d_model: int
    n_heads: int
    d_ff: int
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, T, _ = x.shape
        Dh = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        qkv = nn.Dense(3 * self.d_model, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, self.n_heads, Dh)
        k = k.reshape(B, T, self.n_heads, Dh)
        v = v.reshape(B, T, self.n_heads, Dh)
        scores = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.asarray(
            Dh ** 0.5, self.dtype)
        # Causal mask: position t attends to s <= t.  Built from iota at
        # trace time — no resident [T, T] constant in HBM.
        causal = (jnp.arange(T)[:, None] >= jnp.arange(T)[None, :])
        scores = jnp.where(causal[None, None], scores,
                           jnp.asarray(-1e9, scores.dtype))
        # Softmax in f32: bf16 exp/normalize is where logit noise turns
        # into loss noise; the [B,H,T,T] f32 probs are exactly the
        # activation bytes remat="block" exists to not keep resident.
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        probs = probs.astype(self.dtype)
        att = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, -1)
        att = nn.Dense(self.d_model, dtype=self.dtype, name="attn_out")(att)
        att = nn.Dropout(self.dropout_rate,
                         deterministic=not train)(att)
        x = x + att
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.dtype, name="mlp_out")(h)
        h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return x + h


class TransformerLM(nn.Module):
    """Decoder-only LM: tokens [B, T] (any integer dtype; uint8 is the
    resident-split storage) -> logits [B, T, vocab] f32."""
    vocab_size: int = LM_VOCAB
    n_layers: int = 2
    d_model: int = 64
    n_heads: int = 2
    d_ff: int = 256
    max_len: int = 512
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    remat: str = "none"           # none | block

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if self.remat not in ("none", "block"):
            raise ValueError(f"unknown remat policy {self.remat!r} "
                             "(one of none, block)")
        tokens = tokens.astype(jnp.int32)
        if tokens.ndim != 2:
            raise ValueError(f"token batch must be [B, T], got "
                             f"{tokens.shape}")
        T = tokens.shape[1]
        if T > self.max_len:
            raise ValueError(f"sequence length {T} exceeds max_len "
                             f"{self.max_len}")
        # Refuse-loudly seam (see module docstring): any out-of-vocab id
        # poisons the logits to NaN instead of silently clamping into a
        # wrong embedding row.  The clip below keeps the gather itself
        # in-range; the poison carries the corruption to NaNGuardHook.
        oov = jnp.any((tokens < 0) | (tokens >= self.vocab_size))
        embed = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                         name="embed")
        x = embed(jnp.clip(tokens, 0, self.vocab_size - 1))
        pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                       name="pos")(jnp.arange(T, dtype=jnp.int32))
        x = x + pos[None]
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        block_cls = DecoderBlock
        if self.remat == "block":
            # static_argnums counts __call__'s args with self at 0: the
            # train flag (2) gates dropout and must stay a python bool
            # under the remat trace (the ResNet precedent).
            block_cls = nn.remat(DecoderBlock, static_argnums=(2,))
        for i in range(self.n_layers):
            x = block_cls(self.d_model, self.n_heads, self.d_ff,
                          self.dropout_rate, self.dtype,
                          name=f"block{i}")(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        # Weight-tied head: logits = x @ E^T (flax attend), f32 at the
        # boundary like every other model's logits.
        logits = embed.attend(x).astype(jnp.float32)
        return logits + jnp.where(oov, jnp.float32(jnp.nan),
                                  jnp.float32(0.0))


def build_lm(size: str, vocab_size: int = LM_VOCAB,
             dropout: float = 0.0, dtype: jnp.dtype = jnp.bfloat16,
             remat: str = "none", max_len: int = 512) -> TransformerLM:
    """Size-ladder constructor (``LM_SIZES`` keys)."""
    try:
        dims = LM_SIZES[size]
    except KeyError:
        raise ValueError(f"unknown LM size {size!r}; have "
                         f"{sorted(LM_SIZES)}") from None
    return TransformerLM(vocab_size=vocab_size, max_len=max_len,
                         dropout_rate=dropout, dtype=dtype, remat=remat,
                         **dims)
