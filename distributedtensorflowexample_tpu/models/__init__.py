from distributedtensorflowexample_tpu.models.softmax import SoftmaxRegression
from distributedtensorflowexample_tpu.models.mnist_cnn import MnistCNN
from distributedtensorflowexample_tpu.models.resnet import ResNet20, ResNetCIFAR
from distributedtensorflowexample_tpu.models.transformer_lm import (
    LM_SIZES, LM_VOCAB, TransformerLM, build_lm)

import jax.numpy as jnp


def _lm_entry(size):
    # Dropout defaults to 0.0 for the LM family (trainer_lm overrides the
    # RunConfig 0.5 CNN default); remat/dtype knobs flow through like
    # ResNet's.
    return lambda **kw: build_lm(size,
                                 dropout=kw.get("dropout", 0.0),
                                 dtype=kw.get("dtype", jnp.bfloat16),
                                 remat=kw.get("remat", "none"))


_REGISTRY = {
    "softmax": lambda **kw: SoftmaxRegression(num_classes=10),
    "mnist_cnn": lambda **kw: MnistCNN(num_classes=10,
                                       dropout_rate=kw.get("dropout", 0.5),
                                       dtype=kw.get("dtype", jnp.bfloat16)),
    "resnet20": lambda **kw: ResNet20(num_classes=10,
                                      dtype=kw.get("dtype", jnp.bfloat16),
                                      remat=kw.get("remat", "none")),
    **{size: _lm_entry(size) for size in LM_SIZES},
}


def build_model(name: str, **kw):
    """Model registry keyed by the names the trainer CLIs use."""
    try:
        return _REGISTRY[name](**kw)
    except KeyError:
        raise ValueError(f"unknown model {name!r}; have {sorted(_REGISTRY)}") from None


__all__ = ["SoftmaxRegression", "MnistCNN", "ResNet20", "ResNetCIFAR",
           "TransformerLM", "build_lm", "LM_SIZES", "LM_VOCAB",
           "build_model"]
