from distributedtensorflowexample_tpu.models.softmax import SoftmaxRegression
from distributedtensorflowexample_tpu.models.mnist_cnn import MnistCNN
from distributedtensorflowexample_tpu.models.resnet import ResNet20, ResNetCIFAR

import jax.numpy as jnp

_REGISTRY = {
    "softmax": lambda **kw: SoftmaxRegression(num_classes=10),
    "mnist_cnn": lambda **kw: MnistCNN(num_classes=10,
                                       dropout_rate=kw.get("dropout", 0.5),
                                       dtype=kw.get("dtype", jnp.bfloat16)),
    "resnet20": lambda **kw: ResNet20(num_classes=10,
                                      dtype=kw.get("dtype", jnp.bfloat16),
                                      remat=kw.get("remat", "none")),
}


def build_model(name: str, **kw):
    """Model registry keyed by the names the trainer CLIs use."""
    try:
        return _REGISTRY[name](**kw)
    except KeyError:
        raise ValueError(f"unknown model {name!r}; have {sorted(_REGISTRY)}") from None


__all__ = ["SoftmaxRegression", "MnistCNN", "ResNet20", "ResNetCIFAR", "build_model"]
