"""MNIST softmax regression (component C8, SURVEY.md §2).

Reference behavior [RECONSTRUCTED from BASELINE.json config 1]:
``y = softmax(Wx + b)`` over flattened 28×28 images, cross-entropy loss.
Here it is a pure flax module returning logits; loss lives in ops.losses so
the same model composes with any parallelism mode.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class SoftmaxRegression(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, name="logits")(x)
