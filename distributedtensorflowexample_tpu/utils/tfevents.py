"""TensorBoard event-file scalar writer — pure Python, no TF dependency.

The reference logged ``tf.summary`` scalars that TensorBoard reads from
tfevents files (SURVEY.md §5 metrics row [RECONSTRUCTED]).  JSONL scalars
(training/metrics.py) cover grep/scripting; this module restores the
TensorBoard-compatible artifact itself: a tfevents file is a sequence of
TFRecord-framed, masked-CRC32C-checksummed ``Event`` protobufs, and both
formats are simple enough to emit by hand —

  record  := len:u64le | masked_crc32c(len):u32le | data | masked_crc32c(data):u32le
  Event   := 1: wall_time (double) | 2: step (int64)
             | 3: file_version (string)  -- first record only
             | 5: summary { 1: Value { 1: tag (string), 2: simple_value (float) } }

Only the scalar subset is implemented — exactly what the reference's
``tf.summary.scalar`` calls produced.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), as used by TFRecord framing.

_CRC_TABLE = []
for _i in range(256):
    _crc = _i
    for _ in range(8):
        _crc = (_crc >> 1) ^ (0x82F63B78 if _crc & 1 else 0)
    _CRC_TABLE.append(_crc)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Minimal protobuf wire encoding (only what Event/Summary scalars need).

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field_varint(field: int, value: int) -> bytes:
    # Proto int64: negatives are 10-byte two's complement on the wire.
    return _varint(field << 3) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _field_double(field: int, value: float) -> bytes:
    return _varint((field << 3) | 1) + struct.pack("<d", value)


_FLT_MAX = 3.4028234663852886e38


def _field_float(field: int, value: float) -> bytes:
    # Saturate finite float64 overflow to inf like a float32 cast would —
    # a diverged loss must log as inf, not crash the run mid-train.
    if value > _FLT_MAX:
        value = float("inf")
    elif value < -_FLT_MAX:
        value = float("-inf")
    return _varint((field << 3) | 5) + struct.pack("<f", value)


def _field_bytes(field: int, value: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(value)) + value


def encode_scalar_event(wall_time: float, step: int, tag: str,
                        value: float) -> bytes:
    scalar = _field_bytes(1, tag.encode("utf-8")) + _field_float(2, value)
    summary = _field_bytes(1, scalar)
    return (_field_double(1, wall_time) + _field_varint(2, int(step))
            + _field_bytes(5, summary))


def encode_file_version_event(wall_time: float) -> bytes:
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


def frame_record(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", masked_crc32c(header))
            + data + struct.pack("<I", masked_crc32c(data)))


class TFEventsWriter:
    """Append-only scalar writer producing a TensorBoard-readable logdir.

    One file per writer, named the way TensorBoard discovers them
    (``events.out.tfevents.<ts>.<host>``); the version header is the first
    record, exactly as TF's own ``EventsWriter`` emits it.
    """

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        now = time.time()
        name = (f"events.out.tfevents.{now:.6f}."
                f"{socket.gethostname()}{filename_suffix}")
        self.path = os.path.join(logdir, name)
        self._f = open(self.path, "ab")
        self._f.write(frame_record(encode_file_version_event(now)))
        self._f.flush()

    def scalar(self, step: int, tag: str, value: float,
               wall_time: float | None = None) -> None:
        wall_time = time.time() if wall_time is None else wall_time
        self._f.write(frame_record(
            encode_scalar_event(wall_time, step, tag, float(value))))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


# ---------------------------------------------------------------------------
# Reader — used by tests and available for offline inspection of logs.

def read_events(path: str) -> list[dict]:
    """Parse a tfevents file back into dicts, verifying both CRCs.

    Returns entries like ``{"wall_time": t, "step": n, "tag": s, "value": v}``
    (scalar events) or ``{"file_version": "..."}``.

    A truncated final record (killed writer, concurrent read during a
    flush) ends the parse and returns the valid prefix — TF's reader does
    the same.  A CRC mismatch on a *complete* record raises ValueError.
    """
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return out
            (length,) = struct.unpack("<Q", header)
            hcrc_raw = f.read(4)
            if len(hcrc_raw) < 4:
                return out
            if struct.unpack("<I", hcrc_raw)[0] != masked_crc32c(header):
                raise ValueError(f"bad length crc at offset {f.tell()}")
            data = f.read(length)
            dcrc_raw = f.read(4)
            if len(data) < length or len(dcrc_raw) < 4:
                return out
            if struct.unpack("<I", dcrc_raw)[0] != masked_crc32c(data):
                raise ValueError(f"bad data crc at offset {f.tell()}")
            out.append(_decode_event(data))


def _decode_fields(data: bytes) -> list[tuple[int, int, object]]:
    fields, i = [], 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            value, i = _read_varint(data, i)
        elif wire == 1:
            value = struct.unpack_from("<d", data, i)[0]
            i += 8
        elif wire == 5:
            value = struct.unpack_from("<f", data, i)[0]
            i += 4
        elif wire == 2:
            n, i = _read_varint(data, i)
            value = data[i:i + n]
            i += n
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.append((field, wire, value))
    return fields


def _read_varint(data: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = data[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _decode_event(data: bytes) -> dict:
    event: dict = {}
    for field, _wire, value in _decode_fields(data):
        if field == 1:
            event["wall_time"] = value
        elif field == 2:
            event["step"] = value
        elif field == 3:
            event["file_version"] = value.decode("utf-8")
        elif field == 5:
            for f2, _w2, v2 in _decode_fields(value):
                if f2 == 1:  # Summary.value
                    for f3, _w3, v3 in _decode_fields(v2):
                        if f3 == 1:
                            event["tag"] = v3.decode("utf-8")
                        elif f3 == 2:
                            event["value"] = v3
    return event
