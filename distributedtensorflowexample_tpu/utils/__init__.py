"""Cross-cutting utilities: profiling, timing, chief-aware logging.

The reference's observability was library defaults (TF timeline /
TensorBoard summaries — SURVEY.md §5 "Tracing / profiling"); here the
equivalents are first-class: ``jax.profiler`` trace capture
(:mod:`.profiling`), honest device-synchronized timing (:mod:`.timing`),
and process-0-only logging (:mod:`.logging`).
"""

from distributedtensorflowexample_tpu.utils.logging import chief_print
from distributedtensorflowexample_tpu.utils.profiling import (
    ProfilerHook, trace_context)
from distributedtensorflowexample_tpu.utils.timing import (
    RateMeter, Timer, timed_block)

__all__ = ["ProfilerHook", "trace_context", "Timer", "RateMeter",
           "timed_block", "chief_print"]
