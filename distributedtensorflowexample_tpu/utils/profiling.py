"""Trace capture — the TPU-native replacement for TF timeline dumps.

SURVEY.md §5 maps the reference's (absent, library-default) tracing row to
``jax.profiler`` + TensorBoard.  Two entry points:

* :func:`trace_context` — capture a trace around any code block; view with
  TensorBoard's profile plugin or Perfetto (``xplane.pb`` under *logdir*).
* :class:`ProfilerHook` — a training :class:`~..training.hooks.Hook` that
  captures steps ``(start_step, start_step + num_steps]`` of the live loop,
  which is how "why is steps/sec low" questions get answered on real chips.
"""

from __future__ import annotations

import contextlib

import jax

from distributedtensorflowexample_tpu.training.hooks import Hook


@contextlib.contextmanager
def trace_context(logdir: str):
    """Capture a ``jax.profiler`` trace of the enclosed block into *logdir*."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfilerHook(Hook):
    """Trace a window of live training steps.

    Starts capture after step ``start_step`` completes and stops once at
    least ``num_steps`` further steps have run, so the window contains only
    steady-state steps (never compilation, provided ``start_step`` > 0).
    The hook sees the loop at call boundaries: with a multi-step train call
    (``steps_per_loop`` K) the window rounds up to whole calls, capturing
    up to K-1 extra steps.
    Chief-only by construction on multi-host: every process traces its own
    devices into a per-process subdirectory, matching ``jax.profiler``
    multi-host semantics.
    """

    def __init__(self, logdir: str, start_step: int = 10, num_steps: int = 5):
        self._logdir = logdir
        self._start = max(0, start_step)
        self._stop = self._start + max(1, num_steps)
        self._active = False
        self._done = False

    def after_step(self, step, state, metrics) -> bool:
        if self._done:
            return False
        if not self._active and step > self._start:
            # Resume landed inside or past the window: slide it forward so
            # a requested trace still captures (stop - start) steady-state
            # steps instead of a truncated or empty one.  One-shot: _done
            # prevents re-arming after a completed capture.
            width = self._stop - self._start
            self._start = step
            self._stop = step + width
        if self._start <= step < self._stop and not self._active:
            # Drain in-flight device work so the trace begins at a step
            # boundary rather than mid-pipeline.
            jax.block_until_ready(metrics)
            jax.profiler.start_trace(self._logdir)
            self._active = True
        elif step >= self._stop and self._active:
            jax.block_until_ready(metrics)
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
        return False

    def end(self, state) -> None:
        if self._active:  # loop stopped inside the trace window
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
