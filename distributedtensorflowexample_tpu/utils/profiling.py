"""Trace capture + compiled-module bytes attribution.

SURVEY.md §5 maps the reference's (absent, library-default) tracing row to
``jax.profiler`` + TensorBoard.  Entry points:

* :func:`trace_context` — capture a trace around any code block; view with
  TensorBoard's profile plugin or Perfetto (``xplane.pb`` under *logdir*).
* :class:`ProfilerHook` — a training :class:`~..training.hooks.Hook` that
  captures steps ``(start_step, start_step + num_steps]`` of the live loop,
  which is how "why is steps/sec low" questions get answered on real chips.
* :func:`hlo_bytes_by_op` / :func:`bytes_audit` /
  :func:`cost_and_bytes_audit` — decompose XLA cost-analysis
  ``bytes_accessed`` per HLO op for any compiled step (the PR-2 tentpole:
  the aggregate number alone cannot say WHICH traffic caps arithmetic
  intensity, and it over-counts gathers — see ``bytes_audit``).
"""

from __future__ import annotations

import contextlib
import re
from collections import defaultdict

import jax

from distributedtensorflowexample_tpu.training.hooks import Hook


@contextlib.contextmanager
def trace_context(logdir: str):
    """Capture a ``jax.profiler`` trace of the enclosed block into *logdir*."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfilerHook(Hook):
    """Trace a window of live training steps.

    Starts capture after step ``start_step`` completes and stops once at
    least ``num_steps`` further steps have run, so the window contains only
    steady-state steps (never compilation, provided ``start_step`` > 0).
    The hook sees the loop at call boundaries: with a multi-step train call
    (``steps_per_loop`` K) the window rounds up to whole calls, capturing
    up to K-1 extra steps.
    Chief-only by construction on multi-host: every process traces its own
    devices into a per-process subdirectory, matching ``jax.profiler``
    multi-host semantics.
    """

    def __init__(self, logdir: str, start_step: int = 10, num_steps: int = 5):
        self._logdir = logdir
        self._start = max(0, start_step)
        self._stop = self._start + max(1, num_steps)
        self._active = False
        self._done = False

    def after_step(self, step, state, metrics) -> bool:
        if self._done:
            return False
        if not self._active and step > self._start:
            # Resume landed inside or past the window: slide it forward so
            # a requested trace still captures (stop - start) steady-state
            # steps instead of a truncated or empty one.  One-shot: _done
            # prevents re-arming after a completed capture.
            width = self._stop - self._start
            self._start = step
            self._stop = step + width
        if self._start <= step < self._stop and not self._active:
            # Drain in-flight device work so the trace begins at a step
            # boundary rather than mid-pipeline.
            jax.block_until_ready(metrics)
            jax.profiler.start_trace(self._logdir)
            self._active = True
        elif step >= self._stop and self._active:
            jax.block_until_ready(metrics)
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
        return False

    def end(self, state) -> None:
        if self._active:  # loop stopped inside the trace window
            jax.profiler.stop_trace()
            self._active = False
            self._done = True


# ---------------------------------------------------------------------------
# Per-op bytes attribution from optimized HLO text (PR-2 tentpole).
#
# XLA's ``compiled.cost_analysis()["bytes accessed"]`` is one aggregate; the
# round-5 on-chip record hung the repo's weakest number (0.82 flop/byte for
# the ResNet-20 step) on it with no way to say WHICH ops carry the bytes.
# The optimized HLO text has everything needed to decompose it: every
# instruction line carries its output shape AND its operands' shapes inline,
# so per-instruction bytes = output + operands — the exact convention
# HloCostAnalysis uses (fusion internals free, operands counted at full
# size).  Parsed totals match ``cost_analysis()`` to <0.1% on the programs
# the tests pin.
#
# The decomposition also exposes an artifact the aggregate hides: a fused
# row GATHER from a device-resident split counts the WHOLE split array as
# an operand (e.g. the 153.6 MB uint8 CIFAR split for a 786 KB minibatch
# read), so ``bytes_accessed`` wildly over-states true HBM traffic for
# resident-data programs.  ``effective_bytes`` re-prices gather-category
# ops at rows-actually-touched (output size), which is the honest
# denominator for bandwidth rooflines.

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "s4": 1, "u4": 1, "pred": 1, "c64": 8, "c128": 16,
}
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s+=\s+"     # instruction name
    # Output shape: lazy up to the first `opcode(` — tuple types may
    # contain /*index=N*/ comments, so no explicit char class.
    r"(.*?)\s+"
    r"([\w\-]+)\(")                            # opcode
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\(.*\)\s*->\s*.*\{\s*$")
_CALLS_RE = re.compile(r"(calls|to_apply|body|condition|true_computation"
                       r"|false_computation)=%?([\w.\-]+)")
# N-ary conditionals print their targets as a brace list instead of
# named fields: `branch_computations={%b0, %b1, ...}`.
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')

# No memory traffic of their own: parameters/constants are inputs counted
# at their consumers; tuples/GTE are aliasing.
_SKIP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "after-all",
    "partition-id", "replica-id", "add-dependency", "opt-barrier"})
# Recursed into (their bodies carry the traffic), never counted themselves:
# operands pass by reference.
_CONTROL_OPS = frozenset({"while", "call", "conditional"})

_CATEGORY = {
    "convolution": "conv", "dot": "matmul",
    "all-reduce": "collective", "all-gather": "collective",
    "reduce-scatter": "collective", "collective-permute": "collective",
    "all-to-all": "collective",
    "gather": "gather", "scatter": "gather", "dynamic-slice": "gather",
    "dynamic-update-slice": "gather",
    "transpose": "layout", "copy": "layout", "reshape": "layout",
    "bitcast": "layout", "concatenate": "layout", "slice": "layout",
    "pad": "layout", "reverse": "layout",
    "convert": "cast", "bitcast-convert": "cast",
    "reduce": "reduce", "reduce-window": "reduce",
    "select-and-scatter": "reduce",
    "rng": "rng", "rng-bit-generator": "rng",
    "custom-call": "custom",
}
# A fusion is classified by the highest-priority opcode it fuses — the op
# that explains why the traffic exists (a conv fusion's converts are the
# conv's boundary, not a standalone cast pass).
_FUSION_PRIORITY = (
    "convolution", "dot", "all-reduce", "all-gather", "reduce-scatter",
    "scatter", "gather", "dynamic-update-slice", "dynamic-slice",
    "reduce-window", "reduce", "rng-bit-generator", "transpose", "convert")


def _shape_bytes(token: str) -> int:
    """Total bytes of every ``dtype[d0,d1,...]`` shape in *token* (tuple
    shapes and operand lists sum their members; layout suffixes ignored)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(token):
        width = _DTYPE_BYTES.get(dt)
        if width is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def _split_computations(hlo_text: str):
    """{computation name: [(name, out_token, opcode, raw line), ...]},
    plus the ENTRY computation's name."""
    comps: dict[str, list] = {}
    cur = None
    entry = None
    for line in hlo_text.splitlines():
        mc = _COMP_RE.match(line)
        if mc:
            cur = mc.group(2)
            comps[cur] = []
            if mc.group(1):
                entry = cur
            continue
        if line.startswith("}"):
            cur = None
            continue
        if cur is None:
            continue
        mi = _INSTR_RE.match(line)
        if mi:
            # mi.end() sits just past `opcode(` — the operand list start.
            # (The line's FIRST paren may belong to a tuple output type.)
            comps[cur].append((mi.group(1), mi.group(2), mi.group(3), line,
                               mi.end()))
    return comps, entry


def _fusion_category(instrs) -> str:
    ops = {i[2] for i in instrs}
    for p in _FUSION_PRIORITY:
        if p in ops:
            return _CATEGORY.get(p, "elementwise")
    return "elementwise"


def _operand_token(line: str, start: int) -> str:
    """The operand list of an instruction line: everything inside the
    call parens opened at ``start`` (shapes are printed inline per
    operand).  ``start`` comes from the instruction regex — the line's
    first paren may belong to a tuple OUTPUT type, not the call."""
    inner = line[start:]
    depth = 1
    for i, ch in enumerate(inner):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return inner[:i]
    return inner


def _computation_weights(comps: dict, entry: str, unroll: int) -> dict:
    """Execution weight per computation, walked from ENTRY:
    ``call``/``conditional`` targets inherit the caller's weight,
    ``while`` bodies are weighted ``unroll`` times (the ONE while in our
    programs is the ``lax.scan`` over fused train steps, whose trip count
    IS the unroll).  Fusion ``calls=`` and reduce ``to_apply=``
    computations stay excluded — their internals don't touch memory (or
    the wire) separately.  Shared by the bytes audit and the collective
    inventory so both instruments normalize per-step identically."""
    weights: dict[str, int] = defaultdict(int)

    def visit(name: str, weight: int) -> None:
        weights[name] += weight
        for _, _, opcode, line, _ in comps.get(name, ()):
            if opcode == "while":
                for _, target in _CALLS_RE.findall(line):
                    visit(target, weight * max(1, unroll))
            elif opcode in ("call", "conditional"):
                for _, target in _CALLS_RE.findall(line):
                    visit(target, weight)
                mb = _BRANCHES_RE.search(line)
                if mb:
                    for target in mb.group(1).split(","):
                        target = target.strip().lstrip("%")
                        if target:
                            visit(target, weight)

    visit(entry, 1)
    return weights


def entry_walk(hlo_text: str, unroll: int = 1) -> tuple[dict, str | None,
                                                        dict]:
    """The public seam over the ENTRY-walk every per-program instrument
    shares: ``(computations, entry_name, execution_weights)`` for one
    optimized-HLO text.  ``computations`` maps name -> instruction
    tuples ``(name, out_token, opcode, raw_line, operand_start)``;
    ``entry_name`` is None when the text has no ENTRY (weights then
    empty).  Callers: the bytes/flops audits and collective inventory
    below, and ``analysis/hlo_lint.py``'s contract checks — one parse,
    one opinion about what the module contains."""
    comps, entry = _split_computations(hlo_text)
    if entry is None:
        return comps, None, {}
    return comps, entry, _computation_weights(comps, entry, unroll)


def hlo_bytes_by_op(hlo_text: str, unroll: int = 1) -> list:
    """Per-instruction bytes rows from optimized HLO text.

    Control flow is walked from ENTRY (see :func:`_computation_weights`).

    Returns rows sorted by bytes descending; each row is a dict with
    ``bytes`` (weighted, whole module), ``effective_bytes`` (gather
    operands re-priced at rows-touched — see module comment), ``category``,
    ``opcode``, ``name``, ``out`` (output shape token) and ``op_name``
    (source metadata — the flax module path for model ops).
    """
    comps, entry, weights = entry_walk(hlo_text, unroll)
    if entry is None:
        return []

    rows = []
    for comp, weight in weights.items():
        for name, out_tok, opcode, line, args_at in comps.get(comp, ()):
            if opcode in _SKIP_OPS or opcode in _CONTROL_OPS:
                continue
            operands = _operand_token(line, args_at)
            out_b = _shape_bytes(out_tok)
            op_bytes = [_shape_bytes(s.group(0))
                        for s in _SHAPE_RE.finditer(operands)]
            raw = (out_b + sum(op_bytes)) * weight
            if opcode == "fusion":
                target = None
                for kind, t in _CALLS_RE.findall(line):
                    if kind == "calls":
                        target = t
                cat = _fusion_category(comps.get(target, ()))
            else:
                cat = _CATEGORY.get(opcode, "elementwise")
            effective = raw
            if cat == "gather" and op_bytes:
                # The cost convention charges an indexed read/write for its
                # WHOLE operand; the data actually moved is one output's
                # worth of rows.  Re-price the largest operand at output
                # size (dynamic-update-slice keeps its full-output write —
                # conservative, it aliases in place).
                big = max(op_bytes)
                effective = raw - max(0, big - out_b) * weight
            mm = _OPNAME_RE.search(line)
            rows.append({"bytes": raw, "effective_bytes": effective,
                         "category": cat, "opcode": opcode, "name": name,
                         "out": out_tok.strip(),
                         "op_name": mm.group(1) if mm else ""})
    rows.sort(key=lambda r: -r["bytes"])
    return rows


def bytes_audit(hlo_text: str, unroll: int = 1, top_k: int = 12) -> dict:
    """Summarize :func:`hlo_bytes_by_op` into the audit record bench and
    the CLI tool emit: whole-module and per-step totals (raw + effective),
    per-category decomposition, and the ``top_k`` single ops.

    ``per_step`` divides by ``unroll`` so records from differently-fused
    programs compare directly."""
    rows = hlo_bytes_by_op(hlo_text, unroll=unroll)
    by_cat: dict[str, float] = defaultdict(float)
    by_cat_eff: dict[str, float] = defaultdict(float)
    total = eff = 0
    for r in rows:
        by_cat[r["category"]] += r["bytes"]
        by_cat_eff[r["category"]] += r["effective_bytes"]
        total += r["bytes"]
        eff += r["effective_bytes"]
    u = max(1, unroll)
    top = [{"bytes_per_step": round(r["bytes"] / u),
            "category": r["category"], "opcode": r["opcode"],
            # keep records compact: the tail of the op_name is the
            # module-path part a reader needs
            "op_name": r["op_name"][-80:], "out": r["out"][:60]}
           for r in rows[:top_k]]
    return {
        "bytes_total": total, "bytes_effective_total": eff,
        "bytes_per_step": round(total / u),
        "bytes_effective_per_step": round(eff / u),
        "phantom_gather_bytes_per_step": round((total - eff) / u),
        "by_category_per_step": {k: round(v / u) for k, v in
                                 sorted(by_cat.items(),
                                        key=lambda kv: -kv[1])},
        "by_category_effective_per_step": {
            k: round(v / u) for k, v in
            sorted(by_cat_eff.items(), key=lambda kv: -kv[1])},
        "top_ops": top,
    }


# ---------------------------------------------------------------------------
# Dot-general / convolution FLOP accounting (the MFU denominator).
#
# The bytes audit prices memory traffic; nothing priced the ARITHMETIC —
# the aggregate ``cost_analysis()["flops"]`` lumps matmul flops together
# with elementwise/softmax/reduce noise, so an MFU number derived from it
# over-counts the numerator's useful work and can drift silently with
# any elementwise refactor.  The optimized HLO has what is needed to
# price the MXU work exactly: every ``dot`` line prints its output shape,
# operand shapes, AND ``lhs_contracting_dims`` inline — including the
# batched dot-generals attention einsums lower to — so
#
#     dot flops = 2 * prod(output dims) * prod(contracting dims)
#
# covers plain matmuls, batch-dim matmuls ([B,H,T,Dh] x [B,H,Dh,S]) and
# the vocab head identically (2 flops per MAC, HloCostAnalysis's own
# convention — golden-pinned in tests).  Convolutions price as
# 2 * out_elems * kernel_elems / out_channels (the per-output-element
# MAC count; feature groups cancel out of that ratio).  Dots fused into
# a fusion are priced from the fused computation at the fusion's weight.
# NOT covered: backend custom-calls (e.g. oneDNN conv rewrites) — absent
# from the programs the goldens pin; a custom-call carries no dim
# metadata to price.

_DOT_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")
_CONV_DIM_LABELS_RE = re.compile(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)")


def _first_shape_dims(token: str) -> list[int]:
    """Dims of the FIRST ``dtype[d0,...]`` shape in *token*."""
    m = _SHAPE_RE.search(token)
    if not m:
        return []
    return [int(d) for d in m.group(2).split(",") if d]


def _prod(dims) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def _instr_flops(opcode: str, out_tok: str, line: str,
                 args_at: int) -> int | None:
    """FLOPs of one dot/convolution instruction line (None = not one)."""
    if opcode == "dot":
        m = _DOT_LHS_CONTRACT_RE.search(line)
        if not m:
            return None
        operands = _operand_token(line, args_at)
        lhs = _first_shape_dims(operands)
        contract = [int(d) for d in m.group(1).split(",") if d]
        k = _prod(lhs[i] for i in contract if i < len(lhs))
        return 2 * _prod(_first_shape_dims(out_tok)) * k
    if opcode == "convolution":
        mm = _CONV_DIM_LABELS_RE.search(line)
        if not mm:
            return None
        out_dims = _first_shape_dims(out_tok)
        out_labels = mm.group(3)
        f_pos = out_labels.find("f")
        if f_pos < 0 or f_pos >= len(out_dims):
            return None
        operands = _operand_token(line, args_at)
        shapes = [[int(d) for d in s.split(",") if d]
                  for _, s in _SHAPE_RE.findall(operands)]
        if len(shapes) < 2:
            return None
        kernel_elems = _prod(shapes[1])
        out_ch = max(1, out_dims[f_pos])
        return 2 * _prod(out_dims) * kernel_elems // out_ch
    return None


def hlo_flops_by_op(hlo_text: str, unroll: int = 1) -> list:
    """Per-instruction dot/convolution FLOP rows from optimized HLO text
    (weighted like :func:`hlo_bytes_by_op`: control flow walked from
    ENTRY, scan bodies by trip count; dots INSIDE a fusion priced from
    the fused computation at the fusion's weight)."""
    comps, entry, weights = entry_walk(hlo_text, unroll)
    if entry is None:
        return []

    def fused_rows(target: str, weight: int, via: str):
        out = []
        for name, out_tok, opcode, line, args_at in comps.get(target, ()):
            fl = _instr_flops(opcode, out_tok, line, args_at)
            if fl:
                mm = _OPNAME_RE.search(line)
                out.append({"flops": fl * weight, "opcode": opcode,
                            "name": name, "fusion": via,
                            "out": out_tok.strip()[:60],
                            "op_name": mm.group(1) if mm else ""})
        return out

    rows = []
    for comp, weight in weights.items():
        for name, out_tok, opcode, line, args_at in comps.get(comp, ()):
            if opcode == "fusion":
                for kind, t in _CALLS_RE.findall(line):
                    if kind == "calls":
                        rows.extend(fused_rows(t, weight, name))
                continue
            fl = _instr_flops(opcode, out_tok, line, args_at)
            if fl:
                mm = _OPNAME_RE.search(line)
                rows.append({"flops": fl * weight, "opcode": opcode,
                             "name": name, "fusion": "",
                             "out": out_tok.strip()[:60],
                             "op_name": mm.group(1) if mm else ""})
    rows.sort(key=lambda r: -r["flops"])
    return rows


def flops_audit(hlo_text: str, unroll: int = 1, top_k: int = 8) -> dict:
    """Summarize :func:`hlo_flops_by_op` into the MFU-denominator record:
    per-step dot/conv flops (``per_step`` divides by ``unroll``, the
    bytes-audit convention) plus the ``top_k`` heaviest ops."""
    rows = hlo_flops_by_op(hlo_text, unroll=unroll)
    u = max(1, unroll)
    dot = sum(r["flops"] for r in rows if r["opcode"] == "dot")
    conv = sum(r["flops"] for r in rows if r["opcode"] == "convolution")
    top = [{"flops_per_step": round(r["flops"] / u),
            "opcode": r["opcode"], "op_name": r["op_name"][-80:],
            "out": r["out"]} for r in rows[:top_k]]
    return {
        "matmul_flops_per_step": round(dot / u),
        "conv_flops_per_step": round(conv / u),
        "flops_per_step": round((dot + conv) / u),
        "op_count_per_step": round(len(rows) / u, 4),
        "top_ops": top,
    }


def state_residency_per_device(state) -> dict:
    """Per-device RESIDENT bytes of a train state, read from the live
    array shardings (one addressable shard per leaf — a replicated
    leaf's shard is the whole leaf, a row-sharded leaf's shard is its
    1/D block), split by field.  This is the measured form of the
    ZeRO 1/D claims: the state arrays ARE the compiled step's donated
    arguments, so these bytes are what ``memory_analysis().
    argument_size_in_bytes`` charges for the state (the data split and
    perm ride the same argument total; gradients are step-local and
    live in ``temp_bytes``, which the audit below reports alongside)."""
    def shard_bytes(tree) -> int:
        total = 0
        for leaf in jax.tree.leaves(tree):
            if not hasattr(leaf, "addressable_shards"):
                continue
            shard = leaf.addressable_shards[0]
            n = 1
            for d in shard.data.shape:
                n *= int(d)
            total += n * leaf.dtype.itemsize
        return total

    params = shard_bytes(getattr(state, "params", ()))
    opt = shard_bytes(getattr(state, "opt_state", ()))
    stats = shard_bytes(getattr(state, "batch_stats", ()))
    return {"params_bytes_per_device": params,
            "opt_state_bytes_per_device": opt,
            "batch_stats_bytes_per_device": stats,
            "state_bytes_per_device": params + opt + stats}


def compiled_program_audit(step, args, unroll: int = 1,
                           top_k: int = 12) -> dict:
    """ONE lower+compile serving every per-program instrument: the
    aggregate cost keys (flops / bytes_accessed), the per-op bytes
    audit, the dot/conv flops audit (the MFU denominator), the
    collective inventory, the compiler's own memory analysis
    (``temp_bytes`` is the per-device temp/activation arena — the
    peak-memory number the remat A/B measures), and — when ``args[0]``
    is a train state — its per-device residency split
    (:func:`state_residency_per_device`, the measured 1/D claim for the
    ZeRO knobs).  Each section degrades to ``{}`` independently, the
    shared contract of the single-purpose helpers above."""
    out = {"cost": {}, "bytes": {}, "flops": {}, "collectives": {},
           "memory": {}, "residency": {}}
    try:
        st = args[0] if args else None
        if st is not None and hasattr(st, "params") \
                and hasattr(st, "opt_state"):
            out["residency"] = state_residency_per_device(st)
    except Exception:
        pass
    try:
        compiled = step.lower(*args).compile()
    except Exception:
        return out
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        for key, name in (("flops", "flops"),
                          ("bytes accessed", "bytes_accessed")):
            if key in ca:
                out["cost"][name] = float(ca[key]) / max(1, unroll)
    except Exception:
        pass
    try:
        txt = compiled.as_text()
    except Exception:
        txt = ""
    if txt:
        try:
            out["bytes"] = bytes_audit(txt, unroll=unroll, top_k=top_k)
        except Exception:
            pass
        try:
            out["flops"] = flops_audit(txt, unroll=unroll)
        except Exception:
            pass
        try:
            out["collectives"] = collective_inventory(txt, unroll=unroll)
        except Exception:
            pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["memory"] = {
                "temp_bytes": int(ma.temp_size_in_bytes),
                "argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "alias_bytes": int(ma.alias_size_in_bytes),
                "generated_code_bytes": int(
                    ma.generated_code_size_in_bytes),
            }
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# Per-collective accounting (the comms twin of the bytes audit).
#
# The bytes audit says WHICH ops carry the HBM traffic; nothing said which
# collectives carry the wire traffic — the sync trainer's gradient
# all-reduce and the --shard_update reduce-scatter/all-gather schedule were
# invisible (test_device_data.py could only assert the collective SET).
# The optimized HLO names every collective with its shapes and replica
# groups inline, so the same parse that prices bytes can inventory the
# wire: per-instruction rows, a per-step multiset, and totals that tie out
# EXACTLY against the bytes audit's "collective" category (same text, same
# weights, same out+operands convention).

_COLLECTIVE_OPCODES = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute"})
# Literal forms: nested brace lists ({{0,1},{2,3}}), the empty {}, and
# the iota form ([1,8]<=[8]).
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{.*?\}\}|\{\}|\[[^\]]*\](?:<=\[[^\]]*\])?)")


def collective_inventory(hlo_text: str, unroll: int = 1) -> dict:
    """Per-collective accounting from optimized HLO text.

    Each collective instruction becomes a row: ``opcode`` (async
    ``-start`` forms normalized to the base op; ``-done`` halves skipped
    — one wire transfer, not two), ``count`` (execution weight, whole
    module — scan bodies weighted by trip count), ``out_bytes`` /
    ``operand_bytes`` per execution, ``accounting_bytes`` (out +
    operands, the HloCostAnalysis convention the bytes audit uses — the
    number that ties out against ``bytes_audit``'s "collective"
    category), and ``replica_groups`` (the partition literal: which
    devices reduce together).

    The summary normalizes by ``unroll`` so records from
    differently-fused programs compare directly:

    * ``per_step``: {opcode: {count, out_bytes, accounting_bytes}}
    * ``multiset``: {opcode: count} — the golden per-trainer inventory
      (the ``test_device_data`` collective-set assertion, generalized
      into a measurement)
    * ``total_*_per_step`` rollups.

    ``out_bytes`` is the per-op OUTPUT size (the convention
    ``bench_scaling.collective_traffic`` reports); for a same-size
    all-reduce output==operand, for all-gather output is the gathered
    size, for reduce-scatter the scattered shard.  Collectives inside a
    ``conditional`` (e.g. the async worker average, gated on the period)
    are counted at the caller's weight — sustained traffic for
    period-gated ops is count/period, which the caller divides."""
    comps, entry, weights = entry_walk(hlo_text, unroll)
    empty = {"ops": [], "per_step": {}, "multiset": {},
             "total_count_per_step": 0, "total_out_bytes_per_step": 0,
             "total_accounting_bytes_per_step": 0, "unroll": max(1, unroll)}
    if entry is None:
        return empty

    rows = []
    for comp, weight in weights.items():
        for name, out_tok, opcode, line, args_at in comps.get(comp, ()):
            base = opcode[:-6] if opcode.endswith("-start") else opcode
            if base not in _COLLECTIVE_OPCODES or opcode.endswith("-done"):
                continue
            operands = _operand_token(line, args_at)
            out_b = _shape_bytes(out_tok)
            op_b = sum(_shape_bytes(s.group(0))
                       for s in _SHAPE_RE.finditer(operands))
            mg = _REPLICA_GROUPS_RE.search(line)
            rows.append({"opcode": base, "name": name, "count": weight,
                         "out_bytes": out_b, "operand_bytes": op_b,
                         "accounting_bytes": out_b + op_b,
                         "replica_groups": mg.group(1) if mg else "",
                         "out": out_tok.strip()[:60]})
    rows.sort(key=lambda r: -r["out_bytes"] * r["count"])

    u = max(1, unroll)

    def norm(x):
        # per-step weights are whole numbers for everything our programs
        # emit; keep exactness when they are, floats when they are not
        q = x / u
        return int(q) if q == int(q) else round(q, 6)

    per_step: dict[str, dict] = {}
    for r in rows:
        d = per_step.setdefault(r["opcode"],
                                {"count": 0, "out_bytes": 0,
                                 "accounting_bytes": 0})
        d["count"] += r["count"]
        d["out_bytes"] += r["out_bytes"] * r["count"]
        d["accounting_bytes"] += r["accounting_bytes"] * r["count"]
    for d in per_step.values():
        for k in d:
            d[k] = norm(d[k])
    return {
        "ops": rows,
        "per_step": dict(sorted(per_step.items(),
                                key=lambda kv: -kv[1]["out_bytes"])),
        "multiset": {op: d["count"] for op, d in sorted(per_step.items())},
        "total_count_per_step": norm(sum(r["count"] for r in rows)),
        "total_out_bytes_per_step": norm(
            sum(r["out_bytes"] * r["count"] for r in rows)),
        "total_accounting_bytes_per_step": norm(
            sum(r["accounting_bytes"] * r["count"] for r in rows)),
        "unroll": u,
    }


def collective_inventory_of(step, args, unroll: int = 1) -> dict:
    """Lower+compile a jitted *step* once and inventory its collectives.
    Degrades to ``{}`` when the backend can't lower/expose the module
    (same contract as :func:`cost_and_bytes_audit`).  NOTE: an AOT
    ``lower().compile()`` does NOT populate the jit's own executable
    cache on this jax pin, so calling this costs one extra compile of
    the program — callers gate it (OBS_COLLECTIVES=1, bench phases)
    rather than paying it on every run."""
    try:
        compiled = step.lower(*args).compile()
        return collective_inventory(compiled.as_text(), unroll=unroll)
    except Exception:
        return {}


def cost_and_bytes_audit(step, args, unroll: int = 1, top_k: int = 12,
                         audit: bool = True) -> tuple[dict, dict]:
    """Lower+compile a jitted *step* ONCE and return
    ``(cost, audit)``: per-step flops/bytes from XLA's own cost analysis
    plus the per-op audit.  THE one implementation of the cost-key
    extraction — ``bench._cost_per_step`` delegates here — so the
    aggregate numbers in every record come from the same code path.
    Either half degrades to ``{}`` independently — backends differ in
    what they expose; ``audit=False`` skips the HLO-text parse for
    callers that only want the aggregates."""
    cost: dict = {}
    table: dict = {}
    try:
        compiled = step.lower(*args).compile()
    except Exception:
        return cost, table
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        for key, name in (("flops", "flops"),
                          ("bytes accessed", "bytes_accessed")):
            if key in ca:
                cost[name] = float(ca[key]) / max(1, unroll)
    except Exception:
        pass
    if audit:
        try:
            table = bytes_audit(compiled.as_text(), unroll=unroll,
                                top_k=top_k)
        except Exception:
            pass
    return cost, table
