"""Scoped signal-handler installation (trainer preemption path).

bench.py keeps its own inline copy of this pattern ON PURPOSE: importing
any package module pulls in jax, and bench's record-survival contract
requires its SIGTERM handler live BEFORE the first package import.  Keep
the two restore semantics in sync."""

from __future__ import annotations

import contextlib
import signal
import threading


@contextlib.contextmanager
def installed_signal_handler(signum: int, handler):
    """Install ``handler`` for ``signum`` — main thread only
    (``signal.signal``'s requirement; other threads no-op and yield
    False) — and restore the previous disposition on exit, so embedding
    the caller in a larger process (pytest, a notebook) doesn't
    permanently hijack its signals.

    Restore detail: a previous handler installed by non-Python code
    reads back as ``None``, which ``signal.signal`` refuses to accept —
    restore ``SIG_DFL`` in that case rather than raising TypeError out
    of the ``finally`` (which would mask the in-flight exit path).
    """
    install = threading.current_thread() is threading.main_thread()
    prev = signal.signal(signum, handler) if install else None
    try:
        yield install
    finally:
        if install:
            signal.signal(signum,
                          prev if prev is not None else signal.SIG_DFL)


class SigtermFlag:
    """Truthy once SIGTERM has been delivered.  The handler only flips
    this flag — the cooperative-interruption contract (see TrainLoop:
    raising from a handler after the step donated its input state leaves
    deleted buffers) shared by run_training, tools/faultline.py, and the
    injected-preemption fault (resilience/faults.py)."""

    __slots__ = ("_seen",)

    def __init__(self):
        self._seen = False

    def __bool__(self) -> bool:
        return self._seen

    def __call__(self) -> bool:
        return self._seen


@contextlib.contextmanager
def sigterm_flag():
    """Install a flag-setting SIGTERM handler for the enclosed block and
    yield the flag (poll it at safe boundaries; never raise from it)."""
    flag = SigtermFlag()

    def _handler(signum, frame):
        flag._seen = True

    with installed_signal_handler(signal.SIGTERM, _handler):
        yield flag
