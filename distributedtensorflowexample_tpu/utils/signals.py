"""Scoped signal-handler installation (trainer preemption path).

bench.py keeps its own inline copy of this pattern ON PURPOSE: importing
any package module pulls in jax, and bench's record-survival contract
requires its SIGTERM handler live BEFORE the first package import.  Keep
the two restore semantics in sync."""

from __future__ import annotations

import contextlib
import signal
import threading


@contextlib.contextmanager
def installed_signal_handler(signum: int, handler):
    """Install ``handler`` for ``signum`` — main thread only
    (``signal.signal``'s requirement; other threads no-op and yield
    False) — and restore the previous disposition on exit, so embedding
    the caller in a larger process (pytest, a notebook) doesn't
    permanently hijack its signals.

    Restore detail: a previous handler installed by non-Python code
    reads back as ``None``, which ``signal.signal`` refuses to accept —
    restore ``SIG_DFL`` in that case rather than raising TypeError out
    of the ``finally`` (which would mask the in-flight exit path).
    """
    install = threading.current_thread() is threading.main_thread()
    prev = signal.signal(signum, handler) if install else None
    try:
        yield install
    finally:
        if install:
            signal.signal(signum,
                          prev if prev is not None else signal.SIG_DFL)
