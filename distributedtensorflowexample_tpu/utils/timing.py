"""Device-honest timing.

Under JAX everything is async: a ``time.perf_counter()`` pair around a step
call measures dispatch, not compute.  Every timer here takes an optional
result pytree and ``block_until_ready``'s it before reading the clock, so
reported seconds are wall-clock the device actually spent.  This is the
measurement discipline behind the headline steps/sec/chip metric
(BASELINE.json "metric"; SURVEY.md §5 observability row).
"""

from __future__ import annotations

import collections
import contextlib
import time

import jax


class Timer:
    """Accumulating timer:
    ``with timer.measure() as out: out["result"] = step(...)`` —
    the result pytree is drained before the clock stops."""

    def __init__(self):
        self.total = 0.0
        self.count = 0

    @contextlib.contextmanager
    def measure(self):
        sink: list[tuple[str, float]] = []
        with timed_block(sink=sink) as out:
            yield out
        self.total += sink[0][1]
        self.count += 1

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


@contextlib.contextmanager
def timed_block(label: str = "", sink=None):
    """Time a block; assign ``out["result"]`` inside to sync on device work.

    ``with timed_block("step") as out: out["result"] = step(...)`` — the
    result pytree is drained before the clock is read, so async dispatch
    cannot make the block look faster than the device.
    """
    out = {}
    t0 = time.perf_counter()
    yield out
    if "result" in out:
        jax.block_until_ready(out["result"])
    dt = time.perf_counter() - t0
    if sink is not None:
        sink.append((label, dt))
    else:
        print(f"[timing] {label or 'block'}: {dt * 1e3:.2f} ms", flush=True)


class RateMeter:
    """Sliding steps/sec meter over the last window of events."""

    def __init__(self, window: int = 50):
        self._stamps: collections.deque[float] = collections.deque(
            maxlen=max(2, window))

    def tick(self) -> None:
        self._stamps.append(time.perf_counter())

    @property
    def rate(self) -> float:
        if len(self._stamps) < 2:
            return 0.0
        dt = self._stamps[-1] - self._stamps[0]
        return (len(self._stamps) - 1) / dt if dt > 0 else 0.0
