"""Chief-aware stdout logging.

The reference's multi-process runs printed from every worker; the useful
convention it followed implicitly — chief (task_index 0) owns user-facing
output (SURVEY.md §3b control plane) — is made explicit here for the SPMD
rebuild, where every process runs the identical program.
"""

from __future__ import annotations

import jax


def chief_print(*args, **kwargs) -> None:
    """``print`` on process 0 only (safe before distributed init: then
    process_index() is 0 and it just prints)."""
    if jax.process_index() == 0:
        kwargs.setdefault("flush", True)
        print(*args, **kwargs)
