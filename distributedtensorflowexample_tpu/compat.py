"""Version shims for the jax API surface this codebase targets.

The code is written against the current jax API (``jax.shard_map`` with
``check_vma``, the ``jax_num_cpu_devices`` config); the image may pin an
older jax (0.4.x exposes ``jax.experimental.shard_map.shard_map`` with
``check_rep`` and configures virtual CPU devices only through the
``--xla_force_host_platform_device_count`` XLA flag).  Every call site
goes through these two helpers so the rest of the tree reads as
current-API code and the pin is handled in exactly one place.
"""

from __future__ import annotations

import os

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on current jax; the ``jax.experimental``
    spelling (``check_rep``) on 0.4.x.  Keyword-only like the new API."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def set_num_cpu_devices(n: int) -> None:
    """Configure ``n`` virtual CPU devices BEFORE first backend use.

    Current jax has the ``jax_num_cpu_devices`` config; 0.4.x only honors
    the ``--xla_force_host_platform_device_count`` XLA flag, which is read
    at backend-client creation, so rewriting ``XLA_FLAGS`` here still
    takes effect as long as no jax computation has run yet (the same
    contract the config option has).  An inherited pin (a parent process
    exporting its own count into our environment — the subprocess-test
    shape) is REPLACED while the backend is uninitialized; once backends
    exist, a conflicting value raises like the config route does, instead
    of silently keeping the old count.
    """
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
        return
    except AttributeError:
        pass
    flag = "--xla_force_host_platform_device_count"
    flags = os.environ.get("XLA_FLAGS", "")
    keep = [t for t in flags.split() if not t.startswith(flag)]
    want = f"{flag}={int(n)}"
    if want not in flags.split():
        # The count is actually changing: past backend init the flag is
        # never re-read, so succeeding silently here would strand the
        # caller with the old device count (the config route raises in
        # exactly this situation).
        from jax._src import xla_bridge
        if xla_bridge.backends_are_initialized():
            raise RuntimeError(
                f"backend already initialized with a different CPU device "
                f"count (XLA_FLAGS {flags!r}); cannot re-pin to {n} — the "
                f"flag is read once at backend init")
    os.environ["XLA_FLAGS"] = " ".join(keep + [want]).strip()


def cpu_collective_flags(warn_s: int = 60, terminate_s: int = 300) -> str:
    """The XLA:CPU collective-rendezvous deadline flags, or "" when this
    jaxlib predates them.  An UNKNOWN name in XLA_FLAGS is a FATAL abort
    at first backend init (parse_flags_from_env.cc), so the flags must be
    version-gated, not passed hopefully; 0.4.x jaxlibs don't have them
    (and their looser default rendezvous behavior needs no lifting)."""
    if jax.__version_info__ < (0, 5, 0):
        return ""
    return (f" --xla_cpu_collective_call_warn_stuck_timeout_seconds={warn_s}"
            f" --xla_cpu_collective_call_terminate_timeout_seconds="
            f"{terminate_s}")


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` on jax versions that have it;
    on 0.4.x (which predates the public predicate) the same answer read
    from the runtime's global state — a live coordinator client."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    from jax._src import distributed
    return distributed.global_state.client is not None


def enable_persistent_compilation_cache(
        cache_dir: str, min_compile_secs: float = 0.5) -> None:
    """Enable jax's persistent compilation cache — only on jax versions
    where a deserialized executable is trustworthy.

    On 0.4.x jaxlibs a cache HIT on a program with donated arguments
    comes back without its donation write-back: reproduced on
    jax 0.4.37 / jaxlib 0.4.36 — the jitted train step's BN running
    stats return bitwise-unchanged from a cache-loaded executable while
    the identical program freshly compiled updates them (same loss, so
    the corruption is silent).  A silently wrong training step costs
    more than every compile the cache saves, so on those versions this
    is a no-op and every process pays its own compiles."""
    if jax.__version_info__ < (0, 5, 0):
        return
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
