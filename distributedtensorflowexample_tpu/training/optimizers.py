"""Optimizer construction (reference: GradientDescentOptimizer /
SyncReplicasOptimizer wrapping — SURVEY.md §3b/§3c).

Sync gradient aggregation needs no optimizer wrapper here: by the time
updates are applied the gradients are already the global-batch mean (XLA
psum inside the jitted step), which is exactly what SyncReplicasOptimizer's
PS-side accumulator barrier produced.  So this module only builds the base
transformation + LR schedule.
"""

from __future__ import annotations

import optax

from distributedtensorflowexample_tpu.config import RunConfig


def build_schedule(cfg: RunConfig) -> optax.Schedule:
    base = cfg.learning_rate
    if cfg.lr_schedule == "constant":
        sched = optax.constant_schedule(base)
    elif cfg.lr_schedule == "cosine":
        decay_steps = max(1, cfg.train_steps - cfg.warmup_steps)
        sched = optax.cosine_decay_schedule(base, decay_steps)
    elif cfg.lr_schedule == "step":
        # He-style CIFAR schedule: /10 at 50% and 75% of training.  When
        # warmup is joined in front, this schedule is evaluated at
        # (step - warmup_steps), so express boundaries in that frame to keep
        # the drops at the advertised global steps.
        half = max(1, cfg.train_steps // 2 - cfg.warmup_steps)
        three_q = max(2, (cfg.train_steps * 3) // 4 - cfg.warmup_steps)
        sched = optax.piecewise_constant_schedule(base, {half: 0.1, three_q: 0.1})
    else:
        raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, base, cfg.warmup_steps)
        sched = optax.join_schedules([warmup, sched], [cfg.warmup_steps])
    return sched


def build_optimizer(cfg: RunConfig,
                    mesh=None) -> optax.GradientTransformation:
    sched = build_schedule(cfg)
    if cfg.fused_optimizer:
        if cfg.momentum <= 0.0 or cfg.weight_decay > 0.0:
            raise ValueError(
                "--fused_optimizer implements momentum SGD only; it needs "
                f"momentum > 0 (got {cfg.momentum}) and weight_decay == 0 "
                f"(got {cfg.weight_decay})")
        # Hand-written Pallas apply (ops/pallas/sgd.py); optax-compatible.
        from distributedtensorflowexample_tpu.ops.pallas import (
            fused_momentum_sgd)
        return fused_momentum_sgd(sched, cfg.momentum, mesh=mesh)
    if cfg.momentum > 0.0:
        tx = optax.sgd(sched, momentum=cfg.momentum, nesterov=False)
    else:
        tx = optax.sgd(sched)
    if cfg.weight_decay > 0.0:
        tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    return tx
