"""Optimizer construction (reference: GradientDescentOptimizer /
SyncReplicasOptimizer wrapping — SURVEY.md §3b/§3c).

Sync gradient aggregation needs no optimizer wrapper here: by the time
updates are applied the gradients are already the global-batch mean (XLA
psum inside the jitted step), which is exactly what SyncReplicasOptimizer's
PS-side accumulator barrier produced.  So this module builds the base
transformation + LR schedule, plus one execution-strategy wrapper:
:func:`cross_replica_update_sharding` (the ``--shard_update`` flag) shards
the weight update itself across the data mesh per Xu et al.,
arXiv:2004.13336 — the step definition is unchanged, only WHERE each
parameter's update runs moves (TF-Replicator's separation, 1902.00465).
"""

from __future__ import annotations

import jax
import optax

from distributedtensorflowexample_tpu.config import RunConfig
from distributedtensorflowexample_tpu.refusal import ModeRefusal


def build_schedule(cfg: RunConfig) -> optax.Schedule:
    base = cfg.learning_rate
    if cfg.lr_schedule == "constant":
        sched = optax.constant_schedule(base)
    elif cfg.lr_schedule == "cosine":
        decay_steps = max(1, cfg.train_steps - cfg.warmup_steps)
        sched = optax.cosine_decay_schedule(base, decay_steps)
    elif cfg.lr_schedule == "step":
        # He-style CIFAR schedule: /10 at 50% and 75% of training.  When
        # warmup is joined in front, this schedule is evaluated at
        # (step - warmup_steps), so express boundaries in that frame to keep
        # the drops at the advertised global steps.
        half = max(1, cfg.train_steps // 2 - cfg.warmup_steps)
        three_q = max(2, (cfg.train_steps * 3) // 4 - cfg.warmup_steps)
        sched = optax.piecewise_constant_schedule(base, {half: 0.1, three_q: 0.1})
    else:
        raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, base, cfg.warmup_steps)
        sched = optax.join_schedules([warmup, sched], [cfg.warmup_steps])
    return sched


def _update_shard_spec(shape, axis_name: str, num_shards: int):
    """PartitionSpec sharding the LARGEST axis divisible by *num_shards*
    (replicated when none is).  Per-leaf by shape only, so the optimizer
    state (params-shaped moments) and the gradients resolve identically
    without any tree-structure coupling."""
    from jax.sharding import PartitionSpec as P
    best = None
    for i, d in enumerate(shape):
        if d % num_shards == 0 and d >= num_shards:
            if best is None or d > shape[best]:
                best = i
    if best is None:
        return P()
    parts = [None] * len(shape)
    parts[best] = axis_name
    return P(*parts)


def update_shardings(tree, mesh):
    """Per-leaf NamedShardings for a params-like pytree under the
    cross-replica update sharding (scalars and indivisible leaves
    replicated).  Used to lay out the INITIAL optimizer state so the
    step's first call already sees the sharded layout (donation aliases
    from call one; no replicated->sharded recompile)."""
    from jax.sharding import NamedSharding
    from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
    D = mesh.shape[DATA_AXIS]
    return jax.tree.map(
        lambda x: NamedSharding(
            mesh, _update_shard_spec(getattr(x, "shape", ()), DATA_AXIS, D)),
        tree)


def cross_replica_update_sharding(tx: optax.GradientTransformation,
                                  mesh) -> optax.GradientTransformation:
    """Shard the weight update + optimizer state across the data mesh
    (``--shard_update``; Xu et al., arXiv:2004.13336 / ZeRO-1).

    Inside the jitted step, sharding constraints pin the gradients
    entering ``tx.update``, the optimizer state, and the produced updates
    to a 1/D shard per device (largest divisible axis).  The SPMD
    partitioner then materializes exactly the paper's schedule: the
    gradient all-reduce decomposes into reduce-scatter + (sharded update
    math) + all-gather of the updates — per-chip weight-update HBM
    traffic and optimizer-state residency drop ~1/D, while params stay
    replicated so forward/backward are untouched.  The transformation's
    MATH is unchanged (constraints only place data; the update is
    elementwise per parameter); only the gradient summation order may
    legitimately change (reduce-scatter vs all-reduce), which is why the
    parity test asserts allclose, not bitwise.

    No-op on a 1-extent data axis."""
    from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
    if mesh.shape[DATA_AXIS] <= 1:
        return tx

    def constrain(tree):
        # ONE leaf->sharding rule: the same update_shardings that lays
        # out the initial optimizer state, so the in-step constraints can
        # never drift from the call-one layout (scalars replicate — a
        # replicated constraint is a no-op, no special-casing needed).
        return jax.tree.map(jax.lax.with_sharding_constraint,
                            tree, update_shardings(tree, mesh))

    def init(params):
        return constrain(tx.init(params))

    def update(updates, state, params=None):
        new_updates, new_state = tx.update(
            constrain(updates), state,
            constrain(params) if params is not None else None)
        # The sharded updates feed optax.apply_updates against replicated
        # params — GSPMD inserts the closing all-gather there.
        return constrain(new_updates), constrain(new_state)

    return optax.GradientTransformation(init, update)


def build_optimizer(cfg: RunConfig, mesh=None,
                    wrap_shard_update: bool = True
                    ) -> optax.GradientTransformation:
    """``wrap_shard_update=False`` skips the GSPMD-constraint wrapper
    even when ``cfg.shard_update`` is set: the bucketed step
    (``--bucket_grads`` + ``--shard_update``) IMPLEMENTS the
    reduce-scatter/sharded-update/all-gather schedule explicitly per
    bucket (parallel/bucketing.py) and applies the base transformation
    to flat row shards — constraint-wrapping it there would re-shard
    already-sharded rows."""
    sched = build_schedule(cfg)
    if cfg.fused_optimizer:
        if cfg.momentum <= 0.0 or cfg.weight_decay > 0.0:
            raise ModeRefusal(
                "--fused_optimizer implements momentum SGD only; it needs "
                f"momentum > 0 (got {cfg.momentum}) and weight_decay == 0 "
                f"(got {cfg.weight_decay})")
        if cfg.shard_update:
            raise ModeRefusal(
                "--shard_update shards the update with XLA sharding "
                "constraints; the Pallas fused apply is a custom call XLA "
                "cannot re-partition — use one or the other")
        # Hand-written Pallas apply (ops/pallas/sgd.py); optax-compatible.
        from distributedtensorflowexample_tpu.ops.pallas import (
            fused_momentum_sgd)
        return fused_momentum_sgd(sched, cfg.momentum, mesh=mesh)
    if cfg.momentum > 0.0:
        tx = optax.sgd(sched, momentum=cfg.momentum, nesterov=False)
    else:
        tx = optax.sgd(sched)
    if cfg.weight_decay > 0.0:
        tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    if cfg.shard_update:
        if mesh is None:
            raise ModeRefusal("--shard_update requires a device mesh")
        if wrap_shard_update:
            tx = cross_replica_update_sharding(tx, mesh)
    return tx
