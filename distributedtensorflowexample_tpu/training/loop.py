"""The training loop (component C12, SURVEY.md §2).

Replaces ``MonitoredTrainingSession``: a plain Python loop around ONE jitted
step call, with hooks for stop/checkpoint/eval/logging.  Per-step host work
is a dict lookup and an iterator next — metrics stay on device until the log
boundary, batches are prefetched (``DevicePrefetcher``), so the device never
waits on the host at MNIST-scale step times.
"""

from __future__ import annotations

import time
from typing import Iterable, Iterator

import jax

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.training.hooks import Hook
from distributedtensorflowexample_tpu.training.metrics import MetricsLogger
from distributedtensorflowexample_tpu.training.state import TrainState

# Step-time anatomy counters (obs/timeline.step_anatomy's tie-out
# surface): where each call boundary's wall time goes — the host batch
# fetch, the train-step call (dispatch + compute + collective wait),
# the after_step hooks.  The remainder (logging, loop bookkeeping) is
# the anatomy table's "other".  Per boundary this costs two extra
# perf_counter reads and three lock-free counter adds — inside the
# MetricsHook <1% overhead budget, guarded with it in tests/test_obs.py.
_INPUT_S = obs_metrics.counter(
    "loop_input_seconds_total", "wall seconds fetching batches at loop "
    "call boundaries")
_STEP_S = obs_metrics.counter(
    "loop_step_seconds_total", "wall seconds inside the train-step call "
    "(dispatch + compute + collective wait)")
_HOOK_S = obs_metrics.counter(
    "loop_hook_seconds_total", "wall seconds in after_step hooks "
    "(checkpoint/eval/telemetry)")


class TrainLoop:
    def __init__(self, train_step, batches: Iterator, num_steps: int,
                 hooks: Iterable[Hook] = (), logger: MetricsLogger | None = None,
                 steps_per_call: int = 1, should_stop=None):
        """``steps_per_call``: global steps one train_step call advances
        (the indexed step's ``unroll_steps``).  Hooks fire at call
        boundaries; interval hooks handle strides that jump their mark.

        ``should_stop``: optional zero-arg callable polled at CALL
        boundaries — the cooperative interruption point for signal-driven
        stops (preemption SIGTERM).  Polling, not raising from the
        handler, is load-bearing: the train step DONATES the input state,
        so an exception landing inside the call after donation leaves
        ``state`` pointing at deleted buffers and the save-on-exit path
        crashes with "Array has been deleted" (observed).  At a boundary
        the state is always the last completed step's."""
        self._train_step = train_step
        self._batches = batches
        # Post-dispatch prefetch hook (DeviceDataset.prefetch): computes
        # the NEXT window's epoch permutations while the just-enqueued
        # step runs, so the dispatch boundary never waits on them.
        self._prefetch = getattr(batches, "prefetch", None)
        self._num_steps = num_steps
        self._hooks = list(hooks)
        self._logger = logger or MetricsLogger()
        self._spc = max(1, steps_per_call)
        self._should_stop = should_stop
        self.start_step = 0

    def run(self, state: TrainState) -> TrainState:
        start = int(state.step)
        self.start_step = start
        for h in self._hooks:
            h.begin(self)
        self._logger.start(start)
        metrics = None
        interrupted = None
        try:
            for step in range(start + self._spc, self._num_steps + 1,
                              self._spc):
                if self._should_stop is not None and self._should_stop():
                    break
                t0 = time.perf_counter()
                batch = next(self._batches)
                t1 = time.perf_counter()
                state, metrics = self._train_step(state, batch)
                t2 = time.perf_counter()
                if self._prefetch is not None:
                    # AFTER the step dispatch: the perm updates enqueue
                    # behind the in-flight step and overlap it.  Outside
                    # the t1..t2 window — its host cost is loop
                    # bookkeeping (the anatomy "other" column), not the
                    # train-step call.
                    self._prefetch()
                # Input/step fed BEFORE the hooks run, so MetricsHook's
                # log-boundary "steps" event reads deltas that include
                # THIS boundary; the hook counter necessarily lands
                # after (its window is still open here) — the anatomy
                # hook column therefore trails one boundary (DESIGN §16).
                _INPUT_S.inc(t1 - t0)
                _STEP_S.inc(t2 - t1)
                self._logger.maybe_log(step, metrics)
                # Every hook sees every step (no short-circuit) — a stop
                # request must not mask another hook's work at the same
                # step.  Hook wall time (eval, checkpoint serialization) is
                # discounted from the throughput window so steps_per_sec
                # stays a training rate.
                t_hooks = time.perf_counter()
                stops = [h.after_step(step, state, metrics)
                         for h in self._hooks]
                dt_hooks = time.perf_counter() - t_hooks
                _HOOK_S.inc(dt_hooks)
                self._logger.exclude(dt_hooks)
                if any(stops):
                    break
        except KeyboardInterrupt as e:
            # MonitoredTrainingSession saved on exit; preserve the same
            # Ctrl-C behavior — `state` is the last COMPLETED step's state,
            # safe to hand to the end-hooks (final checkpoint) below.  Say
            # so: the save can take seconds, and a silent pause invites a
            # second Ctrl-C that would abort it.
            from distributedtensorflowexample_tpu.utils.logging import (
                chief_print)
            chief_print(f"interrupted at step {int(state.step)} — running "
                        f"exit hooks (final checkpoint) before exiting")
            interrupted = e
        # Drain outstanding device work so end-hooks (checkpoint) see final
        # values and wall-clock accounting is honest.  A second Ctrl-C
        # landing here (or inside an end-hook) must not skip the remaining
        # exit hooks — the final checkpoint is exactly what the user is
        # about to lose — so catch, keep going, re-raise at the end.
        try:
            if metrics is not None:
                jax.block_until_ready(metrics)
        except KeyboardInterrupt as e:
            interrupted = interrupted or e
        for h in self._hooks:
            try:
                h.end(state)
            except KeyboardInterrupt as e:
                from distributedtensorflowexample_tpu.utils.logging import (
                    chief_print)
                chief_print("interrupt during exit hooks — still running "
                            "remaining exit hooks before exiting")
                interrupted = interrupted or e
        if interrupted is not None:
            raise interrupted
        return state
