"""Training state pytree.

One immutable pytree carries everything the jitted step mutates — the JAX
analog of the reference's mutable graph variables + global_step owned by the
``MonitoredTrainingSession``.  Keeping it a single pytree lets the step
donate it (in-place HBM update, no realloc) and lets Orbax checkpoint it
wholesale.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                     # scalar int32 — the global_step
    params: Any
    opt_state: Any
    batch_stats: Any                      # BN running stats ({} if none)
    rng: jax.Array                        # base PRNG key; fold_in(step) per step
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    apply_fn: Callable = flax.struct.field(pytree_node=False)

    @classmethod
    def create(cls, model, tx: optax.GradientTransformation,
               sample_input: jnp.ndarray, seed: int = 0) -> "TrainState":
        rng = jax.random.PRNGKey(seed)
        init_rng, state_rng = jax.random.split(rng)
        variables = model.init({"params": init_rng, "dropout": init_rng},
                               sample_input, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params), batch_stats=batch_stats,
                   rng=state_rng, tx=tx, apply_fn=model.apply)

    @classmethod
    def create_sharded(cls, model, tx: optax.GradientTransformation,
                       sample_shape: tuple[int, ...], seed: int,
                       sharding) -> "TrainState":
        """Init directly into a (replicated) NamedSharding under jit.

        Initializing under jit with ``out_shardings`` is the multi-host-safe
        path: every process traces the same program, XLA materializes the
        state already laid out on the mesh — no host-side init + transfer.
        """
        def init(rng):
            init_rng, state_rng = jax.random.split(rng)
            variables = model.init({"params": init_rng, "dropout": init_rng},
                                   jnp.zeros(sample_shape, jnp.float32),
                                   train=False)
            params = variables["params"]
            return cls(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=tx.init(params),
                       batch_stats=variables.get("batch_stats", {}),
                       rng=state_rng, tx=tx, apply_fn=model.apply)

        return jax.jit(init, out_shardings=sharding)(jax.random.PRNGKey(seed))
