"""Step metrics + throughput accounting.

steps/sec/chip is THE headline metric (BASELINE.json "metric"), so the loop
owns its measurement: wall time between flushes, device arrays fetched only
at log boundaries (never per step — that would serialize host and device),
scalars mirrored to stdout (the reference's UX), a JSONL scalar log
(greppable), and a native TensorBoard tfevents file (utils/tfevents.py —
the ``tf.summary`` replacement; ``tensorboard --logdir`` works directly).

Throughput windows are honest: the loop reports hook execution time
(eval/checkpoint wall time) via :meth:`exclude`, so ``steps_per_sec``
measures training, not whatever ran between flushes.
"""

from __future__ import annotations

import json
import os
import time

import jax


class MetricsLogger:
    def __init__(self, log_dir: str = "", num_chips: int = 1,
                 is_chief: bool = True, log_every: int = 100):
        self._num_chips = max(1, num_chips)
        self._is_chief = is_chief
        self._log_every = max(1, log_every)
        self._last_time = None
        self._last_step = 0
        self._file = None
        self._events = None
        if log_dir and is_chief:
            os.makedirs(log_dir, exist_ok=True)
            self._file = open(os.path.join(log_dir, "scalars.jsonl"), "a",
                              buffering=1)
            from distributedtensorflowexample_tpu.utils.tfevents import (
                TFEventsWriter)
            self._events = TFEventsWriter(log_dir)
        self.last_steps_per_sec = 0.0

    def start(self, step: int):
        self._last_step = step
        self._last_time = time.perf_counter()

    def exclude(self, seconds: float) -> None:
        """Discount ``seconds`` of non-training wall time (hook execution)
        from the current throughput window."""
        if self._last_time is not None:
            self._last_time += seconds

    def maybe_log(self, step: int, metrics) -> None:
        # Boundary-crossing check (not a modulo): with a multi-step train
        # call the step counter advances in strides, and a stride that
        # jumps over a multiple of log_every must still log.
        if step < self._last_step + self._log_every:
            return
        # Block on the metric values only here, at the log boundary.
        fetched = {k: float(v) for k, v in
                   jax.device_get(metrics).items()}
        now = time.perf_counter()
        if self._last_time is not None and step > self._last_step:
            # dt can only be non-positive if exclude() over-discounted (a
            # hook outlived the window); skip the rate rather than report
            # a negative or bogus one.
            dt = now - self._last_time
            if dt > 0:
                sps = (step - self._last_step) / dt
                self.last_steps_per_sec = sps
                fetched["steps_per_sec"] = round(sps, 2)
                fetched["steps_per_sec_per_chip"] = round(
                    sps / self._num_chips, 2)
        self._last_time = now
        self._last_step = step
        if self._is_chief:
            parts = " ".join(f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                             for k, v in fetched.items())
            print(f"step {step}: {parts}", flush=True)
            if self._file:
                self._file.write(json.dumps({"step": step, **fetched}) + "\n")
            if self._events:
                for name, value in fetched.items():
                    self._events.scalar(step, name, value)
                self._events.flush()

    def scalar(self, step: int, name: str, value: float) -> None:
        if self._is_chief:
            print(f"step {step}: {name}={value:.4f}", flush=True)
            if self._file:
                self._file.write(json.dumps({"step": step, name: value}) + "\n")
            if self._events:
                self._events.scalar(step, name, value)
                self._events.flush()

    def close(self):
        if self._file:
            self._file.close()
            self._file = None
        if self._events:
            self._events.close()
            self._events = None
