"""Step metrics + throughput accounting.

steps/sec/chip is THE headline metric (BASELINE.json "metric"), so the loop
owns its measurement: wall time between flushes, device arrays fetched only
at log boundaries (never per step — that would serialize host and device),
scalars mirrored to stdout (the reference's UX) and a JSONL scalar log (the
``tf.summary`` replacement, greppable and TensorBoard-convertible).
"""

from __future__ import annotations

import json
import os
import time

import jax


class MetricsLogger:
    def __init__(self, log_dir: str = "", num_chips: int = 1,
                 is_chief: bool = True, log_every: int = 100):
        self._num_chips = max(1, num_chips)
        self._is_chief = is_chief
        self._log_every = max(1, log_every)
        self._last_time = None
        self._last_step = 0
        self._file = None
        if log_dir and is_chief:
            os.makedirs(log_dir, exist_ok=True)
            self._file = open(os.path.join(log_dir, "scalars.jsonl"), "a",
                              buffering=1)
        self.last_steps_per_sec = 0.0

    def start(self, step: int):
        self._last_step = step
        self._last_time = time.perf_counter()

    def maybe_log(self, step: int, metrics) -> None:
        # Boundary-crossing check (not a modulo): with a multi-step train
        # call the step counter advances in strides, and a stride that
        # jumps over a multiple of log_every must still log.
        if step < self._last_step + self._log_every:
            return
        # Block on the metric values only here, at the log boundary.
        fetched = {k: float(v) for k, v in
                   jax.device_get(metrics).items()}
        now = time.perf_counter()
        if self._last_time is not None and step > self._last_step:
            dt = now - self._last_time
            sps = (step - self._last_step) / dt
            self.last_steps_per_sec = sps
            fetched["steps_per_sec"] = round(sps, 2)
            fetched["steps_per_sec_per_chip"] = round(sps / self._num_chips, 2)
        self._last_time = now
        self._last_step = step
        if self._is_chief:
            parts = " ".join(f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                             for k, v in fetched.items())
            print(f"step {step}: {parts}", flush=True)
            if self._file:
                self._file.write(json.dumps({"step": step, **fetched}) + "\n")

    def scalar(self, step: int, name: str, value: float) -> None:
        if self._is_chief:
            print(f"step {step}: {name}={value:.4f}", flush=True)
            if self._file:
                self._file.write(json.dumps({"step": step, name: value}) + "\n")

    def close(self):
        if self._file:
            self._file.close()
            self._file = None
