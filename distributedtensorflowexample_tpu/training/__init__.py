from distributedtensorflowexample_tpu.training.state import TrainState
from distributedtensorflowexample_tpu.training.optimizers import build_optimizer
from distributedtensorflowexample_tpu.training.loop import TrainLoop

__all__ = ["TrainState", "build_optimizer", "TrainLoop"]
