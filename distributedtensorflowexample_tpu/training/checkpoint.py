"""Checkpoint/resume via Orbax (SURVEY.md §5: the mandated mapping from
``MonitoredTrainingSession`` checkpoint hooks / ``Saver``).

Semantics preserved from the reference: periodic saves, keep-N rotation,
auto-restore-from-latest on startup, chief-only effective writes (Orbax is
multi-host aware — every process must call save, primary writes).  Gained:
async saves (training does not stall on serialization).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from distributedtensorflowexample_tpu.training.state import TrainState


def saveable_state_dict(state: TrainState) -> dict[str, Any]:
    """The serializable subset of a TrainState — THE one definition of
    what a checkpoint contains, shared with the crash-consistent
    snapshot format (resilience/snapshot.py) so the two restore paths
    can never drift on which fields make a run resumable."""
    # tx/apply_fn are static code, not state — exclude from serialization.
    return {"step": state.step, "params": state.params,
            "opt_state": state.opt_state, "batch_stats": state.batch_stats,
            "rng": state.rng}


_saveable = saveable_state_dict


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True, run_metadata: dict | None = None):
        """``run_metadata``: small JSON-able facts about the writing run
        (e.g. ``sync_mode``) persisted next to the checkpoints so a later
        run can refuse a structurally-incompatible restore with a clear
        error instead of a shape mismatch deep inside Orbax."""
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save))
        self._run_metadata = run_metadata

    def save(self, step: int, state: TrainState, force: bool = False) -> bool:
        step = int(step)
        if step in self._mgr.all_steps():
            return False  # periodic save already covered this step
        self._write_run_metadata()
        return self._mgr.save(step,
                              args=ocp.args.StandardSave(_saveable(state)),
                              force=force)

    def _write_run_metadata(self) -> None:
        """Keep the metadata describing the CURRENT writer: a reused
        directory whose new (non-resumed) run differs must overwrite, or a
        later resume of the new checkpoints would be wrongly refused."""
        if self._run_metadata is None:
            return
        path = os.path.join(self._dir, "run_metadata.json")
        if self.saved_run_metadata() == self._run_metadata:
            return
        if jax.process_index() == 0:  # chief-only, atomic via rename
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._run_metadata, f)
            os.replace(tmp, path)

    def saved_run_metadata(self) -> dict | None:
        """Metadata of the run that wrote this directory (None if absent —
        e.g. a checkpoint written before metadata existed)."""
        path = os.path.join(self._dir, "run_metadata.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state: TrainState, step: int | None = None) -> TrainState:
        """Restore into the structure (and shardings) of ``state``."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return state
        template = jax.tree.map(lambda x: x, _saveable(state))
        restored = self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(template))
        return state.replace(**restored)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
