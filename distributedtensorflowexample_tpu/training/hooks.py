"""Training hooks — the MonitoredTrainingSession hook surface, JAX-native.

The reference attached ``StopAtStepHook`` / checkpoint / summary hooks to
``MonitoredTrainingSession`` (BASELINE.json north star names the API).  Here
a hook sees the loop at well-defined points; stopping is a return value so
the loop stays a plain Python for-loop around one jitted call.
"""

from __future__ import annotations

import os
import time
from typing import TYPE_CHECKING

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import trace as obs_trace

if TYPE_CHECKING:
    from distributedtensorflowexample_tpu.training.state import TrainState


class Hook:
    def begin(self, loop) -> None: ...
    def after_step(self, step: int, state: "TrainState", metrics) -> bool:
        """Return True to request a stop (StopAtStepHook semantics)."""
        return False
    def end(self, state: "TrainState") -> None: ...


class StopAtStepHook(Hook):
    def __init__(self, last_step: int):
        self._last_step = last_step

    def after_step(self, step, state, metrics) -> bool:
        return step >= self._last_step


class _EveryN:
    """Boundary-crossing interval check: fires when the step counter reaches
    or jumps past the next multiple of ``every`` — correct both for stride-1
    loops and multi-step train calls that advance several steps per call."""

    def __init__(self, every: int, start: int = 0):
        self._every = every
        self._next = None if not every else (start // every + 1) * every

    def __call__(self, step: int) -> bool:
        if self._next is None or step < self._next:
            return False
        self._next = (step // self._every + 1) * self._every
        return True


class CheckpointHook(Hook):
    """Periodic + final checkpoint via the Orbax-backed manager."""

    def __init__(self, manager, every: int):
        self._manager = manager
        self._due = _EveryN(every)

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            with obs_trace.span("checkpoint", step=step):
                self._manager.save(step, state)
        return False

    def end(self, state) -> None:
        with obs_trace.span("checkpoint", step=int(state.step), final=True):
            self._manager.save(int(state.step), state, force=True)
            self._manager.wait()


def touch_heartbeat(path: str) -> None:
    """Create/refresh the beat file — THE one beat implementation
    (HeartbeatHook and the heartbeat_flap fault must emit the identical
    beat, or the drill tests a different signal than the watchdog
    reads).  Swallows OSError: a full disk must not kill the run the
    beat protects."""
    try:
        with open(path, "a"):
            pass
        os.utime(path)
    except OSError:
        pass


class HeartbeatHook(Hook):
    """Touch ``path`` at call boundaries so an external watchdog
    (resilience.supervisor) can tell a slow-but-alive run from a wedged
    dispatch: a jit call blocked on a dead backend never returns to the
    boundary, so the touches stop — the liveness signal a wall timeout
    alone can't give.  Installed automatically by run_training and
    tools/faultline.py when the supervisor exports SUPERVISE_HEARTBEAT."""

    def __init__(self, path: str, every: int = 1):
        self._path = path
        self._due = _EveryN(max(1, every))

    def _touch(self) -> None:
        touch_heartbeat(self._path)

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))
        self._touch()

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            self._touch()
        return False

    def end(self, state) -> None:
        self._touch()


class EvalHook(Hook):
    """Periodic exact-accuracy eval on a held-out split."""

    def __init__(self, eval_fn, every: int, logger):
        self._eval_fn = eval_fn
        self._due = _EveryN(every)
        self._logger = logger

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            with obs_trace.span("eval", step=step) as attrs:
                acc = self._eval_fn(state)
                attrs["accuracy"] = round(float(acc), 6)
            self._logger.scalar(step, "eval_accuracy", acc)
        return False


class MetricsHook(Hook):
    """Feed the process-wide obs registry — and the flight recorder,
    when one is installed — from loop call boundaries.

    Per-boundary cost is the registry's lock-free path (one counter
    add, one gauge set, one histogram observe, one ``perf_counter``):
    microbench-guarded under 2 us/increment and measured well under 1%
    of even a CPU step (tests/test_obs.py).  Everything that costs more
    — fetching the loss off device, snapshotting the registry for the
    recorder's delta ring, emitting the ``steps`` span — happens only
    on ``every``-step marks (run_training passes ``log_every``), so the
    device never waits on telemetry between log boundaries.
    """

    def __init__(self, every: int = 1, collectives: dict | None = None):
        self._every = max(1, every)
        self._steps = obs_metrics.counter(
            "train_steps_total", "completed global training steps")
        self._step_g = obs_metrics.gauge(
            "train_step", "last completed global step")
        self._loss_g = obs_metrics.gauge(
            "train_loss", "loss at the last sampled call boundary")
        self._window_h = obs_metrics.histogram(
            "train_window_seconds",
            "wall seconds between loop call boundaries")
        # Per-step collective accounting (utils/profiling.collective_
        # inventory summary, when the trainer armed it): static per-op
        # gauges set once, cumulative counters fed per boundary — two
        # lock-free adds on the hot path, nothing when absent.
        self._coll_ops = self._coll_bytes = None
        if collectives and collectives.get("multiset"):
            ops_g = obs_metrics.gauge(
                "collective_ops_per_step",
                "collectives per training step, from the compiled HLO")
            bytes_g = obs_metrics.gauge(
                "collective_bytes_per_step",
                "collective output bytes per training step")
            for op, d in collectives["per_step"].items():
                ops_g.labels(op=op).set(d["count"])
                bytes_g.labels(op=op).set(d["out_bytes"])
            self._coll_ops = obs_metrics.counter(
                "collective_ops_total",
                "collective operations dispatched (per-step inventory x "
                "completed steps)")
            self._coll_bytes = obs_metrics.counter(
                "collective_bytes_total",
                "collective output bytes moved (per-step inventory x "
                "completed steps)")
            self._coll_ops_per_step = collectives["total_count_per_step"]
            self._coll_bytes_per_step = collectives[
                "total_out_bytes_per_step"]
        self._due = _EveryN(self._every)
        self._last_step = 0
        self._last_t = self._mark_t = time.perf_counter()
        self._mark_step = 0
        self._prev_snap = None

    def begin(self, loop) -> None:
        self._due = _EveryN(self._every, int(loop.start_step))
        self._last_step = self._mark_step = int(loop.start_step)
        self._last_t = self._mark_t = time.perf_counter()
        self._prev_snap = None
        rec = obs_recorder.get()
        if rec is not None:
            rec.note(start_step=int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        now = time.perf_counter()
        advanced = step - self._last_step
        self._steps.inc(advanced)
        self._step_g.set(step)
        self._window_h.observe(now - self._last_t)
        if self._coll_ops is not None:
            self._coll_ops.inc(self._coll_ops_per_step * advanced)
            self._coll_bytes.inc(self._coll_bytes_per_step * advanced)
        self._last_step = step
        self._last_t = now
        if self._due(step):
            rec = obs_recorder.get()
            loss = metrics.get("loss") if isinstance(metrics, dict) else None
            if loss is not None:
                import numpy as np
                lossf = float(np.asarray(loss))
                self._loss_g.set(lossf)
                if rec is not None:
                    rec.record_loss(step, lossf)
            obs_trace.event("steps", now - self._mark_t,
                            step=step, n=step - self._mark_step)
            self._mark_step = step
            self._mark_t = now
            if rec is not None:
                snap = obs_metrics.registry().snapshot()
                if self._prev_snap is not None:
                    rec.record_delta(
                        obs_metrics.MetricsRegistry.delta(
                            self._prev_snap, snap))
                self._prev_snap = snap
        return False
