"""Training hooks — the MonitoredTrainingSession hook surface, JAX-native.

The reference attached ``StopAtStepHook`` / checkpoint / summary hooks to
``MonitoredTrainingSession`` (BASELINE.json north star names the API).  Here
a hook sees the loop at well-defined points; stopping is a return value so
the loop stays a plain Python for-loop around one jitted call.
"""

from __future__ import annotations

import os
import time
from typing import TYPE_CHECKING

from distributedtensorflowexample_tpu.obs import ledger as obs_ledger
from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import recorder as obs_recorder
from distributedtensorflowexample_tpu.obs import trace as obs_trace

if TYPE_CHECKING:
    from distributedtensorflowexample_tpu.training.state import TrainState


class Hook:
    def begin(self, loop) -> None: ...
    def after_step(self, step: int, state: "TrainState", metrics) -> bool:
        """Return True to request a stop (StopAtStepHook semantics)."""
        return False
    def end(self, state: "TrainState") -> None: ...


class StopAtStepHook(Hook):
    def __init__(self, last_step: int):
        self._last_step = last_step

    def after_step(self, step, state, metrics) -> bool:
        return step >= self._last_step


class _EveryN:
    """Boundary-crossing interval check: fires when the step counter reaches
    or jumps past the next multiple of ``every`` — correct both for stride-1
    loops and multi-step train calls that advance several steps per call."""

    def __init__(self, every: int, start: int = 0):
        self._every = every
        self._next = None if not every else (start // every + 1) * every

    def __call__(self, step: int) -> bool:
        if self._next is None or step < self._next:
            return False
        self._next = (step // self._every + 1) * self._every
        return True


class CheckpointHook(Hook):
    """Periodic + final checkpoint via the Orbax-backed manager."""

    def __init__(self, manager, every: int):
        self._manager = manager
        self._due = _EveryN(every)

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            with obs_trace.span("checkpoint", step=step):
                self._manager.save(step, state)
        return False

    def end(self, state) -> None:
        with obs_trace.span("checkpoint", step=int(state.step), final=True):
            self._manager.save(int(state.step), state, force=True)
            self._manager.wait()


def touch_heartbeat(path: str) -> None:
    """Create/refresh the beat file — THE one beat implementation
    (HeartbeatHook and the heartbeat_flap fault must emit the identical
    beat, or the drill tests a different signal than the watchdog
    reads).  Swallows OSError: a full disk must not kill the run the
    beat protects."""
    try:
        with open(path, "a"):
            pass
        os.utime(path)
    except OSError:
        pass


class HeartbeatHook(Hook):
    """Touch ``path`` at call boundaries so an external watchdog
    (resilience.supervisor) can tell a slow-but-alive run from a wedged
    dispatch: a jit call blocked on a dead backend never returns to the
    boundary, so the touches stop — the liveness signal a wall timeout
    alone can't give.  Installed automatically by run_training and
    tools/faultline.py when the supervisor exports SUPERVISE_HEARTBEAT."""

    def __init__(self, path: str, every: int = 1):
        self._path = path
        self._due = _EveryN(max(1, every))

    def _touch(self) -> None:
        touch_heartbeat(self._path)

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))
        self._touch()

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            self._touch()
        return False

    def end(self, state) -> None:
        self._touch()


class EvalHook(Hook):
    """Periodic exact-accuracy eval on a held-out split."""

    def __init__(self, eval_fn, every: int, logger):
        self._eval_fn = eval_fn
        self._due = _EveryN(every)
        self._logger = logger

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            with obs_trace.span("eval", step=step) as attrs:
                acc = self._eval_fn(state)
                attrs["accuracy"] = round(float(acc), 6)
            self._logger.scalar(step, "eval_accuracy", acc)
        return False


class MetricsHook(Hook):
    """Feed the process-wide obs registry — and the flight recorder,
    when one is installed — from loop call boundaries.

    Per-boundary cost is the registry's lock-free path (one counter
    add, one gauge set, one histogram observe, one ``perf_counter``):
    microbench-guarded under 2 us/increment and measured well under 1%
    of even a CPU step (tests/test_obs.py).  Everything that costs more
    — fetching the loss off device, snapshotting the registry for the
    recorder's delta ring, emitting the ``steps`` span — happens only
    on ``every``-step marks (run_training passes ``log_every``), so the
    device never waits on telemetry between log boundaries.
    """

    def __init__(self, every: int = 1, collectives: dict | None = None):
        self._every = max(1, every)
        self._steps = obs_metrics.counter(
            "train_steps_total", "completed global training steps")
        self._step_g = obs_metrics.gauge(
            "train_step", "last completed global step")
        self._loss_g = obs_metrics.gauge(
            "train_loss", "loss at the last sampled call boundary")
        self._window_h = obs_metrics.histogram(
            "train_window_seconds",
            "wall seconds between loop call boundaries")
        # Per-step collective accounting (utils/profiling.collective_
        # inventory summary, when the trainer armed it): static per-op
        # gauges set once, cumulative counters fed per boundary — two
        # lock-free adds on the hot path, nothing when absent.
        self._coll_ops = self._coll_bytes = None
        if collectives and collectives.get("multiset"):
            ops_g = obs_metrics.gauge(
                "collective_ops_per_step",
                "collectives per training step, from the compiled HLO")
            bytes_g = obs_metrics.gauge(
                "collective_bytes_per_step",
                "collective output bytes per training step")
            for op, d in collectives["per_step"].items():
                ops_g.labels(op=op).set(d["count"])
                bytes_g.labels(op=op).set(d["out_bytes"])
            self._coll_ops = obs_metrics.counter(
                "collective_ops_total",
                "collective operations dispatched (per-step inventory x "
                "completed steps)")
            self._coll_bytes = obs_metrics.counter(
                "collective_bytes_total",
                "collective output bytes moved (per-step inventory x "
                "completed steps)")
            self._coll_ops_per_step = collectives["total_count_per_step"]
            self._coll_bytes_per_step = collectives[
                "total_out_bytes_per_step"]
        # Anatomy counters (registration is idempotent: these resolve to
        # the SAME families training/loop.py feeds) — the "steps" event
        # carries their per-window deltas so obs/timeline.step_anatomy
        # can decompose each window without the full registry.
        self._in_c = obs_metrics.counter("loop_input_seconds_total")
        self._stp_c = obs_metrics.counter("loop_step_seconds_total")
        self._hk_c = obs_metrics.counter("loop_hook_seconds_total")
        self._due = _EveryN(self._every)
        self._last_step = 0
        self._last_t = self._mark_t = time.perf_counter()
        self._mark_step = 0
        self._mark_cat = (0.0, 0.0, 0.0)
        self._prev_snap = None

    def begin(self, loop) -> None:
        self._due = _EveryN(self._every, int(loop.start_step))
        self._last_step = self._mark_step = int(loop.start_step)
        self._last_t = self._mark_t = time.perf_counter()
        self._mark_cat = (self._in_c.value, self._stp_c.value,
                          self._hk_c.value)
        self._prev_snap = None
        rec = obs_recorder.get()
        if rec is not None:
            rec.note(start_step=int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        now = time.perf_counter()
        advanced = step - self._last_step
        self._steps.inc(advanced)
        self._step_g.set(step)
        self._window_h.observe(now - self._last_t)
        if self._coll_ops is not None:
            self._coll_ops.inc(self._coll_ops_per_step * advanced)
            self._coll_bytes.inc(self._coll_bytes_per_step * advanced)
        self._last_step = step
        self._last_t = now
        if self._due(step):
            rec = obs_recorder.get()
            loss = metrics.get("loss") if isinstance(metrics, dict) else None
            if loss is not None:
                import numpy as np
                lossf = float(np.asarray(loss))
                self._loss_g.set(lossf)
                if rec is not None:
                    rec.record_loss(step, lossf)
            # Anatomy deltas since the last mark.  input/compute include
            # this boundary (the loop feeds them pre-hooks); the hook
            # counter's window for THIS boundary is still open, so the
            # hook column covers up to the previous boundary — the
            # tie-out contract in DESIGN.md §16 and tests/test_obs.py.
            cat = (self._in_c.value, self._stp_c.value, self._hk_c.value)
            obs_trace.event("steps", now - self._mark_t,
                            step=step, n=step - self._mark_step,
                            input_s=round(cat[0] - self._mark_cat[0], 6),
                            compute_s=round(cat[1] - self._mark_cat[1], 6),
                            hook_s=round(cat[2] - self._mark_cat[2], 6))
            self._mark_cat = cat
            self._mark_step = step
            self._mark_t = now
            if rec is not None:
                snap = obs_metrics.registry().snapshot()
                if self._prev_snap is not None:
                    rec.record_delta(
                        obs_metrics.MetricsRegistry.delta(
                            self._prev_snap, snap))
                self._prev_snap = snap
            # Run-ledger sample (OBS_LEDGER): piggybacks on the log
            # boundary this hook already owns, and the ledger's own
            # TIME bound (OBS_LEDGER_SAMPLE_S) keeps the file kilobytes
            # no matter the cadence — nothing on non-mark boundaries.
            led = obs_ledger.get()
            if led is not None:
                led.sample(step)
        return False


class AnomalyHook(Hook):
    """Online anomaly detection at loop boundaries (obs/anomaly.py):
    step-time EWMA regression against the run's own warmup-pinned
    baseline, NaN and loss-plateau sentinels — detection only, never a
    stop (NaNGuardHook owns the kill; this hook owns the evidence).

    Per-boundary cost is a handful of float ops (the same lock-free
    budget as MetricsHook, guarded with it in tests/test_obs.py).
    Everything heavier fires only at ``every``-step marks: the loss
    sentinels read the ``train_loss`` gauge MetricsHook just set
    (install this hook AFTER MetricsHook — trainers/common.py and
    faultline do — so no second device fetch is ever paid), and
    ``health_path`` gets an atomic health.json rewrite.  A NEW firing
    additionally bumps ``anomaly_flags_total``, emits an ``anomaly``
    trace event, and dumps a flight (``final=False``) so the postmortem
    ring covers the steps around the anomaly, not just the death.

    The regression detector's window EXCLUDES checkpoint/snapshot/eval
    span time (read as sum deltas from the ``span_seconds`` histogram
    the spans already feed): a periodic save is seconds against sub-ms
    steps, so the first post-warmup checkpoint would otherwise score as
    a guaranteed false regression against the warmup-pinned baseline —
    MetricsHook makes the same exclusion for throughput via
    ``logger.exclude``."""

    _EXCLUDED_SPANS = ("checkpoint", "snapshot", "eval")

    def __init__(self, every: int = 1, health_path: str = "",
                 health=None):
        from distributedtensorflowexample_tpu.obs import anomaly
        self._anomaly = anomaly
        self._every = max(1, every)
        self._health_path = health_path
        self._health = health or anomaly.RunHealth()
        self._loss_g = obs_metrics.gauge("train_loss")
        self._spans = [obs_metrics.histogram("span_seconds").labels(name=n)
                       for n in self._EXCLUDED_SPANS]
        self._due = _EveryN(self._every)
        self._last_step = 0
        self._last_t = time.perf_counter()
        self._last_excl = sum(c.sum for c in self._spans)
        # This hook's RunHealth IS the process's live health: register
        # it as the /health source so an HTTP scrape (obs/serve.py,
        # OBS_HTTP_PORT) serves the same §16 payload the health FILE
        # gets at hook cadence — but read at scrape time, not file age.
        from distributedtensorflowexample_tpu.obs import serve as obs_serve
        obs_serve.set_health_source(self._health.payload)

    def begin(self, loop) -> None:
        self._due = _EveryN(self._every, int(loop.start_step))
        self._last_step = int(loop.start_step)
        self._last_t = time.perf_counter()
        self._last_excl = sum(c.sum for c in self._spans)

    def _fired(self, kinds: list, step: int) -> None:
        for kind in kinds:
            self._anomaly.FLAGS_TOTAL.labels(kind=kind).inc()
            obs_trace.event("anomaly", 0.0, step=step, kind=kind,
                            z=round(self._health.step_time.z, 3))
            obs_recorder.dump_global(f"anomaly_{kind}", final=False)

    def after_step(self, step, state, metrics) -> bool:
        now = time.perf_counter()
        excl = sum(c.sum for c in self._spans)
        window = max(0.0, (now - self._last_t)
                     - (excl - self._last_excl))
        fired = self._health.observe_window(step, step - self._last_step,
                                            window)
        self._last_step = step
        self._last_t = now
        self._last_excl = excl
        if self._due(step):
            st = self._health.step_time
            if st.armed:
                self._anomaly.STEP_TIME_Z.set(round(st.z, 3))
            # The gauge MetricsHook set this same boundary; untouched
            # (monotonic_ts None) means no loss has been sampled yet.
            if self._loss_g._bare.monotonic_ts is not None:
                fired += self._health.observe_loss(
                    step, float(self._loss_g.value))
            if fired:
                self._fired(fired, step)
            if self._health_path:
                self._health.write(self._health_path)
        elif fired:
            self._fired(fired, step)
        return False

    def end(self, state) -> None:
        if self._health_path:
            self._health.step = int(state.step)
            self._health.write(self._health_path)
