"""Training hooks — the MonitoredTrainingSession hook surface, JAX-native.

The reference attached ``StopAtStepHook`` / checkpoint / summary hooks to
``MonitoredTrainingSession`` (BASELINE.json north star names the API).  Here
a hook sees the loop at well-defined points; stopping is a return value so
the loop stays a plain Python for-loop around one jitted call.
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from distributedtensorflowexample_tpu.training.state import TrainState


class Hook:
    def begin(self, loop) -> None: ...
    def after_step(self, step: int, state: "TrainState", metrics) -> bool:
        """Return True to request a stop (StopAtStepHook semantics)."""
        return False
    def end(self, state: "TrainState") -> None: ...


class StopAtStepHook(Hook):
    def __init__(self, last_step: int):
        self._last_step = last_step

    def after_step(self, step, state, metrics) -> bool:
        return step >= self._last_step


class _EveryN:
    """Boundary-crossing interval check: fires when the step counter reaches
    or jumps past the next multiple of ``every`` — correct both for stride-1
    loops and multi-step train calls that advance several steps per call."""

    def __init__(self, every: int, start: int = 0):
        self._every = every
        self._next = None if not every else (start // every + 1) * every

    def __call__(self, step: int) -> bool:
        if self._next is None or step < self._next:
            return False
        self._next = (step // self._every + 1) * self._every
        return True


class CheckpointHook(Hook):
    """Periodic + final checkpoint via the Orbax-backed manager."""

    def __init__(self, manager, every: int):
        self._manager = manager
        self._due = _EveryN(every)

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            self._manager.save(step, state)
        return False

    def end(self, state) -> None:
        self._manager.save(int(state.step), state, force=True)
        self._manager.wait()


class HeartbeatHook(Hook):
    """Touch ``path`` at call boundaries so an external watchdog
    (resilience.supervisor) can tell a slow-but-alive run from a wedged
    dispatch: a jit call blocked on a dead backend never returns to the
    boundary, so the touches stop — the liveness signal a wall timeout
    alone can't give.  Installed automatically by run_training and
    tools/faultline.py when the supervisor exports SUPERVISE_HEARTBEAT."""

    def __init__(self, path: str, every: int = 1):
        self._path = path
        self._due = _EveryN(max(1, every))

    def _touch(self) -> None:
        try:
            with open(self._path, "a"):
                pass
            os.utime(self._path)
        except OSError:
            pass    # a full disk must not kill the run the beat protects

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))
        self._touch()

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            self._touch()
        return False

    def end(self, state) -> None:
        self._touch()


class EvalHook(Hook):
    """Periodic exact-accuracy eval on a held-out split."""

    def __init__(self, eval_fn, every: int, logger):
        self._eval_fn = eval_fn
        self._due = _EveryN(every)
        self._logger = logger

    def begin(self, loop) -> None:
        self._due = _EveryN(self._due._every, int(loop.start_step))

    def after_step(self, step, state, metrics) -> bool:
        if self._due(step):
            self._logger.scalar(step, "eval_accuracy", self._eval_fn(state))
        return False
