"""Params-stay-sharded decode: the ZeRO-3 read path.

The replicated engine (serving/engine.py) materializes the full param
tree before serving — the read path paid none of what PR 12's ZeRO-3
bought the write path (lm_base residency 458→115 MB/device).  This
module keeps the TRAINING-side resident layout resident at serve time:
params stay the per-bucket flat ``[D*W_b]`` rows sharded one row per
device (``parallel/zero3.py``'s layout, verbatim), and the compiled
decode step all-gathers each bucket's row *inside* the program just
before its einsums consume the leaves — the gathered tree is a
step-local TEMPORARY the compiler frees after last use, so persistent
params residency is exactly 1/D (measured from live shardings:
:meth:`ShardedDecodeEngine.params_residency`, the same instrument as
BENCH_lm_cpu_r12's claim).

The gather schedule is zero3's own: one tiled all-gather per bucket,
issue order pinned by the ``_tie`` double-buffer chain (bucket i's
gather chained onto a scalar probe of bucket i-2's output, so at most
two gathered buckets are in flight ahead of their consumers — on CPU a
compile-shape statement, on TPU the latency-hiding win).  The schedule
is not emergent: :data:`SHARDED_DECODE_HLO_CONTRACT` budgets EXACTLY
one all-gather per bucket (symbolic ``"B"`` — fewer is a regression,
more is a finding, and any other collective is an unbudgeted finding by
construction), keeps the donated-cache aliasing claims, and graftlint's
HLO front checks it on freshly compiled text next to the replicated
path's 0-collective budget.

The KV-cache shards over the SLOT axis (``shard_map``): each device
holds ``slots/D`` slots' rows and decodes them against the gathered
params — slot math is batch-independent (engine.py's argument), so the
sharded step's tokens are bitwise the replicated engine's (pinned in
tests/test_serving.py against the same snapshot).  ``slots`` must
divide evenly across the mesh; anything else is refused by name.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distributedtensorflowexample_tpu.compat import shard_map
from distributedtensorflowexample_tpu.models.transformer_lm import (
    TransformerLM)
from distributedtensorflowexample_tpu.parallel.bucketing import (
    _unbucket_rows)
from distributedtensorflowexample_tpu.parallel.mesh import DATA_AXIS
from distributedtensorflowexample_tpu.parallel.zero3 import (
    Zero3Layout, _tie)
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.serving.engine import (
    DEFAULT_SLOTS, ServingLM, _prefill_buckets, serving_lm_for)

#: The sharded decode step's compiled-HLO contract (graftlint HLO
#: front, next to the replicated path's DECODE_HLO_CONTRACT): donated
#: caches actually aliased and never ENTRY-copied (steady-state decode
#: still reallocates nothing cache-shaped), EXACTLY one all-gather per
#: param bucket (symbolic "B" = the layout's plan length — shrinking
#: the schedule is as much a finding as growing it), and since
#: collectives absent from the budget are findings by construction, any
#: all-reduce/reduce-scatter appearing in a decode step is caught the
#: way zero3's AG-before-RS is pinned.  f32 ceiling as everywhere.
SHARDED_DECODE_HLO_CONTRACT = {
    "mode": "serve_decode_sharded",
    "require_alias": True,
    "no_donated_copy": True,
    "collective_budget": {"all-gather": "B"},
    "dtype_ceiling": "f32",
}


class ShardedDecodeEngine:
    """The DecodeEngine's row-resident twin: same public surface (the
    ContinuousBatcher drives either), but ``params`` is the zero3
    bucket-row tuple at 1/D per device and the caches shard over the
    slot axis.  Speculative decoding, sampling, and the prefix cache
    are replicated-path features (they need the logits/verify seams);
    the batcher refuses those combinations by name."""

    def __init__(self, model: TransformerLM, rows, layout: Zero3Layout,
                 *, slots: int = DEFAULT_SLOTS, cache_len: int = 128,
                 prefill_smallest: int = 8, overlap: bool = True):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if cache_len > model.max_len:
            raise ModeRefusal(
                f"--max_len {cache_len} exceeds the model's positional "
                f"table ({model.max_len} rows) — the snapshot was "
                f"trained with max_len {model.max_len}; a longer cache "
                f"would index past the table, not extrapolate it")
        D = layout.num_devices
        if slots < 1:
            raise ValueError(f"slots {slots} must be >= 1")
        if slots % D != 0:
            raise ModeRefusal(
                f"--slots {slots} does not divide across the {D}-device "
                f"mesh — the KV-cache shards over the slot axis "
                f"(slots/D rows per device), so the slot count must be "
                f"a multiple of the mesh size; use --slots "
                f"{((slots + D - 1) // D) * D}")
        self.model = model
        self.smodel = serving_lm_for(model)
        self.layout = layout
        self.mesh = layout.mesh
        self.slots = int(slots)
        self.cache_len = int(cache_len)
        self.vocab = int(model.vocab_size)
        self.buckets = _prefill_buckets(self.cache_len, prefill_smallest)
        # Rows re-pinned to the resident sharding (a restore may hand
        # them back single-device); this is a 1/D-sized placement, never
        # a materialization.
        row_sh = NamedSharding(self.mesh, P(DATA_AXIS))
        self.rows = tuple(jax.device_put(r, row_sh) for r in rows)
        L = model.n_layers
        H = model.n_heads
        Dh = model.d_model // H
        shape = (L, self.slots, self.cache_len, H, Dh)
        cache_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
        self._ck = jax.device_put(jnp.zeros(shape, model.dtype), cache_sh)
        self._cv = jax.device_put(jnp.zeros(shape, model.dtype), cache_sh)
        self.cache_bytes = 2 * int(np.prod(shape)) * \
            np.dtype(model.dtype).itemsize
        self.positions = np.zeros((self.slots,), np.int32)
        self.last_tokens = np.zeros((self.slots,), np.int32)
        self.decode_steps = 0
        self.prefills = 0
        self._warm_buckets: set = set()
        self.last_prefill_was_cold = False

        smodel = self.smodel
        specs, plan, treedef = (layout.leaf_specs, layout.plan,
                                layout.treedef)
        depth = 2 if overlap else 1
        Sl = self.slots // D

        def gather_params(p_rows):
            # zero3's AG-prefetch schedule, verbatim: one tiled
            # all-gather per bucket, issue order pinned by the _tie
            # chain; the gathered leaves are bitwise the replicated
            # leaves (concatenate/reshape move bytes, never arithmetic).
            full_rows = []
            for bi, row in enumerate(p_rows):
                j = bi - depth
                if j >= 0:
                    row = _tie(row, full_rows[j].ravel()[0].astype(
                        jnp.float32))
                full_rows.append(jax.lax.all_gather(
                    row, DATA_AXIS, axis=0, tiled=True).reshape(D, -1))
            leaves: list = [None] * len(specs)
            for bi, idxs in enumerate(plan):
                for i, piece in _unbucket_rows(full_rows[bi], specs,
                                               idxs).items():
                    leaves[i] = piece
            return jax.tree.unflatten(treedef, leaves)

        def _decode_body(p_rows, ck, cv, tok, pos):
            # Local view: ck/cv [L, S/D, T, H, Dh], tok/pos [S/D] — each
            # device decodes its own slots against the gathered tree.
            params = gather_params(p_rows)
            logits, ck, cv = smodel.apply({"params": params}, tok, pos,
                                          ck, cv,
                                          method=ServingLM.decode)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), ck, cv

        def _prefill_body(p_rows, ck, cv, toks, slot, length):
            # Replicated compute, owner-only write: every device runs
            # the prompt forward (prefill is the rare step; simplicity
            # beats a scatter here), and only the slot's owner lands the
            # K/V rows — non-owners resolve ``local`` to S/D, one past
            # their shard, and the scatter drops out of bounds.
            params = gather_params(p_rows)
            logits, k, v = smodel.apply({"params": params}, toks,
                                        method=ServingLM.prefill)
            d = jax.lax.axis_index(DATA_AXIS)
            local = jnp.where((slot >= d * Sl) & (slot < (d + 1) * Sl),
                              slot - d * Sl, Sl).astype(jnp.int32)
            ck = ck.at[:, local, :toks.shape[1]].set(k[:, 0])
            cv = cv.at[:, local, :toks.shape[1]].set(v[:, 0])
            last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                                axis=0, keepdims=False)
            return jnp.argmax(last).astype(jnp.int32), ck, cv

        P_ = jax.sharding.PartitionSpec
        pspec = jax.tree.map(lambda _: P_(DATA_AXIS), self.rows)
        cspec = P_(None, DATA_AXIS)
        self._decode_fn = shard_map(
            _decode_body, mesh=self.mesh,
            in_specs=(pspec, cspec, cspec, P_(DATA_AXIS), P_(DATA_AXIS)),
            out_specs=(P_(DATA_AXIS), cspec, cspec), check_vma=False)
        self._decode_jit = jax.jit(self._decode_fn,
                                   donate_argnums=(1, 2))
        self._prefill_jit = jax.jit(shard_map(
            _prefill_body, mesh=self.mesh,
            in_specs=(pspec, cspec, cspec, P_(), P_(), P_()),
            out_specs=(P_(), cspec, cspec), check_vma=False),
            donate_argnums=(1, 2))

    # --- the steps (DecodeEngine's surface) --------------------------------
    def bucket_for(self, prompt_len: int, max_new: int) -> int:
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len + max_new > self.cache_len:
            raise ModeRefusal(
                f"prompt ({prompt_len} tokens) + --max_new ({max_new}) "
                f"exceeds the engine's --max_len cache ({self.cache_len} "
                f"rows/slot) — the request can never finish; raise "
                f"--max_len or shorten the request")
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise AssertionError("bucket table misses cache_len")  # unreachable

    def prefill(self, slot: int, prompt: np.ndarray,
                max_new: int = 1) -> int:
        prompt = np.asarray(prompt, np.int32).ravel()
        P = len(prompt)
        bucket = self.bucket_for(P, max_new)
        self.last_prefill_was_cold = bucket not in self._warm_buckets
        self._warm_buckets.add(bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :P] = prompt
        tok, self._ck, self._cv = self._prefill_jit(
            self.rows, self._ck, self._cv, jnp.asarray(padded),
            np.int32(slot), np.int32(P))
        self.positions[slot] = P
        self.last_tokens[slot] = int(tok)
        self.prefills += 1
        return int(tok)

    def prefill_many(self, assignments: list) -> dict:
        """Sequential on the sharded path (prefill compute is
        replicated per device; batching it is the REPLICATED engine's
        amortization rung) — same return shape so the batcher drives
        either engine.  No last-logits seam: sampling is refused with
        this engine by name upstream."""
        out: dict = {}
        cold = False
        for slot, prompt, max_new in assignments:
            tok = self.prefill(slot, prompt, max_new)
            cold = cold or self.last_prefill_was_cold
            out[slot] = (tok, None)
        self.last_prefill_was_cold = cold
        return out

    def decode(self, busy=None) -> np.ndarray:
        toks, self._ck, self._cv = self._decode_jit(
            self.rows, self._ck, self._cv, self.last_tokens,
            self.positions)
        out = np.asarray(toks)
        advance = (np.ones(self.slots, bool) if busy is None
                   else np.zeros(self.slots, bool))
        if busy is not None:
            advance[list(busy)] = True
        self.last_tokens = np.where(advance, out, self.last_tokens) \
            .astype(np.int32)
        self.positions = self.positions + advance.astype(np.int32)
        self.decode_steps += 1
        return out

    def set_slot(self, slot: int, last_token: int, position: int) -> None:
        self.last_tokens[slot] = int(last_token)
        self.positions[slot] = int(position)

    # --- the contract surface ---------------------------------------------
    def decode_hlo(self) -> str:
        """Freshly compiled sharded decode-step text — what graftlint
        checks :data:`SHARDED_DECODE_HLO_CONTRACT` against (symbol
        ``B`` = the layout's bucket count)."""
        lowered = jax.jit(self._decode_fn, donate_argnums=(1, 2)).lower(
            self.rows, self._ck, self._cv, self.last_tokens,
            self.positions)
        return lowered.compile().as_text()

    def params_residency(self) -> dict:
        """The 1/D claim from LIVE shardings (the BENCH_lm_cpu_r12
        instrument's method: bytes of the addressable shard vs bytes of
        the logical array) — rows are ``[D*W_b]`` sharded one row per
        device, so ``frac_per_device`` is exactly ``1/D``, and a silent
        replication regression shows up as 1.0, not as folklore."""
        total = 0
        per_dev = 0
        for row in jax.tree.leaves(self.rows):
            itemsize = np.dtype(row.dtype).itemsize
            total += int(row.size) * itemsize
            shard = row.addressable_shards[0]
            per_dev += int(np.prod(shard.data.shape)) * itemsize
        return {
            "params_bytes_total": int(total),
            "params_bytes_per_device": int(per_dev),
            "frac_per_device": per_dev / total if total else 0.0,
            "num_devices": self.layout.num_devices,
            "num_buckets": self.layout.num_buckets,
        }
