"""serving/ — the continuous-batching graft-LM inference engine (PR 15).

The north star serves "heavy traffic from millions of users", and until
this package the repo was 100% training.  serving/ is the read path the
training stack's snapshots promote into:

- :mod:`~distributedtensorflowexample_tpu.serving.engine` — the
  donate-and-reuse compiled decode step over a preallocated per-slot
  KV-cache (explicit batched einsums mirroring
  ``models/transformer_lm.py``, token-exact with the training forward),
  pinned by an HLO contract next to the step builder;
- :mod:`~distributedtensorflowexample_tpu.serving.promote` — snapshot →
  serving promotion over the SnapshotStore validity checks (torn newest
  falls back; ``zero3_rows``/``bucket_rows`` states materialize through
  the PR 12 ``Zero3Layout.materialize`` seam);
- :mod:`~distributedtensorflowexample_tpu.serving.queue` — the request
  queue + continuous batcher: new requests admitted into open decode
  slots at step boundaries (never batch-drain), padding-bucketed
  prefill, a latency-SLO admission knob, p50/p99/tokens-per-sec through
  the ``obs/`` registry;
- :mod:`~distributedtensorflowexample_tpu.serving.loadgen` — the
  closed-loop load generator behind ``bench_serving.py``'s
  throughput-vs-SLO curves;
- :mod:`~distributedtensorflowexample_tpu.serving.frontend` — the
  opt-in (``SERVE_PORT``) stdlib HTTP request front.

serving/ imports jax by design (it runs the model); the reverse edge is
forbidden — ``obs/`` must never grow a serving import (the stdlib-only
import-graph proof in graftlint stays the arbiter, and
tests/test_serving.py pins the directional edge).
"""

from distributedtensorflowexample_tpu.serving.engine import (  # noqa: F401
    DECODE_HLO_CONTRACT, DecodeEngine, ServingLM, serving_lm_for)
from distributedtensorflowexample_tpu.serving.promote import (  # noqa: F401
    PromotedModel, init_lm_snapshot, promote)
from distributedtensorflowexample_tpu.serving.queue import (  # noqa: F401
    ContinuousBatcher, Request, RequestQueue)

__all__ = [
    "DECODE_HLO_CONTRACT", "DecodeEngine", "ServingLM", "serving_lm_for",
    "PromotedModel", "init_lm_snapshot", "promote",
    "ContinuousBatcher", "Request", "RequestQueue",
]
