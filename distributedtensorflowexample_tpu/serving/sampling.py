"""Temperature / top-k sampling with per-request RNG lanes.

Sampling runs on the HOST from the decode step's f32 logits
(``engine.decode_logits`` — the greedy fused-argmax program, and its
pinned HLO contract, are untouched).  Each request gets its own
counter-based RNG lane keyed ``(worker seed, request id, token
index)``: the same rid replayed against the same snapshot and knobs
produces the SAME tokens regardless of slot placement, admission
order, or what the other slots are doing — the serving analog of the
trainers' seeded-determinism rule, and what makes a retried request's
output reproducible across placements.

Greedy stays the default; a sampler is opt-in per worker
(``--sample_temp``/``--sample_top_k``/``--sample_seed``).  It composes
with batched prefill and the prefix cache (both hand back the last
position's logits, so even the FIRST token is sampled), but not with
speculative decoding — acceptance there compares bitwise-greedy
tokens, and the batcher refuses the combination by name.
"""

from __future__ import annotations

import zlib

import numpy as np

from distributedtensorflowexample_tpu.refusal import ModeRefusal


class Sampler:
    """Stateless per-call sampling: every token draw reseeds its lane
    from ``(seed, rid, index)``, so there is no host RNG state to
    snapshot or to race — determinism is structural, not disciplined."""

    def __init__(self, *, temperature: float = 1.0, top_k: int = 0,
                 seed: int = 0):
        if not temperature > 0:
            raise ModeRefusal(
                f"--sample_temp {temperature} must be > 0 (temperature "
                f"0 is greedy — run without a sampler for that)")
        if top_k < 0:
            raise ModeRefusal(f"--sample_top_k {top_k} must be >= 0 "
                              f"(0 = full vocabulary)")
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)

    def describe(self) -> dict:
        return {"temperature": self.temperature, "top_k": self.top_k,
                "seed": self.seed}

    def sample(self, rid: str, index: int, logits) -> int:
        """Draw token ``index`` of request ``rid`` from f32 ``logits``
        [V] (the decode step's own, so the distribution is exactly the
        model's — the host just rolls the dice)."""
        rng = np.random.default_rng(np.random.SeedSequence(
            [self.seed & 0xFFFFFFFF,
             zlib.crc32(str(rid).encode()),
             int(index)]))
        scores = np.asarray(logits, np.float64) / self.temperature
        if self.top_k and self.top_k < scores.size:
            kth = np.partition(scores, -self.top_k)[-self.top_k]
            scores = np.where(scores >= kth, scores, -np.inf)
        scores = scores - scores.max()
        probs = np.exp(scores)
        probs /= probs.sum()
        return int(rng.choice(scores.size, p=probs))
