"""Prefix-cache sharing: K/V rows keyed on prompt-prefix digest.

Requests in one deployment overwhelmingly share prompt heads (system
preambles, few-shot scaffolding), and a transformer's K/V rows for a
prefix depend ONLY on that prefix — so the rows one slot computed are
bitwise the rows any other slot would compute for the same head.  This
registry stores each admitted prompt's rows under a chained SHA-256
digest of its token bytes (digest of ``prompt[:i]`` is an incremental
update of ``prompt[:i-1]``'s, so all P prefix keys cost one pass) and
admission consults it first:

- **full hit** — the whole prompt is registered: splice the stored rows
  into the slot (``engine.write_rows``), hand back the stored first
  token + last-position logits, and the request pays ZERO forward work;
- **partial hit** — some proper prefix is registered: splice its rows,
  then run only the SUFFIX through the engine's batched-verify window
  (``engine.extend``) — the forward shrinks from P to P-n tokens;
- **miss** — normal prefill, then the new prompt registers so the next
  request with this head hits.

Exactness is the engine's own pad-row invariant: stored rows beyond the
real prefix are junk the decode mask excludes until overwritten, so a
hit's continuation is bitwise the cold path's (pinned in
tests/test_serving.py).  Hit/miss/partial land on the ``serve_*``
metrics family; eviction is LRU with a bounded entry count (rows are
device memory — the capacity knob is the residency bound).

Replicated-engine feature: the row import/export seams read and write
the slot axis the sharded engine shards over; the batcher refuses the
combination by name.
"""

from __future__ import annotations

import collections
import hashlib

import numpy as np

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.refusal import ModeRefusal

_PREFIX_LOOKUPS = obs_metrics.counter(
    "serve_prefix_lookups_total",
    "prefix-cache admissions by outcome (hit / partial / miss)")
_PREFIX_ROWS = obs_metrics.counter(
    "serve_prefix_rows_reused_total",
    "K/V cache rows served from the prefix registry instead of compute")
_PREFIX_ENTRIES = obs_metrics.gauge(
    "serve_prefix_entries", "prompts resident in the prefix registry")


def prefix_digests(prompt) -> list:
    """Chained digests: ``out[i]`` keys ``prompt[:i+1]``.  One
    incremental SHA-256 pass (``copy()`` forks the running state), so
    registering and probing P prefixes costs O(P), not O(P^2)."""
    h = hashlib.sha256()
    out = []
    for t in np.asarray(prompt, np.int32).ravel():
        h.update(int(t).to_bytes(4, "little", signed=True))
        out.append(h.hexdigest())
    return out


class PrefixCache:
    """The per-worker registry.  Single-writer like the engine it
    wraps: the batcher thread is the only caller, so there is no lock
    — concurrency stays in the request queue."""

    def __init__(self, engine, *, capacity: int = 64):
        for seam in ("read_rows", "write_rows", "extend"):
            if not hasattr(engine, seam):
                raise ModeRefusal(
                    "--prefix_cache needs the engine's K/V row "
                    "import/export seams, which the params-stay-sharded "
                    "engine (--sharded_mesh) does not expose — its "
                    "cache rows shard over the slot axis; prefix "
                    "sharing composes with the replicated path only")
        if capacity < 1:
            raise ValueError(f"prefix-cache capacity {capacity} must "
                             f"be >= 1")
        self.engine = engine
        self.capacity = int(capacity)
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self.hits = 0
        self.partial_hits = 0
        self.misses = 0
        self.rows_reused = 0

    def __len__(self) -> int:
        return len(self._entries)

    def admit(self, slot: int, prompt) -> tuple | None:
        """Try to serve ``slot``'s admission from the registry.
        Returns ``(first_token, last_logits, outcome)`` on a hit
        (engine slot state already set — no prefill needed), or None on
        a miss (the caller prefills, then :meth:`register`s)."""
        prompt = np.asarray(prompt, np.int32).ravel()
        P = len(prompt)
        digests = prefix_digests(prompt)
        entry = self._entries.get(digests[-1])
        if entry is not None:
            self._entries.move_to_end(digests[-1])
            self.engine.write_rows(slot, entry["k"], entry["v"])
            self.engine.set_slot(slot, entry["first_token"], P)
            self.hits += 1
            self.rows_reused += P
            _PREFIX_LOOKUPS.labels(outcome="hit").inc()
            _PREFIX_ROWS.inc(P)
            return entry["first_token"], entry["last_logits"], "hit"
        for n in range(P - 1, 0, -1):
            entry = self._entries.get(digests[n - 1])
            if entry is None:
                continue
            self._entries.move_to_end(digests[n - 1])
            self.engine.write_rows(slot, entry["k"], entry["v"])
            tok, last = self.engine.extend(slot, prompt[n:], start=n)
            self.engine.set_slot(slot, tok, P)
            self.partial_hits += 1
            self.rows_reused += n
            _PREFIX_LOOKUPS.labels(outcome="partial").inc()
            _PREFIX_ROWS.inc(n)
            # The completed prompt is itself a future head.
            self._store(digests[-1], slot, P, tok, last)
            return tok, last, "partial"
        self.misses += 1
        _PREFIX_LOOKUPS.labels(outcome="miss").inc()
        return None

    def register(self, slot: int, prompt, first_token: int,
                 last_logits) -> None:
        """Store a freshly prefilled prompt's rows (the miss path's
        second half; hits re-register nothing — their entry just moved
        to the LRU head)."""
        prompt = np.asarray(prompt, np.int32).ravel()
        self._store(prefix_digests(prompt)[-1], slot, len(prompt),
                    int(first_token), last_logits)

    def _store(self, digest: str, slot: int, length: int,
               first_token: int, last_logits) -> None:
        if digest in self._entries:
            self._entries.move_to_end(digest)
            return
        k, v = self.engine.read_rows(slot, length)
        self._entries[digest] = {
            "length": int(length), "k": k, "v": v,
            "first_token": int(first_token),
            "last_logits": (None if last_logits is None
                            else np.asarray(last_logits)),
        }
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
        _PREFIX_ENTRIES.set(len(self._entries))

    def stats(self) -> dict:
        return {"hits": self.hits, "partial_hits": self.partial_hits,
                "misses": self.misses, "rows_reused": self.rows_reused,
                "entries": len(self._entries),
                "capacity": self.capacity}
