"""Snapshot → serving promotion: the training stack's recovery format
is the serving stack's model source.

A serving worker must never trust a snapshot MORE than the supervisor
does, so promotion goes through the exact SnapshotStore validity
machinery (manifest-last commit, size+crc re-check, newest-valid
fallback past a torn final write — resilience/snapshot.py): a corrupted
newest snapshot costs one snapshot interval of model freshness, never
the serving worker.

Layout awareness: training snapshots are written in the layout the run
trained in (``run_meta.update_layout``): plain ``tree``, ZeRO-1
``bucket_rows`` (optimizer state as per-bucket 1/D rows), or ZeRO-3
``zero3_rows`` (params AND optimizer state as rows).  The TRAINER
refuses cross-layout resumes by name, because resuming must be bitwise;
serving only needs the params, so promotion instead *materializes*:
a row-layout snapshot restores into a row-shaped template and the full
param tree is gathered back through the PR 12 seam
(``Zero3Layout.materialize`` — the same jitted gather eval/export use),
never through a second opinion about the bucket plan.

The promotion template's optimizer is the repo-wide training default
(SGD + momentum): the snapshot payload is the full
``saveable_state_dict`` leaf list, and restoring demands a
leaf-count-identical template even though serving discards everything
but the params.  A snapshot written by a run with a different optimizer
fails the leaf-count check loudly (SnapshotStore.restore's existing
error) rather than mis-binding.
"""

from __future__ import annotations

import dataclasses
import os
import sys

import jax.numpy as jnp
import numpy as np
import optax

from distributedtensorflowexample_tpu.models import build_model
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.resilience.snapshot import (
    SnapshotStore)
from distributedtensorflowexample_tpu.training.state import TrainState

_LAYOUTS = ("tree", "bucket_rows", "zero3_rows")


def _log(msg: str) -> None:
    print(f"serve.promote: {msg}", file=sys.stderr, flush=True)


def serve_snapshot_default() -> str:
    """``SERVE_SNAPSHOT``: the snapshot directory tools/serve_lm.py and
    bench_serving.py load when ``--snapshot`` is not passed — empty
    means the flag is required."""
    return os.environ.get("SERVE_SNAPSHOT", "")


def _default_tx():
    # The repo-wide training default (trainers, faultline, bench_lm):
    # promotion templates must mirror what the snapshot writers ran.
    return optax.sgd(0.1, momentum=0.9)


@dataclasses.dataclass
class PromotedModel:
    """What promotion hands the engine: the full (materialized) param
    tree plus the provenance the serving ledger rows carry."""
    model: object               # the training TransformerLM (arch facts)
    params: object              # full tree, layout-independent
    step: int                   # snapshot step served
    layout: str                 # update_layout the snapshot was written in
    manifest: dict              # the winning snapshot's manifest


def _template(model, tx, layout: str, meta: dict, sample_len: int):
    """(template TrainState, zero3 layout-or-None) for a snapshot's
    declared layout — row layouts rebuild the exact bucket geometry
    from the manifest's recorded mesh size + bucket cap."""
    base = TrainState.create(model, tx,
                             jnp.zeros((1, sample_len), jnp.int32))
    if layout == "tree":
        return base, None
    mesh_size = meta.get("mesh_size")
    bucket_bytes = meta.get("bucket_bytes")
    if not mesh_size or not bucket_bytes:
        raise ValueError(
            f"snapshot layout {layout!r} needs manifest meta "
            f"mesh_size+bucket_bytes to rebuild the row geometry; this "
            f"manifest carries {sorted(meta)} — it was not written by a "
            f"layout-stamping writer")
    import jax

    from distributedtensorflowexample_tpu.engine.engine import (
        apply_update_layout)
    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    if mesh_size > len(jax.devices()):
        raise ModeRefusal(
            f"snapshot was written at mesh_size {mesh_size} "
            f"(--shard_params/--shard_update rows are a function of D) "
            f"but this process sees {len(jax.devices())} device(s) — "
            f"materializing needs a mesh at least that wide")
    mesh = make_mesh(int(mesh_size))
    # The row converters shard across the mesh; the template's params
    # must live ON it first (TrainState.create places single-device).
    # The re-layout itself is the Engine's shared pass — the one the
    # snapshot writer ran — so the row geometry can't drift.
    repl = jax.device_put(base.params, replicated_sharding(mesh))
    rowed, z3 = apply_update_layout(
        base.replace(params=repl), tx, update_layout=layout,
        bucket_bytes=int(bucket_bytes), mesh=mesh)
    if layout == "bucket_rows":
        # Params stay the single-device create() tree: only the
        # optimizer state is row-shaped in a ZeRO-1 snapshot.
        return base.replace(opt_state=rowed.opt_state), None
    return base.replace(opt_state=rowed.opt_state, params=rowed.params), z3


def promote(snapshot_dir: str, size: str, *, step: int | None = None,
            tx=None, sample_len: int = 8) -> PromotedModel:
    """Load the newest VALID snapshot of a graft-LM ``size`` from
    ``snapshot_dir`` and return the full serving params.

    - newest-first with fallback: a torn/corrupt newest snapshot is
      discarded (counted on ``snapshot_fallbacks_total``) and the
      previous valid one serves — the supervisor's contract, reused;
    - layout cross-check: a manifest stamped with a different model
      size than requested is refused by name (binding a 4-layer tree
      into an 8-layer template would fail anyway, but late and
      unreadably);
    - row layouts materialize through ``Zero3Layout.materialize``.
    """
    store = SnapshotStore(snapshot_dir)
    if step is None:
        step = store.latest_valid()
    if step is None:
        raise ValueError(
            f"no valid snapshot in {snapshot_dir!r} — nothing to "
            f"promote (run training, or serve_lm's init_if_missing "
            f"mode for a demo-grade init)")
    man = store.manifest(step) or {}
    meta = man.get("meta") or {}
    snap_model = meta.get("model")
    if snap_model and snap_model != size:
        raise ModeRefusal(
            f"snapshot {step} in {snapshot_dir} was written by model "
            f"{snap_model!r}; this worker was asked to serve --size "
            f"{size!r} — refusing to bind across architectures")
    layout = meta.get("update_layout", "tree")
    if layout not in _LAYOUTS:
        raise ValueError(f"snapshot {step} declares unknown "
                         f"update_layout {layout!r} (one of {_LAYOUTS})")
    model = build_model(size)
    template, z3 = _template(model, tx or _default_tx(), layout, meta,
                             sample_len)
    state = store.restore(template, step=step)
    params = z3.materialize(state.params) if z3 is not None \
        else state.params
    _log(f"promoted snapshot step {step} ({layout}) from "
         f"{snapshot_dir}")
    return PromotedModel(model=model, params=params, step=int(step),
                         layout=layout, manifest=man)


@dataclasses.dataclass
class ShardedPromotion:
    """What sharded promotion hands the row-resident engine: the
    bucket rows at 1/D per device plus the layout that explains them —
    the full tree is NEVER a member (that absence is the point)."""
    model: object               # the training TransformerLM (arch facts)
    rows: tuple                 # per-bucket [D*W_b] rows, 1/D resident
    layout: object              # the Zero3Layout (plan, mesh, treedef)
    step: int                   # snapshot step served
    source_layout: str          # update_layout the snapshot was written in
    manifest: dict              # the winning snapshot's manifest


def promote_sharded(snapshot_dir: str, size: str, *,
                    step: int | None = None, tx=None,
                    sample_len: int = 8, mesh_size: int | None = None,
                    bucket_bytes: int | None = None) -> ShardedPromotion:
    """Promotion that keeps params SHARDED: the serving twin of
    :func:`promote` for the params-stay-sharded engine
    (serving/sharded.py).  A ``zero3_rows`` snapshot restores into its
    row template and the rows are handed over AS IS — no
    ``Zero3Layout.materialize``, so the full tree is never resident in
    the worker, which is what the measured-1/D acceptance criterion
    means.  A ``tree``/``bucket_rows`` snapshot starts replicated by
    format; its params convert DOWN through ``Zero3Layout.init_rows``
    (which donates — the replicated copy stops existing the moment the
    layout does).

    ``mesh_size`` for a ``zero3_rows`` snapshot is the manifest's (rows
    are a function of D; asking for a different one is refused by
    name).  For replicated formats it defaults to the manifest's
    recorded mesh, else every visible device."""
    import jax

    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        DEFAULT_BUCKET_BYTES)
    from distributedtensorflowexample_tpu.parallel.zero3 import (
        Zero3Layout)

    store = SnapshotStore(snapshot_dir)
    if step is None:
        step = store.latest_valid()
    if step is None:
        raise ValueError(
            f"no valid snapshot in {snapshot_dir!r} — nothing to "
            f"promote (run training, or serve_lm's init_if_missing "
            f"mode for a demo-grade init)")
    man = store.manifest(step) or {}
    meta = man.get("meta") or {}
    snap_model = meta.get("model")
    if snap_model and snap_model != size:
        raise ModeRefusal(
            f"snapshot {step} in {snapshot_dir} was written by model "
            f"{snap_model!r}; this worker was asked to serve --size "
            f"{size!r} — refusing to bind across architectures")
    layout_name = meta.get("update_layout", "tree")
    if layout_name not in _LAYOUTS:
        raise ValueError(f"snapshot {step} declares unknown "
                         f"update_layout {layout_name!r} "
                         f"(one of {_LAYOUTS})")
    model = build_model(size)
    if layout_name == "zero3_rows":
        snap_mesh = int(meta.get("mesh_size") or 0)
        if mesh_size is not None and int(mesh_size) != snap_mesh:
            raise ModeRefusal(
                f"snapshot {step} holds zero3_rows written at mesh_size "
                f"{snap_mesh} but --sharded_mesh {mesh_size} was "
                f"requested — the row layout is a function of D; "
                f"re-shard through a training-side conversion, or serve "
                f"at the snapshot's mesh size")
        template, z3 = _template(model, tx or _default_tx(), layout_name,
                                 meta, sample_len)
        state = store.restore(template, step=step)
        _log(f"promoted snapshot step {step} (zero3_rows, rows kept "
             f"sharded at 1/{z3.num_devices}) from {snapshot_dir}")
        return ShardedPromotion(model=model, rows=tuple(state.params),
                                layout=z3, step=int(step),
                                source_layout=layout_name, manifest=man)
    # Replicated-by-format snapshot: restore full, convert DOWN.
    template, _ = _template(model, tx or _default_tx(), layout_name,
                            meta, sample_len)
    state = store.restore(template, step=step)
    D = int(mesh_size or meta.get("mesh_size") or len(jax.devices()))
    if D > len(jax.devices()):
        raise ModeRefusal(
            f"--sharded_mesh {D} exceeds the {len(jax.devices())} "
            f"visible device(s) — the row layout shards one row per "
            f"device")
    bb = int(bucket_bytes or meta.get("bucket_bytes")
             or DEFAULT_BUCKET_BYTES)
    mesh = make_mesh(D)
    repl = jax.device_put(state.params, replicated_sharding(mesh))
    z3 = Zero3Layout(repl, bb, mesh)
    rows = z3.init_rows(repl)       # donates: the full copy dies here
    _log(f"promoted snapshot step {step} ({layout_name} → zero3 rows "
         f"at 1/{D}, bucket_bytes {bb}) from {snapshot_dir}")
    return ShardedPromotion(model=model, rows=tuple(rows), layout=z3,
                            step=int(step), source_layout=layout_name,
                            manifest=man)


def init_lm_snapshot(snapshot_dir: str, size: str, seed: int = 0,
                     sample_len: int = 8) -> int:
    """Write a demo-grade snapshot: a seeded, untrained graft-LM state
    in the standard store format (the serving path exercises the FULL
    promotion machinery against it — validity checks, layout stamp,
    fallback).  Returns the snapshot step (0).  Idempotent: an existing
    valid snapshot wins (save() dedupes by step)."""
    model = build_model(size)
    state = TrainState.create(model, _default_tx(),
                              jnp.zeros((1, sample_len), jnp.int32),
                              seed=seed)
    store = SnapshotStore(snapshot_dir)
    store.save(state, cursor={"seed": seed, "step": 0},
               meta={"model": size, "update_layout": "tree",
                     "writer": "init_lm_snapshot"})
    return int(state.step)


# --- canary promotion (the self-healing rung, resilience/remediate.py) -----

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def canary_fraction_default() -> float:
    """``HEAL_CANARY_FRACTION``: share of requests routed to a canary
    candidate while it proves itself (default 0.25)."""
    return _env_float("HEAL_CANARY_FRACTION", 0.25)


def canary_window_default() -> int:
    """``HEAL_CANARY_WINDOW``: canary-arm completions required before a
    promote/rollback verdict (default 16)."""
    return int(_env_float("HEAL_CANARY_WINDOW", 16))


def canary_p99_ratio_default() -> float:
    """``HEAL_CANARY_P99_RATIO``: canary p99 over this multiple of the
    baseline arm's p99 inside the window = regression → rollback
    (default 2.0)."""
    return _env_float("HEAL_CANARY_P99_RATIO", 2.0)


def params_healthy(params) -> bool:
    """Every float leaf finite — the pre-exposure canary probe: a
    NaN-poisoned snapshot (the OOV-poison shape, a torn quantizer, a
    diverged run an operator promoted by mistake) is caught BEFORE a
    single request routes to it.  Cheap relative to one prefill."""
    import jax
    for leaf in jax.tree.leaves(params):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) \
                and not np.all(np.isfinite(arr)):
            return False
    return True


class Canary:
    """Canary promotion state machine: a candidate snapshot serves a
    deterministic ``fraction`` of requests first, and the promotion
    commits only after a clean observation window — auto-rollback on a
    NaN probe or a p99 regression vs the baseline arm.

    State: ``probing`` → (``rolled_back`` | ``serving``) →
    (``promoted`` | ``rolled_back``).  This object owns the DECISION
    only; the serving harness owns the two engine arms and the drain
    (an in-flight canary request always decodes to completion —
    rollback must never drop admitted work, exactly the eviction
    protocol's rule).  Verdicts land as ``heal_canary_promote`` /
    ``heal_canary_rollback`` ledger rows via the remediation engine."""

    def __init__(self, baseline_step: int, candidate_step: int, *,
                 fraction: float | None = None,
                 window: int | None = None,
                 p99_ratio: float | None = None):
        self.baseline_step = int(baseline_step)
        self.candidate_step = int(candidate_step)
        self.fraction = canary_fraction_default() if fraction is None \
            else float(fraction)
        self.window = canary_window_default() if window is None \
            else int(window)
        self.p99_ratio = canary_p99_ratio_default() if p99_ratio is None \
            else float(p99_ratio)
        self.state = "probing"
        self.reason = ""
        self._lat: dict[str, list] = {"canary": [], "baseline": []}
        self._bad: int = 0

    def admit_candidate(self, candidate_params) -> bool:
        """The pre-exposure probe; False = immediate rollback (the
        candidate never serves)."""
        if not params_healthy(candidate_params):
            self.state = "rolled_back"
            self.reason = ("candidate params carry non-finite values — "
                           "rolled back before serving a single request")
            return False
        self.state = "serving"
        return True

    def route(self, rid: str) -> str:
        """Deterministic request routing while ``serving``: the same
        rid always lands on the same arm (a retried request must not
        flap arms mid-experiment)."""
        if self.state != "serving":
            return "baseline"
        import zlib
        bucket = zlib.crc32(str(rid).encode()) % 10_000
        return "canary" if bucket < self.fraction * 10_000 else "baseline"

    def observe(self, arm: str, latency_s: float, ok: bool = True) -> None:
        if not ok and arm == "canary":
            self._bad += 1
        self._lat.setdefault(arm, []).append(float(latency_s))

    @staticmethod
    def _p99(tape: list) -> float | None:
        if not tape:
            return None
        from distributedtensorflowexample_tpu.serving.queue import (
            percentile)
        return percentile(sorted(tape), 0.99)

    def verdict(self) -> str | None:
        """None while the window is still filling; else ``promote`` /
        ``rollback`` (state committed, latched)."""
        if self.state in ("promoted", "rolled_back"):
            return ("promote" if self.state == "promoted"
                    else "rollback")
        if self._bad:
            self.state = "rolled_back"
            self.reason = (f"{self._bad} canary request(s) failed "
                           f"(NaN/garbage outcome) inside the window")
            return "rollback"
        can = self._lat["canary"]
        if len(can) < self.window:
            return None
        p99c = self._p99(can)
        p99b = self._p99(self._lat["baseline"])
        if p99b and p99c is not None and p99c > self.p99_ratio * p99b:
            self.state = "rolled_back"
            self.reason = (f"canary p99 {p99c * 1000:.1f}ms > "
                           f"{self.p99_ratio:g}x baseline p99 "
                           f"{p99b * 1000:.1f}ms over {len(can)} "
                           f"canary completions")
            return "rollback"
        self.state = "promoted"
        self.reason = (f"clean window: {len(can)} canary completions, "
                       f"p99 {0 if p99c is None else p99c * 1000:.1f}ms"
                       + (f" vs baseline {p99b * 1000:.1f}ms" if p99b
                          else ""))
        return "promote"

    def payload(self) -> dict:
        p99c, p99b = self._p99(self._lat["canary"]), \
            self._p99(self._lat["baseline"])
        return {
            "state": self.state, "reason": self.reason,
            "baseline_step": self.baseline_step,
            "candidate_step": self.candidate_step,
            "fraction": self.fraction, "window": self.window,
            "p99_ratio": self.p99_ratio,
            "canary_n": len(self._lat["canary"]),
            "baseline_n": len(self._lat["baseline"]),
            "canary_p99_ms": (None if p99c is None
                              else round(p99c * 1000, 3)),
            "baseline_p99_ms": (None if p99b is None
                                else round(p99b * 1000, 3)),
            "canary_failures": self._bad}


def as_prompt(tokens, vocab: int) -> np.ndarray:
    """Validate a request's prompt tokens on the HOST, before anything
    reaches the device: out-of-vocab ids are refused by name — the
    training-side OOV NaN-poison guards corruption mid-run, but a live
    batch must never be poisoned by one bad request (the refusal is the
    serving analog: loud, per-request, batch untouched)."""
    arr = np.asarray(tokens)
    if arr.ndim != 1 or arr.size == 0:
        raise ValueError(f"prompt must be a non-empty 1-D token list, "
                         f"got shape {arr.shape}")
    if not np.issubdtype(arr.dtype, np.integer):
        raise ValueError(f"prompt tokens must be integers, got dtype "
                         f"{arr.dtype}")
    if int(arr.min()) < 0 or int(arr.max()) >= vocab:
        raise ModeRefusal(
            f"request carries out-of-vocab token id(s) (valid range "
            f"[0, {vocab})) — refused at admission; the --size model's "
            f"vocabulary is fixed at training time and an OOV gather "
            f"would silently clamp into a wrong embedding row")
    return arr.astype(np.int32)
