"""Speculative decoding on the size ladder: a small model drafts, the
served model verifies — tokens stay EXACTLY the served model's.

One round per step boundary: the draft engine (e.g. lm_tiny) runs k+1
sequential decode steps over the busy slots (k proposals + one
cache-maintenance step — see below), then the target engine scores the
window ``[last_token, d_1..d_k]`` in ONE batched verify step
(``engine.verify_step`` — the decode program extended one causal
diagonal, serving/engine.py).  Window query j's greedy argmax ``g_j``
is bitwise what the target's j-th sequential decode step would have
produced — NOT folklore: plain decode IS the K == 1 verify window (one
program family, engine.py's ServingBlock docstring has the tie-flip
incident that forced this), so the only cross-shape assumption is the
kernel batch-stability bucketed prefill already rests on.  Acceptance
is exact-match prefix: the longest ``a`` with ``d_i == g_{i-1}`` for
i ≤ a, and the round emits ``e = min(a+1, remaining)`` tokens
``g_0..g_{e-1}`` — the +1 is the verify step's own "free" token (on
total rejection the round still emits g_0, exactly one plain decode
step's worth, so speculation never decodes SLOWER in steps, only in
draft-side work).  Output is therefore bitwise plain greedy by
construction — the oracle tests in tests/test_serving.py pin it against
solo greedy runs (including a bench-shaped mixed-bucket churn workload),
and ``bench_serving.py`` counts any divergence on a ``*_mismatch``
column the ratchet holds at zero.

Cache discipline: the verify scatter lands the window's K/V at rows
``p..p+k``, so accepted rows hold the right tokens' K/V by the accept
rule and rejected rows are junk beyond the new frontier ``p+e`` —
masked until the next write lands on each (the engine's
scatter-before-read rule).  The draft cache is reconciled the same way:
its rows ``p..p+e-1`` already hold the accepted tokens' K/V (drafted ==
accepted on the prefix) — and because a fully-accepted round has
``e == k+1``, the draft must have written row ``p+k`` too, which is
exactly why it steps k+1 times, not k (its j-th step writes row
``p+j-1``; the (k+1)-th proposal is discarded).  ``set_slot`` then
repoints both frontiers.  Slots not in the round pass position
``cache_len``: their scatters drop out of bounds and their output rows
are discarded — a parked or non-busy slot cannot be corrupted by
someone else's verify.

Sampling composes with none of this (acceptance compares GREEDY
tokens); the batcher refuses the combination by name.
"""

from __future__ import annotations

import numpy as np

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.refusal import ModeRefusal

_SPEC_ROUNDS = obs_metrics.counter(
    "serve_spec_rounds_total", "speculative draft+verify rounds")
_SPEC_EMITTED = obs_metrics.counter(
    "serve_spec_emitted_tokens_total", "tokens emitted by verify rounds")
_SPEC_ACCEPTED = obs_metrics.counter(
    "serve_spec_accepted_draft_total", "draft tokens accepted by verify")
_SPEC_DRAFTED = obs_metrics.counter(
    "serve_spec_drafted_tokens_total", "draft tokens proposed")
_SPEC_ACCEPT_LEN = obs_metrics.gauge(
    "serve_spec_accept_len", "rolling mean tokens emitted per slot-round")


class SpecDecoder:
    """Drafts on ``draft_engine``, verifies on ``engine``; the
    ContinuousBatcher drives one :meth:`round` per step boundary in
    place of one decode step.  Both engines must agree on geometry
    (slots, cache rows, vocabulary) — the accept rule compares token
    ids and the caches advance in lockstep."""

    def __init__(self, engine, draft_engine, *, k: int = 4):
        if k < 1:
            raise ValueError(f"draft window k {k} must be >= 1")
        if not hasattr(engine, "verify_step"):
            raise ModeRefusal(
                "--spec_draft needs the target engine's batched-verify "
                "seam, which the params-stay-sharded engine "
                "(--sharded_mesh) does not expose — speculative "
                "decoding composes with the replicated path only")
        if draft_engine.vocab != engine.vocab:
            raise ModeRefusal(
                f"draft model vocab {draft_engine.vocab} != target "
                f"vocab {engine.vocab} — acceptance compares token ids, "
                f"so the ladder sizes must share a vocabulary")
        if draft_engine.slots != engine.slots \
                or draft_engine.cache_len != engine.cache_len:
            raise ValueError(
                f"draft geometry (slots {draft_engine.slots}, cache "
                f"{draft_engine.cache_len}) must match the target's "
                f"(slots {engine.slots}, cache {engine.cache_len}) — "
                f"the caches advance in lockstep")
        self.engine = engine
        self.draft = draft_engine
        self.k = int(k)
        self.rounds = 0
        self.emitted = 0
        self.accepted_draft = 0
        self.drafted = 0
        self._accept_tape: list = []

    # --- lifecycle hooks (the batcher calls these) -------------------------
    def on_admit(self, slot: int, prompt, max_new: int) -> None:
        """Prefill the DRAFT cache for an admitted request (the target
        prefill already happened on the admission path)."""
        self.draft.prefill(slot, prompt, max_new)

    def park(self, slot: int) -> None:
        """Mirror the batcher's slot parking onto the draft engine."""
        self.draft.set_slot(slot, 0, 0)

    # --- the round ---------------------------------------------------------
    def round(self, busy: list, remaining: dict) -> dict:
        """One draft+verify round over ``busy`` slots (``remaining[s]``
        = tokens request s still needs, >= 1).  Returns {slot: [emitted
        tokens]} — between 1 and min(k+1, remaining) per slot, bitwise
        the target's plain-greedy tokens."""
        eng, draft, k = self.engine, self.draft, self.k
        S = eng.slots
        # k+1 draft steps for k proposals: a full-acceptance round emits
        # e == k+1 tokens and repoints the draft frontier to p+k+1, so
        # the draft cache must hold K/V through row p+k — which only its
        # (k+1)-th step writes (step j writes row p+j-1).  Without it,
        # every fully-accepted round left ONE junk row below the new
        # frontier and self-draft acceptance collapsed within a few
        # rounds (the d_{k+1} proposal itself is discarded).
        drafts = np.zeros((k + 1, S), np.int32)
        for j in range(k + 1):
            drafts[j] = draft.decode(busy=busy)
        toks = np.zeros((S, k + 1), np.int32)
        pos = np.full((S,), eng.cache_len, np.int32)
        for s in busy:
            toks[s, 0] = eng.last_tokens[s]
            toks[s, 1:] = drafts[:k, s]
            pos[s] = eng.positions[s]
        g, _ = eng.verify_step(toks, pos)
        out: dict = {}
        for s in busy:
            d, gs = drafts[:, s], g[s]
            a = 0
            while a < k and d[a] == gs[a]:
                a += 1
            e = min(a + 1, int(remaining[s]))
            emitted = [int(t) for t in gs[:e]]
            p = int(eng.positions[s])
            eng.set_slot(s, emitted[-1], p + e)
            draft.set_slot(s, emitted[-1], p + e)
            out[s] = emitted
            self.emitted += e
            self.accepted_draft += min(a, e)
            self._accept_tape.append(e)
        self.rounds += 1
        self.drafted += k * len(busy)
        _SPEC_ROUNDS.inc()
        _SPEC_DRAFTED.inc(k * len(busy))
        _SPEC_EMITTED.inc(sum(len(v) for v in out.values()))
        _SPEC_ACCEPTED.inc(sum(min(len(v) - 1, k) for v in out.values()))
        tape = self._accept_tape[-256:]
        _SPEC_ACCEPT_LEN.set(round(sum(tape) / len(tape), 4))
        return out

    def stats(self) -> dict:
        tape = self._accept_tape
        return {
            "k": self.k,
            "rounds": self.rounds,
            "emitted": self.emitted,
            "drafted": self.drafted,
            "accepted_draft": self.accepted_draft,
            "accept_len_mean": (round(sum(tape) / len(tape), 4)
                                if tape else None),
        }
