"""Request queue + continuous batcher: admission at step boundaries,
never batch-drain.

The naive serving loop forms a batch, decodes it to completion, then
admits the next batch — so a 4-token request arriving behind a
500-token one waits the whole long decode.  Continuous batching admits
a new request into any OPEN slot at the next step boundary: the decode
step's shape is static (all S slots compute every step), so joining a
running batch costs one bucketed prefill, not a drain.  The engine's
slot math is batch-independent by construction (serving/engine.py), so
a mid-decode admission cannot perturb the requests already in flight —
tests/test_serving.py pins that a request admitted mid-decode produces
bitwise the tokens it produces solo.

Admission is SLO-aware (``SERVE_SLO_MS``, 0 = off): a queued request is
priced at admission time — wait so far + a prefill estimate + max_new x
the decode-step EWMA — and one that can no longer finish inside the SLO
is REJECTED loudly (counted, latency-stamped) instead of admitted to
miss.  Under overload a closed-loop client sees fast rejections and the
in-SLO goodput stays measurable; that rejection edge is exactly the
knee ``bench_serving.py``'s throughput-vs-SLO curves sweep out.

Shutdown is the trainer's loss-free TERM protocol, re-read for serving:
on ``drain()`` the batcher stops admitting, decodes every in-flight
slot to completion (bounded by each request's max_new), rejects the
still-queued tail (outcome ``drained`` — the client's cue to retry
against the next placement), and returns — the worker then exits 143
with every ACCEPTED-and-admitted request answered.  Telemetry flows
through the shared obs registry: queue depth, slot occupancy,
tokens/sec counters, a latency histogram, and p50/p99 gauges refreshed
from the exact host-side tape.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time

import numpy as np

from distributedtensorflowexample_tpu.obs import metrics as obs_metrics
from distributedtensorflowexample_tpu.obs import trace as obs_trace
from distributedtensorflowexample_tpu.refusal import ModeRefusal
from distributedtensorflowexample_tpu.serving.engine import DecodeEngine
from distributedtensorflowexample_tpu.serving.promote import as_prompt

_REQUESTS = obs_metrics.counter(
    "serve_requests_total", "serving requests by outcome "
    "(ok / slo_rejected / drained / refused / oov_refused / "
    "bad_request)")
_TOKENS = obs_metrics.counter(
    "serve_tokens_total", "tokens generated (completed requests only)")
_STEPS = obs_metrics.counter(
    "serve_decode_steps_total", "compiled decode steps executed")
_PREFILLS = obs_metrics.counter(
    "serve_prefills_total", "bucketed prefill calls, by bucket")
_QUEUE_DEPTH = obs_metrics.gauge(
    "serve_queue_depth", "requests queued, not yet admitted to a slot")
_SLOTS_BUSY = obs_metrics.gauge(
    "serve_slots_busy", "decode slots holding a live request")
_LATENCY = obs_metrics.histogram(
    "serve_latency_seconds", "request end-to-end latency (submit to "
    "last token)")
_P50 = obs_metrics.gauge(
    "serve_latency_p50_ms", "rolling p50 of completed-request latency")
_P99 = obs_metrics.gauge(
    "serve_latency_p99_ms", "rolling p99 of completed-request latency")


def serve_slo_ms_default() -> float:
    """``SERVE_SLO_MS``: default end-to-end latency SLO driving
    admission (0 = admit everything; CLI flags override)."""
    try:
        return float(os.environ.get("SERVE_SLO_MS", ""))
    except ValueError:
        return 0.0


def recent_p99_ms(completed: list, window: int = 32) -> float | None:
    """p99 (ms) over the newest ``window`` completed requests — the
    remediation layer's breach/recovery signal.  Whole-tape percentiles
    (``stats()``) never recover from an early bad episode; a windowed
    read answers "is it still slow NOW", which is what an SLO-tighten
    decision (and its verification) needs."""
    tape = sorted(r.latency_s for r in completed[-window:]
                  if r.latency_s is not None)
    if not tape:
        return None
    return round(percentile(tape, 0.99) * 1000.0, 3)


def percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (exact, no
    interpolation surprises in records)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[idx])


@dataclasses.dataclass
class Request:
    """One generation request and its whole lifecycle tape."""
    rid: str
    prompt: np.ndarray
    max_new: int
    submit_t: float
    admit_t: float | None = None
    first_token_t: float | None = None
    done_t: float | None = None
    outcome: str = ""           # ok | slo_rejected | drained | refused
    error: str = ""             # the refusal text, when refused
    tokens: list = dataclasses.field(default_factory=list)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    @property
    def latency_s(self) -> float | None:
        if self.done_t is None:
            return None
        return self.done_t - self.submit_t

    def finish(self, outcome: str, now: float) -> None:
        self.outcome = outcome
        self.done_t = now
        self.done.set()


class RequestQueue:
    """Thread-safe FIFO between submitters (loadgen threads, the HTTP
    front) and the single batcher thread.  OOV prompts are refused at
    ``submit`` — by name, before the queue ever sees them."""

    def __init__(self, vocab: int):
        self.vocab = vocab
        self._q: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._seq = 0
        self._closed = False

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)

    def submit(self, prompt, max_new: int, rid: str | None = None,
               now: float | None = None) -> Request:
        try:
            arr = as_prompt(prompt, self.vocab)
        except ModeRefusal:
            _REQUESTS.labels(outcome="oov_refused").inc()
            raise
        except ValueError:
            # Shape/dtype defects, not vocabulary: an operator tuning
            # a tokenizer off the oov counter must not chase these.
            _REQUESTS.labels(outcome="bad_request").inc()
            raise
        with self._cv:
            self._seq += 1
            req = Request(rid=rid or f"req{self._seq}", prompt=arr,
                          max_new=int(max_new),
                          submit_t=time.monotonic() if now is None
                          else now)
            if self._closed:
                # A submit racing the drain (TERM already landed) is
                # answered immediately — a worker on its way out must
                # never leave a caller blocked on a request nothing
                # will ever decode.
                req.finish("drained", time.monotonic())
                _REQUESTS.labels(outcome="drained").inc()
                return req
            self._q.append(req)
            _QUEUE_DEPTH.set(len(self._q))
            self._cv.notify_all()
        return req

    def close(self) -> None:
        """Stop accepting work: every later submit is answered
        ``drained`` synchronously (the drain path calls this FIRST, so
        the submit/drain race cannot strand a waiter)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def pop(self) -> Request | None:
        with self._cv:
            req = self._q.popleft() if self._q else None
            _QUEUE_DEPTH.set(len(self._q))
            return req

    def drain_pending(self) -> list:
        with self._cv:
            out = list(self._q)
            self._q.clear()
            _QUEUE_DEPTH.set(0)
            return out

    def wait_nonempty(self, timeout_s: float) -> bool:
        with self._cv:
            if self._q:
                return True
            self._cv.wait(timeout_s)
            return bool(self._q)


@dataclasses.dataclass
class _Slot:
    req: Request | None = None


class ContinuousBatcher:
    """The serving loop: admit → decode → retire, one step boundary at
    a time, on one thread (the engine's donated caches are single-
    writer by construction — concurrency lives in the queue, never in
    the device state)."""

    def __init__(self, engine: DecodeEngine, queue: RequestQueue, *,
                 slo_ms: float | None = None, eos_id: int | None = None,
                 on_step=None, spec=None, sampler=None,
                 prefix_cache=None):
        if spec is not None and sampler is not None:
            raise ModeRefusal(
                "--sample_temp/--sample_top_k cannot combine with "
                "--spec_draft: speculative acceptance compares "
                "bitwise-GREEDY tokens against the draft (the oracle "
                "contract), and a sampled token has no greedy oracle — "
                "run one or the other")
        if sampler is not None and not hasattr(engine, "decode_logits"):
            raise ModeRefusal(
                "--sample_temp/--sample_top_k need the engine's "
                "logits-returning decode seam, which the "
                "params-stay-sharded engine (--sharded_mesh) does not "
                "expose — sampling composes with the replicated path "
                "only")
        self.engine = engine
        self.queue = queue
        self.slo_ms = serve_slo_ms_default() if slo_ms is None \
            else float(slo_ms)
        self.eos_id = eos_id
        self.on_step = on_step          # per-boundary callback (heartbeat)
        self.spec = spec                # SpecDecoder (serving/spec.py)
        self.sampler = sampler          # Sampler (serving/sampling.py)
        self.prefix_cache = prefix_cache  # PrefixCache (serving/prefix.py)
        self._slots = [_Slot() for _ in range(engine.slots)]
        # Step-time EWMA feeding the admission predictor; seeded on the
        # first measured step (the compile step is excluded — it would
        # poison the estimate ~1000x and reject everything for a while).
        self._step_ewma_s: float | None = None
        self._prefill_ewma_s: float | None = None
        self.completed: list = []       # finished Requests (tape)
        self.rejected: list = []
        self.admitted_total = 0

    def set_slo_ms(self, slo_ms: float) -> float:
        """The remediation seam (resilience/remediate.py's slo_tighten
        actuator): swap the live admission SLO and return the previous
        value.  ``slo_ms`` is read per-admission, so the change takes
        effect at the next step boundary — no drain, no restart, and
        requests already admitted are unaffected (tightening admission
        must never drop admitted work)."""
        was, self.slo_ms = self.slo_ms, float(slo_ms)
        return was

    # --- admission --------------------------------------------------------
    def _predicted_latency_s(self, req: Request, now: float) -> float:
        wait = now - req.submit_t
        pre = self._prefill_ewma_s or 0.0
        step = self._step_ewma_s or 0.0
        return wait + pre + req.max_new * step

    def _free_slots(self) -> list:
        return [i for i, s in enumerate(self._slots) if s.req is None]

    def _admit(self, now: float) -> None:
        """Fill open slots from the queue head; SLO-reject requests
        that can no longer finish in time (they would only burn slot
        capacity to miss).  Admissions passing the gates are collected
        and prefilled as ONE batch per padding bucket
        (``engine.prefill_many`` — the burst-amortization rung)."""
        free = self._free_slots()
        batch: list = []
        while free and len(self.queue):
            req = self.queue.pop()
            if req is None:
                break
            try:
                # Geometry check BEFORE the slot is spent: a request
                # that can never finish inside the cache is refused by
                # name — one impossible request must cost itself, never
                # the serving loop (the batcher thread has no other
                # handler above it).
                self.engine.bucket_for(len(req.prompt), req.max_new)
            except ValueError as e:
                req.error = str(e)
                req.finish("refused", time.monotonic())
                _REQUESTS.labels(outcome="refused").inc()
                self.rejected.append(req)
                continue
            if self.slo_ms > 0 and self._predicted_latency_s(
                    req, now) * 1000.0 > self.slo_ms:
                req.finish("slo_rejected", time.monotonic())
                _REQUESTS.labels(outcome="slo_rejected").inc()
                self.rejected.append(req)
                continue
            batch.append((free.pop(0), req))
        if batch:
            self._prefill_batch(batch)
        _SLOTS_BUSY.set(self.engine.slots - len(self._free_slots()))

    def _prefill_batch(self, batch: list) -> None:
        """Admit ``batch`` = [(slot, req), ...]: prefix-cache probes
        first (a hit skips the forward entirely), the remaining misses
        in one bucketed ``prefill_many`` call, then per-request
        bookkeeping (first token — sampled when a sampler is armed —
        tracing spans, draft-engine prefill for speculation)."""
        served: dict = {}                 # slot -> (first, logits, outcome)
        todo: list = []
        for slot, req in batch:
            hit = None if self.prefix_cache is None \
                else self.prefix_cache.admit(slot, req.prompt)
            if hit is not None:
                served[slot] = hit
            else:
                todo.append((slot, req))
        t0 = time.monotonic()
        if todo:
            out = self.engine.prefill_many(
                [(slot, req.prompt, req.max_new) for slot, req in todo])
            dt = time.monotonic() - t0
            # The first prefill per (bucket, batch) shape pays the
            # compile — a wall time ~1000x steady state that must never
            # seed the admission predictor (a compile-poisoned EWMA
            # under an SLO rejects everything, and with nothing
            # admitted it never decays back: a livelock).  The EWMA
            # tracks PER-REQUEST cost, so batched admissions make the
            # predictor cheaper, as measured.
            if not self.engine.last_prefill_was_cold:
                per = dt / len(todo)
                self._prefill_ewma_s = per \
                    if self._prefill_ewma_s is None \
                    else 0.8 * self._prefill_ewma_s + 0.2 * per
            for slot, req in todo:
                first, last = out[slot]
                served[slot] = (first, last, "prefill")
                if self.prefix_cache is not None:
                    self.prefix_cache.register(slot, req.prompt, first,
                                               last)
                _PREFILLS.labels(
                    bucket=self.engine.bucket_for(len(req.prompt),
                                                  req.max_new)).inc()
        prefill_dt = time.monotonic() - t0
        for slot, req in batch:
            first, last, outcome = served[slot]
            if self.sampler is not None:
                # Even the first token is sampled (index 0 of the
                # request's RNG lane) — the prefill seam hands back the
                # last position's logits for exactly this.
                first = self.sampler.sample(req.rid, 0, last)
                self.engine.set_slot(slot, first,
                                     int(self.engine.positions[slot]))
            req.admit_t = req.first_token_t = time.monotonic()
            obs_trace.event("serve_queue", req.admit_t - req.submit_t,
                            t0_s=req.submit_t, rid=req.rid, slot=slot)
            obs_trace.event("serve_prefill", prefill_dt, t0_s=t0,
                            rid=req.rid, slot=slot, outcome=outcome,
                            batch=len(todo))
            req.tokens.append(int(first))
            self._slots[slot].req = req
            self.admitted_total += 1
            if self.spec is not None:
                self.spec.on_admit(slot, req.prompt, req.max_new)
            # max_new == 1 finishes on the prefill's own token.
            self._maybe_retire(slot, time.monotonic())

    def _maybe_retire(self, slot: int, now: float) -> bool:
        req = self._slots[slot].req
        if req is None:
            return True
        full = len(req.tokens) >= req.max_new
        eos = self.eos_id is not None and req.tokens \
            and req.tokens[-1] == self.eos_id
        if not (full or eos):
            return False
        req.finish("ok", now)
        _REQUESTS.labels(outcome="ok").inc()
        _TOKENS.inc(len(req.tokens))
        _LATENCY.observe(req.latency_s)
        t0 = req.first_token_t if req.first_token_t is not None else now
        obs_trace.event("serve_decode", now - t0, t0_s=t0, rid=req.rid,
                        slot=slot, tokens=len(req.tokens),
                        outcome=req.outcome)
        self.completed.append(req)
        self._slots[slot].req = None
        # Park the freed slot's frontier at 0: idle slots still compute
        # every step, and an unbounded frontier would walk past the
        # positional table for nothing.
        self.engine.set_slot(slot, 0, 0)
        if self.spec is not None:
            self.spec.park(slot)
        if len(self.completed) % 32 == 0 or len(self.completed) < 8:
            tape = sorted(r.latency_s for r in self.completed)
            _P50.set(round(percentile(tape, 0.50) * 1000.0, 3))
            _P99.set(round(percentile(tape, 0.99) * 1000.0, 3))
        return True

    # --- the loop ---------------------------------------------------------
    def _busy(self) -> list:
        return [i for i, s in enumerate(self._slots) if s.req is not None]

    def _note_step_time(self, dt: float) -> None:
        # The engine's FIRST decode step pays the compile — never let
        # it seed the admission predictor (see the prefill comment:
        # a compile-poisoned EWMA under an SLO is a reject-everything
        # livelock, because nothing admitted means nothing ever decays
        # it).  Once seeded, a 50x outlier (a recompile) is skipped.
        # Under speculation dt is a whole round (>= 1 emitted token per
        # slot), so max_new x EWMA stays a conservative upper bound.
        if self.engine.decode_steps > 1:
            if self._step_ewma_s is None:
                self._step_ewma_s = dt
            elif dt < 50 * self._step_ewma_s:
                self._step_ewma_s = 0.8 * self._step_ewma_s + 0.2 * dt

    def _decode_once(self) -> int:
        """One decode boundary over the busy slots, dispatched by mode:
        a speculative round (draft k, verify once, emit 1..k+1 tokens
        per slot), a sampled step (logits out, host draws each token on
        its request's RNG lane), or the default greedy fused-argmax
        step.  Retires whatever finished.  Returns live slots decoded."""
        busy = self._busy()
        if not busy:
            return 0
        t0 = time.monotonic()
        if self.spec is not None:
            remaining = {
                s: self._slots[s].req.max_new - len(self._slots[s].req.tokens)
                for s in busy}
            emitted = self.spec.round(busy, remaining)
            self._note_step_time(time.monotonic() - t0)
            _STEPS.inc()
            now = time.monotonic()
            for slot in busy:
                toks = emitted[slot]
                if self.eos_id is not None and self.eos_id in toks:
                    # Plain greedy stops AT eos; a round must not hand
                    # the request tokens greedy would never have
                    # produced (the oracle contract).
                    toks = toks[:toks.index(self.eos_id) + 1]
                self._slots[slot].req.tokens.extend(toks)
                self._maybe_retire(slot, now)
        elif self.sampler is not None:
            logits = self.engine.decode_logits(busy=busy)
            self._note_step_time(time.monotonic() - t0)
            _STEPS.inc()
            now = time.monotonic()
            for slot in busy:
                req = self._slots[slot].req
                tok = self.sampler.sample(req.rid, len(req.tokens),
                                          logits[slot])
                self.engine.set_slot(slot, tok,
                                     int(self.engine.positions[slot]))
                req.tokens.append(tok)
                self._maybe_retire(slot, now)
        else:
            toks = self.engine.decode(busy=busy)
            self._note_step_time(time.monotonic() - t0)
            _STEPS.inc()
            now = time.monotonic()
            for slot in busy:
                req = self._slots[slot].req
                req.tokens.append(int(toks[slot]))
                self._maybe_retire(slot, now)
        _SLOTS_BUSY.set(self.engine.slots - len(self._free_slots()))
        return len(busy)

    def step(self) -> int:
        """One boundary: admit into open slots, one decode boundary
        over the batch, retire finished requests.  Returns the number
        of live slots decoded (0 = idle boundary)."""
        self._admit(time.monotonic())
        n = self._decode_once()
        if n == 0:
            return 0
        if self.on_step is not None:
            self.on_step(self)
        return n

    def run(self, should_stop=lambda: False,
            idle_wait_s: float = 0.02) -> None:
        """Serve until ``should_stop()`` — then drain (see module
        docstring).  Idle boundaries block on the queue's condition
        variable, so an idle worker burns no CPU busy-looping the
        decode step against zero slots."""
        while not should_stop():
            if self.step() == 0:
                self.queue.wait_nonempty(idle_wait_s)
        self.drain()

    def drain(self) -> None:
        """The TERM half of loss-free teardown: stop admitting, decode
        every in-flight request to completion, reject the queued tail
        loudly (outcome ``drained`` — re-submittable against the next
        placement, never silently lost)."""
        t0 = time.monotonic()
        in_flight = len(self._busy())
        self.queue.close()           # later submits answer 'drained'
        now = time.monotonic()
        tail = self.queue.drain_pending()
        for req in tail:
            req.finish("drained", now)
            _REQUESTS.labels(outcome="drained").inc()
            obs_trace.event("serve_drain", now - req.submit_t,
                            t0_s=req.submit_t, rid=req.rid,
                            outcome="drained")
            self.rejected.append(req)
        # In-flight work decodes to completion through the SAME
        # per-boundary dispatch serving used — an in-flight speculative
        # batch keeps drafting+verifying mid-drain (its tokens are
        # greedy's tokens either way), a sampled batch keeps its RNG
        # lanes.
        while self._busy():
            self._decode_once()
        _SLOTS_BUSY.set(0)
        obs_trace.event("serve_drain", time.monotonic() - t0, t0_s=t0,
                        in_flight=in_flight, tail=len(tail))

    # --- stats ------------------------------------------------------------
    def stats(self) -> dict:
        tape = sorted(r.latency_s for r in self.completed)
        toks = sum(len(r.tokens) for r in self.completed)
        span = (max(r.done_t for r in self.completed)
                - min(r.submit_t for r in self.completed)) \
            if self.completed else 0.0
        return {
            "completed": len(self.completed),
            "rejected": {
                "slo": sum(1 for r in self.rejected
                           if r.outcome == "slo_rejected"),
                "refused": sum(1 for r in self.rejected
                               if r.outcome == "refused"),
                "drained": sum(1 for r in self.rejected
                               if r.outcome == "drained")},
            "tokens": toks,
            "tokens_per_sec": round(toks / span, 3) if span else None,
            "p50_ms": round(percentile(tape, 0.50) * 1000.0, 3),
            "p99_ms": round(percentile(tape, 0.99) * 1000.0, 3),
            "decode_steps": self.engine.decode_steps,
            "prefills": self.engine.prefills,
            "slo_ms": self.slo_ms,
            "slots": self.engine.slots,
            "step_ewma_ms": (round(self._step_ewma_s * 1000.0, 3)
                             if self._step_ewma_s else None),
            "spec": None if self.spec is None else self.spec.stats(),
            "sampler": (None if self.sampler is None
                        else self.sampler.describe()),
            "prefix_cache": (None if self.prefix_cache is None
                             else self.prefix_cache.stats()),
        }
