"""The decode engine: one donate-and-reuse compiled step over a
preallocated per-slot KV-cache.

Training computes every position of every sequence each step; serving
generates one token per live request per step, so the arithmetic that
matters is (a) the prompt's one-time *prefill* (full causal attention,
exactly the training forward) and (b) the steady-state *decode* step: a
single-query attention against the K/V rows every earlier position
already produced.  This module keeps those rows resident — two
``[L, S, T, H, Dh]``-shaped buffers, one slot per concurrently-decoding
request — and compiles ONE decode step whose cache arguments are
DONATED: XLA aliases the updated cache onto the input buffers
(``input_output_alias`` in the compiled header), so steady-state decode
allocates nothing cache-shaped per step.  That claim is not folklore —
:data:`DECODE_HLO_CONTRACT` is declared next to the step builder and
checked on freshly compiled text by graftlint's HLO front
(``analysis/hlo_lint.py``), the same way the ZeRO schedules are pinned.

Numerics: the serving modules mirror ``models/transformer_lm.py``
sub-module for sub-module — same flax layers, same names (so a training
param tree binds directly), same explicit batched einsums with the same
contraction dims, softmax in f32, logits in f32.  A single-query decode
attends over masked cache rows whose ``-1e9`` scores underflow to
exactly 0.0 after the f32 exp, so the engine's greedy tokens are
token-for-token IDENTICAL to teacher-forced greedy decoding through the
training model (pinned in tests/test_serving.py) — and because every
slot's math is batch-dim-independent (einsums batch over slots,
LayerNorm is per-row), a request's output does not depend on what the
other slots are doing.  Continuous batching is therefore free of
cross-request contamination *by construction*, and the mid-decode
admission test asserts bitwise-equal output against a solo run.

Out-of-vocab requests never reach the device: admission refuses them by
name (``refusal.ModeRefusal``, serving/queue.py) — the training-side
NaN-poison exists to catch corruption mid-flight, but a live batch must
not be poisoned by one bad request.
"""

from __future__ import annotations

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distributedtensorflowexample_tpu.models.transformer_lm import (
    TransformerLM)
from distributedtensorflowexample_tpu.refusal import ModeRefusal

#: The decode step's compiled-HLO contract (graftlint HLO front,
#: analysis/hlo_lint.py `serving_suite`): the KV-cache donation actually
#: aliased (require_alias) and no ENTRY copy of a donated cache buffer
#: (no_donated_copy) — together, the "steady-state decode reallocates
#: nothing cache-shaped" claim; no collective may appear (decode is a
#: single-device program today — an exact 0 budget makes ANY collective
#: a finding); no float wider than f32 anywhere (the f32
#: softmax/logits ceiling the training models hold).
DECODE_HLO_CONTRACT = {
    "mode": "serve_decode",
    "require_alias": True,
    "no_donated_copy": True,
    "collective_budget": {"all-reduce": 0},
    "dtype_ceiling": "f32",
}

#: Default decode-slot count (SERVE_SLOTS overrides): enough concurrency
#: to show continuous batching on the CPU demo without compiling a wide
#: program tier-1 never fills.
DEFAULT_SLOTS = 4


def serve_slots_default() -> int:
    """``SERVE_SLOTS``: default concurrent decode slots for
    tools/serve_lm.py and bench_serving.py (CLI flags override)."""
    try:
        return max(1, int(os.environ.get("SERVE_SLOTS", "")))
    except ValueError:
        return DEFAULT_SLOTS


class ServingBlock(nn.Module):
    """One decoder block with the training block's exact sub-module
    names (``ln1``/``qkv``/``attn_out``/``ln2``/``mlp_in``/``mlp_out``)
    so the training param tree binds unchanged, and two methods:
    :meth:`prefill` (full causal attention — the training forward's
    einsums verbatim, plus the K/V it produced) and :meth:`decode`
    (single-query attention against the slot's cache rows)."""
    d_model: int
    n_heads: int
    d_ff: int
    dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        self.ln1 = nn.LayerNorm(dtype=self.dtype, name="ln1")
        self.qkv = nn.Dense(3 * self.d_model, dtype=self.dtype,
                            name="qkv")
        self.attn_out = nn.Dense(self.d_model, dtype=self.dtype,
                                 name="attn_out")
        self.ln2 = nn.LayerNorm(dtype=self.dtype, name="ln2")
        self.mlp_in = nn.Dense(self.d_ff, dtype=self.dtype, name="mlp_in")
        self.mlp_out = nn.Dense(self.d_model, dtype=self.dtype,
                                name="mlp_out")

    def _mlp(self, x):
        h = self.ln2(x)
        h = self.mlp_in(h)
        h = nn.gelu(h)
        h = self.mlp_out(h)
        return x + h

    def prefill(self, x):
        """x [B, P, d] -> (x', k [B, P, H, Dh], v [B, P, H, Dh])."""
        B, P, _ = x.shape
        Dh = self.d_model // self.n_heads
        h = self.ln1(x)
        qkv = self.qkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, P, self.n_heads, Dh)
        k = k.reshape(B, P, self.n_heads, Dh)
        v = v.reshape(B, P, self.n_heads, Dh)
        scores = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.asarray(
            Dh ** 0.5, self.dtype)
        causal = (jnp.arange(P)[:, None] >= jnp.arange(P)[None, :])
        scores = jnp.where(causal[None, None], scores,
                           jnp.asarray(-1e9, scores.dtype))
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        probs = probs.astype(self.dtype)
        att = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, P, -1)
        x = x + self.attn_out(att)
        return self._mlp(x), k, v

    def decode(self, x, ck, cv, pos):
        """One token per slot: x [S, d], cache rows ck/cv [S, T, H, Dh],
        pos [S] (the row this step writes, = each slot's sequence
        length so far).  The new K/V scatter at ``pos`` precedes the
        attention read, so the current token attends to itself like the
        training forward's diagonal; rows past ``pos`` are masked to
        -1e9, which the f32 exp maps to exactly 0.0 — stale cache
        content beyond a slot's frontier can never leak into its
        output."""
        S, T = ck.shape[0], ck.shape[1]
        Dh = self.d_model // self.n_heads
        h = self.ln1(x)
        qkv = self.qkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, self.n_heads, Dh)
        k = k.reshape(S, self.n_heads, Dh)
        v = v.reshape(S, self.n_heads, Dh)
        sl = jnp.arange(S)
        ck = ck.at[sl, pos].set(k)
        cv = cv.at[sl, pos].set(v)
        scores = jnp.einsum("shd,sthd->sht", q, ck) / jnp.asarray(
            Dh ** 0.5, self.dtype)
        live = (jnp.arange(T)[None, :] <= pos[:, None])     # [S, T]
        scores = jnp.where(live[:, None], scores,
                           jnp.asarray(-1e9, scores.dtype))
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        probs = probs.astype(self.dtype)
        att = jnp.einsum("sht,sthd->shd", probs, cv).reshape(S, -1)
        x = x + self.attn_out(att)
        return self._mlp(x), ck, cv


class ServingLM(nn.Module):
    """The decode-side TransformerLM: same top-level names (``embed``,
    ``pos``, ``block{i}``, ``ln_f``) and weight-tied f32 logits, with
    prefill/decode methods instead of the training ``__call__``.
    ``max_len`` must equal the TRAINING model's (it is the positional
    table's row count — a param shape, not a serving knob; the serving
    cache length is the engine's separate ``cache_len``)."""
    vocab_size: int
    n_layers: int
    d_model: int
    n_heads: int
    d_ff: int
    max_len: int
    dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        self.embed = nn.Embed(self.vocab_size, self.d_model,
                              dtype=self.dtype, name="embed")
        self.pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                            name="pos")
        self.blocks = [ServingBlock(self.d_model, self.n_heads,
                                    self.d_ff, self.dtype,
                                    name=f"block{i}")
                       for i in range(self.n_layers)]
        self.ln_f = nn.LayerNorm(dtype=self.dtype, name="ln_f")

    def prefill(self, tokens):
        """tokens [1, P] -> (logits [1, P, V] f32,
        k [L, P, H, Dh], v [L, P, H, Dh])."""
        P = tokens.shape[1]
        x = self.embed(tokens)
        x = x + self.pos(jnp.arange(P, dtype=jnp.int32))[None]
        ks, vs = [], []
        for blk in self.blocks:
            x, k, v = blk.prefill(x)
            ks.append(k[0])
            vs.append(v[0])
        x = self.ln_f(x)
        logits = self.embed.attend(x).astype(jnp.float32)
        return logits, jnp.stack(ks), jnp.stack(vs)

    def decode(self, tok, positions, ck, cv):
        """tok [S], positions [S], caches [L, S, T, H, Dh] ->
        (logits [S, V] f32, ck, cv)."""
        x = self.embed(tok) + self.pos(positions)
        new_k, new_v = [], []
        for i, blk in enumerate(self.blocks):
            x, k_i, v_i = blk.decode(x, ck[i], cv[i], positions)
            new_k.append(k_i)
            new_v.append(v_i)
        ck = jnp.stack(new_k)
        cv = jnp.stack(new_v)
        x = self.ln_f(x)
        logits = self.embed.attend(x).astype(jnp.float32)
        return logits, ck, cv


def serving_lm_for(model: TransformerLM) -> ServingLM:
    """The serving twin of a training model — every architecture field
    copied, so the training param tree binds bit-for-bit."""
    return ServingLM(vocab_size=model.vocab_size,
                     n_layers=model.n_layers, d_model=model.d_model,
                     n_heads=model.n_heads, d_ff=model.d_ff,
                     max_len=model.max_len, dtype=model.dtype)


def _prefill_buckets(cache_len: int, smallest: int = 8) -> tuple:
    """Padding buckets for prefill: powers of two from ``smallest`` up
    to ``cache_len`` (inclusive as the final bucket).  Each bucket is
    one compiled prefill program; a prompt pads to the smallest bucket
    that fits, so N distinct prompt lengths cost log(N) compiles, not
    N."""
    out = []
    b = smallest
    while b < cache_len:
        out.append(b)
        b *= 2
    out.append(cache_len)
    return tuple(out)


class DecodeEngine:
    """Slots + caches + the two compiled programs (bucketed prefill,
    the donated decode step).  Host-side bookkeeping (which slot is
    live, each request's tokens) belongs to the ContinuousBatcher; this
    class owns only the device state and refuses geometry it cannot
    serve.

    Donation discipline: both programs donate the cache buffers, so
    after every call the PREVIOUS cache handles are dead — the engine
    always rebinds, and no caller ever holds a cache reference."""

    def __init__(self, model: TransformerLM, params, *,
                 slots: int = DEFAULT_SLOTS, cache_len: int = 128,
                 prefill_smallest: int = 8):
        if cache_len > model.max_len:
            raise ModeRefusal(
                f"--max_len {cache_len} exceeds the model's positional "
                f"table ({model.max_len} rows) — the snapshot was "
                f"trained with max_len {model.max_len}; a longer cache "
                f"would index past the table, not extrapolate it")
        if slots < 1:
            raise ValueError(f"slots {slots} must be >= 1")
        self.model = model
        self.smodel = serving_lm_for(model)
        self.params = params
        self.slots = int(slots)
        self.cache_len = int(cache_len)
        self.vocab = int(model.vocab_size)
        self.buckets = _prefill_buckets(self.cache_len, prefill_smallest)
        L = model.n_layers
        H = model.n_heads
        Dh = model.d_model // H
        shape = (L, self.slots, self.cache_len, H, Dh)
        self._ck = jnp.zeros(shape, model.dtype)
        self._cv = jnp.zeros(shape, model.dtype)
        self.cache_bytes = 2 * int(np.prod(shape)) * \
            np.dtype(model.dtype).itemsize
        # Host-owned scalars-per-slot, uploaded per call (tiny): the
        # returned next-token array is the only per-step device output
        # besides the aliased caches.
        self.positions = np.zeros((self.slots,), np.int32)
        self.last_tokens = np.zeros((self.slots,), np.int32)
        self.decode_steps = 0
        self.prefills = 0
        # Which prefill buckets have compiled: the first call per
        # bucket pays the jit compile, and callers timing prefill for
        # an admission predictor must know to exclude it.
        self._warm_buckets: set = set()
        self.last_prefill_was_cold = False

        smodel = self.smodel

        def _decode(params, ck, cv, tok, pos):
            logits, ck, cv = smodel.apply({"params": params}, tok, pos,
                                          ck, cv,
                                          method=ServingLM.decode)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), ck, cv

        def _prefill(params, ck, cv, toks, slot, length):
            logits, k, v = smodel.apply({"params": params}, toks,
                                        method=ServingLM.prefill)
            ck = jax.lax.dynamic_update_slice(ck, k[:, None],
                                              (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v[:, None],
                                              (0, slot, 0, 0, 0))
            last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                                axis=0, keepdims=False)
            return jnp.argmax(last).astype(jnp.int32), ck, cv

        self._decode_fn = _decode
        self._decode_jit = jax.jit(_decode, donate_argnums=(1, 2))
        # One jit object; the per-bucket programs are its shape-keyed
        # cache entries (slot + length stay traced scalars so slot
        # choice never recompiles).
        self._prefill_jit = jax.jit(_prefill, donate_argnums=(1, 2))

    # --- the two steps ----------------------------------------------------
    def bucket_for(self, prompt_len: int, max_new: int) -> int:
        """Smallest padding bucket holding ``prompt_len``, refusing
        work that cannot finish inside the cache."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len + max_new > self.cache_len:
            raise ModeRefusal(
                f"prompt ({prompt_len} tokens) + --max_new ({max_new}) "
                f"exceeds the engine's --max_len cache ({self.cache_len} "
                f"rows/slot) — the request can never finish; raise "
                f"--max_len or shorten the request")
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise AssertionError("bucket table misses cache_len")  # unreachable

    def prefill(self, slot: int, prompt: np.ndarray,
                max_new: int = 1) -> int:
        """Fill ``slot``'s cache rows from the prompt and return the
        first generated token.  Pads to the chosen bucket with token 0 —
        pad rows land in the cache beyond the slot's frontier, where the
        decode mask excludes them until a real token overwrites each."""
        prompt = np.asarray(prompt, np.int32).ravel()
        P = len(prompt)
        bucket = self.bucket_for(P, max_new)
        self.last_prefill_was_cold = bucket not in self._warm_buckets
        self._warm_buckets.add(bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :P] = prompt
        tok, self._ck, self._cv = self._prefill_jit(
            self.params, self._ck, self._cv, jnp.asarray(padded),
            np.int32(slot), np.int32(P))
        self.positions[slot] = P
        self.last_tokens[slot] = int(tok)
        self.prefills += 1
        return int(tok)

    def decode(self, busy=None) -> np.ndarray:
        """One decode step over ALL slots (idle slots compute too — the
        program has one static shape; their outputs are ignored and
        their stale rows are overwritten the next time the slot is
        live).  Returns the next token per slot and advances the BUSY
        slots' frontiers (``busy=None`` advances all): an idle slot's
        parked frontier must not drift toward the cache/positional-
        table edge one row per step of everyone else's work."""
        toks, self._ck, self._cv = self._decode_jit(
            self.params, self._ck, self._cv, self.last_tokens,
            self.positions)
        out = np.asarray(toks)
        advance = (np.ones(self.slots, bool) if busy is None
                   else np.zeros(self.slots, bool))
        if busy is not None:
            advance[list(busy)] = True
        self.last_tokens = np.where(advance, out, self.last_tokens) \
            .astype(np.int32)
        self.positions = self.positions + advance.astype(np.int32)
        self.decode_steps += 1
        return out

    def set_slot(self, slot: int, last_token: int, position: int) -> None:
        """Host bookkeeping hook (the batcher parks retired slots at
        position 0 so their frontier never walks off the cache end)."""
        self.last_tokens[slot] = int(last_token)
        self.positions[slot] = int(position)

    # --- the contract surface --------------------------------------------
    def decode_hlo(self) -> str:
        """Freshly compiled decode-step text — what graftlint's HLO
        front checks :data:`DECODE_HLO_CONTRACT` against.  Compiled
        from the UNDONATED argument values via a separate lowering (the
        live step's buffers must not be consumed by a lint pass)."""
        lowered = jax.jit(self._decode_fn,
                          donate_argnums=(1, 2)).lower(
            self.params, self._ck, self._cv, self.last_tokens,
            self.positions)
        return lowered.compile().as_text()
