"""The decode engine: one donate-and-reuse compiled step over a
preallocated per-slot KV-cache.

Training computes every position of every sequence each step; serving
generates one token per live request per step, so the arithmetic that
matters is (a) the prompt's one-time *prefill* (full causal attention,
exactly the training forward) and (b) the steady-state *decode* step: a
single-query attention against the K/V rows every earlier position
already produced.  This module keeps those rows resident — two
``[L, S, T, H, Dh]``-shaped buffers, one slot per concurrently-decoding
request — and compiles ONE decode step whose cache arguments are
DONATED: XLA aliases the updated cache onto the input buffers
(``input_output_alias`` in the compiled header), so steady-state decode
allocates nothing cache-shaped per step.  That claim is not folklore —
:data:`DECODE_HLO_CONTRACT` is declared next to the step builder and
checked on freshly compiled text by graftlint's HLO front
(``analysis/hlo_lint.py``), the same way the ZeRO schedules are pinned.

Numerics: the serving modules mirror ``models/transformer_lm.py``
sub-module for sub-module — same flax layers, same names (so a training
param tree binds directly), same explicit batched einsums with the same
contraction dims, softmax in f32, logits in f32.  A single-query decode
attends over masked cache rows whose ``-1e9`` scores underflow to
exactly 0.0 after the f32 exp, so the engine's greedy tokens are
token-for-token IDENTICAL to teacher-forced greedy decoding through the
training model (pinned in tests/test_serving.py) — and because every
slot's math is batch-dim-independent (einsums batch over slots,
LayerNorm is per-row), a request's output does not depend on what the
other slots are doing.  Continuous batching is therefore free of
cross-request contamination *by construction*, and the mid-decode
admission test asserts bitwise-equal output against a solo run.

Out-of-vocab requests never reach the device: admission refuses them by
name (``refusal.ModeRefusal``, serving/queue.py) — the training-side
NaN-poison exists to catch corruption mid-flight, but a live batch must
not be poisoned by one bad request.
"""

from __future__ import annotations

import functools
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distributedtensorflowexample_tpu.models.transformer_lm import (
    TransformerLM)
from distributedtensorflowexample_tpu.refusal import ModeRefusal

#: The decode step's compiled-HLO contract (graftlint HLO front,
#: analysis/hlo_lint.py `serving_suite`): the KV-cache donation actually
#: aliased (require_alias) and no ENTRY copy of a donated cache buffer
#: (no_donated_copy) — together, the "steady-state decode reallocates
#: nothing cache-shaped" claim; no collective may appear (decode is a
#: single-device program today — an exact 0 budget makes ANY collective
#: a finding); no float wider than f32 anywhere (the f32
#: softmax/logits ceiling the training models hold).
DECODE_HLO_CONTRACT = {
    "mode": "serve_decode",
    "require_alias": True,
    "no_donated_copy": True,
    "collective_budget": {"all-reduce": 0},
    "dtype_ceiling": "f32",
}

#: Default decode-slot count (SERVE_SLOTS overrides): enough concurrency
#: to show continuous batching on the CPU demo without compiling a wide
#: program tier-1 never fills.
DEFAULT_SLOTS = 4


def serve_slots_default() -> int:
    """``SERVE_SLOTS``: default concurrent decode slots for
    tools/serve_lm.py and bench_serving.py (CLI flags override)."""
    try:
        return max(1, int(os.environ.get("SERVE_SLOTS", "")))
    except ValueError:
        return DEFAULT_SLOTS


class ServingBlock(nn.Module):
    """One decoder block with the training block's exact sub-module
    names (``ln1``/``qkv``/``attn_out``/``ln2``/``mlp_in``/``mlp_out``)
    so the training param tree binds unchanged, and two methods:
    :meth:`prefill` (full causal attention — the training forward's
    einsums verbatim, plus the K/V it produced) and :meth:`verify`
    (a K-token teacher-forced window against the slot's cache rows;
    plain decode is the K == 1 window).

    There is deliberately NO separate single-query decode method.  An
    earlier revision had one, and its einsums ("shd,sthd->sht") were a
    DIFFERENT compiled structure from the window's ("skhd,sthd->shkt")
    — close enough to agree almost always, far enough that on the bf16
    logit grid a near-tied argmax could flip between the two programs
    (observed: two tokens both at logit 2.59375, decode picking one,
    verify the other).  Speculative decoding's bitwise-greedy oracle
    cannot rest on two programs that may disagree at ties, so decode IS
    verify at K == 1: one program family, one numerics, and the only
    cross-shape assumption left — per-element stability when K is a
    pure batch dimension — is the same one bucketed prefill already
    relies on (B=1 vs B=3 prompts bitwise, pinned in tests)."""
    d_model: int
    n_heads: int
    d_ff: int
    dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        self.ln1 = nn.LayerNorm(dtype=self.dtype, name="ln1")
        self.qkv = nn.Dense(3 * self.d_model, dtype=self.dtype,
                            name="qkv")
        self.attn_out = nn.Dense(self.d_model, dtype=self.dtype,
                                 name="attn_out")
        self.ln2 = nn.LayerNorm(dtype=self.dtype, name="ln2")
        self.mlp_in = nn.Dense(self.d_ff, dtype=self.dtype, name="mlp_in")
        self.mlp_out = nn.Dense(self.d_model, dtype=self.dtype,
                                name="mlp_out")

    def _mlp(self, x):
        h = self.ln2(x)
        h = self.mlp_in(h)
        h = nn.gelu(h)
        h = self.mlp_out(h)
        return x + h

    def prefill(self, x):
        """x [B, P, d] -> (x', k [B, P, H, Dh], v [B, P, H, Dh])."""
        B, P, _ = x.shape
        Dh = self.d_model // self.n_heads
        h = self.ln1(x)
        qkv = self.qkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, P, self.n_heads, Dh)
        k = k.reshape(B, P, self.n_heads, Dh)
        v = v.reshape(B, P, self.n_heads, Dh)
        scores = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.asarray(
            Dh ** 0.5, self.dtype)
        causal = (jnp.arange(P)[:, None] >= jnp.arange(P)[None, :])
        scores = jnp.where(causal[None, None], scores,
                           jnp.asarray(-1e9, scores.dtype))
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        probs = probs.astype(self.dtype)
        att = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, P, -1)
        x = x + self.attn_out(att)
        return self._mlp(x), k, v

    def verify(self, x, ck, cv, pos):
        """A K-token window per slot: x [S, K, d], cache rows ck/cv
        [S, T, H, Dh], pos [S] (the row the window starts at).  The
        window's K/V scatter at rows ``pos..pos+K-1`` precedes the
        read; window query j attends rows ``<= pos+j`` — the decode
        mask extended one causal diagonal into the window.

        This is the ONLY token-step program: plain decode is this
        window at K == 1 (:meth:`ServingBlock.decode` was deleted for
        cause — see the class docstring).  Window query j's math per
        (slot, head, query) touches K only as a batch dimension, so its
        argmax equals what j sequential K == 1 steps would have
        produced under the same kernel-batch-stability that already
        underwrites bucketed prefill (pinned bitwise in
        tests/test_serving.py).  A slot parked at ``pos == T`` scatters
        out of bounds (dropped) and its outputs are garbage by
        construction — callers discard non-busy rows."""
        S, K, _ = x.shape
        T = ck.shape[1]
        Dh = self.d_model // self.n_heads
        h = self.ln1(x)
        qkv = self.qkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, K, self.n_heads, Dh)
        k = k.reshape(S, K, self.n_heads, Dh)
        v = v.reshape(S, K, self.n_heads, Dh)
        rows = pos[:, None] + jnp.arange(K, dtype=pos.dtype)[None]  # [S, K]
        sl = jnp.arange(S)[:, None]
        ck = ck.at[sl, rows].set(k)
        cv = cv.at[sl, rows].set(v)
        scores = jnp.einsum("skhd,sthd->shkt", q, ck) / jnp.asarray(
            Dh ** 0.5, self.dtype)
        live = (jnp.arange(T)[None, None, :] <= rows[:, :, None])  # [S,K,T]
        scores = jnp.where(live[:, None], scores,
                           jnp.asarray(-1e9, scores.dtype))
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        probs = probs.astype(self.dtype)
        att = jnp.einsum("shkt,sthd->skhd", probs, cv).reshape(S, K, -1)
        x = x + self.attn_out(att)
        return self._mlp(x), ck, cv



class ServingLM(nn.Module):
    """The decode-side TransformerLM: same top-level names (``embed``,
    ``pos``, ``block{i}``, ``ln_f``) and weight-tied f32 logits, with
    prefill/decode methods instead of the training ``__call__``.
    ``max_len`` must equal the TRAINING model's (it is the positional
    table's row count — a param shape, not a serving knob; the serving
    cache length is the engine's separate ``cache_len``)."""
    vocab_size: int
    n_layers: int
    d_model: int
    n_heads: int
    d_ff: int
    max_len: int
    dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        self.embed = nn.Embed(self.vocab_size, self.d_model,
                              dtype=self.dtype, name="embed")
        self.pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                            name="pos")
        self.blocks = [ServingBlock(self.d_model, self.n_heads,
                                    self.d_ff, self.dtype,
                                    name=f"block{i}")
                       for i in range(self.n_layers)]
        self.ln_f = nn.LayerNorm(dtype=self.dtype, name="ln_f")

    def prefill(self, tokens):
        """tokens [B, P] -> (logits [B, P, V] f32,
        k [L, B, P, H, Dh], v [L, B, P, H, Dh]).  Batched: B queued
        prompts padded into one bucket share one forward, so admission
        under burst pays one dispatch instead of B (each prompt's math
        is batch-independent — same rows, same results)."""
        P = tokens.shape[1]
        x = self.embed(tokens)
        x = x + self.pos(jnp.arange(P, dtype=jnp.int32))[None]
        ks, vs = [], []
        for blk in self.blocks:
            x, k, v = blk.prefill(x)
            ks.append(k)
            vs.append(v)
        x = self.ln_f(x)
        logits = self.embed.attend(x).astype(jnp.float32)
        return logits, jnp.stack(ks), jnp.stack(vs)

    def verify(self, toks, positions, ck, cv):
        """toks [S, K], positions [S], caches [L, S, T, H, Dh] ->
        (logits [S, K, V] f32, ck, cv) — the speculative-verify /
        suffix-extend program (see ServingBlock.verify)."""
        K = toks.shape[1]
        x = self.embed(toks) + self.pos(
            positions[:, None] + jnp.arange(K, dtype=jnp.int32)[None])
        new_k, new_v = [], []
        for i, blk in enumerate(self.blocks):
            x, k_i, v_i = blk.verify(x, ck[i], cv[i], positions)
            new_k.append(k_i)
            new_v.append(v_i)
        ck = jnp.stack(new_k)
        cv = jnp.stack(new_v)
        x = self.ln_f(x)
        logits = self.embed.attend(x).astype(jnp.float32)
        return logits, ck, cv

    def decode(self, tok, positions, ck, cv):
        """tok [S], positions [S], caches [L, S, T, H, Dh] ->
        (logits [S, V] f32, ck, cv) — the K == 1 window of
        :meth:`verify`, NOT a separate program (see ServingBlock: two
        token-step programs can flip a near-tied argmax between them,
        which breaks the speculative path's bitwise-greedy oracle)."""
        logits, ck, cv = self.verify(tok[:, None], positions, ck, cv)
        return logits[:, 0], ck, cv


def serving_lm_for(model: TransformerLM) -> ServingLM:
    """The serving twin of a training model — every architecture field
    copied, so the training param tree binds bit-for-bit."""
    return ServingLM(vocab_size=model.vocab_size,
                     n_layers=model.n_layers, d_model=model.d_model,
                     n_heads=model.n_heads, d_ff=model.d_ff,
                     max_len=model.max_len, dtype=model.dtype)


def _prefill_buckets(cache_len: int, smallest: int = 8) -> tuple:
    """Padding buckets for prefill: powers of two from ``smallest`` up
    to ``cache_len`` (inclusive as the final bucket).  Each bucket is
    one compiled prefill program; a prompt pads to the smallest bucket
    that fits, so N distinct prompt lengths cost log(N) compiles, not
    N."""
    out = []
    b = smallest
    while b < cache_len:
        out.append(b)
        b *= 2
    out.append(cache_len)
    return tuple(out)


# --- the compiled programs (module-level: ONE jit cache per process) ------
# jax.jit keys its compile cache on (function identity, static args,
# shapes).  Built as closures inside ``DecodeEngine.__init__`` these were
# per-INSTANCE jit objects, so a second engine of identical geometry
# recompiled every program the first had already paid for (~3 s per
# engine on one CPU core) — and fresh engines are routine: a spec DRAFT
# engine next to its target, a promoted replica, every test.  The
# ServingLM module passes STATICALLY (flax modules hash by config), so
# equal-config engines share programs process-wide; donation stays on
# the cache operands only.

def _decode_step_fn(smodel, params, ck, cv, tok, pos):
    logits, ck, cv = smodel.apply({"params": params}, tok, pos, ck, cv,
                                  method=ServingLM.decode)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), ck, cv


_decode_step = jax.jit(_decode_step_fn, static_argnums=0,
                       donate_argnums=(2, 3))


@functools.partial(jax.jit, static_argnums=0, donate_argnums=(2, 3))
def _decode_logits_step(smodel, params, ck, cv, tok, pos):
    # The sampling seam: same decode program, f32 logits out instead of
    # the fused argmax (greedy keeps its own program — and its pinned
    # HLO contract — untouched).
    return smodel.apply({"params": params}, tok, pos, ck, cv,
                        method=ServingLM.decode)


@functools.partial(jax.jit, static_argnums=0, donate_argnums=(2, 3))
def _verify_window(smodel, params, ck, cv, toks, pos):
    logits, ck, cv = smodel.apply({"params": params}, toks, pos, ck, cv,
                                  method=ServingLM.verify)
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
            logits, ck, cv)


@functools.partial(jax.jit, static_argnums=0, donate_argnums=(2, 3))
def _prefill_bucketed(smodel, params, ck, cv, toks, slots_ix, lengths):
    # toks [B, Pb] — B queued prompts in one bucketed forward;
    # slots_ix/lengths [B].  Each prompt's K/V rows scatter into its own
    # slot; the "first generated token" is the argmax at each prompt's
    # true last position (pad rows beyond it are never read).
    logits, k, v = smodel.apply({"params": params}, toks,
                                method=ServingLM.prefill)
    ck = ck.at[:, slots_ix, :toks.shape[1]].set(k)
    cv = cv.at[:, slots_ix, :toks.shape[1]].set(v)
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    return (jnp.argmax(last, axis=-1).astype(jnp.int32), last, ck, cv)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _splice_rows(ck, cv, k_rows, v_rows, slot):
    # Prefix-cache import: splice stored [L, W, H, Dh] rows into one
    # slot (rows beyond the real prefix are stale bucket padding —
    # masked until overwritten, like prefill's own).
    ck = jax.lax.dynamic_update_slice(ck, k_rows[:, None],
                                      (0, slot, 0, 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v_rows[:, None],
                                      (0, slot, 0, 0, 0))
    return ck, cv


class DecodeEngine:
    """Slots + caches + the two compiled programs (bucketed prefill,
    the donated decode step).  Host-side bookkeeping (which slot is
    live, each request's tokens) belongs to the ContinuousBatcher; this
    class owns only the device state and refuses geometry it cannot
    serve.

    Donation discipline: both programs donate the cache buffers, so
    after every call the PREVIOUS cache handles are dead — the engine
    always rebinds, and no caller ever holds a cache reference."""

    def __init__(self, model: TransformerLM, params, *,
                 slots: int = DEFAULT_SLOTS, cache_len: int = 128,
                 prefill_smallest: int = 8):
        if cache_len > model.max_len:
            raise ModeRefusal(
                f"--max_len {cache_len} exceeds the model's positional "
                f"table ({model.max_len} rows) — the snapshot was "
                f"trained with max_len {model.max_len}; a longer cache "
                f"would index past the table, not extrapolate it")
        if slots < 1:
            raise ValueError(f"slots {slots} must be >= 1")
        self.model = model
        self.smodel = serving_lm_for(model)
        self.params = params
        self.slots = int(slots)
        self.cache_len = int(cache_len)
        self.vocab = int(model.vocab_size)
        self.buckets = _prefill_buckets(self.cache_len, prefill_smallest)
        L = model.n_layers
        H = model.n_heads
        Dh = model.d_model // H
        shape = (L, self.slots, self.cache_len, H, Dh)
        self._ck = jnp.zeros(shape, model.dtype)
        self._cv = jnp.zeros(shape, model.dtype)
        self.cache_bytes = 2 * int(np.prod(shape)) * \
            np.dtype(model.dtype).itemsize
        # Host-owned scalars-per-slot, uploaded per call (tiny): the
        # returned next-token array is the only per-step device output
        # besides the aliased caches.
        self.positions = np.zeros((self.slots,), np.int32)
        self.last_tokens = np.zeros((self.slots,), np.int32)
        self.decode_steps = 0
        self.prefills = 0
        # Which (bucket, batch) prefill shapes have compiled: the first
        # call per shape pays the jit compile, and callers timing
        # prefill for an admission predictor must know to exclude it.
        self._warm_buckets: set = set()
        self.last_prefill_was_cold = False

        # The compiled programs live at module level (shared jit cache
        # across engines — see the block above _decode_step); this
        # UNJITTED binding exists for callers that need a fresh
        # variant lowering of the decode step (the HLO contract's
        # donation-teeth test compiles it WITHOUT donation).
        self._decode_fn = functools.partial(_decode_step_fn, self.smodel)

    # --- the two steps ----------------------------------------------------
    def bucket_for(self, prompt_len: int, max_new: int) -> int:
        """Smallest padding bucket holding ``prompt_len``, refusing
        work that cannot finish inside the cache."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len + max_new > self.cache_len:
            raise ModeRefusal(
                f"prompt ({prompt_len} tokens) + --max_new ({max_new}) "
                f"exceeds the engine's --max_len cache ({self.cache_len} "
                f"rows/slot) — the request can never finish; raise "
                f"--max_len or shorten the request")
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise AssertionError("bucket table misses cache_len")  # unreachable

    def prefill(self, slot: int, prompt: np.ndarray,
                max_new: int = 1) -> int:
        """Fill ``slot``'s cache rows from the prompt and return the
        first generated token.  Pads to the chosen bucket with token 0 —
        pad rows land in the cache beyond the slot's frontier, where the
        decode mask excludes them until a real token overwrites each."""
        (tok, _), = self.prefill_many([(slot, prompt, max_new)]).values()
        return tok

    def prefill_many(self, assignments: list) -> dict:
        """Batched prefill: ``assignments`` is [(slot, prompt, max_new),
        ...]; prompts sharing a padding bucket share ONE forward (the
        burst-amortization rung: B admissions cost one dispatch per
        bucket, not B).  Returns {slot: (first_token, last_logits)} —
        the f32 logits at each prompt's last position, for callers that
        sample the first token instead of taking the fused argmax.
        ``last_prefill_was_cold`` reports whether ANY group compiled."""
        groups: dict = {}
        for slot, prompt, max_new in assignments:
            prompt = np.asarray(prompt, np.int32).ravel()
            bucket = self.bucket_for(len(prompt), max_new)
            groups.setdefault(bucket, []).append((slot, prompt))
        out: dict = {}
        cold = False
        for bucket, group in sorted(groups.items()):
            B = len(group)
            if (bucket, B) not in self._warm_buckets:
                cold = True
            self._warm_buckets.add((bucket, B))
            padded = np.zeros((B, bucket), np.int32)
            slots_ix = np.zeros((B,), np.int32)
            lengths = np.zeros((B,), np.int32)
            for i, (slot, prompt) in enumerate(group):
                padded[i, :len(prompt)] = prompt
                slots_ix[i] = slot
                lengths[i] = len(prompt)
            toks, last, self._ck, self._cv = _prefill_bucketed(
                self.smodel, self.params, self._ck, self._cv,
                jnp.asarray(padded), slots_ix, lengths)
            toks = np.asarray(toks)
            last = np.asarray(last)
            for i, (slot, prompt) in enumerate(group):
                self.positions[slot] = len(prompt)
                self.last_tokens[slot] = int(toks[i])
                out[slot] = (int(toks[i]), last[i])
            self.prefills += B
        self.last_prefill_was_cold = cold
        return out

    def decode(self, busy=None) -> np.ndarray:
        """One decode step over ALL slots (idle slots compute too — the
        program has one static shape; their outputs are ignored and
        their stale rows are overwritten the next time the slot is
        live).  Returns the next token per slot and advances the BUSY
        slots' frontiers (``busy=None`` advances all): an idle slot's
        parked frontier must not drift toward the cache/positional-
        table edge one row per step of everyone else's work."""
        toks, self._ck, self._cv = _decode_step(
            self.smodel, self.params, self._ck, self._cv,
            self.last_tokens, self.positions)
        out = np.asarray(toks)
        advance = (np.ones(self.slots, bool) if busy is None
                   else np.zeros(self.slots, bool))
        if busy is not None:
            advance[list(busy)] = True
        self.last_tokens = np.where(advance, out, self.last_tokens) \
            .astype(np.int32)
        self.positions = self.positions + advance.astype(np.int32)
        self.decode_steps += 1
        return out

    def decode_logits(self, busy=None) -> np.ndarray:
        """One decode step returning the f32 logits [S, V] instead of
        the fused argmax — the sampling path.  Advances the busy slots'
        frontiers like :meth:`decode`, but the caller OWNS each busy
        slot's next token: it must ``set_slot(slot, token,
        positions[slot])`` before the next step (greedy's fused-argmax
        program, and its HLO contract, are untouched by this seam)."""
        logits, self._ck, self._cv = _decode_logits_step(
            self.smodel, self.params, self._ck, self._cv,
            self.last_tokens, self.positions)
        out = np.asarray(logits)
        advance = (np.ones(self.slots, bool) if busy is None
                   else np.zeros(self.slots, bool))
        if busy is not None:
            advance[list(busy)] = True
        self.positions = self.positions + advance.astype(np.int32)
        self.decode_steps += 1
        return out

    def verify_step(self, toks, positions) -> tuple:
        """One batched K-token verify over all slots: toks [S, K],
        positions [S] (a slot not participating passes position ==
        cache_len — its scatters drop out of bounds and its output rows
        are garbage to discard).  Returns (greedy [S, K] int32,
        logits [S, K, V] f32).  Advances NOTHING — the caller owns
        accept/rollback bookkeeping via :meth:`set_slot`."""
        g, logits, self._ck, self._cv = _verify_window(
            self.smodel, self.params, self._ck, self._cv,
            jnp.asarray(np.asarray(toks, np.int32)),
            jnp.asarray(np.asarray(positions, np.int32)))
        self.decode_steps += 1
        return np.asarray(g), np.asarray(logits)

    def extend(self, slot: int, tokens, start: int) -> tuple:
        """Append already-known ``tokens`` to ``slot``'s cache at rows
        ``start..`` (the prefix-cache suffix path) via the verify
        program, padded to a power-of-two window.  Returns
        (next_token, last_logits) at the final appended position."""
        tokens = np.asarray(tokens, np.int32).ravel()
        n = len(tokens)
        if n < 1:
            raise ValueError("empty extension")
        K = 1
        while K < n:
            K *= 2
        toks = np.zeros((self.slots, K), np.int32)
        pos = np.full((self.slots,), self.cache_len, np.int32)
        toks[slot, :n] = tokens
        pos[slot] = int(start)
        g, logits = self.verify_step(toks, pos)
        return int(g[slot, n - 1]), logits[slot, n - 1]

    def read_rows(self, slot: int, width: int) -> tuple:
        """Export ``slot``'s first ``width`` K/V rows as independent
        device arrays [L, width, H, Dh] (the prefix-cache registration
        read).  Blocked to completion so the copies cannot race the
        next step's cache donation."""
        k = self._ck[:, slot, :width]
        v = self._cv[:, slot, :width]
        return jax.block_until_ready(k), jax.block_until_ready(v)

    def write_rows(self, slot: int, k_rows, v_rows) -> None:
        """Import stored K/V rows into ``slot`` (the prefix-cache hit
        write); the caller then ``set_slot``s the real prefix length."""
        self._ck, self._cv = _splice_rows(
            self._ck, self._cv, k_rows, v_rows, np.int32(slot))

    def set_slot(self, slot: int, last_token: int, position: int) -> None:
        """Host bookkeeping hook (the batcher parks retired slots at
        position 0 so their frontier never walks off the cache end)."""
        self.last_tokens[slot] = int(last_token)
        self.positions[slot] = int(position)

    # --- the contract surface --------------------------------------------
    def decode_hlo(self) -> str:
        """Freshly compiled decode-step text — what graftlint's HLO
        front checks :data:`DECODE_HLO_CONTRACT` against.  Compiled
        from the UNDONATED argument values via a separate lowering (the
        live step's buffers must not be consumed by a lint pass)."""
        lowered = _decode_step.lower(
            self.smodel, self.params, self._ck, self._cv,
            self.last_tokens, self.positions)
        return lowered.compile().as_text()
