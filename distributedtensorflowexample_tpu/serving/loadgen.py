"""Closed-loop load generator: the measurement half of the serving path.

Open-loop generators (fixed arrival rate) measure a latency curve but
overload the system at will; a CLOSED loop — K client threads, each
submitting one request, waiting for its completion, then immediately
submitting the next — self-limits to the system's actual service rate,
so sweeping K traces out the throughput/latency trade directly:
tokens/sec climbs with K until the slots saturate, then p50/p99 climb
instead.  With the SLO admission knob on, the same sweep yields the
throughput-vs-SLO curve ``bench_serving.py`` records (in-SLO goodput vs
the rejection rate at each operating point).

Determinism: prompts are generated from a seeded RNG keyed by request
index, so request #17 is byte-identical across runs, placements, and
resumes — the property the scheduler drill leans on when a TERM'd
serving worker's relaunch re-issues exactly the unfinished ids.

Resumable driving: ``DriveFile`` is the victim-script progress tape of
the serving world — one appended line per COMPLETED request.  A TERM'd
worker drains its in-flight requests (they complete and append), the
relaunch reads the tape, and re-issues only the ids with no line: no
accepted request is ever lost, none is answered twice.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

_DEF_CLIENTS = 2
_DEF_REQUESTS = 16


def load_clients_default() -> int:
    """``SERVE_LOAD_CLIENTS``: default closed-loop client thread count
    for serve_lm --drive and bench_serving (CLI flags override)."""
    try:
        return max(1, int(os.environ.get("SERVE_LOAD_CLIENTS", "")))
    except ValueError:
        return _DEF_CLIENTS


def load_requests_default() -> int:
    """``SERVE_LOAD_REQUESTS``: default request count one drive/bench
    point issues (CLI flags override)."""
    try:
        return max(1, int(os.environ.get("SERVE_LOAD_REQUESTS", "")))
    except ValueError:
        return _DEF_REQUESTS


def make_prompt(index: int, vocab: int, seed: int = 0,
                min_len: int = 4, max_len: int = 12) -> np.ndarray:
    """Deterministic per-index prompt (seeded, index-keyed): the same
    request id always carries the same bytes."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, index]))
    n = int(rng.integers(min_len, max_len + 1))
    return rng.integers(0, vocab, size=n).astype(np.int32)


class DriveFile:
    """Append-only completed-request tape (torn-tail tolerant like
    every journal reader in the repo): ``{"id": i, "tokens": [...]}``
    per line."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def done_ids(self) -> dict[int, list]:
        out: dict[int, list] = {}
        try:
            with open(self.path) as f:
                lines = f.read().splitlines()
        except OSError:
            return out
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue        # torn tail: that id re-issues
            if isinstance(rec, dict) and isinstance(rec.get("id"), int):
                out[rec["id"]] = rec.get("tokens") or []
        return out

    def append(self, rid: int, tokens: list) -> None:
        line = json.dumps({"id": rid, "tokens": list(tokens)},
                          sort_keys=True)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())


class ClosedLoopLoadGen:
    """K client threads against one RequestQueue, driving a fixed id
    set to completion.  ``run()`` blocks until every target id has a
    completed line (or ``stop`` is set — the TERM path: clients stop
    issuing, in-flight requests drain through the batcher)."""

    def __init__(self, queue, *, total: int, clients: int,
                 max_new: int, vocab: int, seed: int = 0,
                 drive_file: DriveFile | None = None,
                 prompt_min: int = 4, prompt_max: int = 12,
                 max_attempts: int = 5, think_ms: float = 0.0):
        self.queue = queue
        self.total = int(total)
        self.clients = max(1, int(clients))
        self.max_new = int(max_new)
        self.vocab = int(vocab)
        self.seed = seed
        self.drive = drive_file
        self.prompt_min, self.prompt_max = prompt_min, prompt_max
        # Closed-loop clients resubmit a rejected id — but a system
        # whose SLO rejects EVERYTHING (the sweep's tightest points)
        # must end the measurement, not hang it: after max_attempts an
        # id is given up and counted, and the goodput at that operating
        # point is honestly ~0.
        self.max_attempts = max(1, int(max_attempts))
        # Think time: the classic closed-loop load parameter — a client
        # pauses this long after each completion before its next
        # request, so offered load is tunable below saturation (and a
        # drill can hold a worker busy for a predictable span).
        self.think_ms = float(think_ms)
        self.stop = threading.Event()
        self.results: list = []          # finished Request objects
        self.gave_up: list[int] = []
        self._pending: list[int] = []
        self._attempts: dict[int, int] = {}
        self._lock = threading.Lock()

    def _next_id(self) -> int | None:
        with self._lock:
            return self._pending.pop(0) if self._pending else None

    def _requeue(self, rid: int) -> None:
        with self._lock:
            self._pending.append(rid)

    def _client(self) -> None:
        while not self.stop.is_set():
            rid = self._next_id()
            if rid is None:
                return
            prompt = make_prompt(rid, self.vocab, self.seed,
                                 self.prompt_min, self.prompt_max)
            req = self.queue.submit(prompt, self.max_new, rid=f"d{rid}")
            req.done.wait()
            self.results.append(req)
            if req.outcome == "ok":
                if self.drive is not None:
                    self.drive.append(rid, req.tokens)
                if self.think_ms > 0:
                    self.stop.wait(self.think_ms / 1000.0)
            elif req.outcome == "refused":
                # Geometry refusal is deterministic: the same id would
                # be refused forever — give up immediately, loudly.
                self.gave_up.append(rid)
            else:
                # slo_rejected / drained: the id is NOT done — a later
                # client turn (or the next placement) re-issues it,
                # until its attempt budget runs out.  Tiny backoff so
                # an overloaded queue isn't hammered by instant
                # re-submissions of the same id.
                with self._lock:
                    n = self._attempts[rid] = \
                        self._attempts.get(rid, 0) + 1
                if n >= self.max_attempts:
                    self.gave_up.append(rid)
                else:
                    self._requeue(rid)
                    time.sleep(0.002)

    def run(self) -> dict:
        already = self.drive.done_ids() if self.drive is not None else {}
        self._pending = [i for i in range(self.total) if i not in already]
        skipped = self.total - len(self._pending)
        threads = [threading.Thread(target=self._client, daemon=True,
                                    name=f"loadgen-{i}")
                   for i in range(self.clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {"issued": len(self.results), "resumed_skip": skipped,
                "wall_s": round(time.monotonic() - t0, 3),
                "gave_up": len(self.gave_up),
                "remaining": len(self._pending)}
