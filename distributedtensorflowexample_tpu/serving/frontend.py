"""Opt-in HTTP request front (``SERVE_PORT``) for the serving worker.

The obs scrape endpoint (``OBS_HTTP_PORT``, obs/serve.py) answers "how
is this process doing"; THIS server answers actual requests — the two
are deliberately separate ports with separate contracts: telemetry is
read-only and must never block, while ``POST /generate`` holds the
connection open until the request completes (or is rejected by the SLO
admission / the draining worker).

- ``POST /generate`` — body ``{"tokens": [ints], "max_new": n}``;
  response ``{"id", "tokens", "outcome", "latency_ms"}`` with HTTP 200
  for ok, 429 for an SLO rejection (back off and retry), 503 while
  draining (retry against the next placement), 400 for a malformed,
  out-of-vocab, or can-never-finish (prompt + max_new over the cache)
  request (the ModeRefusal text passes through — the client learns
  WHY, not just that);
- ``GET /stats`` — the batcher's live stats dict (same payload the
  drive mode writes at exit).

Loopback by default, daemon threads, failure-is-refusal semantics —
the obs/serve.py stance, because a request front that can kill the
worker it fronts is a self-DoS.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from distributedtensorflowexample_tpu.refusal import ModeRefusal


def serve_port_default() -> int:
    """``SERVE_PORT``: request-front port for tools/serve_lm.py
    (0/unset = in-process only, no HTTP front)."""
    try:
        return int(os.environ.get("SERVE_PORT", ""))
    except ValueError:
        return 0


def _log(msg: str) -> None:
    print(f"serve.frontend: {msg}", file=sys.stderr, flush=True)


class _Handler(BaseHTTPRequestHandler):
    queue = None                # class-bound by RequestFront.start
    batcher = None

    def log_message(self, format, *args):  # noqa: A002 (stdlib casing)
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload, sort_keys=True).encode() + b"\n"
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib casing)
        try:
            if self.path == "/stats":
                self._send(200, self.batcher.stats())
            else:
                self._send(404, {"error": f"unknown path {self.path}",
                                 "paths": ["/generate (POST)", "/stats"]})
        except BrokenPipeError:
            pass
        except Exception as e:
            try:
                self._send(500, {"error": repr(e)})
            except Exception:
                pass

    def do_POST(self):  # noqa: N802 (stdlib casing)
        try:
            if self.path != "/generate":
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            n = int(self.headers.get("Content-Length") or 0)
            try:
                body = json.loads(self.rfile.read(n) or b"{}")
                tokens = body["tokens"]
                max_new = int(body.get("max_new", 16))
            except (json.JSONDecodeError, KeyError, TypeError,
                    ValueError) as e:
                self._send(400, {"error": f"bad request body: {e!r}; "
                                          f"expected {{'tokens': [ints],"
                                          f" 'max_new': n}}"})
                return
            try:
                req = self.queue.submit(tokens, max_new)
            except ModeRefusal as e:
                self._send(400, {"error": str(e), "outcome":
                                 "oov_refused"})
                return
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            req.done.wait()
            code = {"ok": 200, "slo_rejected": 429,
                    "drained": 503, "refused": 400}.get(req.outcome, 500)
            payload = {
                "id": req.rid, "outcome": req.outcome,
                "tokens": req.tokens if req.outcome == "ok" else [],
                "latency_ms": round((req.latency_s or 0.0) * 1000.0, 3)}
            if req.error:
                payload["error"] = req.error
            self._send(code, payload)
        except BrokenPipeError:
            pass        # client hung up mid-wait: its problem
        except Exception as e:
            try:
                self._send(500, {"error": repr(e)})
            except Exception:
                pass


class RequestFront:
    """The serving thread wrapper (obs/serve.py's ObsServer shape;
    ``port=0`` never binds — callers gate on :func:`serve_port_default`
    or an explicit flag)."""

    def __init__(self, queue, batcher, port: int,
                 host: str = "127.0.0.1"):
        self._queue = queue
        self._batcher = batcher
        self._host = host
        self._port = int(port)
        self._httpd: ThreadingHTTPServer | None = None

    @property
    def port(self) -> int:
        return (self._httpd.server_address[1] if self._httpd is not None
                else self._port)

    def start(self) -> "RequestFront | None":
        handler = type("_BoundHandler", (_Handler,),
                       {"queue": self._queue, "batcher": self._batcher})
        try:
            self._httpd = ThreadingHTTPServer((self._host, self._port),
                                              handler)
        except (OSError, OverflowError) as e:
            _log(f"could not bind {self._host}:{self._port} ({e}) — "
                 f"serving in-process only")
            return None
        self._httpd.daemon_threads = True
        threading.Thread(target=self._httpd.serve_forever,
                         kwargs={"poll_interval": 0.5},
                         name="serve-front", daemon=True).start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
