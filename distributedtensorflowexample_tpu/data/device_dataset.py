"""Device-resident dataset: the whole split lives in HBM, minibatches are
gathered on device (pairs with ``parallel.sync.make_indexed_train_step``).

The reference fed every step over the feed_dict / input-pipeline boundary
(SURVEY.md §3a: "the feed-dict copy is the per-step overhead").  At MNIST
scale that copy is THE bottleneck on TPU — measured ~1.4 ms of H2D per
step against a ~0.07 ms compiled step on one v5e chip — and no amount of
prefetch depth hides a transfer that is 20x the step.  MNIST (183 MB) and
CIFAR-10 (590 MB) fit trivially in HBM, so the TPU-native design uploads
the split once and moves nothing per step: the epoch's shuffled index
order is itself computed on device (``jax.random.permutation``), and the
step slices its batch out of it by global-step position.

Epoch double-buffering: the dataset always holds TWO epoch permutations in
one device array of shape ``(2, epoch_len)`` — epoch ``e`` in slot
``e % 2``, epoch ``e+1`` in the other slot.  The train step picks the slot
from ``state.step // steps_per_epoch`` per fused sub-step, so one compiled
multi-step call may cross an epoch boundary mid-scan.  That decouples the
dispatch-amortizing unroll (``steps_per_next`` / ``unroll_steps``) from
epoch arithmetic entirely: any unroll up to ``steps_per_epoch`` works, and
the next epoch's permutation is computed (asynchronously, off the critical
path) a whole epoch before it is first read.

Shuffling semantics match the host ``Batcher``: epochs without
replacement, the sub-batch remainder rows dropped per epoch.

Per-epoch host work: one tiny jitted row update into the perm pair.
Per-step host work: a dict re-yield.

Multi-host: every process holds the identical split (same loaders, same
seed — the reference's workers did the same), the arrays are replicated on
the mesh, and every process computes the identical permutations; the train
step re-shards each gathered batch along the data axis on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class DeviceDataset:
    """Iterator yielding ``{"images", "labels", "perm"}`` device pytrees.

    ``perm`` has shape ``(2, epoch_len)``: the current epoch's shuffled
    index order in slot ``epoch % 2``, the next epoch's in the other slot.
    The arrays are the same device buffers every step — only one perm row
    is replaced, once per epoch.  Pass ``start_step`` (e.g. after a
    resume) so epoch slots line up with the step's position arithmetic.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, mesh=None, seed: int = 0,
                 shuffle: bool = True, start_step: int = 0,
                 steps_per_next: int = 1):
        """``steps_per_next``: global steps consumed per ``next()`` — set to
        the train step's ``unroll_steps`` so the perm pair is refreshed on
        the right call.  Any value in ``[1, steps_per_epoch]`` works (a
        fused window may cross one epoch boundary, never two)."""
        if len(images) < batch_size:
            raise ValueError(
                f"dataset of {len(images)} examples is smaller than "
                f"batch {batch_size}")
        self._n = len(images)
        self.steps_per_epoch = self._n // batch_size
        self.epoch_len = self.steps_per_epoch * batch_size
        if not 1 <= steps_per_next <= self.steps_per_epoch:
            raise ValueError(
                f"steps_per_next {steps_per_next} must be in [1, "
                f"steps_per_epoch={self.steps_per_epoch}] (a fused window "
                f"may cross at most one epoch boundary)")
        self._spn = steps_per_next
        self._step = int(start_step)
        self._slot_epochs: list[int | None] = [None, None]

        if mesh is not None:
            from distributedtensorflowexample_tpu.parallel.mesh import (
                replicated_sharding)
            repl = replicated_sharding(mesh)
            if jax.process_count() > 1:
                put = lambda x: jax.make_array_from_process_local_data(repl, x)
            else:
                put = lambda x: jax.device_put(x, repl)
        else:
            repl, put = None, jax.device_put
        self.images = put(np.ascontiguousarray(images))
        self.labels = put(np.ascontiguousarray(labels))

        base = jax.random.PRNGKey(seed)

        def make_perm(epoch: jnp.ndarray) -> jnp.ndarray:
            key = jax.random.fold_in(base, epoch)
            if shuffle:
                order = jax.random.permutation(key, self._n)
            else:
                order = jnp.arange(self._n)
            return order[:self.epoch_len].astype(jnp.int32)

        def set_row(pair, row, slot):
            return jax.lax.dynamic_update_slice(pair, row[None], (slot, 0))

        jit_kw = {"out_shardings": repl} if repl is not None else {}
        self._make_perm = jax.jit(make_perm, **jit_kw)
        # Donated: the stale epoch's row is overwritten in place in HBM;
        # the runtime sequences the write after any in-flight reads.
        self._set_row = jax.jit(set_row, donate_argnums=0, **jit_kw)
        self._pair = jax.jit(
            lambda: jnp.zeros((2, self.epoch_len), jnp.int32), **jit_kw)()

    def _ensure_epoch(self, epoch: int) -> None:
        slot = epoch % 2
        if self._slot_epochs[slot] != epoch:
            perm = self._make_perm(jnp.asarray(epoch, jnp.int32))
            self._pair = self._set_row(self._pair, perm,
                                       jnp.asarray(slot, jnp.int32))
            self._slot_epochs[slot] = epoch

    def __iter__(self):
        return self

    def __next__(self):
        epoch = self._step // self.steps_per_epoch
        # Both the window's possible epochs stay resident: e in slot e%2,
        # e+1 in the other — computed one epoch ahead (double-buffered).
        self._ensure_epoch(epoch)
        self._ensure_epoch(epoch + 1)
        self._step += self._spn
        return {"images": self.images, "labels": self.labels,
                "perm": self._pair}
