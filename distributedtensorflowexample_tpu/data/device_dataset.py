"""Device-resident dataset: the whole split lives in HBM, minibatches are
gathered on device (pairs with ``parallel.sync.make_indexed_train_step``).

The reference fed every step over the feed_dict / input-pipeline boundary
(SURVEY.md §3a: "the feed-dict copy is the per-step overhead").  At MNIST
scale that copy is THE bottleneck on TPU — measured ~1.4 ms of H2D per
step against a ~0.07 ms compiled step on one v5e chip — and no amount of
prefetch depth hides a transfer that is 20x the step.  MNIST (183 MB) and
CIFAR-10 (590 MB) fit trivially in HBM, so the TPU-native design uploads
the split once and moves only nothing per step: the epoch's shuffled index
order is itself computed on device (``jax.random.permutation``), and the
step slices its batch out of it by global-step position.

Per-epoch host work: one tiny jitted permutation dispatch.  Per-step host
work: a dict re-yield.  Shuffling semantics match the host ``Batcher``:
epochs without replacement, remainder rows dropped per epoch.

Multi-host: every process holds the identical split (same loaders, same
seed — the reference's workers did the same), the arrays are replicated on
the mesh, and every process computes the identical permutation; the train
step re-shards each gathered batch along the data axis on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class DeviceDataset:
    """Iterator yielding ``{"images", "labels", "perm"}`` device pytrees.

    The arrays are the same device buffers every step — only ``perm`` is
    replaced, once per epoch.  Pass ``start_step`` (e.g. after a resume)
    so epoch boundaries line up with the step's position arithmetic.
    """

    # Epochs are truncated to a multiple of a power-of-two granule derived
    # from (dataset size, batch) ONLY — never from steps_per_next — so
    # changing steps_per_loop between runs or across a resume cannot
    # silently remap which permutation/position a given global step sees.
    # The granule is the largest power of two ≤ the cap whose truncation
    # drops at most 1/16 of the epoch's batches.
    EPOCH_MULTIPLE_CAP = 32

    @classmethod
    def epoch_multiple(cls, raw_steps: int) -> int:
        m = 1
        while m * 2 <= min(cls.EPOCH_MULTIPLE_CAP, raw_steps):
            m *= 2
        while m > 1 and (raw_steps % m) * 16 > raw_steps:
            m //= 2
        return m

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, mesh=None, seed: int = 0,
                 shuffle: bool = True, start_step: int = 0,
                 steps_per_next: int = 1):
        """``steps_per_next``: global steps consumed per ``next()`` — set to
        the train step's ``unroll_steps`` so the permutation swaps on the
        right call.  Must be a power of two dividing the epoch multiple
        (a scan window never crosses an epoch boundary)."""
        if len(images) < batch_size:
            raise ValueError(
                f"dataset of {len(images)} examples is smaller than "
                f"batch {batch_size}")
        self._n = len(images)
        raw_steps = self._n // batch_size
        multiple = self.epoch_multiple(raw_steps)
        if steps_per_next < 1 or multiple % steps_per_next:
            raise ValueError(
                f"steps_per_next {steps_per_next} must be a power of two "
                f"dividing {multiple} (epoch multiple for {self._n} "
                f"examples at batch {batch_size})")
        self.steps_per_epoch = (raw_steps // multiple) * multiple
        self.epoch_len = self.steps_per_epoch * batch_size
        if not shuffle and self.steps_per_epoch < raw_steps:
            import warnings
            warnings.warn(
                f"shuffle=False with epoch truncated from {raw_steps} to "
                f"{self.steps_per_epoch} steps: the last "
                f"{self._n - self.epoch_len} examples will never be seen")
        self._spn = steps_per_next
        self._step = int(start_step)
        self._epoch = None
        self._perm = None

        if mesh is not None:
            from distributedtensorflowexample_tpu.parallel.mesh import (
                replicated_sharding)
            repl = replicated_sharding(mesh)
            if jax.process_count() > 1:
                put = lambda x: jax.make_array_from_process_local_data(repl, x)
            else:
                put = lambda x: jax.device_put(x, repl)
        else:
            repl, put = None, jax.device_put
        self.images = put(np.ascontiguousarray(images))
        self.labels = put(np.ascontiguousarray(labels))

        base = jax.random.PRNGKey(seed)

        def make_perm(epoch: jnp.ndarray) -> jnp.ndarray:
            key = jax.random.fold_in(base, epoch)
            if shuffle:
                order = jax.random.permutation(key, self._n)
            else:
                order = jnp.arange(self._n)
            return order[:self.epoch_len].astype(jnp.int32)

        self._make_perm = (jax.jit(make_perm, out_shardings=repl)
                           if repl is not None else jax.jit(make_perm))

    def __iter__(self):
        return self

    def __next__(self):
        epoch = self._step // self.steps_per_epoch
        if epoch != self._epoch:
            self._epoch = epoch
            self._perm = self._make_perm(jnp.asarray(epoch, jnp.int32))
        self._step += self._spn
        return {"images": self.images, "labels": self.labels,
                "perm": self._perm}
