"""Device-resident dataset: the whole split lives in HBM, minibatches are
gathered on device (pairs with ``parallel.sync.make_indexed_train_step``).

The reference fed every step over the feed_dict / input-pipeline boundary
(SURVEY.md §3a: "the feed-dict copy is the per-step overhead").  At MNIST
scale that copy is THE bottleneck on TPU — measured ~1.4 ms of H2D per
step against a ~0.07 ms compiled step on one v5e chip — and no amount of
prefetch depth hides a transfer that is 20x the step.  MNIST (183 MB) and
CIFAR-10 (590 MB) fit trivially in HBM, so the TPU-native design uploads
the split once and moves nothing per step: the epoch's shuffled index
order is itself computed on device (``jax.random.permutation``), and the
step slices its batch out of it by global-step position.

Epoch multi-buffering: the dataset holds a ring of S epoch permutations in
one device array of shape ``(S, epoch_len)`` — epoch ``e`` in slot
``e % S``.  The train step picks the slot from ``state.step //
steps_per_epoch`` per fused sub-step, so one compiled multi-step call may
cross up to ``S - 1`` epoch boundaries mid-scan.  That decouples the
dispatch-amortizing unroll (``steps_per_next`` / ``unroll_steps``) from
epoch arithmetic entirely: ``S`` is sized automatically from
``steps_per_next`` (every epoch a window can touch, plus one prefetch
slot), so multi-epoch fused windows work and the next epoch's permutation
is computed (asynchronously, off the critical path) an epoch before it is
first read.  Ring-slot overwrites are safe out of order: the jitted row
update donates the buffer, and the device stream sequences it after every
already-enqueued step that reads the old row.

Shuffling semantics match the host ``Batcher``: epochs without
replacement, the sub-batch remainder rows dropped per epoch.

Per-epoch host work: one tiny jitted row update into the perm pair.
Per-step host work: a dict re-yield.

Multi-host: every process holds the identical split (same loaders, same
seed — the reference's workers did the same), the arrays are replicated on
the mesh, and every process computes the identical permutations; the train
step re-shards each gathered batch along the data axis on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def make_dequant_lut(spec: str) -> np.ndarray:
    """The 256 float32 values a uint8 pixel can dequantize to, computed
    on the HOST with the loader's own numpy ops (mnist.py / cifar10.py:
    ``raw/255.0`` then optionally ``(x - MEAN) / STD``) so the lookup is
    BITWISE-exact — recomputing the arithmetic in XLA is NOT safe (XLA
    strength-reduces the division by 255 to a reciprocal multiply, ~1
    ulp off on ~40% of values, measured).  Shape [256] ("unit") or
    [256, C] (per-channel normalization)."""
    if spec == "unit":
        return np.arange(256, dtype=np.float32) / 255.0
    if spec == "cifar":
        from distributedtensorflowexample_tpu.data.cifar10 import (
            CIFAR10_MEAN, CIFAR10_STD)
        base = np.arange(256, dtype=np.float32)[:, None] / 255.0
        return ((base - CIFAR10_MEAN) / CIFAR10_STD).astype(np.float32)
    raise ValueError(f"unknown dequant spec {spec!r}")


def make_dequant_affine(spec: str) -> tuple[np.ndarray, np.ndarray]:
    """(scale, bias) float32 vectors (shape [1] or [C]) such that
    ``u * scale + bias`` reproduces the loader's float pipeline to ~1 ulp
    (NOT bitwise: the reciprocal-multiply form rounds differently from
    the loader's division on ~40% of byte values — measured; the LUT
    path exists for callers that need exact bits).  This is the
    ``quantize="scale"`` dequant: two fused elementwise ops per pixel,
    the fastest measured form (AB_quantize_r05.json: 1,963 steps/s vs
    1,654 float32-resident vs 1,620 exact one-hot on the headline)."""
    if spec == "unit":
        return (np.float32([1.0]) / 255.0, np.zeros(1, np.float32))
    if spec == "cifar":
        from distributedtensorflowexample_tpu.data.cifar10 import (
            CIFAR10_MEAN, CIFAR10_STD)
        scale = (1.0 / (255.0 * np.float64(CIFAR10_STD))).astype(np.float32)
        bias = (-np.float64(CIFAR10_MEAN) / CIFAR10_STD).astype(np.float32)
        return scale, bias
    raise ValueError(f"unknown dequant spec {spec!r}")


def apply_dequant_affine(u8: jnp.ndarray, scale: jnp.ndarray,
                         bias: jnp.ndarray) -> jnp.ndarray:
    """uint8 pixels -> ~float32 via the fused affine form (see
    make_dequant_affine for the ~1-ulp caveat and the measured wins)."""
    return u8.astype(jnp.float32) * scale + bias


def apply_dequant_lut(u8: jnp.ndarray, lut: jnp.ndarray) -> jnp.ndarray:
    """uint8 pixels -> float32 through a [256] / [256, C] LUT, expressed
    as a one-hot matmul so it runs on the MXU.

    The obvious ``lut[idx]`` gather is catastrophically slow on TPU: the
    round-5 on-chip trace (PROFILE_auto_r05.json window) measured it at
    ~10 ns/element — 8.2 ms/step on ResNet-20's batch, 56% of the whole
    step; the same-window A/B (AB_quantize_r05.json) put the headline at
    479 steps/s with the gather vs 1,620 with this form.

    Exactness: the one-hot rows are exact {0,1} in bfloat16 and each
    output element's dot product has exactly ONE nonzero term, so the
    result is the table entry itself — PROVIDED the table operand loses
    no bits.  A float32 table downcast to bfloat16 would lose 16
    mantissa bits, so the table is split into three bfloat16 components
    (f32 has 24 mantissa bits = 3 x 8): ``hi = bf16(v)``,
    ``mid = bf16(v - hi)``, ``lo = bf16(v - hi - mid)``.  Every split
    subtraction is exact (Sterbenz: operands within a factor of 2), the
    residual after two splits has <= 8 significant bits so ``lo`` is
    exact, and the f32 reconstruction ``(hi + mid) + lo`` is exact
    because each partial sum is representable.  Three bf16 matmuls, each
    picking one component, summed in that order — bitwise-identical to
    the host table (asserted on-chip by the quantize parity tests)."""
    from distributedtensorflowexample_tpu.data.augment_device import (
        _mm_dtype)
    md = _mm_dtype()   # bf16 on accelerators; f32 on CPU (no bf16 GEMM
    #                    there, and f32 one-hot dots are exact anyway —
    #                    the split terms below degenerate to v + 0 + 0)
    idx = u8.astype(jnp.int32)
    oh = (idx[..., None] == jnp.arange(256, dtype=jnp.int32)).astype(md)
    hi = lut.astype(md)
    mid = (lut - hi.astype(jnp.float32)).astype(md)
    lo = (lut - hi.astype(jnp.float32)
          - mid.astype(jnp.float32)).astype(md)
    if lut.ndim == 1:
        part = lambda t: jnp.einsum(
            "...k,k->...", oh, t, preferred_element_type=jnp.float32)
    else:
        # Per-channel table: channel c of pixel p uses column c —
        # contraction over the 256 axis with c as a batch dim.
        part = lambda t: jnp.einsum(
            "...ck,kc->...c", oh, t, preferred_element_type=jnp.float32)
    return (part(hi) + part(mid)) + part(lo)


def dequantize_images(u8: jnp.ndarray, spec: str) -> jnp.ndarray:
    """uint8 pixels -> the float32 values the loader would have produced
    (see make_dequant_lut for the bitwise-exactness argument)."""
    return apply_dequant_lut(u8, jnp.asarray(make_dequant_lut(spec)))


def _dequant_numpy(u8: np.ndarray, spec: str) -> np.ndarray:
    """Host-side reference of dequantize_images (verification path)."""
    x = u8.astype(np.float32) / 255.0
    if spec == "cifar":
        from distributedtensorflowexample_tpu.data.cifar10 import (
            CIFAR10_MEAN, CIFAR10_STD)
        x = (x - CIFAR10_MEAN) / CIFAR10_STD
    return x


def _try_quantize(x: np.ndarray, chunk: int = 4096):
    """(uint8 split, dequant spec) if ``x`` is EXACTLY representable as
    dequantize_images(u8, spec) for one of the known pipelines (raw
    [0,1] "unit" pixels, or CIFAR mean/std-normalized); else None.

    Exactness is verified bitwise chunk-by-chunk (bounded memory), so a
    caller can never lose precision silently: anything not byte-exact —
    arbitrary float inputs, a future normalization this doesn't know —
    stays float32-resident."""
    if x.dtype != np.float32 or x.ndim < 2 or x.size == 0:
        # Empty splits fall through to the caller's own size validation
        # (min()/max() on a zero-length array would raise here first).
        return None
    lo, hi = float(x.min()), float(x.max())
    candidates = []
    if 0.0 <= lo and hi <= 1.0:
        candidates.append(("unit",
                           lambda c: np.rint(c * 255.0)))
    if x.shape[-1] == 3:
        from distributedtensorflowexample_tpu.data.cifar10 import (
            CIFAR10_MEAN, CIFAR10_STD)
        candidates.append(("cifar", lambda c: np.rint(
            (c.astype(np.float64) * CIFAR10_STD + CIFAR10_MEAN) * 255.0)))
    for spec, recover in candidates:
        out = np.empty(x.shape, np.uint8)
        ok = True
        for i in range(0, len(x), chunk):
            c = x[i:i + chunk]
            u = recover(c)
            if u.min() < 0 or u.max() > 255:
                ok = False
                break
            u = u.astype(np.uint8)
            if not np.array_equal(_dequant_numpy(u, spec), c):
                ok = False
                break
            out[i:i + chunk] = u
        if ok:
            return out, spec
    return None


class DeviceDataset:
    """Iterator yielding ``{"images", "labels", "perm"}`` device pytrees.

    ``perm`` has shape ``(num_slots, epoch_len)``: epoch ``e``'s shuffled
    index order lives in slot ``e % num_slots``.  The arrays are the same
    device buffers every step — only one perm row is replaced, once per
    epoch.  Pass ``start_step`` (e.g. after a resume) so epoch slots line
    up with the step's position arithmetic.  Pass ``num_slots`` to the
    step factory (``make_indexed_train_step(..., num_slots=ds.num_slots)``)
    so its slot arithmetic matches.
    """

    @staticmethod
    def ring_slots_for(window_steps: int, steps_per_epoch: int) -> int:
        """Perm-ring size for a ``window_steps``-step fused window: every
        epoch one window can touch (a K-step window starting mid-epoch
        spans ceil(K / spe) boundaries at worst -> that many + 1 epochs)
        plus one slot so the next epoch prefetches without evicting a row
        the in-flight window still reads.  THE single source of the slot
        arithmetic — the step factories use it for their defaults, so
        dataset and gather can't drift."""
        return -(-window_steps // steps_per_epoch) + 2

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, mesh=None, seed: int = 0,
                 shuffle: bool = True, start_step: int = 0,
                 steps_per_next: int = 1, quantize: str = "auto",
                 data_sharding: str = "replicated"):
        """``steps_per_next``: global steps consumed per ``next()`` — set to
        the train step's ``unroll_steps`` so the perm ring is refreshed on
        the right call.  Any value >= 1 works; the ring is sized to hold
        every epoch one window can touch plus a prefetch slot.

        ``quantize`` stores the split as uint8 in HBM when the float32
        pixels are BITWISE-recoverable from one of the known 8-bit
        pipelines (verified element-exact at build time; see
        ``_try_quantize``): the per-step on-device gather then moves 4x
        fewer bytes.  Modes (on-chip numbers: AB_quantize_r05.json,
        headline config, same window):

        - ``"scale"``: uint8 + fused affine dequant — the fastest form
          (1,963 steps/s vs 1,654 float32-resident), ~1 ulp from the
          loader's floats (make_dequant_affine).
        - ``"exact"``: uint8 + one-hot-matmul LUT dequant — bitwise
          identical to the float32-resident path (1,620 steps/s).
        - ``"off"``: float32-resident, no quantization (raw uint8 input
          still dequantizes, exactly, since storage is already 8-bit).
        - ``"auto"`` (default): ``"scale"``.

        The dequant constants travel INSIDE the yielded data pytree
        (``data["lut"]`` or ``data["dq_scale"]/["dq_bias"]``) and the
        device gather dispatches on the pytree structure, so no call
        site can forget to dequantize.

        ``data_sharding="sharded"`` (VERDICT r4 #8) shards the resident
        split ROW-WISE over the mesh's data axis instead of replicating
        it: per-device HBM for the split drops by the mesh size, lifting
        the per-device ceiling for datasets bigger than CIFAR.  The epoch
        permutation is then built per device shard (device ``d`` shuffles
        its own rows) and interleaved so the step's standard slice
        arithmetic hands every device positions that live in ITS shard —
        the gather stays collective-free (``sync.make_device_gather``'s
        shard_map branch translates to local row space).  Shuffling
        semantics become per-shard (the reference's per-worker dataset
        sharding under MultiWorkerMirroredStrategy) rather than global;
        rows beyond ``mesh_size * (n // mesh_size)`` are dropped.  Pass
        the SAME mode to the step factory."""
        if quantize not in ("auto", "off", "exact", "scale"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        # "auto" picks the fastest measured dequant (AB_quantize_r05.json:
        # scale 1,963 > off 1,654 > exact 1,620 steps/s on the headline);
        # "exact" keeps the bitwise f32-parity guarantee at ~f32 speed.
        self.quantize = "scale" if quantize == "auto" else quantize
        if data_sharding not in ("replicated", "sharded"):
            raise ValueError(f"unknown data_sharding {data_sharding!r}")
        if data_sharding == "sharded" and mesh is None:
            raise ValueError("data_sharding='sharded' requires a mesh")
        self.data_sharding = data_sharding
        self.dequant: str | None = None
        if images.dtype == np.uint8:
            # Raw bytes: downstream floats are u/255 by convention.
            self.dequant = "unit"
        elif self.quantize in ("scale", "exact"):
            q = _try_quantize(np.asarray(images))
            if q is not None:
                images, self.dequant = q
        if len(images) < batch_size:
            raise ValueError(
                f"dataset of {len(images)} examples is smaller than "
                f"batch {batch_size}")
        if data_sharding == "sharded":
            # The data-axis extent, NOT mesh.size: they agree on today's
            # 1-D meshes, but the P(DATA_AXIS) row placement and the
            # gather's shard count are defined by the axis — a future
            # multi-axis mesh must not silently mis-translate indices.
            from distributedtensorflowexample_tpu.parallel.mesh import (
                DATA_AXIS)
            self._D = mesh.shape[DATA_AXIS]
        else:
            self._D = 1
        if data_sharding == "sharded":
            if batch_size % self._D:
                raise ValueError(
                    f"sharded data: batch {batch_size} must divide across "
                    f"{self._D} devices")
            n_used = self._D * (len(images) // self._D)
            images, labels = images[:n_used], labels[:n_used]
            self._rows_per_dev = n_used // self._D
            self._bpd = batch_size // self._D
            # Per-shard epoch arithmetic: each device steps through ITS
            # rows_per_dev rows in bpd-row sub-batches.
            self.steps_per_epoch = self._rows_per_dev // self._bpd
        else:
            self.steps_per_epoch = len(images) // batch_size
        self._n = len(images)
        self.epoch_len = self.steps_per_epoch * batch_size
        if steps_per_next < 1:
            raise ValueError(
                f"steps_per_next {steps_per_next} must be >= 1")
        self.num_slots = self.ring_slots_for(steps_per_next,
                                             self.steps_per_epoch)
        self._spn = steps_per_next
        self._step = int(start_step)
        self._slot_epochs: list[int | None] = [None] * self.num_slots

        if mesh is not None:
            from distributedtensorflowexample_tpu.parallel.mesh import (
                DATA_AXIS, replicated_sharding)
            repl = replicated_sharding(mesh)
            if jax.process_count() > 1:
                put = lambda x: jax.make_array_from_process_local_data(repl, x)
            else:
                put = lambda x: jax.device_put(x, repl)
            if data_sharding == "sharded":
                from jax.sharding import NamedSharding, PartitionSpec as P
                rows = NamedSharding(mesh, P(DATA_AXIS))
                if jax.process_count() > 1:
                    # Mesh device order groups devices by process (see
                    # put_global_batch): process p owns a contiguous row
                    # block of the sharded split.
                    pc, pi = jax.process_count(), jax.process_index()
                    per = self._n // pc
                    put_rows = lambda x: jax.make_array_from_process_local_data(
                        rows, np.ascontiguousarray(x[pi * per:(pi + 1) * per]))
                else:
                    put_rows = lambda x: jax.device_put(x, rows)
            else:
                put_rows = put
        else:
            repl, put = None, jax.device_put
            put_rows = put
        self.images = put_rows(np.ascontiguousarray(images))
        self.labels = put_rows(np.ascontiguousarray(labels))
        # The dequant constants ride in the yielded pytree; WHICH keys
        # are present encodes the mode statically (pytree structure), so
        # the gather dispatches at trace time with no factory plumbing.
        self._lut, self._affine = None, None
        if self.dequant is not None:
            if self.quantize == "scale":
                s, b = make_dequant_affine(self.dequant)
                self._affine = (put(s), put(b))
            else:
                # "exact" — and "off" with raw uint8 input, where storage
                # is already 8-bit and exact bits cost nothing extra.
                self._lut = put(make_dequant_lut(self.dequant))

        base = jax.random.PRNGKey(seed)

        def make_perm(epoch: jnp.ndarray) -> jnp.ndarray:
            key = jax.random.fold_in(base, epoch)
            if data_sharding == "sharded":
                # Per-shard shuffle, interleaved so global positions
                # [s*B + d*bpd, s*B + (d+1)*bpd) always hold indices from
                # device d's row block — the step's standard slice
                # arithmetic then never needs a cross-device gather.
                D, L, bpd = self._D, self._rows_per_dev, self._bpd
                keys = jax.vmap(lambda d: jax.random.fold_in(key, d))(
                    jnp.arange(D))
                if shuffle:
                    local = jax.vmap(
                        lambda k: jax.random.permutation(k, L))(keys)
                else:
                    local = jnp.broadcast_to(jnp.arange(L), (D, L))
                local = local[:, :self.steps_per_epoch * bpd]
                local = local + (jnp.arange(D) * L)[:, None]
                order = (local.reshape(D, self.steps_per_epoch, bpd)
                         .transpose(1, 0, 2).reshape(-1))
                return order.astype(jnp.int32)
            if shuffle:
                order = jax.random.permutation(key, self._n)
            else:
                order = jnp.arange(self._n)
            return order[:self.epoch_len].astype(jnp.int32)

        def set_row(pair, row, slot):
            return jax.lax.dynamic_update_slice(pair, row[None], (slot, 0))

        jit_kw = {"out_shardings": repl} if repl is not None else {}
        self._make_perm = jax.jit(make_perm, **jit_kw)
        # Donated: the stale epoch's row is overwritten in place in HBM;
        # the runtime sequences the write after any in-flight reads.
        self._set_row = jax.jit(set_row, donate_argnums=0, **jit_kw)
        self._ring = jax.jit(
            lambda: jnp.zeros((self.num_slots, self.epoch_len), jnp.int32),
            **jit_kw)()

    def _ensure_epoch(self, epoch: int) -> None:
        slot = epoch % self.num_slots
        if self._slot_epochs[slot] != epoch:
            perm = self._make_perm(jnp.asarray(epoch, jnp.int32))
            self._ring = self._set_row(self._ring, perm,
                                       jnp.asarray(slot, jnp.int32))
            self._slot_epochs[slot] = epoch

    def __iter__(self):
        return self

    def peek(self):
        """The next window's data WITHOUT consuming it — for compile/cost
        probes that must not advance the ring past the training state."""
        first = self._step // self.steps_per_epoch
        last = (self._step + self._spn - 1) // self.steps_per_epoch
        # Every epoch this window touches, plus one prefetched ahead (the
        # prefetch may reuse the slot of an epoch an ALREADY-ENQUEUED call
        # still reads — safe, the donated row update is stream-ordered
        # after it).
        for epoch in range(first, last + 2):
            self._ensure_epoch(epoch)
        data = {"images": self.images, "labels": self.labels,
                "perm": self._ring}
        if self._lut is not None:
            data["lut"] = self._lut
        if self._affine is not None:
            data["dq_scale"], data["dq_bias"] = self._affine
        return data

    def __next__(self):
        data = self.peek()
        self._step += self._spn
        return data
