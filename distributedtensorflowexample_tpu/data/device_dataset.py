"""Device-resident dataset: the whole split lives in HBM, minibatches are
gathered on device (pairs with ``parallel.sync.make_indexed_train_step``).

The reference fed every step over the feed_dict / input-pipeline boundary
(SURVEY.md §3a: "the feed-dict copy is the per-step overhead").  At MNIST
scale that copy is THE bottleneck on TPU — measured ~1.4 ms of H2D per
step against a ~0.07 ms compiled step on one v5e chip — and no amount of
prefetch depth hides a transfer that is 20x the step.  MNIST (183 MB) and
CIFAR-10 (590 MB) fit trivially in HBM, so the TPU-native design uploads
the split once and moves nothing per step: the epoch's shuffled index
order is itself computed on device (``jax.random.permutation``), and the
step slices its batch out of it by global-step position.

Epoch multi-buffering: the dataset holds a ring of S epoch permutations in
one device array of shape ``(S, epoch_len)`` — epoch ``e`` in slot
``e % S``.  The train step picks the slot from ``state.step //
steps_per_epoch`` per fused sub-step, so one compiled multi-step call may
cross up to ``S - 1`` epoch boundaries mid-scan.  That decouples the
dispatch-amortizing unroll (``steps_per_next`` / ``unroll_steps``) from
epoch arithmetic entirely: ``S`` is sized automatically from
``steps_per_next`` (every epoch a window can touch, plus one prefetch
slot), so multi-epoch fused windows work and the next epoch's permutation
is computed (asynchronously, off the critical path) an epoch before it is
first read.  Ring-slot overwrites are safe out of order: the jitted row
update donates the buffer, and the device stream sequences it after every
already-enqueued step that reads the old row.

Shuffling semantics match the host ``Batcher``: epochs without
replacement, the sub-batch remainder rows dropped per epoch.

Per-epoch host work: one tiny jitted row update into the perm pair.
Per-step host work: a dict re-yield.

Multi-host: every process holds the identical split (same loaders, same
seed — the reference's workers did the same), the arrays are replicated on
the mesh, and every process computes the identical permutations; the train
step re-shards each gathered batch along the data axis on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class DeviceDataset:
    """Iterator yielding ``{"images", "labels", "perm"}`` device pytrees.

    ``perm`` has shape ``(num_slots, epoch_len)``: epoch ``e``'s shuffled
    index order lives in slot ``e % num_slots``.  The arrays are the same
    device buffers every step — only one perm row is replaced, once per
    epoch.  Pass ``start_step`` (e.g. after a resume) so epoch slots line
    up with the step's position arithmetic.  Pass ``num_slots`` to the
    step factory (``make_indexed_train_step(..., num_slots=ds.num_slots)``)
    so its slot arithmetic matches.
    """

    @staticmethod
    def ring_slots_for(window_steps: int, steps_per_epoch: int) -> int:
        """Perm-ring size for a ``window_steps``-step fused window: every
        epoch one window can touch (a K-step window starting mid-epoch
        spans ceil(K / spe) boundaries at worst -> that many + 1 epochs)
        plus one slot so the next epoch prefetches without evicting a row
        the in-flight window still reads.  THE single source of the slot
        arithmetic — the step factories use it for their defaults, so
        dataset and gather can't drift."""
        return -(-window_steps // steps_per_epoch) + 2

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, mesh=None, seed: int = 0,
                 shuffle: bool = True, start_step: int = 0,
                 steps_per_next: int = 1):
        """``steps_per_next``: global steps consumed per ``next()`` — set to
        the train step's ``unroll_steps`` so the perm ring is refreshed on
        the right call.  Any value >= 1 works; the ring is sized to hold
        every epoch one window can touch plus a prefetch slot."""
        if len(images) < batch_size:
            raise ValueError(
                f"dataset of {len(images)} examples is smaller than "
                f"batch {batch_size}")
        self._n = len(images)
        self.steps_per_epoch = self._n // batch_size
        self.epoch_len = self.steps_per_epoch * batch_size
        if steps_per_next < 1:
            raise ValueError(
                f"steps_per_next {steps_per_next} must be >= 1")
        self.num_slots = self.ring_slots_for(steps_per_next,
                                             self.steps_per_epoch)
        self._spn = steps_per_next
        self._step = int(start_step)
        self._slot_epochs: list[int | None] = [None] * self.num_slots

        if mesh is not None:
            from distributedtensorflowexample_tpu.parallel.mesh import (
                replicated_sharding)
            repl = replicated_sharding(mesh)
            if jax.process_count() > 1:
                put = lambda x: jax.make_array_from_process_local_data(repl, x)
            else:
                put = lambda x: jax.device_put(x, repl)
        else:
            repl, put = None, jax.device_put
        self.images = put(np.ascontiguousarray(images))
        self.labels = put(np.ascontiguousarray(labels))

        base = jax.random.PRNGKey(seed)

        def make_perm(epoch: jnp.ndarray) -> jnp.ndarray:
            key = jax.random.fold_in(base, epoch)
            if shuffle:
                order = jax.random.permutation(key, self._n)
            else:
                order = jnp.arange(self._n)
            return order[:self.epoch_len].astype(jnp.int32)

        def set_row(pair, row, slot):
            return jax.lax.dynamic_update_slice(pair, row[None], (slot, 0))

        jit_kw = {"out_shardings": repl} if repl is not None else {}
        self._make_perm = jax.jit(make_perm, **jit_kw)
        # Donated: the stale epoch's row is overwritten in place in HBM;
        # the runtime sequences the write after any in-flight reads.
        self._set_row = jax.jit(set_row, donate_argnums=0, **jit_kw)
        self._ring = jax.jit(
            lambda: jnp.zeros((self.num_slots, self.epoch_len), jnp.int32),
            **jit_kw)()

    def _ensure_epoch(self, epoch: int) -> None:
        slot = epoch % self.num_slots
        if self._slot_epochs[slot] != epoch:
            perm = self._make_perm(jnp.asarray(epoch, jnp.int32))
            self._ring = self._set_row(self._ring, perm,
                                       jnp.asarray(slot, jnp.int32))
            self._slot_epochs[slot] = epoch

    def __iter__(self):
        return self

    def peek(self):
        """The next window's data WITHOUT consuming it — for compile/cost
        probes that must not advance the ring past the training state."""
        first = self._step // self.steps_per_epoch
        last = (self._step + self._spn - 1) // self.steps_per_epoch
        # Every epoch this window touches, plus one prefetched ahead (the
        # prefetch may reuse the slot of an epoch an ALREADY-ENQUEUED call
        # still reads — safe, the donated row update is stream-ordered
        # after it).
        for epoch in range(first, last + 2):
            self._ensure_epoch(epoch)
        return {"images": self.images, "labels": self.labels,
                "perm": self._ring}

    def __next__(self):
        data = self.peek()
        self._step += self._spn
        return data
