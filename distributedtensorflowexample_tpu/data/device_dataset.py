"""Device-resident dataset: the whole split lives in HBM, minibatches are
gathered on device (pairs with ``parallel.sync.make_indexed_train_step``).

The reference fed every step over the feed_dict / input-pipeline boundary
(SURVEY.md §3a: "the feed-dict copy is the per-step overhead").  At MNIST
scale that copy is THE bottleneck on TPU — measured ~1.4 ms of H2D per
step against a ~0.07 ms compiled step on one v5e chip — and no amount of
prefetch depth hides a transfer that is 20x the step.  MNIST (183 MB) and
CIFAR-10 (590 MB) fit trivially in HBM, so the TPU-native design uploads
the split once and moves nothing per step: the epoch's shuffled index
order is itself computed on device (``jax.random.permutation``), and the
step slices its batch out of it by global-step position.

Epoch multi-buffering: the dataset holds a ring of S epoch permutations in
one device array of shape ``(S, epoch_len)`` — epoch ``e`` in slot
``e % S``.  The train step picks the slot from ``state.step //
steps_per_epoch`` per fused sub-step, so one compiled multi-step call may
cross up to ``S - 1`` epoch boundaries mid-scan.  That decouples the
dispatch-amortizing unroll (``steps_per_next`` / ``unroll_steps``) from
epoch arithmetic entirely: ``S`` is sized automatically from
``steps_per_next`` (every epoch TWO consecutive windows can touch, plus a
margin slot), so multi-epoch fused windows work and the next window's
permutations are computed by ``prefetch()`` INSIDE the in-flight step's
window (the loop calls it right after the step dispatch) instead of at
the next dispatch boundary.  Ring-slot overwrites are safe out of order:
the jitted row
update donates the buffer, and the device stream sequences it after every
already-enqueued step that reads the old row.

Shuffling semantics match the host ``Batcher``: epochs without
replacement, the sub-batch remainder rows dropped per epoch.

Per-epoch host work: one tiny jitted row update into the perm pair.
Per-step host work: a dict re-yield.

Multi-host: every process holds the identical split (same loaders, same
seed — the reference's workers did the same), the arrays are replicated on
the mesh, and every process computes the identical permutations; the train
step re-shards each gathered batch along the data axis on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


# Host-side canonical dequant arithmetic lives in data.dequant (numpy-
# only, shared with the loaders); re-exported here because this module is
# its historical home and every consumer imports it from here.
from distributedtensorflowexample_tpu.data.dequant import (  # noqa: F401
    affine_matches_lut, affine_numpy, make_dequant_affine, make_dequant_lut)
from distributedtensorflowexample_tpu.data.dequant import (
    dequant_numpy as _dequant_numpy)
from distributedtensorflowexample_tpu.data.dequant import (
    try_quantize as _try_quantize)

#: The in-step dequant implementations a caller may request.  "auto"
#: resolves per split at quantize time (see ``resolve_dequant_impl``);
#: the rest force one kernel:
#:   affine  f32(u) * scale + bias — one fused multiply-add per pixel,
#:           the fastest measured form and bitwise-identical to the LUT
#:           for every spec where ``affine_matches_lut`` holds (both
#:           shipped specs; re-verified on device per backend)
#:   onehot  one-hot @ LUT matmul — bitwise by construction on any
#:           backend (each dot has exactly one nonzero term); the
#:           fallback for non-affine-representable splits
#:   lut     lut[u] elementwise gather — the round-4 default this PR
#:           demotes: measured ~10 ns/element on TPU (PROFILE_auto_r05,
#:           56% of the ResNet step; headline 479.6 vs 1,962.6 steps/s
#:           same-window).  Kept ONLY as a named diagnostic so the bench
#:           can keep attesting the tax.
#:   pallas  fused row-gather + affine dequant in one Pallas kernel
#:           (ops/pallas/dequant.py) — gathers uint8 rows and emits the
#:           float32 batch in a single HBM pass
DEQUANT_IMPLS = ("auto", "affine", "onehot", "lut", "pallas")

_AFFINE_DEVICE_OK: dict[tuple[str, str], bool] = {}


def dequant_affine_is_bitwise(spec: str) -> bool:
    """True iff THIS backend's jitted affine dequant reproduces all 256
    LUT entries bitwise.  The host check (``affine_matches_lut``) proves
    the arithmetic is affine-representable; this one additionally pins
    the backend's rounding (the affine is one FUSED multiply-add — a
    backend that emitted a separate mul and add would double-round and
    diverge on the biased specs).  One tiny jit per (spec, backend) per
    process, cached."""
    key = (spec, jax.default_backend())
    hit = _AFFINE_DEVICE_OK.get(key)
    if hit is not None:
        return hit
    lut = make_dequant_lut(spec)
    s, b = make_dequant_affine(spec)
    u = np.arange(256, dtype=np.uint8)
    if lut.ndim == 2:
        u = np.broadcast_to(u[:, None], (256, lut.shape[1]))
    # lower().compile() and call the executable directly, with PLAIN
    # numpy operands: the check may run INSIDE an outer trace
    # (resolve_dequant_impl is reached from dequant_host_batch, which
    # lives in the jitted step), where any jnp op — including asarray or
    # a jit call — would be traced symbolically, and the whole point is
    # a CONCRETE answer about this backend's compiled rounding.  The
    # compiled executable converts numpy args itself, outside tracing.
    args = (np.ascontiguousarray(u), s, b)
    compiled = jax.jit(apply_dequant_affine).lower(*args).compile()
    got = np.asarray(compiled(*args))
    ok = bool(np.array_equal(got.view(np.int32),
                             np.ascontiguousarray(lut).view(np.int32)))
    _AFFINE_DEVICE_OK[key] = ok
    return ok


def resolve_dequant_impl(spec: str | None, dequant_impl: str = "auto",
                         quantize: str = "auto") -> str:
    """The ONE resolution rule for which in-step dequant kernel runs —
    shared by the train path (``DeviceDataset``), eval
    (``parallel.sync.make_resident_eval``), the host-fed path
    (``dequant_host_batch``) and the bench, so no pair of consumers can
    silently resolve differently (the train/eval-asymmetry hazard).

    ``auto`` lowers to the affine fast path when the split's 256-entry
    LUT is bitwise-reproducible by ``f32(u) * scale + bias`` (verified
    against ``make_dequant_affine`` on the host AND on this backend —
    true for the MNIST "unit" and CIFAR "cifar" loader specs); otherwise
    it keeps the bitwise contract through the one-hot LUT form, unless
    the caller asked for ``quantize="scale"`` (explicitly speed-over-
    bits), which stays affine."""
    if dequant_impl not in DEQUANT_IMPLS:
        raise ValueError(f"unknown dequant_impl {dequant_impl!r} "
                         f"(one of {DEQUANT_IMPLS})")
    if dequant_impl != "auto":
        return dequant_impl
    if spec is None:
        return "affine"     # no dequant will run; name the fast default
    if affine_matches_lut(spec) and dequant_affine_is_bitwise(spec):
        return "affine"
    return "affine" if quantize == "scale" else "onehot"


def apply_dequant_affine(u8: jnp.ndarray, scale: jnp.ndarray,
                         bias: jnp.ndarray) -> jnp.ndarray:
    """uint8 pixels -> ~float32 via the fused affine form (see
    make_dequant_affine for the ~1-ulp caveat and the measured wins)."""
    return u8.astype(jnp.float32) * scale + bias


def apply_dequant_lut(u8: jnp.ndarray, lut: jnp.ndarray) -> jnp.ndarray:
    """uint8 pixels -> float32 through a [256] / [256, C] LUT, expressed
    as a one-hot matmul so it runs on the MXU.

    The obvious ``lut[idx]`` gather is catastrophically slow on TPU: the
    round-5 on-chip trace (PROFILE_auto_r05.json window) measured it at
    ~10 ns/element — 8.2 ms/step on ResNet-20's batch, 56% of the whole
    step; the same-window A/B (AB_quantize_r05.json) put the headline at
    479 steps/s with the gather vs 1,620 with this form.

    Exactness: the one-hot rows are exact {0,1} in bfloat16 and each
    output element's dot product has exactly ONE nonzero term, so the
    result is the table entry itself — PROVIDED the table operand loses
    no bits.  A float32 table downcast to bfloat16 would lose 16
    mantissa bits, so the table is split into three bfloat16 components
    (f32 has 24 mantissa bits = 3 x 8): ``hi = bf16(v)``,
    ``mid = bf16(v - hi)``, ``lo = bf16(v - hi - mid)``.  Every split
    subtraction is exact (Sterbenz: operands within a factor of 2), the
    residual after two splits has <= 8 significant bits so ``lo`` is
    exact, and the f32 reconstruction ``(hi + mid) + lo`` is exact
    because each partial sum is representable.  Three bf16 matmuls, each
    picking one component, summed in that order — bitwise-identical to
    the host table (asserted on-chip by the quantize parity tests)."""
    from distributedtensorflowexample_tpu.data.augment_device import (
        _mm_dtype)
    md = _mm_dtype()   # bf16 on accelerators; f32 on CPU (no bf16 GEMM
    #                    there, and f32 one-hot dots are exact anyway —
    #                    the split terms below degenerate to v + 0 + 0)
    idx = u8.astype(jnp.int32)
    oh = (idx[..., None] == jnp.arange(256, dtype=jnp.int32)).astype(md)
    hi = lut.astype(md)
    mid = (lut - hi.astype(jnp.float32)).astype(md)
    lo = (lut - hi.astype(jnp.float32)
          - mid.astype(jnp.float32)).astype(md)
    if lut.ndim == 1:
        part = lambda t: jnp.einsum(
            "...k,k->...", oh, t, preferred_element_type=jnp.float32)
    else:
        # Per-channel table: channel c of pixel p uses column c —
        # contraction over the 256 axis with c as a batch dim.
        part = lambda t: jnp.einsum(
            "...ck,kc->...c", oh, t, preferred_element_type=jnp.float32)
    return (part(hi) + part(mid)) + part(lo)


def apply_dequant_gather(u8: jnp.ndarray, lut: jnp.ndarray) -> jnp.ndarray:
    """uint8 pixels -> float32 via an ELEMENTWISE ``lut[u]`` gather — the
    round-4 default the round-5 window measured as the dequant tax
    (PROFILE_auto_r05: ~10 ns/element, 56% of the ResNet step;
    AB_quantize_r05: headline 479.6 steps/s/chip vs 1,962.6 affine in
    the same window).  Retained ONLY as the ``dequant_impl="lut"``
    diagnostic so the bench can keep the regression attested; nothing
    resolves to it automatically."""
    idx = u8.astype(jnp.int32)
    if lut.ndim == 1:
        return jnp.take(lut, idx, axis=0)
    # Per-channel table: channel c of pixel p reads lut[u[p, c], c].
    return jnp.take_along_axis(
        lut, idx.reshape(-1, lut.shape[1]), axis=0).reshape(u8.shape)


def dequantize_images(u8: jnp.ndarray, spec: str,
                      dequant_impl: str = "onehot") -> jnp.ndarray:
    """uint8 pixels -> the float32 values the loader would have produced,
    through the named impl (default: the backend-independent bitwise
    one-hot form; pass the resolved impl for the fast path)."""
    if dequant_impl == "affine":
        s, b = make_dequant_affine(spec)
        return apply_dequant_affine(u8, jnp.asarray(s), jnp.asarray(b))
    if dequant_impl == "lut":
        return apply_dequant_gather(u8, jnp.asarray(make_dequant_lut(spec)))
    if dequant_impl != "onehot":
        # Callers pass a RESOLVED impl ("auto"/"pallas" must be lowered
        # via resolve_dequant_impl first) — routing a typo to the one-hot
        # kernel silently would be the wrong-kernel hazard the resolver
        # exists to prevent.
        raise ValueError(f"unresolved dequant_impl {dequant_impl!r} "
                         f"(expected affine, onehot, or lut)")
    return apply_dequant_lut(u8, jnp.asarray(make_dequant_lut(spec)))


class DeviceDataset:
    """Iterator yielding ``{"images", "labels", "perm"}`` device pytrees.

    ``perm`` has shape ``(num_slots, epoch_len)``: epoch ``e``'s shuffled
    index order lives in slot ``e % num_slots``.  The arrays are the same
    device buffers every step — only one perm row is replaced, once per
    epoch.  Pass ``start_step`` (e.g. after a resume) so epoch slots line
    up with the step's position arithmetic.  Pass ``num_slots`` to the
    step factory (``make_indexed_train_step(..., num_slots=ds.num_slots)``)
    so its slot arithmetic matches.
    """

    @staticmethod
    def ring_slots_for(window_steps: int, steps_per_epoch: int) -> int:
        """Perm-ring size for a ``window_steps``-step fused window: every
        epoch TWO consecutive windows can touch (a K-step window starting
        mid-epoch spans ceil(K / spe) boundaries at worst; sizing for 2K
        lets ``prefetch()`` compute the NEXT window's permutations while
        the current window is still in flight — inside the donated step
        window, off the dispatch boundary) plus one margin slot so the
        epoch prefetched one ahead never evicts a row an in-flight window
        still reads.  THE single source of the slot arithmetic — the step
        factories use it for their defaults, so dataset and gather can't
        drift."""
        return -(-2 * window_steps // steps_per_epoch) + 2

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, mesh=None, seed: int = 0,
                 shuffle: bool = True, start_step: int = 0,
                 steps_per_next: int = 1, quantize: str = "auto",
                 dequant_impl: str = "auto",
                 data_sharding: str = "replicated",
                 token_data: bool = False):
        """``steps_per_next``: global steps consumed per ``next()`` — set to
        the train step's ``unroll_steps`` so the perm ring is refreshed on
        the right call.  Any value >= 1 works; the ring is sized to hold
        every epoch one window can touch plus a prefetch slot.

        ``quantize`` stores the split as uint8 in HBM when the float32
        pixels are BITWISE-recoverable from one of the known 8-bit
        pipelines (verified element-exact at build time; see
        ``data.dequant.try_quantize``): the per-step on-device gather
        then moves 4x fewer bytes.  ``"auto"``/``"scale"``/``"exact"``
        all select uint8 storage; ``"off"`` keeps the split
        float32-resident (raw uint8 input still dequantizes, exactly,
        since storage is already 8-bit).

        ``dequant_impl`` picks the in-step dequant kernel
        (``DEQUANT_IMPLS``; resolution rule: ``resolve_dequant_impl``).
        The default ``"auto"`` lowers to the fused AFFINE fast path —
        verified bitwise against the 256-entry LUT at quantize time, true
        for both shipped loader specs (AB_quantize_r05.json, same-window:
        affine 1,962.6 steps/s/chip vs 479.6 for the round-4 LUT-gather
        default, vs 1,654 float32-resident) — and falls back to the
        bitwise one-hot form only for a split whose host arithmetic an
        affine map cannot reproduce.

        The dequant constants travel INSIDE the yielded data pytree
        (``data["lut"]`` or ``data["dq_scale"]/["dq_bias"]``) and the
        device gather dispatches on the pytree structure, so no call
        site can forget to dequantize.  The RESOLVED impl is recorded on
        ``self.dequant_impl`` (None when nothing dequantizes) so bench
        records can attest which kernel actually ran.

        ``data_sharding="sharded"`` (VERDICT r4 #8) shards the resident
        split ROW-WISE over the mesh's data axis instead of replicating
        it: per-device HBM for the split drops by the mesh size, lifting
        the per-device ceiling for datasets bigger than CIFAR.  The epoch
        permutation is then built per device shard (device ``d`` shuffles
        its own rows) and interleaved so the step's standard slice
        arithmetic hands every device positions that live in ITS shard —
        the gather stays collective-free (``sync.make_device_gather``'s
        shard_map branch translates to local row space).  Shuffling
        semantics become per-shard (the reference's per-worker dataset
        sharding under MultiWorkerMirroredStrategy) rather than global;
        rows beyond ``mesh_size * (n // mesh_size)`` are dropped.  Pass
        the SAME mode to the step factory.

        ``token_data=True`` marks an INTEGER split (transformer-LM
        tokens): no dequantization ever runs — the per-step gather
        yields raw token ids and the model upcasts.  ``quantize`` then
        selects the storage width instead of a dequant pipeline: any
        non-"off" mode stores ids that fit a byte as uint8 (4x less
        resident HBM + gather traffic than int32 — the quantized data
        path's win applied to tokens); "off" keeps/restores int32.  The
        yielded pytree carries a ``"tokens"`` marker leaf so the
        gather's dequant dispatch (static on pytree structure, like the
        dq_scale/lut keys) passes the batch through instead of refusing
        the uint8-without-constants shape."""
        if quantize not in ("auto", "off", "exact", "scale"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        self.quantize = quantize
        if data_sharding not in ("replicated", "sharded"):
            raise ValueError(f"unknown data_sharding {data_sharding!r}")
        if data_sharding == "sharded" and mesh is None:
            raise ValueError("data_sharding='sharded' requires a mesh")
        self.data_sharding = data_sharding
        self.token_data = bool(token_data)
        self.dequant: str | None = None
        if token_data:
            images = np.asarray(images)
            if not np.issubdtype(images.dtype, np.integer):
                raise ValueError(
                    f"token_data=True expects an integer token split, got "
                    f"{images.dtype} (float pipelines are the image path)")
            if quantize == "off":
                if images.dtype != np.int32:
                    images = images.astype(np.int32)
            elif images.dtype != np.uint8:
                if images.size and (images.min() < 0 or images.max() > 255):
                    raise ValueError(
                        "token ids exceed uint8 range; store them int32 "
                        "with quantize='off' (a silent wrap would corrupt "
                        "every out-of-byte id)")
                images = images.astype(np.uint8)
        elif images.dtype == np.uint8:
            # Raw bytes: downstream floats are u * (1/255) by convention.
            self.dequant = "unit"
        elif quantize != "off":
            q = _try_quantize(np.asarray(images))
            if q is not None:
                images, self.dequant = q
        # The in-step kernel, resolved ONCE here (the same rule eval and
        # the host-fed path use) and recorded for bench attestation.
        self.dequant_impl: str | None = (
            resolve_dequant_impl(self.dequant, dequant_impl, quantize)
            if self.dequant is not None else None)
        if len(images) < batch_size:
            raise ValueError(
                f"dataset of {len(images)} examples is smaller than "
                f"batch {batch_size}")
        if data_sharding == "sharded":
            # The data-axis extent, NOT mesh.size: they agree on today's
            # 1-D meshes, but the P(DATA_AXIS) row placement and the
            # gather's shard count are defined by the axis — a future
            # multi-axis mesh must not silently mis-translate indices.
            from distributedtensorflowexample_tpu.parallel.mesh import (
                DATA_AXIS)
            self._D = mesh.shape[DATA_AXIS]
        else:
            self._D = 1
        if data_sharding == "sharded":
            if batch_size % self._D:
                raise ValueError(
                    f"sharded data: batch {batch_size} must divide across "
                    f"{self._D} devices")
            n_used = self._D * (len(images) // self._D)
            images, labels = images[:n_used], labels[:n_used]
            self._rows_per_dev = n_used // self._D
            self._bpd = batch_size // self._D
            # Per-shard epoch arithmetic: each device steps through ITS
            # rows_per_dev rows in bpd-row sub-batches.
            self.steps_per_epoch = self._rows_per_dev // self._bpd
        else:
            self.steps_per_epoch = len(images) // batch_size
        self._n = len(images)
        self.epoch_len = self.steps_per_epoch * batch_size
        if steps_per_next < 1:
            raise ValueError(
                f"steps_per_next {steps_per_next} must be >= 1")
        self.num_slots = self.ring_slots_for(steps_per_next,
                                             self.steps_per_epoch)
        self._spn = steps_per_next
        self._step = int(start_step)
        self._slot_epochs: list[int | None] = [None] * self.num_slots

        if mesh is not None:
            from distributedtensorflowexample_tpu.parallel.mesh import (
                DATA_AXIS, replicated_sharding)
            repl = replicated_sharding(mesh)
            if jax.process_count() > 1:
                put = lambda x: jax.make_array_from_process_local_data(repl, x)
            else:
                put = lambda x: jax.device_put(x, repl)
            if data_sharding == "sharded":
                from jax.sharding import NamedSharding, PartitionSpec as P
                rows = NamedSharding(mesh, P(DATA_AXIS))
                if jax.process_count() > 1:
                    # Mesh device order groups devices by process (see
                    # put_global_batch): process p owns a contiguous row
                    # block of the sharded split.
                    pc, pi = jax.process_count(), jax.process_index()
                    per = self._n // pc
                    put_rows = lambda x: jax.make_array_from_process_local_data(
                        rows, np.ascontiguousarray(x[pi * per:(pi + 1) * per]))
                else:
                    put_rows = lambda x: jax.device_put(x, rows)
            else:
                put_rows = put
        else:
            repl, put = None, jax.device_put
            put_rows = put
        self.images = put_rows(np.ascontiguousarray(images))
        self.labels = put_rows(np.ascontiguousarray(labels))
        # The dequant constants ride in the yielded pytree; WHICH keys
        # are present encodes the impl family statically (pytree
        # structure), so the gather dispatches at trace time with no
        # factory plumbing: affine/pallas carry (scale, bias), the LUT
        # forms carry the 256-entry table.
        self._lut, self._affine = None, None
        if self.dequant_impl in ("affine", "pallas"):
            s, b = make_dequant_affine(self.dequant)
            self._affine = (put(s), put(b))
        elif self.dequant_impl is not None:
            self._lut = put(make_dequant_lut(self.dequant))
        # Token splits: a replicated scalar whose PRESENCE in the pytree
        # (not its value) tells the gather this uint8 batch is ids, not
        # quantized pixels — the same static-structure dispatch the
        # dq_scale/lut keys use.
        self._tokens_marker = (put(np.zeros((), np.int32))
                               if self.token_data else None)

        base = jax.random.PRNGKey(seed)

        def make_perm(epoch: jnp.ndarray) -> jnp.ndarray:
            key = jax.random.fold_in(base, epoch)
            if data_sharding == "sharded":
                # Per-shard shuffle, interleaved so global positions
                # [s*B + d*bpd, s*B + (d+1)*bpd) always hold indices from
                # device d's row block — the step's standard slice
                # arithmetic then never needs a cross-device gather.
                D, L, bpd = self._D, self._rows_per_dev, self._bpd
                keys = jax.vmap(lambda d: jax.random.fold_in(key, d))(
                    jnp.arange(D))
                if shuffle:
                    local = jax.vmap(
                        lambda k: jax.random.permutation(k, L))(keys)
                else:
                    local = jnp.broadcast_to(jnp.arange(L), (D, L))
                local = local[:, :self.steps_per_epoch * bpd]
                local = local + (jnp.arange(D) * L)[:, None]
                order = (local.reshape(D, self.steps_per_epoch, bpd)
                         .transpose(1, 0, 2).reshape(-1))
                return order.astype(jnp.int32)
            if shuffle:
                order = jax.random.permutation(key, self._n)
            else:
                order = jnp.arange(self._n)
            return order[:self.epoch_len].astype(jnp.int32)

        def set_row(pair, row, slot):
            return jax.lax.dynamic_update_slice(pair, row[None], (slot, 0))

        jit_kw = {"out_shardings": repl} if repl is not None else {}
        self._make_perm = jax.jit(make_perm, **jit_kw)
        # Donated: the stale epoch's row is overwritten in place in HBM;
        # the runtime sequences the write after any in-flight reads.
        self._set_row = jax.jit(set_row, donate_argnums=0, **jit_kw)
        self._ring = jax.jit(
            lambda: jnp.zeros((self.num_slots, self.epoch_len), jnp.int32),
            **jit_kw)()

    def _ensure_epoch(self, epoch: int) -> None:
        slot = epoch % self.num_slots
        if self._slot_epochs[slot] != epoch:
            perm = self._make_perm(jnp.asarray(epoch, jnp.int32))
            self._ring = self._set_row(self._ring, perm,
                                       jnp.asarray(slot, jnp.int32))
            self._slot_epochs[slot] = epoch

    def __iter__(self):
        return self

    def peek(self):
        """The next window's data WITHOUT consuming it — for compile/cost
        probes that must not advance the ring past the training state."""
        first = self._step // self.steps_per_epoch
        last = (self._step + self._spn - 1) // self.steps_per_epoch
        # The epochs THIS window reads, plus one ahead (the pre-round-5
        # contract: the next epoch is resident before it is first read).
        # In the steady state ``prefetch()`` — called by the loop AFTER
        # the step dispatch — already computed this exact set inside the
        # in-flight step's window, so this loop is a pure host check;
        # consumers that never call prefetch compute it here at the
        # dispatch boundary instead.
        for epoch in range(first, last + 2):
            self._ensure_epoch(epoch)
        data = {"images": self.images, "labels": self.labels,
                "perm": self._ring}
        if self._lut is not None:
            data["lut"] = self._lut
        if self._affine is not None:
            data["dq_scale"], data["dq_bias"] = self._affine
        if self._tokens_marker is not None:
            data["tokens"] = self._tokens_marker
        return data

    def __next__(self):
        data = self.peek()
        self._step += self._spn
        return data

    def prefetch(self) -> None:
        """Dispatch the NEXT window's permutation updates (plus one epoch
        of margin) — called by the train loop right AFTER it enqueues the
        step consuming the previous window, so the perm computation and
        the donated row writes overlap the in-flight step instead of
        taxing the next dispatch boundary.  Out-of-order slot overwrites
        are safe: the donated row update is stream-ordered after every
        already-enqueued read of the old row, and ``ring_slots_for``
        sizes the ring so two consecutive windows' epochs plus the margin
        never collide."""
        first = self._step // self.steps_per_epoch
        last = (self._step + self._spn - 1) // self.steps_per_epoch
        for epoch in range(first, last + 2):
            self._ensure_epoch(epoch)
