"""CIFAR-10 input pipeline (component C11 in SURVEY.md §2).

Reference behavior [RECONSTRUCTED]: ``tf.data``/``tf.keras.datasets`` loading
with crop/flip augmentation and per-replica sharding under the distribution
strategies.  Rebuild: pure-numpy parsing of the canonical CIFAR-10 binary
batches, numpy-side augmentation (random crop with 4px pad + horizontal
flip), synthetic fallback when the bytes are absent.
"""

from __future__ import annotations

import os
import pickle
import sys
import tarfile

import numpy as np

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], dtype=np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], dtype=np.float32)
_SYNTH_SIZES = {"train": 50000, "test": 10000}


def _to_nhwc(chw_rows: np.ndarray) -> np.ndarray:
    """[N, 3072] uint8 CHW rows -> [N,32,32,3] float32 in [0,1] — the ONE
    conversion every layout path (pickle dir, binary, tar) must share.
    Multiplies by the canonical f32 1/255 (the repo-wide affine
    byte->float convention, data.dequant), not an f32 division, so the
    uint8-resident fast path dequantizes to these exact bits."""
    from distributedtensorflowexample_tpu.data.dequant import U8_UNIT_SCALE
    nhwc = chw_rows.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return nhwc.astype(np.float32) * U8_UNIT_SCALE


def _load_from_tar(data_dir: str, split: str):
    """Read the pickle batches straight out of an unextracted
    ``cifar-10-python.tar.gz`` (the exact artifact the canonical download
    URL serves) so the README's one-command fetch needs no extract step."""
    names = ([f"data_batch_{i}" for i in range(1, 6)]
             if split == "train" else ["test_batch"])
    for tarname in ("cifar-10-python.tar.gz", "cifar-10-python.tar"):
        path = os.path.join(data_dir, tarname)
        if not os.path.exists(path):
            continue
        images, labels = [], []
        try:
            with tarfile.open(path) as tf:
                members = {os.path.basename(m.name): m
                           for m in tf.getmembers()}
                if any(n not in members for n in names):
                    continue              # incomplete tar: try the next
                for name in names:
                    d = pickle.load(tf.extractfile(members[name]),
                                    encoding="bytes")
                    images.append(
                        _to_nhwc(np.asarray(d[b"data"], dtype=np.uint8)))
                    labels.append(np.asarray(d[b"labels"], dtype=np.int32))
        except Exception as e:
            # Corrupt/truncated/odd tar (interrupted download, directory
            # members, short pickles...): behave like the pre-tar loader
            # did — ignore it (caller falls back, loudly).  stderr, NOT
            # stdout: bench consumers json-parse every stdout line.
            print(f"warning: ignoring unreadable {path}: {e!r}",
                  file=sys.stderr, flush=True)
            continue
        return np.concatenate(images), np.concatenate(labels)
    return None


def _load_binary_batches(data_dir: str, split: str):
    """Parse CIFAR-10 in the python-pickle, plain-binary, or unextracted
    tar layout."""
    base = None
    for cand in (data_dir, os.path.join(data_dir, "cifar-10-batches-py"),
                 os.path.join(data_dir, "cifar-10-batches-bin")):
        if os.path.isdir(cand) and any(
                n.startswith(("data_batch", "test_batch")) for n in os.listdir(cand)):
            base = cand
            break
    if base is None:
        return _load_from_tar(data_dir, split)
    names = ([f"data_batch_{i}" for i in range(1, 6)] if split == "train"
             else ["test_batch"])
    from distributedtensorflowexample_tpu import native

    images, labels = [], []
    for name in names:
        path = os.path.join(base, name)
        if os.path.exists(path):          # python pickle layout
            with open(path, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            images.append(_to_nhwc(np.asarray(d[b"data"], dtype=np.uint8)))
            labels.append(np.asarray(d[b"labels"], dtype=np.int32))
        elif os.path.exists(path + ".bin"):  # binary layout: 1 label byte + 3072
            with open(path + ".bin", "rb") as f:
                raw = f.read()
            if native.available():        # C++ parse straight to NHWC float
                imgs, lbls = native.parse_cifar(raw)
            else:
                rows = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 3073)
                imgs, lbls = _to_nhwc(rows[:, 1:]), rows[:, 0].astype(np.int32)
            images.append(imgs)
            labels.append(lbls)
        else:
            return None
    return np.concatenate(images), np.concatenate(labels)


def load_cifar10(data_dir: str, split: str = "train",
                 synthetic_size: int | None = None, seed: int = 0,
                 normalize: bool = True,
                 source: str = "real") -> tuple[np.ndarray, np.ndarray]:
    """Return (images [N,32,32,3] float32, labels [N] int32).

    ``source``: ``"real"`` (default — the pickle/binary/tar batches must
    exist, missing bytes are a crisp error naming ``--dataset synthetic``
    as the opt-in), ``"synthetic"`` (explicit deterministic split, no
    warning), or ``"fallback"`` (real if present else synthetic with a
    loud warning — harness use).  See ``load_mnist``.
    """
    if source not in ("real", "synthetic", "fallback"):
        raise ValueError(f"unknown source {source!r}")
    loaded = (None if source == "synthetic"
              else _load_binary_batches(data_dir, split))
    if loaded is None:
        if source == "real":
            raise FileNotFoundError(
                f"CIFAR-10 {split!r} bytes not found in {data_dir!r} "
                f"(expected data_batch_*/test_batch in pickle, .bin, or "
                f"cifar-10-python.tar.gz layout). Point --data_dir at the "
                f"batches, or pass --dataset synthetic to train on the "
                f"deterministic synthetic split instead.")
        if source == "fallback":
            from distributedtensorflowexample_tpu.data.synthetic import (
                warn_synthetic)
            warn_synthetic("CIFAR-10", split, data_dir,
                           "data_batch_*/cifar-10-*")
        num = synthetic_size or _SYNTH_SIZES[split]
        loaded = make_synthetic(num, (32, 32, 3), 10, seed=seed,
                                sample_seed=seed * 2 + (1 if split == "train" else 2))
    images, labels = loaded
    if normalize:
        # The FUSED affine form of (x - MEAN) / STD, applied to the
        # recovered bytes with one rounding (data.dequant is the single
        # definition of this arithmetic): bitwise-identical to what the
        # in-step affine dequant of the uint8-resident split computes, so
        # quantized and float-resident training agree bit for bit.  Every
        # source above is byte-derived ([0,1] floats on the u/255 grid),
        # so the rint recovery is exact — VERIFIED chunk-by-chunk below,
        # not assumed: a future non-byte source (interpolation, padding,
        # a pre-scaled array) must fail loudly here, never be silently
        # snapped to the 8-bit grid.
        from distributedtensorflowexample_tpu.data.dequant import (
            affine_numpy, dequant_numpy)
        out = np.empty(images.shape, np.float32)
        for i in range(0, len(images), 4096):   # bounded transients, like
            c = images[i:i + 4096]              # try_quantize
            u8 = np.rint(np.clip(c, 0.0, 1.0) * 255.0).astype(np.uint8)
            if not np.array_equal(dequant_numpy(u8, "unit"), c):
                raise ValueError(
                    "load_cifar10(normalize=True) expects byte-derived "
                    "[0,1] pixels (u/255 grid); got values off the grid "
                    "— normalize them upstream instead")
            out[i:i + 4096] = affine_numpy(u8, "cifar")
        images = out
    return images, labels


def augment(images: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
    """Random 4px-pad crop + horizontal flip, the reference's augmentations.

    Runs on the host every training step.  The random draws happen here in
    a fixed order (ys, xs, flips), then the pixel work dispatches to the
    native C++ loader when built (one fused OpenMP pass, no padded
    intermediate) or to a fully-vectorized numpy fallback — both produce
    bit-identical batches for a given rng state.
    """
    ys, xs, flips = _draw(rng, images.shape[0])
    from distributedtensorflowexample_tpu import native
    # f32 and u8 both have native kernels (dataio.cc crop_flip_impl<T>);
    # anything else takes the dtype-preserving numpy fallback.
    if native.available() and images.dtype in (np.float32, np.uint8):
        return native.augment_crop_flip(images, ys, xs, flips)
    return _augment_numpy(images, ys, xs, flips)


def _draw(rng: np.random.RandomState, n: int):
    """The augmentation's random draws, in one fixed order — shared by the
    plain, native, and fused paths so all are bit-identical per rng state."""
    ys = rng.randint(0, 9, size=n)
    xs = rng.randint(0, 9, size=n)
    flips = rng.rand(n) < 0.5
    return ys, xs, flips


def _fused_gather_augment(src: np.ndarray, idx: np.ndarray,
                          rng: np.random.RandomState) -> np.ndarray:
    """Native single-pass gather+crop+flip (dataio.cc gather_augment_f32):
    batch rows are pulled from the training array and augmented straight
    into the output, skipping the intermediate gathered copy."""
    from distributedtensorflowexample_tpu import native
    return native.gather_augment(src, idx, *_draw(rng, idx.size))


# Batcher fuses the gather with this augmentation when native is available
# (see pipeline.Batcher._gather); draws stay in the same order as augment().
augment.fused_native = _fused_gather_augment
# Pure pixel rearrangement: safe to run on uint8-quantized batches
# (Batcher only auto-quantizes under an augment that declares this).
augment.u8_safe = True


def _augment_numpy(images: np.ndarray, ys: np.ndarray, xs: np.ndarray,
                   flips: np.ndarray) -> np.ndarray:
    """Vectorized fallback (one strided-window gather + one masked flip)."""
    n, h, w, _ = images.shape
    padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    # windows: [n, 9, 9, c, h, w] view; fancy-index one crop per image.
    windows = np.lib.stride_tricks.sliding_window_view(padded, (h, w), axis=(1, 2))
    crops = windows[np.arange(n), ys, xs]          # [n, c, h, w] (copy)
    crops = np.moveaxis(crops, 1, -1)              # back to NHWC
    return np.where(flips[:, None, None, None], crops[:, :, ::-1, :], crops)
