"""CIFAR-10 input pipeline (component C11 in SURVEY.md §2).

Reference behavior [RECONSTRUCTED]: ``tf.data``/``tf.keras.datasets`` loading
with crop/flip augmentation and per-replica sharding under the distribution
strategies.  Rebuild: pure-numpy parsing of the canonical CIFAR-10 binary
batches, numpy-side augmentation (random crop with 4px pad + horizontal
flip), synthetic fallback when the bytes are absent.
"""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], dtype=np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], dtype=np.float32)
_SYNTH_SIZES = {"train": 50000, "test": 10000}


def _load_binary_batches(data_dir: str, split: str):
    """Parse CIFAR-10 in either the python-pickle or plain binary layout."""
    base = None
    for cand in (data_dir, os.path.join(data_dir, "cifar-10-batches-py"),
                 os.path.join(data_dir, "cifar-10-batches-bin")):
        if os.path.isdir(cand) and any(
                n.startswith(("data_batch", "test_batch")) for n in os.listdir(cand)):
            base = cand
            break
    if base is None:
        return None
    names = ([f"data_batch_{i}" for i in range(1, 6)] if split == "train"
             else ["test_batch"])
    images, labels = [], []
    for name in names:
        path = os.path.join(base, name)
        if os.path.exists(path):          # python pickle layout
            with open(path, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            images.append(np.asarray(d[b"data"], dtype=np.uint8))
            labels.append(np.asarray(d[b"labels"], dtype=np.int32))
        elif os.path.exists(path + ".bin"):  # binary layout: 1 label byte + 3072
            raw = np.fromfile(path + ".bin", dtype=np.uint8).reshape(-1, 3073)
            labels.append(raw[:, 0].astype(np.int32))
            images.append(raw[:, 1:])
        else:
            return None
    images = np.concatenate(images).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return images.astype(np.float32) / 255.0, np.concatenate(labels)


def load_cifar10(data_dir: str, split: str = "train",
                 synthetic_size: int | None = None, seed: int = 0,
                 normalize: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Return (images [N,32,32,3] float32, labels [N] int32)."""
    loaded = _load_binary_batches(data_dir, split)
    if loaded is None:
        num = synthetic_size or _SYNTH_SIZES[split]
        loaded = make_synthetic(num, (32, 32, 3), 10, seed=seed,
                                sample_seed=seed * 2 + (1 if split == "train" else 2))
    images, labels = loaded
    if normalize:
        images = (images - CIFAR10_MEAN) / CIFAR10_STD
    return images, labels


def augment(images: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
    """Random 4px-pad crop + horizontal flip, the reference's augmentations.

    Fully vectorized (one strided-window gather + one masked flip): this
    runs on the host per training step, so a per-image Python loop would
    serialize the input pipeline at exactly the scale where the TPU is
    fastest (see pipeline.py docstring).
    """
    n, h, w, _ = images.shape
    padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    # windows: [n, 9, 9, c, h, w] view; fancy-index one crop per image.
    windows = np.lib.stride_tricks.sliding_window_view(padded, (h, w), axis=(1, 2))
    ys = rng.randint(0, 9, size=n)
    xs = rng.randint(0, 9, size=n)
    crops = windows[np.arange(n), ys, xs]          # [n, c, h, w] (copy)
    crops = np.moveaxis(crops, 1, -1)              # back to NHWC
    flips = (rng.rand(n) < 0.5)[:, None, None, None]
    return np.where(flips, crops[:, :, ::-1, :], crops)
