"""Host-side batching + device prefetch.

Replaces the reference's feed_dict / tf.data input path.  At MNIST's tiny
per-step compute the input pipeline is the scaling hazard (SURVEY.md §7
"hard parts"), so batches are (a) assembled with pure-numpy gather (no
per-example Python), (b) sharded per-process for multi-host, and (c)
``jax.device_put`` ahead of the step onto the batch ``NamedSharding`` so the
jitted step never blocks on host→HBM transfer.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import jax
import numpy as np


def put_local_batch(batch, sharding):
    """Device-put a batch whose arrays are this PROCESS'S LOCAL SHARD of the
    global batch (what :class:`Batcher` yields under process sharding)."""
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            batch)
    return jax.device_put(batch, sharding)


def put_global_batch(batch, sharding):
    """Device-put a batch whose arrays are the FULL GLOBAL batch, identical
    on every process (e.g. an eval split every host loaded).

    Each process keeps only the contiguous row-range its devices own —
    mesh device order is jax.devices(), which groups devices by process, so
    shard p of the leading axis lives on process p's devices.
    """
    pc = jax.process_count()
    if pc == 1:
        return jax.device_put(batch, sharding)
    pi = jax.process_index()

    def local_rows(x):
        if x.shape[0] % pc:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by {pc} processes")
        per = x.shape[0] // pc
        return x[pi * per:(pi + 1) * per]

    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, local_rows(x)), batch)


class Batcher:
    """Infinite shuffled minibatch stream over an in-memory array pair.

    ``process_index/process_count`` give each host a disjoint shard of every
    global batch — the per-worker sharding MultiWorkerMirroredStrategy did
    for the reference (SURVEY.md §3d).
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, seed: int = 0, shuffle: bool = True,
                 process_index: int = 0, process_count: int = 1,
                 augment_fn: Callable[[np.ndarray, np.random.RandomState],
                                      np.ndarray] | None = None,
                 quantize: str = "auto"):
        """``quantize`` != "off" keeps a bitwise-recoverable 8-bit split
        as uint8 (see ``device_dataset._try_quantize``), so every
        per-step host gather AND host->device upload moves 4x fewer
        bytes — the H2D copy is this path's bottleneck at small step
        times.  The consumer step must then be built with
        ``dequant=batcher.dequant`` (enforced at trace time by
        ``parallel.sync.dequant_host_batch``); the device-side dequant
        here is always the exact LUT (H2D dominates this path, so the
        "scale"/"exact" distinction of the resident path buys nothing —
        both select uint8 storage).  Crop/flip
        augmentation is pure pixel rearrangement, so it runs on the
        uint8 batch unchanged — the native C++ gather/augment kernels
        have uint8 variants (dataio.cc), so the fused path applies."""
        if batch_size % process_count:
            raise ValueError(
                f"global batch {batch_size} not divisible by {process_count} processes")
        if len(images) < batch_size:
            raise ValueError(
                f"dataset of {len(images)} examples is smaller than the "
                f"global batch {batch_size}; shapes downstream are static")
        if quantize not in ("auto", "off", "exact", "scale"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        # Quantization is only valid when the augment hook is a pure
        # pixel rearrangement (crop/flip — marked ``u8_safe`` on the
        # function, e.g. cifar10.augment): an arbitrary float-arithmetic
        # augment fed uint8 would promote/wrap and silently train on
        # 0-255-scale values, the exact failure the in-step dequant
        # guard exists to prevent.
        u8_safe = augment_fn is None or getattr(augment_fn, "u8_safe", False)
        self.dequant: str | None = None
        if images.dtype == np.uint8:
            if u8_safe:
                self.dequant = "unit"   # raw bytes: floats are u/255
            else:
                # The hook expects floats; dequantize the raw split on
                # the host rather than feed it bytes.
                from distributedtensorflowexample_tpu.data.device_dataset \
                    import _dequant_numpy
                images = _dequant_numpy(images, "unit")
        elif quantize != "off" and u8_safe:
            from distributedtensorflowexample_tpu.data.device_dataset import (
                _try_quantize)
            q = _try_quantize(np.asarray(images))
            if q is not None:
                images, self.dequant = q
        self._images = images
        self._labels = labels
        self._global_batch = batch_size
        self._local_batch = batch_size // process_count
        self._rng = np.random.RandomState(seed)
        self._shuffle = shuffle
        self._pidx = process_index
        self._pcount = process_count
        self._augment = augment_fn
        self._order = np.arange(len(images))
        self._pos = 0
        self._epoch = 0
        if shuffle:
            self._rng.shuffle(self._order)

    @property
    def local_batch_size(self) -> int:
        return self._local_batch

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return self

    def __next__(self) -> dict[str, np.ndarray]:
        # Draw a global batch of indices (all processes draw identically from
        # the same seed), then keep only this process's contiguous slice.
        if self._pos + self._global_batch > len(self._order):
            self._epoch += 1
            self._pos = 0
            if self._shuffle:
                self._rng.shuffle(self._order)
        idx = self._order[self._pos:self._pos + self._global_batch]
        self._pos += self._global_batch
        lo = self._pidx * self._local_batch
        idx = idx[lo:lo + self._local_batch]
        return self._assemble(idx)

    def _assemble(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        """Batch-row assembly — native C++ parallel gather when built (the
        hot host-side copy at small per-step compute), numpy otherwise.
        An augmentation exposing ``fused_native`` (cifar10.augment) is fused
        into the gather: one pass, no intermediate batch copy."""
        from distributedtensorflowexample_tpu import native
        use_native = (native.available()
                      and self._images.dtype in (np.float32, np.uint8)
                      and self._labels.dtype == np.int32)
        if not use_native:
            images = self._images[idx]
            if self._augment is not None:
                images = self._augment(images, self._rng)
            return {"image": images, "label": self._labels[idx]}
        fused = getattr(self._augment, "fused_native", None)
        if fused is not None:
            images = fused(self._images, idx, self._rng)
        else:
            images = native.gather(self._images, idx)
            if self._augment is not None:
                images = self._augment(images, self._rng)
        return {"image": images, "label": native.gather(self._labels, idx)}


class DevicePrefetcher:
    """Keep ``depth`` batches in flight on device ahead of the train step.

    ``device_put`` with a ``Sharding`` starts the async host→HBM copy; by the
    time the step consumes a batch the transfer has overlapped with the
    previous step's compute.  This is the JAX-native replacement for the
    feed_dict copy called out in SURVEY.md §3a as the per-step overhead.
    """

    def __init__(self, it: Iterator[dict[str, np.ndarray]],
                 sharding: jax.sharding.Sharding | None = None, depth: int = 2):
        self._it = it
        self._sharding = sharding
        self._buf: collections.deque = collections.deque()
        self._depth = max(1, depth)

    def _put(self, batch):
        if self._sharding is None:
            return jax.device_put(batch)
        # Batcher yields this process's local shard; assemble the global
        # array from per-process data (a bare device_put would wrongly
        # treat the local shard as the whole global array on multi-host).
        return put_local_batch(batch, self._sharding)

    def __iter__(self):
        return self

    def __next__(self):
        while len(self._buf) < self._depth:
            self._buf.append(self._put(next(self._it)))
        return self._buf.popleft()
