"""Token data for the transformer-LM workload (models/transformer_lm.py).

No real corpus ships with this image (the same no-network constraint as
MNIST/CIFAR), so the ``lm`` dataset IS a deterministic synthetic corpus —
a seeded order-1 Markov chain over ``LM_VOCAB`` tokens with peaked
transitions: from token ``t`` the next token is ``perm[t]`` with
probability ``1 - noise``, else uniform.  That gives the split real,
learnable structure (a single attention layer reaches the ~1.0-nat
bigram floor from the ~5.5-nat uniform start) while every byte stays
reproducible from ``(seed, sample_seed)`` — the same learnable-synthetic
discipline as ``data.synthetic.make_synthetic``.

Storage follows the quantized-data-path convention: the model inputs are
returned as **uint8** (``LM_VOCAB`` < 256 by design), so
``DeviceDataset(token_data=True)`` holds the resident split at 1 byte
per token — 4x less HBM and per-step gather traffic than int32 — and
the model upcasts after the gather.  Targets stay int32 (the loss-side
label convention).
"""

from __future__ import annotations

import numpy as np

from distributedtensorflowexample_tpu.models.transformer_lm import LM_VOCAB

#: Sequence length of the shipped splits: inputs/targets are [N, SEQ_LEN]
#: (each raw sequence is SEQ_LEN+1 tokens; targets are the 1-shifted view).
LM_SEQ_LEN = 128
#: How peaked the Markov transitions are (fraction following perm[t]).
LM_FOLLOW = 0.85
_SYNTH_SIZES = {"train": 2048, "test": 512}


def make_synthetic_tokens(num: int, seq_len: int, vocab: int, seed: int,
                          sample_seed: int | None = None,
                          follow: float = LM_FOLLOW) -> np.ndarray:
    """[num, seq_len + 1] int32 token sequences from the seeded Markov
    chain.  ``seed`` fixes the transition structure (the learnable part);
    splits that must generalize to each other share ``seed`` and differ
    in ``sample_seed`` — the ``make_synthetic`` contract."""
    rng = np.random.RandomState(seed)
    pref = rng.permutation(vocab).astype(np.int32)
    srng = np.random.RandomState(seed if sample_seed is None else sample_seed)
    seq = np.empty((num, seq_len + 1), np.int32)
    seq[:, 0] = srng.randint(0, vocab, size=num)
    for t in range(1, seq_len + 1):
        follows = srng.rand(num) < follow
        rand_tok = srng.randint(0, vocab, size=num).astype(np.int32)
        seq[:, t] = np.where(follows, pref[seq[:, t - 1]], rand_tok)
    return seq


def load_lm(data_dir: str, split: str, seed: int = 0,
            source: str = "real", num: int | None = None,
            seq_len: int = LM_SEQ_LEN,
            vocab: int = LM_VOCAB) -> tuple[np.ndarray, np.ndarray]:
    """(inputs uint8 [N, seq_len], targets int32 [N, seq_len]).

    ``source`` mirrors the image loaders' contract for signature parity,
    but every source resolves to the deterministic synthetic corpus:
    unlike MNIST (where real bytes may be mounted and a silent synthetic
    substitution would mislabel accuracies), there is no real-corpus
    format this loader knows — the synthetic chain IS the dataset's
    definition, so no fallback warning fires.  ``data_dir`` is accepted
    (and ignored) for the same parity reason."""
    del data_dir
    if source not in ("real", "synthetic", "fallback"):
        raise ValueError(f"unknown source {source!r}")
    if num is None:
        try:
            num = _SYNTH_SIZES[split]
        except KeyError:
            raise ValueError(f"unknown split {split!r} (one of "
                             f"{sorted(_SYNTH_SIZES)})") from None
    # Train/test share the chain (seed) and differ in which sequences are
    # drawn (sample_seed), so test perplexity measures generalization to
    # unseen walks of the SAME structure.
    sample_seed = seed + {"train": 1, "test": 2}.get(split, 3)
    seq = make_synthetic_tokens(num, seq_len, vocab, seed,
                                sample_seed=sample_seed)
    if vocab > 256:
        return seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int32)
    return (np.ascontiguousarray(seq[:, :-1]).astype(np.uint8),
            np.ascontiguousarray(seq[:, 1:]).astype(np.int32))
