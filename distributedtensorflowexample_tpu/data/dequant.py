"""Canonical uint8 <-> float32 dequantization arithmetic (host side).

THE definition of what a stored byte means in float: every producer
(mnist/cifar loaders, the native C++ parser, the synthetic generator) and
every consumer (the in-step device dequant in ``parallel.sync``, the host
reference ``dequant_numpy``, the recovery check ``try_quantize``) routes
through the constants and the rounding rule defined here, so bitwise
parity between any two paths is a property of this module, not a
coincidence to re-verify per call site.

The canonical form is the fused AFFINE map ``f32(u) * scale + bias`` with
ONE rounding (an FMA): that is what XLA emits for the jnp expression, and
it is the fastest dequant measured on chip (AB_quantize_r05.json: 1,963
steps/s/chip vs 479.6 for the round-4 LUT-gather default it replaces —
the 4.1x "dequant tax" this module's round-5 redesign kills).  The host
reference reproduces the single rounding exactly in float64: for byte
inputs and these constants the f64 product and sum are exact, so the one
f32 cast at the end IS the fma rounding.  ``affine_matches_lut`` verifies
per spec, over all 256 byte values, that the affine reproduces the
tabulated loader arithmetic bitwise — true for both shipped specs by
construction (the loaders compute through this module), and the guard
that makes ``dequant_impl="auto"`` fall back to the bitwise one-hot LUT
form if a future spec introduces non-affine host arithmetic (e.g. a
gamma curve).

Numpy-only on purpose: the loaders must stay importable without jax (the
device-side appliers live in ``data.device_dataset``).
"""

from __future__ import annotations

import numpy as np

#: float32 1/255 — the "unit" spec's scale.  Multiplying by this constant
#: (NOT dividing by 255: an f32 division rounds differently on 126 of the
#: 256 byte values, and XLA lowers the division to this multiply anyway)
#: is the canonical byte -> [0,1] conversion everywhere in the repo.
U8_UNIT_SCALE = np.float32(1.0) / np.float32(255.0)


def make_dequant_affine(spec: str) -> tuple[np.ndarray, np.ndarray]:
    """(scale, bias) float32 vectors (shape [1] or [C]) of the canonical
    affine dequant ``f32(u) * scale + bias`` for ``spec``.

    - ``"unit"``: raw pixels, floats are ``u * (1/255)`` (bias 0).
    - ``"cifar"``: mean/std-normalized CIFAR pixels, the whole
      ``(u/255 - MEAN) / STD`` pipeline folded into one affine map with
      the constants reduced in float64.
    """
    if spec == "unit":
        return (np.asarray([U8_UNIT_SCALE], np.float32),
                np.zeros(1, np.float32))
    if spec == "cifar":
        from distributedtensorflowexample_tpu.data.cifar10 import (
            CIFAR10_MEAN, CIFAR10_STD)
        scale = (1.0 / (255.0 * np.float64(CIFAR10_STD))).astype(np.float32)
        bias = (-np.float64(CIFAR10_MEAN) / CIFAR10_STD).astype(np.float32)
        return scale, bias
    raise ValueError(f"unknown dequant spec {spec!r}")


def affine_numpy(u8: np.ndarray, spec: str) -> np.ndarray:
    """The canonical host dequant: ``f32(u) * scale + bias`` with ONE
    rounding, reproduced exactly via float64 (the product of a byte value
    and an f32 constant is exact in f64, as is adding the f32 bias, so the
    final f32 cast is the fused multiply-add's single rounding — bitwise
    what XLA's contracted mul+add computes on the gathered batch)."""
    s, b = make_dequant_affine(spec)
    x = u8.astype(np.float64) * s.astype(np.float64) + b.astype(np.float64)
    return x.astype(np.float32)


def make_dequant_lut(spec: str) -> np.ndarray:
    """The 256 float32 values a uint8 pixel dequantizes to — the
    canonical affine arithmetic tabulated.  Shape [256] ("unit") or
    [256, C] (per-channel normalization).  Consumed by the one-hot-matmul
    and gather dequant impls; bitwise-identical to the affine impl for
    every spec where ``affine_matches_lut`` holds (both shipped specs)."""
    u = np.arange(256, dtype=np.uint8)[:, None]
    out = affine_numpy(u, spec)
    return out[:, 0] if out.shape[1] == 1 else out


def affine_matches_lut(spec: str) -> bool:
    """True iff the affine form reproduces ALL 256 LUT entries bitwise —
    the quantize-time verification that lets ``dequant_impl="auto"``
    lower to the affine fast path while keeping the bitwise-parity
    contract.  Bitwise means bitwise: compared as integer bit patterns,
    so even a -0.0/+0.0 swap would fail."""
    lut = make_dequant_lut(spec)
    u = np.arange(256, dtype=np.uint8)[:, None]
    aff = affine_numpy(u, spec)
    aff = aff[:, 0] if lut.ndim == 1 else aff
    return bool(np.array_equal(lut.view(np.int32), aff.view(np.int32)))


def dequant_numpy(u8: np.ndarray, spec: str) -> np.ndarray:
    """Host-side reference dequantization (the float32 values the loader
    produces for these bytes) — an alias of the canonical affine."""
    return affine_numpy(u8, spec)


def try_quantize(x: np.ndarray, chunk: int = 4096):
    """(uint8 split, dequant spec) if ``x`` is EXACTLY representable as
    ``dequant_numpy(u8, spec)`` for one of the known pipelines (raw
    [0,1] "unit" pixels, or CIFAR mean/std-normalized); else None.

    Exactness is verified bitwise chunk-by-chunk (bounded memory), so a
    caller can never lose precision silently: anything not byte-exact —
    arbitrary float inputs, a future normalization this doesn't know —
    stays float32-resident."""
    if x.dtype != np.float32 or x.ndim < 2 or x.size == 0:
        # Empty splits fall through to the caller's own size validation
        # (min()/max() on a zero-length array would raise here first).
        return None
    lo, hi = float(x.min()), float(x.max())
    candidates = []
    if 0.0 <= lo and hi <= 1.0:
        candidates.append(("unit",
                           lambda c: np.rint(c * 255.0)))
    if x.shape[-1] == 3:
        from distributedtensorflowexample_tpu.data.cifar10 import (
            CIFAR10_MEAN, CIFAR10_STD)
        candidates.append(("cifar", lambda c: np.rint(
            (c.astype(np.float64) * CIFAR10_STD + CIFAR10_MEAN) * 255.0)))
    for spec, recover in candidates:
        out = np.empty(x.shape, np.uint8)
        ok = True
        for i in range(0, len(x), chunk):
            c = x[i:i + chunk]
            u = recover(c)
            if u.min() < 0 or u.max() > 255:
                ok = False
                break
            u = u.astype(np.uint8)
            if not np.array_equal(dequant_numpy(u, spec), c):
                ok = False
                break
            out[i:i + chunk] = u
        if ok:
            return out, spec
    return None
