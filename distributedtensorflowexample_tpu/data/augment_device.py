"""On-device CIFAR augmentation (random reflect-pad-4 crop + hflip).

The host pipeline augments with numpy/C++ (``cifar10.augment``); this is
the same transform expressed as jnp for use INSIDE the jitted train step,
so the device-resident input path (``DeviceDataset`` +
``make_indexed_train_step``) covers the augmented CIFAR workloads too —
batches never touch the host.  Same distribution as the host path (crop
offsets uniform on [0, 8], flip probability 1/2, reflect padding), but a
different RNG stream (``jax.random`` vs the host ``RandomState``), so a
device-augmented run is deterministic per seed yet not bit-identical to a
host-augmented run.

The per-image crop+flip is expressed as two one-hot SELECTOR MATMULS
(one picking output rows, one picking-and-optionally-reversing output
columns), not as ``vmap(dynamic_slice)``: XLA lowers the vmap'd dynamic
crop to a SERIAL per-image while loop on TPU — the round-5 trace
(PROFILE_auto_r05.json window) measured it at ~4.4 ms/step on ResNet-20's
batch-256 input, and the same-window A/B (AB_augment_r05.json) runs the
selector form at batch-gemm speed.  The selection is exact routing:
every output pixel is ``1.0 * one input pixel``.  uint8 pixels are exact
in bfloat16 (integers <= 255 fit its 8-bit mantissa), so one bf16 matmul
pair suffices; float32 pixels are split into three bf16 components
(8+8+8 = 24 mantissa bits, each split subtraction exact by Sterbenz) and
routed per component, so the float path is bitwise-exact too.

All shapes are static and everything is (batched) matmul + elementwise —
XLA fuses the whole thing into the step on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

PAD = 4


def _mm_dtype():
    """Matmul component dtype: bfloat16 on accelerators (MXU-native, and
    the 3-way split keeps float32 routing exact); float32 on CPU, whose
    XLA has no bf16 GEMM — f32 dots are exact for one-hot routing, and
    the split degenerates to ``x + 0 + 0`` through the same code path."""
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


def _selector_apply(padded: jnp.ndarray, R: jnp.ndarray,
                    C: jnp.ndarray) -> jnp.ndarray:
    """Route pixels: out[b,r,k,c] = padded[b, yrow(r), xcol(k), c] where
    the one-hot selectors R [B,H,HP] / C [B,HP,W] encode the per-image
    row/column picks.  f32 accumulation — exact for values exact in the
    operand dtype (every output element's dot has ONE nonzero term)."""
    out = jnp.einsum("brh,bhwc->brwc", R, padded,
                     preferred_element_type=jnp.float32)
    return jnp.einsum("brwc,bwk->brkc", out.astype(R.dtype), C,
                      preferred_element_type=jnp.float32)


def cifar_augment_device(images: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """[B, H, W, C] uint8 or float → same shape+dtype, randomly cropped +
    flipped (pure pixel rearrangement, bitwise-exact for both dtypes)."""
    b, h, w, c = images.shape
    ky, kx, kf = jax.random.split(key, 3)
    ys = jax.random.randint(ky, (b,), 0, 2 * PAD + 1)
    xs = jax.random.randint(kx, (b,), 0, 2 * PAD + 1)
    flips = jax.random.bernoulli(kf, 0.5, (b,))
    padded = jnp.pad(images, ((0, 0), (PAD, PAD), (PAD, PAD), (0, 0)),
                     mode="reflect")
    hp = h + 2 * PAD
    # R[b, r, hh] = (hh == ys[b] + r): output row r reads padded row
    # ys[b]+r.
    md = _mm_dtype()
    rows = ys[:, None, None] + jnp.arange(h)[None, :, None]
    R = (jnp.arange(hp)[None, None, :] == rows).astype(md)
    # C[b, ww, k] = (ww == xs[b] + (flip ? w-1-k : k)): column pick with
    # the horizontal flip folded into the same selector.
    k = jnp.arange(w)[None, None, :]
    src = jnp.where(flips[:, None, None], w - 1 - k, k) + xs[:, None, None]
    C = (jnp.arange(hp)[None, :, None] == src).astype(md)

    if images.dtype == jnp.uint8:
        out = _selector_apply(padded.astype(md), R, C)
        return out.astype(images.dtype)
    x = padded.astype(jnp.float32)
    hi = x.astype(md)
    mid = (x - hi.astype(jnp.float32)).astype(md)
    lo = (x - hi.astype(jnp.float32) - mid.astype(jnp.float32)).astype(md)
    out = (_selector_apply(hi, R, C) + _selector_apply(mid, R, C)
           ) + _selector_apply(lo, R, C)
    return out.astype(images.dtype)
