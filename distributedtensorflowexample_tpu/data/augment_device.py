"""On-device CIFAR augmentation (random reflect-pad-4 crop + hflip).

The host pipeline augments with numpy/C++ (``cifar10.augment``); this is
the same transform expressed as jnp for use INSIDE the jitted train step,
so the device-resident input path (``DeviceDataset`` +
``make_indexed_train_step``) covers the augmented CIFAR workloads too —
batches never touch the host.  Same distribution as the host path (crop
offsets uniform on [0, 8], flip probability 1/2, reflect padding), but a
different RNG stream (``jax.random`` vs the host ``RandomState``), so a
device-augmented run is deterministic per seed yet not bit-identical to a
host-augmented run.

The per-image crop+flip is expressed as two one-hot SELECTOR MATMULS
(one picking output rows, one picking-and-optionally-reversing output
columns), not as ``vmap(dynamic_slice)``: XLA lowers the vmap'd dynamic
crop to a SERIAL per-image while loop on TPU — the round-5 trace
(PROFILE_auto_r05.json window) measured it at ~4.4 ms/step on ResNet-20's
batch-256 input, and the same-window A/B (AB_augment_r05.json) runs the
selector form at batch-gemm speed.  The selection is exact routing:
every output pixel is ``1.0 * one input pixel``.  uint8 pixels are exact
in bfloat16 (integers <= 255 fit its 8-bit mantissa), so one bf16 matmul
pair suffices; float32 pixels are split into three bf16 components
(8+8+8 = 24 mantissa bits, each split subtraction exact by Sterbenz) and
routed per component, so the float path is bitwise-exact too.

All shapes are static and everything is (batched) matmul + elementwise —
XLA fuses the whole thing into the step on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

PAD = 4


def _mm_dtype():
    """Matmul component dtype: bfloat16 on accelerators (MXU-native, and
    the 3-way split keeps float32 routing exact); float32 on CPU, whose
    XLA has no bf16 GEMM — f32 dots are exact for one-hot routing, and
    the split degenerates to ``x + 0 + 0`` through the same code path."""
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


def _selector_apply(padded: jnp.ndarray, R: jnp.ndarray,
                    C: jnp.ndarray) -> jnp.ndarray:
    """Route pixels: out[b,r,k,c] = padded[b, yrow(r), xcol(k), c] where
    the one-hot selectors R [B,H,HP] / C [B,HP,W] encode the per-image
    row/column picks.  f32 accumulation — exact for values exact in the
    operand dtype (every output element's dot has ONE nonzero term)."""
    out = jnp.einsum("brh,bhwc->brwc", R, padded,
                     preferred_element_type=jnp.float32)
    return jnp.einsum("brwc,bwk->brkc", out.astype(R.dtype), C,
                      preferred_element_type=jnp.float32)


def _crop_flip_selectors(images: jnp.ndarray, key: jax.Array):
    """(padded, R, C): the reflect-padded input plus the per-image one-hot
    row/column selectors encoding a random crop + hflip draw — the shared
    front half of both augment entry points, so the fused dequant variant
    below draws EXACTLY the same crops/flips as the plain one."""
    b, h, w, c = images.shape
    ky, kx, kf = jax.random.split(key, 3)
    ys = jax.random.randint(ky, (b,), 0, 2 * PAD + 1)
    xs = jax.random.randint(kx, (b,), 0, 2 * PAD + 1)
    flips = jax.random.bernoulli(kf, 0.5, (b,))
    padded = jnp.pad(images, ((0, 0), (PAD, PAD), (PAD, PAD), (0, 0)),
                     mode="reflect")
    hp = h + 2 * PAD
    # R[b, r, hh] = (hh == ys[b] + r): output row r reads padded row
    # ys[b]+r.
    md = _mm_dtype()
    rows = ys[:, None, None] + jnp.arange(h)[None, :, None]
    R = (jnp.arange(hp)[None, None, :] == rows).astype(md)
    # C[b, ww, k] = (ww == xs[b] + (flip ? w-1-k : k)): column pick with
    # the horizontal flip folded into the same selector.
    k = jnp.arange(w)[None, None, :]
    src = jnp.where(flips[:, None, None], w - 1 - k, k) + xs[:, None, None]
    C = (jnp.arange(hp)[None, :, None] == src).astype(md)
    return padded, R, C


def cifar_augment_device(images: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """[B, H, W, C] uint8 or float → same shape+dtype, randomly cropped +
    flipped (pure pixel rearrangement, bitwise-exact for both dtypes)."""
    padded, R, C = _crop_flip_selectors(images, key)
    md = R.dtype

    if images.dtype == jnp.uint8:
        out = _selector_apply(padded.astype(md), R, C)
        return out.astype(images.dtype)
    x = padded.astype(jnp.float32)
    hi = x.astype(md)
    mid = (x - hi.astype(jnp.float32)).astype(md)
    lo = (x - hi.astype(jnp.float32) - mid.astype(jnp.float32)).astype(md)
    out = (_selector_apply(hi, R, C) + _selector_apply(mid, R, C)
           ) + _selector_apply(lo, R, C)
    return out.astype(images.dtype)


def cifar_augment_dequant_device(images: jnp.ndarray, key: jax.Array,
                                 scale: jnp.ndarray,
                                 bias: jnp.ndarray) -> jnp.ndarray:
    """[B, H, W, C] uint8 → float32: random crop + hflip AND the affine
    dequant (``f32(u) * scale + bias``, constants from the data pytree's
    ``dq_scale``/``dq_bias``) in ONE pass — the round-5 input-share fix
    for the augmented path.

    The plain route (``cifar_augment_device`` then dequant) materializes
    an augmented uint8 batch between the two: the selector matmuls
    accumulate in f32, cast BACK to uint8, and the dequant re-reads and
    re-converts it.  Here the selectors' f32 output (exact — every output
    pixel's dot has one nonzero term, and bytes are exact in bf16) feeds
    the affine directly, so XLA fuses crop/flip/dequant into the selector
    matmuls' epilogue: no uint8 intermediate, one fewer elementwise pass
    over the batch.  Bitwise-identical to augment-then-dequant: the
    routed f32 values ARE the byte values, so the affine sees the same
    inputs either way (same crops/flips too — ``_crop_flip_selectors`` is
    shared)."""
    if images.dtype != jnp.uint8:
        raise TypeError(f"cifar_augment_dequant_device fuses the uint8 "
                        f"dequant; got {images.dtype} (use "
                        f"cifar_augment_device)")
    padded, R, C = _crop_flip_selectors(images, key)
    out = _selector_apply(padded.astype(R.dtype), R, C)
    # out[b,r,k,c] holds the exact routed byte value in f32; scale/bias
    # are [1] or [C] and broadcast over the trailing channel axis — the
    # same fused multiply-add apply_dequant_affine computes.
    return out * scale + bias
