"""On-device CIFAR augmentation (random reflect-pad-4 crop + hflip).

The host pipeline augments with numpy/C++ (``cifar10.augment``); this is
the same transform expressed as jnp for use INSIDE the jitted train step,
so the device-resident input path (``DeviceDataset`` +
``make_indexed_train_step``) covers the augmented CIFAR workloads too —
batches never touch the host.  Same distribution as the host path (crop
offsets uniform on [0, 8], flip probability 1/2, reflect padding), but a
different RNG stream (``jax.random`` vs the host ``RandomState``), so a
device-augmented run is deterministic per seed yet not bit-identical to a
host-augmented run.

All shapes are static: pad → per-image ``dynamic_slice`` under ``vmap`` →
masked flip.  XLA fuses the whole thing into the step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

PAD = 4


def cifar_augment_device(images: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """[B, H, W, C] any dtype → same shape, randomly cropped + flipped
    (pure pixel rearrangement: runs on uint8-resident batches too)."""
    b, h, w, c = images.shape
    ky, kx, kf = jax.random.split(key, 3)
    ys = jax.random.randint(ky, (b,), 0, 2 * PAD + 1)
    xs = jax.random.randint(kx, (b,), 0, 2 * PAD + 1)
    flips = jax.random.bernoulli(kf, 0.5, (b,))
    padded = jnp.pad(images, ((0, 0), (PAD, PAD), (PAD, PAD), (0, 0)),
                     mode="reflect")

    def crop(img, y0, x0):
        return jax.lax.dynamic_slice(img, (y0, x0, 0), (h, w, c))

    crops = jax.vmap(crop)(padded, ys, xs)
    return jnp.where(flips[:, None, None, None], crops[:, :, ::-1, :], crops)
