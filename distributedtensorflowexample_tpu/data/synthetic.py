"""Deterministic synthetic classification data.

The reference relied on network downloads (``input_data.read_data_sets`` /
``tf.keras.datasets``).  This environment has no network, so every loader
falls back to a deterministic, *learnable* synthetic distribution: each class
is a fixed random template and samples are noisy blends of their class
template.  Linear models reach high accuracy on it, which keeps the reference's
implicit run-to-verify convergence checks meaningful without the real bytes.
"""

from __future__ import annotations

import os
import sys

import numpy as np

_warned: set[tuple[str, str]] = set()


def warn_synthetic(dataset: str, split: str, data_dir: str,
                   expected: str) -> None:
    """LOUD once-per-(dataset,split) notice that a real-data path fell back
    to the synthetic distribution — accuracies from such runs are NOT
    comparable to the reference's real-dataset numbers (round-2 verdict:
    the silent fallback made every recorded accuracy ambiguous).
    Suppress with DISTTF_TPU_QUIET_SYNTHETIC=1 (CI noise control)."""
    if os.environ.get("DISTTF_TPU_QUIET_SYNTHETIC") == "1":
        return     # before _warned.add: quiet mode must not consume the
    if (dataset, split) in _warned:     # once-per-process warning
        return
    _warned.add((dataset, split))
    print(f"WARNING: {dataset} {split!r} bytes not found in {data_dir!r} "
          f"(expected {expected}); using the DETERMINISTIC SYNTHETIC "
          f"fallback split. Accuracy targets for the real dataset do not "
          f"apply — see README 'Real datasets'.", file=sys.stderr, flush=True)


def make_synthetic(num: int, shape: tuple[int, ...], num_classes: int,
                   seed: int, noise: float = 0.35,
                   sample_seed: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Class-template images in [0,1] float32 + int32 labels.

    ``seed`` fixes the class templates (the learnable structure); splits that
    must generalize to each other share ``seed`` and differ in
    ``sample_seed`` (which labels are drawn and which noise is added).
    """
    rng = np.random.RandomState(seed)
    templates = rng.rand(num_classes, *shape).astype(np.float32)
    srng = np.random.RandomState(seed if sample_seed is None else sample_seed)
    labels = srng.randint(0, num_classes, size=(num,)).astype(np.int32)
    eps = srng.rand(num, *shape).astype(np.float32)
    images = (1.0 - noise) * templates[labels] + noise * eps
    images = np.clip(images, 0.0, 1.0)
    # Snap pixels to the 8-bit grid (u * 1/255 — the canonical affine
    # byte->float convention, data.dequant), like every real image
    # source: keeps the distribution learnable AND lets DeviceDataset
    # store the split as uint8 in HBM (4x less gather traffic per
    # training step — see DeviceDataset quantize docs).
    from distributedtensorflowexample_tpu.data.dequant import U8_UNIT_SCALE
    images = np.rint(images * 255.0).astype(np.float32) * U8_UNIT_SCALE
    return images, labels
