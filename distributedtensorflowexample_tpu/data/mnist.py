"""MNIST input pipeline (component C10 in SURVEY.md §2).

Reference behavior [RECONSTRUCTED — reference tree was empty]: an
``input_data.read_data_sets(data_dir)``-style download + minibatch feed.
TPU-native rebuild: pure-numpy IDX parsing with no TF dependency; if the
standard IDX files are absent (no network in this environment) we fall back
to deterministic synthetic data with the same shapes (see ``synthetic.py``).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from distributedtensorflowexample_tpu.data.synthetic import make_synthetic

_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}
_SYNTH_SIZES = {"train": 60000, "test": 10000}


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def _read_idx_images(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        raw = f.read()
    from distributedtensorflowexample_tpu import native
    if native.available():
        return native.parse_idx_images(raw)
    magic, n, rows, cols = struct.unpack(">IIII", raw[:16])
    if magic != 2051:
        raise ValueError(f"bad IDX image magic {magic} in {path}")
    data = np.frombuffer(raw, dtype=np.uint8, count=n * rows * cols, offset=16)
    return data.reshape(n, rows, cols, 1).astype(np.float32) / 255.0


def _read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        raw = f.read()
    from distributedtensorflowexample_tpu import native
    if native.available():
        return native.parse_idx_labels(raw)
    magic, n = struct.unpack(">II", raw[:8])
    if magic != 2049:
        raise ValueError(f"bad IDX label magic {magic} in {path}")
    return np.frombuffer(raw, dtype=np.uint8, count=n, offset=8).astype(np.int32)


def load_mnist(data_dir: str, split: str = "train",
               synthetic_size: int | None = None,
               seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Return (images [N,28,28,1] float32 in [0,1], labels [N] int32).

    Reads standard IDX(.gz) files from ``data_dir`` when present, otherwise
    generates deterministic synthetic data of the canonical split sizes.
    """
    img_name, lbl_name = _FILES[split]
    img_path = os.path.join(data_dir, img_name)
    lbl_path = os.path.join(data_dir, lbl_name)
    if os.path.exists(img_path) or os.path.exists(img_path + ".gz"):
        return _read_idx_images(img_path), _read_idx_labels(lbl_path)
    from distributedtensorflowexample_tpu.data.synthetic import warn_synthetic
    warn_synthetic("MNIST", split, data_dir, img_name)
    num = synthetic_size or _SYNTH_SIZES[split]
    # Same class templates for both splits; disjoint sample draws — so a
    # model trained on "train" genuinely generalizes to "test".
    return make_synthetic(num, (28, 28, 1), 10, seed=seed,
                          sample_seed=seed * 2 + (1 if split == "train" else 2))
