"""MNIST input pipeline (component C10 in SURVEY.md §2).

Reference behavior [RECONSTRUCTED — reference tree was empty]: an
``input_data.read_data_sets(data_dir)``-style download + minibatch feed.
TPU-native rebuild: pure-numpy IDX parsing with no TF dependency; if the
standard IDX files are absent (no network in this environment) we fall back
to deterministic synthetic data with the same shapes (see ``synthetic.py``).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from distributedtensorflowexample_tpu.data.dequant import U8_UNIT_SCALE
from distributedtensorflowexample_tpu.data.synthetic import make_synthetic

_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}
_SYNTH_SIZES = {"train": 60000, "test": 10000}


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def _read_idx_images(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        raw = f.read()
    from distributedtensorflowexample_tpu import native
    if native.available():
        return native.parse_idx_images(raw)
    magic, n, rows, cols = struct.unpack(">IIII", raw[:16])
    if magic != 2051:
        raise ValueError(f"bad IDX image magic {magic} in {path}")
    data = np.frombuffer(raw, dtype=np.uint8, count=n * rows * cols, offset=16)
    # Multiply by the canonical f32 1/255, NOT divide: the affine form is
    # the repo-wide byte->float convention (data.dequant), so the in-step
    # affine dequant of the uint8-resident split is bitwise-identical to
    # these floats.  (An f32 division rounds differently on 126/256 byte
    # values — it was what forced the 4.1x-slower LUT dequant.)
    return data.reshape(n, rows, cols, 1).astype(np.float32) * U8_UNIT_SCALE


def _read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        raw = f.read()
    from distributedtensorflowexample_tpu import native
    if native.available():
        return native.parse_idx_labels(raw)
    magic, n = struct.unpack(">II", raw[:8])
    if magic != 2049:
        raise ValueError(f"bad IDX label magic {magic} in {path}")
    return np.frombuffer(raw, dtype=np.uint8, count=n, offset=8).astype(np.int32)


def load_mnist(data_dir: str, split: str = "train",
               synthetic_size: int | None = None,
               seed: int = 0,
               source: str = "real") -> tuple[np.ndarray, np.ndarray]:
    """Return (images [N,28,28,1] float32 in [0,1], labels [N] int32).

    ``source`` selects where the bytes come from (VERDICT r4 #5 — no
    silent substitution on the user surface):

    - ``"real"`` (default): the standard IDX(.gz) files must exist in
      ``data_dir``; a missing file is a crisp ``FileNotFoundError`` that
      names ``--dataset synthetic`` as the opt-in.
    - ``"synthetic"``: the deterministic synthetic split, explicitly
      requested — no warning.
    - ``"fallback"``: real if present, else synthetic with a LOUD
      once-per-split warning (for harnesses that must run with or
      without the bytes, e.g. bench.py on a data-less chip host).
    """
    if source not in ("real", "synthetic", "fallback"):
        raise ValueError(f"unknown source {source!r}")
    img_name, lbl_name = _FILES[split]
    img_path = os.path.join(data_dir, img_name)
    lbl_path = os.path.join(data_dir, lbl_name)
    have = os.path.exists(img_path) or os.path.exists(img_path + ".gz")
    if source != "synthetic" and have:
        return _read_idx_images(img_path), _read_idx_labels(lbl_path)
    if source == "real":
        raise FileNotFoundError(
            f"MNIST {split!r} bytes not found in {data_dir!r} (expected "
            f"{img_name}[.gz]). Point --data_dir at the IDX files, or pass "
            f"--dataset synthetic to train on the deterministic synthetic "
            f"split instead.")
    if source == "fallback":
        from distributedtensorflowexample_tpu.data.synthetic import (
            warn_synthetic)
        warn_synthetic("MNIST", split, data_dir, img_name)
    num = synthetic_size or _SYNTH_SIZES[split]
    # Same class templates for both splits; disjoint sample draws — so a
    # model trained on "train" genuinely generalizes to "test".
    return make_synthetic(num, (28, 28, 1), 10, seed=seed,
                          sample_seed=seed * 2 + (1 if split == "train" else 2))
