from distributedtensorflowexample_tpu.data.mnist import load_mnist
from distributedtensorflowexample_tpu.data.cifar10 import load_cifar10
from distributedtensorflowexample_tpu.data.lm import load_lm
from distributedtensorflowexample_tpu.data.device_dataset import DeviceDataset
from distributedtensorflowexample_tpu.data.pipeline import Batcher, DevicePrefetcher

__all__ = ["load_mnist", "load_cifar10", "load_lm", "Batcher",
           "DevicePrefetcher", "DeviceDataset"]
