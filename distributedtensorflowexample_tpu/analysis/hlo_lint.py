"""Compiled-HLO contract linter (the second front of graftlint).

The collective schedules the ZeRO knobs promise — zero3's every-bucket
all-gather textually BEFORE its reduce-scatter with no step-closing AG,
zero1's RS+AG pair, the bucketed modes' op-count budgets — were pinned
only by runtime golden multisets in tests (arXiv:2004.13336's schedule
as folklore).  This module makes each a declarative CONTRACT checked
against compiled-HLO text: the modules that build the schedules declare
what their compiled form must look like (``HLO_CONTRACT`` next to the
code in ``parallel/{sync,bucketing,zero3}.py``, and the serving decode
step's ``DECODE_HLO_CONTRACT`` in ``serving/engine.py`` — KV-cache
donation aliased, no donated-buffer copy, no collectives), and
:func:`check_contract` proves it on any program text — a freshly
compiled step, a checked-in artifact, or the synthetic violations
tests/test_analysis.py plants.

Reuses ``utils/profiling.py``'s ENTRY-walk (:func:`~...profiling.
entry_walk`) and :func:`~...profiling.collective_inventory` so the
contract checks and the measurement instruments share ONE parser — no
second opinion about what a module contains.  Within
:func:`check_contract` a single ``entry_walk`` serves the schedule,
donation, and dtype checks; the budget check calls
``collective_inventory`` (one more pass of the same parser) because
its trip-count-weighted multiset is the exact number the runtime
goldens pin.

Contract keys (all optional; a missing key = not checked):

* ``ag_rs_paired`` — k-th all-gather textually precedes the k-th
  reduce-scatter (the zero3 forward-prefetch shape).
* ``no_trailing_all_gather`` — no AG after the last RS (zero3: the
  updated 1/D row writes straight back; a step-closing AG is ZeRO-1
  leaking in).
* ``rs_ag_paired`` — k-th RS textually precedes the k-th AG (zero1:
  the update-closing gather follows its reduce-scatter).
* ``collective_budget`` — {opcode: count}; int values are upper
  bounds, symbol expressions (``"B"``/``"B+2"``/``"P+2"`` with B =
  buckets, P = param leaves, resolved via the ``symbols`` argument)
  are EXACT — the schedule promises that many, and a count shrunk to
  zero is as much a regression as growth.  Collectives absent from
  the budget are findings: a schedule may not grow a new collective
  silently.
* ``require_alias`` — the module header must carry a non-empty
  ``input_output_alias`` (donation actually aliased something).
* ``no_donated_copy`` — no ENTRY ``copy`` of a donated-and-aliased
  parameter (a copy-before-write defeats the in-place update donation
  paid for).
* ``dtype_ceiling`` — no ``convert`` to a FLOAT dtype wider than the
  ceiling anywhere in the executed program (quantized paths must not
  upcast past their declared precision).

This module imports jax transitively (via utils/profiling) — keep it
out of analysis/__init__ imports; tools/graftlint.py loads it lazily.
"""

from __future__ import annotations

import re

from distributedtensorflowexample_tpu.analysis import Finding
from distributedtensorflowexample_tpu.utils.profiling import (
    _DTYPE_BYTES, _INSTR_RE, _SHAPE_RE, collective_inventory, entry_walk)

HLO_RULES = ("hlo-ag-before-rs", "hlo-trailing-ag", "hlo-rs-ag-pair",
             "hlo-budget", "hlo-donation", "hlo-dtype-ceiling",
             "hlo-contract")

_COLLECTIVES = frozenset({"all-reduce", "all-gather", "reduce-scatter",
                          "all-to-all", "collective-permute"})
_FLOAT_DTYPES = frozenset({"f16", "bf16", "f32", "f64"})
_SYM_RE = re.compile(r"^([A-Z])(?:\+(\d+))?$")
_PARAM_NUM_RE = re.compile(r"parameter\((\d+)\)")


def collective_schedule(hlo_text: str,
                        walk: tuple | None = None) -> list[tuple[str, int]]:
    """Ordered ``(opcode, position)`` of collective instructions in
    EXECUTED computations (ENTRY-walk weights > 0), in textual order —
    which for an ``is_scheduled`` module is issue order within each
    computation.  Async ``-start`` halves normalize to the base op,
    ``-done`` halves are skipped (one transfer, not two).  ``walk`` is
    an optional precomputed ``entry_walk`` result so one parse serves
    every check (``check_contract`` threads it through)."""
    comps, entry, weights = walk if walk is not None \
        else entry_walk(hlo_text)
    if entry is None:
        return []
    live = {name for name, w in weights.items() if w > 0}
    seq: list[tuple[str, int]] = []
    pos = 0
    cur = None
    for line in hlo_text.splitlines():
        pos += 1
        stripped = line.strip()
        if stripped.endswith("{"):
            m = re.match(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(", stripped)
            if m:
                cur = m.group(1)
            continue
        if stripped.startswith("}"):
            cur = None
            continue
        if cur not in live:
            continue
        mi = _INSTR_RE.match(line)
        if not mi:
            continue
        opcode = mi.group(3)
        base = opcode[:-6] if opcode.endswith("-start") else opcode
        if base in _COLLECTIVES and not opcode.endswith("-done"):
            seq.append((base, pos))
    return seq


def _resolve_budget(value, symbols: dict[str, int]) -> int | None:
    if isinstance(value, int):
        return value
    m = _SYM_RE.match(str(value))
    if not m or m.group(1) not in symbols:
        return None
    return symbols[m.group(1)] + int(m.group(2) or 0)


def _alias_param_ids(hlo_text: str) -> list[int] | None:
    """Donated-parameter numbers from the module header's
    ``input_output_alias={...}`` (balanced-brace scan: entries nest
    ``{output_index}: (param, {param_index}, kind)``).  None = the
    header carries no alias map at all."""
    at = hlo_text.find("input_output_alias=")
    if at < 0:
        return None
    start = hlo_text.find("{", at)
    if start < 0:
        return None
    depth = 0
    for i in range(start, min(len(hlo_text), start + 100_000)):
        if hlo_text[i] == "{":
            depth += 1
        elif hlo_text[i] == "}":
            depth -= 1
            if depth == 0:
                body = hlo_text[start + 1:i]
                return sorted({int(m.group(1)) for m in
                               re.finditer(r"\(\s*(\d+)\s*,", body)})
    return None


def check_contract(hlo_text: str, contract: dict, *,
                   unroll: int = 1,
                   symbols: dict[str, int] | None = None) -> list[Finding]:
    """Check one compiled module against one contract; returns findings
    (empty = the program honors the contract)."""
    mode = contract.get("mode", "?")
    path = f"<hlo:{mode}>"
    symbols = symbols or {}
    findings: list[Finding] = []
    # ONE entry_walk serves the schedule, donation, and dtype checks;
    # the budget check goes through collective_inventory, the shared
    # measurement instrument (its weighted multiset is the same number
    # the runtime goldens pin — deliberately not reimplemented here).
    walk = entry_walk(hlo_text)
    comps, entry, weights = walk
    seq = collective_schedule(hlo_text, walk=walk)
    ags = [p for op, p in seq if op == "all-gather"]
    rss = [p for op, p in seq if op == "reduce-scatter"]

    # The paired rules are EXACT when the bucket count is known: B
    # buckets promise exactly B pairs, so an empty schedule (zero
    # collectives — e.g. a layout regression that compiles the gathers
    # away) is a violation, never a vacuous pass.
    expected_b = symbols.get("B")
    if contract.get("ag_rs_paired") or contract.get("rs_ag_paired"):
        if expected_b is not None and (len(ags) != expected_b
                                       or len(rss) != expected_b):
            rule = ("hlo-ag-before-rs" if contract.get("ag_rs_paired")
                    else "hlo-rs-ag-pair")
            findings.append(Finding(
                rule, path, 0, f"{rule}:{mode}:buckets",
                f"{mode}: expected exactly {expected_b} AG/RS pair(s) "
                f"(one per bucket), found {len(ags)} all-gather(s) / "
                f"{len(rss)} reduce-scatter(s)"))

    if contract.get("ag_rs_paired"):
        if len(ags) != len(rss):
            findings.append(Finding(
                "hlo-ag-before-rs", path, 0,
                f"hlo-ag-before-rs:{mode}:count",
                f"{mode}: {len(ags)} all-gathers vs {len(rss)} "
                f"reduce-scatters — the per-bucket AG/RS pairing is "
                f"broken"))
        else:
            for k, (a, r) in enumerate(zip(ags, rss)):
                if a >= r:
                    findings.append(Finding(
                        "hlo-ag-before-rs", path, a,
                        f"hlo-ag-before-rs:{mode}:{k}",
                        f"{mode}: bucket {k}'s all-gather (line {a}) "
                        f"does not textually precede its reduce-scatter "
                        f"(line {r}) — the forward prefetch schedule is "
                        f"not what compiled"))

    if contract.get("no_trailing_all_gather") and rss:
        trailing = [a for a in ags if a > max(rss)]
        if trailing:
            findings.append(Finding(
                "hlo-trailing-ag", path, trailing[0],
                f"hlo-trailing-ag:{mode}",
                f"{mode}: {len(trailing)} all-gather(s) after the last "
                f"reduce-scatter — a step-closing AG (the ZeRO-1 "
                f"update-closing gather) leaked into a schedule that "
                f"promises none"))

    if contract.get("rs_ag_paired"):
        if not rss or not ags or len(ags) != len(rss):
            findings.append(Finding(
                "hlo-rs-ag-pair", path, 0,
                f"hlo-rs-ag-pair:{mode}:count",
                f"{mode}: expected matched RS+AG pairs, got "
                f"{len(rss)} reduce-scatter(s) / {len(ags)} "
                f"all-gather(s)"))
        else:
            for k, (r, a) in enumerate(zip(rss, ags)):
                if r >= a:
                    findings.append(Finding(
                        "hlo-rs-ag-pair", path, r,
                        f"hlo-rs-ag-pair:{mode}:{k}",
                        f"{mode}: bucket {k}'s update-closing all-gather "
                        f"(line {a}) does not follow its reduce-scatter "
                        f"(line {r})"))

    budget = contract.get("collective_budget")
    if budget:
        inv = collective_inventory(hlo_text, unroll=unroll)
        multiset = inv["multiset"]
        for op, count in sorted(multiset.items()):
            if op not in budget:
                findings.append(Finding(
                    "hlo-budget", path, 0, f"hlo-budget:{mode}:{op}",
                    f"{mode}: collective {op!r} (x{count}) is not in "
                    f"the mode's declared budget {sorted(budget)} — a "
                    f"new collective appeared silently"))
        # Symbol-valued entries ("B"/"B+2"/"P+2") are EXACT — the
        # schedule promises that many, and a shrunken count (down to
        # zero, where the op never enters the multiset) is as much a
        # regression as growth.  Plain ints stay upper bounds.
        for op, decl in sorted(budget.items()):
            count = multiset.get(op, 0)
            cap = _resolve_budget(decl, symbols)
            if cap is None:
                findings.append(Finding(
                    "hlo-budget", path, 0, f"hlo-budget:{mode}:{op}",
                    f"{mode}: budget {decl!r} for {op} names a "
                    f"symbol missing from {sorted(symbols)}"))
            elif isinstance(decl, str) and count != cap:
                findings.append(Finding(
                    "hlo-budget", path, 0, f"hlo-budget:{mode}:{op}",
                    f"{mode}: {count} {op} ops != the exact budget "
                    f"{decl!r}={cap} — the schedule changed"))
            elif count > cap:
                findings.append(Finding(
                    "hlo-budget", path, 0, f"hlo-budget:{mode}:{op}",
                    f"{mode}: {count} {op} ops exceed the budget "
                    f"{decl!r}={cap} — the schedule grew"))

    alias_ids = _alias_param_ids(hlo_text)
    if contract.get("require_alias") and not alias_ids:
        findings.append(Finding(
            "hlo-donation", path, 0, f"hlo-donation:{mode}:alias",
            f"{mode}: module header carries no input_output_alias — "
            f"donation aliased nothing (the donated state is being "
            f"copied, not updated in place)"))

    if contract.get("no_donated_copy") and alias_ids:
        pname_by_id: dict[str, int] = {}
        for name, _out, opcode, line, _at in comps.get(entry, ()):
            if opcode == "parameter":
                m = _PARAM_NUM_RE.search(line)
                if m:
                    pname_by_id[name] = int(m.group(1))
        donated_names = {n for n, i in pname_by_id.items()
                         if i in alias_ids}
        for name, _out, opcode, line, _at in comps.get(entry, ()):
            if opcode != "copy":
                continue
            for dn in donated_names:
                # The name must end where it ends: HLO names carry
                # dotted suffixes (%p0 vs %p0.1 are DIFFERENT
                # instructions), so \b alone would prefix-match.
                if re.search(rf"%{re.escape(dn)}(?![\w.\-])", line):
                    findings.append(Finding(
                        "hlo-donation", path, 0,
                        f"hlo-donation:{mode}:copy:{dn}",
                        f"{mode}: donated parameter {dn} (arg "
                        f"{pname_by_id[dn]}) is copied in ENTRY — the "
                        f"donation did not alias; the in-place update "
                        f"is paying for a full copy"))

    ceiling = contract.get("dtype_ceiling")
    if ceiling:
        cap_bytes = _DTYPE_BYTES.get(ceiling)
        if cap_bytes is None:
            # A misspelled ceiling ("float32"/"fp32") must not
            # silently disable the check — same stance as an
            # unresolvable budget symbol.
            findings.append(Finding(
                "hlo-dtype-ceiling", path, 0,
                f"hlo-dtype-ceiling:{mode}:config",
                f"{mode}: dtype_ceiling {ceiling!r} is not an HLO "
                f"dtype (expected e.g. 'f32'/'bf16') — the upcast "
                f"check cannot run"))
        elif entry is not None:
            flagged: set[str] = set()
            for comp, w in weights.items():
                if w <= 0:
                    continue
                for name, out_tok, opcode, _line, _at in comps.get(
                        comp, ()):
                    if opcode != "convert":
                        continue
                    m = _SHAPE_RE.search(out_tok)
                    if not m:
                        continue
                    dt = m.group(1)
                    if dt in _FLOAT_DTYPES and dt not in flagged \
                            and _DTYPE_BYTES.get(dt, 0) > cap_bytes:
                        flagged.add(dt)
                        findings.append(Finding(
                            "hlo-dtype-ceiling", path, 0,
                            f"hlo-dtype-ceiling:{mode}:{dt}",
                            f"{mode}: convert to {dt} ({name}) exceeds "
                            f"the declared dtype ceiling {ceiling} — "
                            f"a quantized path is silently upcasting"))
    return findings


# ---------------------------------------------------------------------------
# The repo's mode suite: compile the per-mode flagship-shaped programs
# (softmax — the bitwise-pinnable workload every schedule test uses)
# and check each against the contract declared NEXT TO its step
# builder.  Needs a live multi-device jax backend; tools/graftlint.py
# pins CPU devices first when none are configured.

def mode_suite(bucket_bytes: int = 16 << 10) -> list[dict]:
    """Build + compile the four mode programs and return
    ``[{mode, hlo, contract, symbols, unroll}]``.  ``bucket_bytes``
    defaults small enough that softmax splits into TWO buckets, so the
    per-bucket pairing rules check a real ladder, not the B=1
    degenerate case."""
    import jax
    import optax

    from distributedtensorflowexample_tpu.data import DeviceDataset
    from distributedtensorflowexample_tpu.data.synthetic import (
        make_synthetic)
    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel import bucketing, sync
    from distributedtensorflowexample_tpu.parallel import zero3 as z3mod
    from distributedtensorflowexample_tpu.parallel.bucketing import (
        init_bucketed_opt_state, plan_buckets)
    from distributedtensorflowexample_tpu.parallel.sync import (
        make_indexed_train_step)
    from distributedtensorflowexample_tpu.parallel.zero3 import Zero3Layout
    from distributedtensorflowexample_tpu.training.state import TrainState

    mesh = make_mesh()
    x, y = make_synthetic(512, (28, 28, 1), 10, seed=0)
    mk_tx = lambda: optax.sgd(0.1, momentum=0.9)   # noqa: E731

    def state():
        return TrainState.create_sharded(build_model("softmax"), mk_tx(),
                                         (64, 28, 28, 1), 0,
                                         replicated_sharding(mesh))

    def compiled_text(step, st, ds):
        with mesh:
            return step.lower(st, ds.peek()).compile().as_text()

    s0 = state()
    leaves = jax.tree.leaves(s0.params)
    symbols = {"P": len(leaves),
               "B": len(plan_buckets(leaves, bucket_bytes))}
    ds = DeviceDataset(x, y, 64, mesh=mesh, seed=4)
    mk = dict(mesh=mesh, num_slots=ds.num_slots)
    out = []

    plain = make_indexed_train_step(64, ds.steps_per_epoch, **mk)
    out.append({"mode": "sync_dp", "hlo": compiled_text(plain, s0, ds),
                "contract": sync.HLO_CONTRACT, "symbols": symbols})

    bkt = make_indexed_train_step(64, ds.steps_per_epoch,
                                  bucket_bytes=bucket_bytes, **mk)
    out.append({"mode": "bucketed_allreduce",
                "hlo": compiled_text(bkt, state(), ds),
                "contract": bucketing.BUCKETED_HLO_CONTRACT,
                "symbols": symbols})

    z1 = make_indexed_train_step(64, ds.steps_per_epoch,
                                 bucket_bytes=bucket_bytes,
                                 bucket_shard_update=True, **mk)
    s_z1 = state()
    s_z1 = s_z1.replace(opt_state=init_bucketed_opt_state(
        mk_tx(), s_z1.params, bucket_bytes, mesh))
    out.append({"mode": "zero1", "hlo": compiled_text(z1, s_z1, ds),
                "contract": bucketing.ZERO1_HLO_CONTRACT,
                "symbols": symbols})

    s_z3 = state()
    layout = Zero3Layout(s_z3.params, bucket_bytes, mesh)
    z3 = make_indexed_train_step(64, ds.steps_per_epoch,
                                 zero3_layout=layout, **mk)
    s_z3 = s_z3.replace(opt_state=init_bucketed_opt_state(
        mk_tx(), s_z3.params, bucket_bytes, mesh))
    s_z3 = s_z3.replace(params=layout.init_rows(s_z3.params))
    out.append({"mode": "zero3", "hlo": compiled_text(z3, s_z3, ds),
                "contract": z3mod.HLO_CONTRACT,
                "symbols": dict(symbols, B=layout.num_buckets)})
    return out


def serving_suite() -> list[dict]:
    """Compile the serving decode steps (lm_tiny, a small slot/cache
    geometry — the contracts are about STRUCTURE: donation aliasing, no
    donated-parameter copy, the collective budget, the f32 ceiling;
    none of it scales with geometry) and pair each with the contract
    declared next to its step builder: the replicated engine's
    0-collective ``serve_decode`` and, on a multi-device process, the
    params-stay-sharded ``serve_decode_sharded`` whose budget is
    EXACTLY one all-gather per param bucket (symbol B resolves from the
    compiled layout's plan, the zero3 idiom)."""
    import jax
    import jax.numpy as jnp
    import optax

    from distributedtensorflowexample_tpu.models import build_model
    from distributedtensorflowexample_tpu.parallel import (
        make_mesh, replicated_sharding)
    from distributedtensorflowexample_tpu.parallel.zero3 import Zero3Layout
    from distributedtensorflowexample_tpu.serving.engine import (
        DECODE_HLO_CONTRACT, DecodeEngine)
    from distributedtensorflowexample_tpu.serving.sharded import (
        SHARDED_DECODE_HLO_CONTRACT, ShardedDecodeEngine)
    from distributedtensorflowexample_tpu.training.state import TrainState

    model = build_model("lm_tiny")
    state = TrainState.create(model, optax.sgd(0.1, momentum=0.9),
                              jnp.zeros((1, 8), jnp.int32))
    engine = DecodeEngine(model, state.params, slots=2, cache_len=16)
    out = [{"mode": "serve_decode", "hlo": engine.decode_hlo(),
            "contract": DECODE_HLO_CONTRACT, "symbols": {}}]
    if len(jax.devices()) >= 2:
        mesh = make_mesh(2)
        repl = jax.device_put(state.params, replicated_sharding(mesh))
        layout = Zero3Layout(repl, 16 << 10, mesh)
        sharded = ShardedDecodeEngine(model, layout.init_rows(repl),
                                      layout, slots=2, cache_len=16)
        out.append({"mode": "serve_decode_sharded",
                    "hlo": sharded.decode_hlo(),
                    "contract": SHARDED_DECODE_HLO_CONTRACT,
                    "symbols": {"B": layout.num_buckets}})
    return out


def run_hlo_lint(bucket_bytes: int = 16 << 10) -> list[Finding]:
    """Compile the mode suite + the serving decode step and check every
    program against its declared contract — the graftlint HLO front."""
    findings: list[Finding] = []
    for prog in mode_suite(bucket_bytes=bucket_bytes) + serving_suite():
        findings += check_contract(prog["hlo"], prog["contract"],
                                   symbols=prog["symbols"])
    return findings
