# graftlint: stdlib-only
"""Repo-invariant AST linter (the source front of graftlint).

Six rules, each a static proof of a convention the repo previously
enforced by runtime probe or reviewer memory:

* ``stdlib-only`` — whole-import-graph proof that obs/ (and any module
  tagged ``# graftlint: stdlib-only``) never reaches jax/numpy at
  import time.  Supersedes tests/test_ledger.py's per-module
  subprocess walk: the graph covers every module the probe covered AND
  says WHICH import chain breaks the contract.
* ``env-registry`` / ``env-dynamic`` / ``env-dead`` — every named
  ``os.environ`` read in the package appears in
  :mod:`analysis.env_registry` with a one-line doc; dynamic reads must
  resolve through constant call sites; registry entries nothing reads
  are dead knobs.
* ``named-refusal`` — a ``raise ValueError`` whose message names a CLI
  flag (``--token``) is a mode-legality refusal and must be a
  :class:`~distributedtensorflowexample_tpu.refusal.ModeRefusal`, so
  the whole refusal surface stays one grep.
* ``clock-seam`` — no bare ``time.time()``/``time.monotonic()``/
  ``datetime.now()`` in obs/ — nor in the control plane
  (``resilience/scheduler.py``, ``resilience/remediate.py``) —
  outside the ``obs/metrics.py`` seam (``_now``/``_wall``): the
  bitwise-flight contract says tests pin timestamps by monkeypatching
  ONE place, and sim/'s virtual clock drives the REAL scheduler +
  remediator through the same seam.
* ``keep-in-sync`` — paired ``KEEP-IN-SYNC(<id>) digest=<hex12>`` ...
  ``KEEP-IN-SYNC-END(<id>)`` regions must exist in >= 2 files and all
  carry the digest of the pair's current content, so drift between
  mirrored tables (e.g. the capture-phase tables in
  tools/bench_capture.sh vs tools/supervise.py) fails the gate
  instead of waiting for an on-chip window to expose it.
* ``engine-owns-wiring`` — the PR 19 front-end contract: raw
  step-wiring names (the ``parallel/`` step builders, worker/opt-state
  re-layout constructors, ``shard_map``) may be imported or referenced
  only under ``engine/`` and ``parallel/``; everywhere else a workload
  is a declarative RunSpec and ``engine.Engine`` owns the wiring.
  Scope: package modules plus repo-root and ``tools/`` scripts
  (``tests/`` exempt — parity tests drive the raw builders as ground
  truth on purpose).  Standing exceptions live in
  :data:`WIRING_ALLOWLIST` with one-line reasons; one-off escapes go
  through the waiver budget like every other rule.

Stdlib-only by construction (this module is itself under the
``stdlib-only`` rule via its tag).  All functions take the repo root +
package name so tests run the same rules over seeded tmp trees.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re

from distributedtensorflowexample_tpu.analysis import Finding

SRC_RULES = ("stdlib-only", "env-registry", "env-dynamic", "env-dead",
             "named-refusal", "clock-seam", "keep-in-sync",
             "engine-owns-wiring")

STDLIB_TAG = "graftlint: stdlib-only"
#: Import-time reachability to any of these fails the stdlib-only rule
#: (the jax/numpy families the subprocess probe banned, plus the other
#: third-party deps the repo carries — none may load from obs/).
BANNED_THIRD_PARTY = frozenset({
    "jax", "jaxlib", "numpy", "flax", "optax", "tensorflow", "orbax",
    "scipy", "ml_dtypes"})

_FLAG_RE = re.compile(r"--[a-z][a-z0-9_]+")
_SKIP_DIRS = frozenset({"__pycache__", ".git", ".pytest_cache", ".claude",
                        "node_modules", ".ipynb_checkpoints"})

# Built by concatenation so this module's own source never matches the
# scanner (the begin form requires a literal "(" right after the word).
_MARK_WORD = "KEEP-IN-" + "SYNC"
_MARK_BEGIN_RE = re.compile(
    _MARK_WORD + r"\(([A-Za-z0-9._\-]+)\)(?:\s+digest=([0-9a-f]{6,}))?")
_MARK_END_RE = re.compile(_MARK_WORD + r"-END\(([A-Za-z0-9._\-]+)\)")
_DIGEST_LEN = 12


def _walk_files(root: str, exts: tuple[str, ...]):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS
                             and not d.startswith("."))
        for name in sorted(filenames):
            if os.path.splitext(name)[1] in exts:
                yield os.path.join(dirpath, name)


def _rel(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


# ---------------------------------------------------------------------------
# Package model: every module parsed once, shared by the AST rules.

class _Module:
    def __init__(self, dotted: str, path: str, source: str,
                 tree: ast.AST, is_pkg: bool):
        self.dotted = dotted          # "" = the package itself
        self.path = path
        self.source = source
        self.tree = tree
        self.is_pkg = is_pkg
        # The tag must be a COMMENT LINE of its own — prose merely
        # mentioning the phrase (a docstring describing the rule) must
        # not turn a jax-importing module into a stdlib-only root.
        self.tagged = any(line.strip() == "# " + STDLIB_TAG
                          for line in source.splitlines())


def _load_package(repo_root: str, package: str) -> dict[str, _Module]:
    pkg_dir = os.path.join(repo_root, package)
    mods: dict[str, _Module] = {}
    for path in _walk_files(pkg_dir, (".py",)):
        rel = os.path.relpath(path, pkg_dir).replace(os.sep, "/")
        parts = rel[:-3].split("/")
        is_pkg = parts[-1] == "__init__"
        if is_pkg:
            parts = parts[:-1]
        dotted = ".".join(parts)
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue    # not this linter's finding to report
        mods[dotted] = _Module(dotted, path, source, tree, is_pkg)
    return mods


class _ImportCollector(ast.NodeVisitor):
    """Module-level imports only (class bodies and top-level try/if
    execute at import; function bodies are lazy and out of scope —
    exactly the boundary the subprocess probe measured)."""

    def __init__(self, package: str, mod: _Module, known: set[str]):
        self._package = package
        self._mod = mod
        self._known = known
        self.external: list[tuple[str, int]] = []   # (top name, lineno)
        self.internal: list[tuple[str, int]] = []   # (dotted, lineno)

    def visit_FunctionDef(self, node):      # noqa: N802 - ast API
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _add_internal(self, dotted: str, lineno: int) -> None:
        # Importing a.b.c executes a/__init__ and a.b/__init__ too.
        parts = dotted.split(".") if dotted else []
        for i in range(len(parts) + 1):
            anc = ".".join(parts[:i])
            if anc in self._known:
                self.internal.append((anc, lineno))

    def visit_Import(self, node):           # noqa: N802 - ast API
        for alias in node.names:
            top = alias.name.split(".")[0]
            if top == self._package:
                self._add_internal(alias.name[len(self._package) + 1:],
                                   node.lineno)
            else:
                self.external.append((top, node.lineno))

    def visit_ImportFrom(self, node):       # noqa: N802 - ast API
        if node.level:
            parts = self._mod.dotted.split(".") if self._mod.dotted else []
            pkg_parts = parts if self._mod.is_pkg else parts[:-1]
            up = node.level - 1
            base_parts = pkg_parts[:len(pkg_parts) - up] if up else pkg_parts
            base = ".".join(base_parts + (node.module.split(".")
                                          if node.module else []))
        elif node.module:
            top = node.module.split(".")[0]
            if top != self._package:
                self.external.append((top, node.lineno))
                return
            base = node.module[len(self._package) + 1:]
        else:
            return
        self._add_internal(base, node.lineno)
        for alias in node.names:
            cand = (base + "." if base else "") + alias.name
            if cand in self._known:
                self._add_internal(cand, node.lineno)


def check_stdlib_only(repo_root: str, package: str,
                      mods: dict[str, _Module] | None = None
                      ) -> list[Finding]:
    """The import-graph proof: from every stdlib-only root (obs/ plus
    tagged modules), walk intra-package module-level imports and flag
    any reachable module that imports a banned third-party name.  The
    finding message carries the chain — the part the subprocess probe
    could never say."""
    mods = mods if mods is not None else _load_package(repo_root, package)
    known = set(mods)
    imports: dict[str, _ImportCollector] = {}
    for dotted, mod in mods.items():
        col = _ImportCollector(package, mod, known)
        col.visit(mod.tree)
        imports[dotted] = col

    roots = sorted(d for d, m in mods.items()
                   if d == "obs" or d.startswith("obs.") or m.tagged)
    findings: list[Finding] = []
    seen: set[tuple[str, str]] = set()
    for root in roots:
        if root not in mods:
            continue
        parent: dict[str, str | None] = {root: None}
        queue = [root]
        while queue:
            cur = queue.pop(0)
            for name, lineno in imports[cur].external:
                if name in BANNED_THIRD_PARTY and (cur, name) not in seen:
                    seen.add((cur, name))
                    chain: list[str] = []
                    node: str | None = cur
                    while node is not None:
                        chain.append(node or package)
                        node = parent[node]
                    findings.append(Finding(
                        "stdlib-only", _rel(mods[cur].path, repo_root),
                        lineno, f"stdlib-only:{cur or package}:{name}",
                        f"stdlib-only module {chain[-1]} reaches "
                        f"third-party {name!r} at import time via "
                        f"{' <- '.join(reversed(chain))}"))
            for dep, _ in imports[cur].internal:
                if dep not in parent:
                    parent[dep] = cur
                    queue.append(dep)
    return findings


# ---------------------------------------------------------------------------
# Env registry rule.

_ENV_READ_ATTRS = frozenset({"get", "setdefault", "pop"})


class _EnvCollector(ast.NodeVisitor):
    """Collects env-knob uses, resolving the import idioms first:
    ``os.environ`` / ``os.getenv`` through any ``import os as X``
    alias, and ``from os import environ/getenv`` (with or without
    ``as``) — the same no-laundering stance the clock-seam rule takes,
    so a one-line idiom change cannot hide a knob from the registry."""

    def __init__(self, tree: ast.AST):
        self.named: list[tuple[str, int]] = []      # (VAR, lineno)
        self.dynamic: list[tuple[str, int]] = []    # (funcname, lineno)
        self._func_stack: list[str] = []
        self._os_names = {"os"}
        self._environ_names: set[str] = set()
        self._getenv_names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "os":
                        self._os_names.add(a.asname or "os")
            elif isinstance(node, ast.ImportFrom) and node.module == "os":
                for a in node.names:
                    if a.name == "environ":
                        self._environ_names.add(a.asname or a.name)
                    elif a.name == "getenv":
                        self._getenv_names.add(a.asname or a.name)

    def _is_environ(self, node) -> bool:
        if (isinstance(node, ast.Attribute) and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id in self._os_names):
            return True
        return (isinstance(node, ast.Name)
                and node.id in self._environ_names)

    def _record(self, arg, lineno: int) -> None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            self.named.append((arg.value, lineno))
        else:
            self.dynamic.append((self._func_stack[-1]
                                 if self._func_stack else "<module>",
                                 lineno))

    def visit_FunctionDef(self, node):      # noqa: N802 - ast API
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):             # noqa: N802 - ast API
        func = node.func
        if (isinstance(func, ast.Attribute)
                and func.attr in _ENV_READ_ATTRS
                and self._is_environ(func.value) and node.args):
            self._record(node.args[0], node.lineno)
        elif (isinstance(func, ast.Attribute) and func.attr == "getenv"
                and isinstance(func.value, ast.Name)
                and func.value.id in self._os_names and node.args):
            self._record(node.args[0], node.lineno)
        elif (isinstance(func, ast.Name)
                and func.id in self._getenv_names and node.args):
            self._record(node.args[0], node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):        # noqa: N802 - ast API
        if self._is_environ(node.value):
            self._record(node.slice, node.lineno)
        self.generic_visit(node)

    def visit_Compare(self, node):          # noqa: N802 - ast API
        if (len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and self._is_environ(node.comparators[0])):
            self._record(node.left, node.lineno)
        self.generic_visit(node)


def load_env_registry(repo_root: str, package: str) -> dict[str, str]:
    """Parse ``<package>/analysis/env_registry.py`` WITHOUT importing it
    (the linter must run over seeded tmp trees that are not on
    sys.path): the ENV_REGISTRY dict literal is extracted by AST."""
    path = os.path.join(repo_root, package, "analysis", "env_registry.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return {}
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "ENV_REGISTRY":
                try:
                    return ast.literal_eval(node.value)
                except ValueError:
                    return {}
    return {}


def check_env_registry(repo_root: str, package: str,
                       mods: dict[str, _Module] | None = None,
                       registry: dict[str, str] | None = None
                       ) -> list[Finding]:
    mods = mods if mods is not None else _load_package(repo_root, package)
    if registry is None:
        registry = load_env_registry(repo_root, package)

    per_mod: dict[str, _EnvCollector] = {}
    for dotted, mod in mods.items():
        col = _EnvCollector(mod.tree)
        col.visit(mod.tree)
        per_mod[dotted] = col

    # Dynamic reads resolve through their enclosing helper's constant
    # call sites anywhere in the package (obs/ledger.py's _env_float
    # pattern): _env_float("OBS_LEDGER_SAMPLE_S", 30.0) IS a read of
    # that name.  A helper no constant call site names stays a finding.
    dyn_funcs = {fn for col in per_mod.values() for fn, _ in col.dynamic
                 if fn != "<module>"}
    resolved: dict[str, list[tuple[str, str, int]]] = {f: []
                                                       for f in dyn_funcs}
    if dyn_funcs:
        for dotted, mod in mods.items():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fname = None
                if isinstance(node.func, ast.Name):
                    fname = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                if (fname in dyn_funcs
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    resolved[fname].append(
                        (node.args[0].value, dotted, node.lineno))

    findings: list[Finding] = []
    reported: set[str] = set()
    used_names: set[str] = set()

    def check_name(name: str, path: str, lineno: int) -> None:
        used_names.add(name)
        if name in registry or name in reported:
            return
        reported.add(name)
        findings.append(Finding(
            "env-registry", path, lineno, f"env-registry:{name}",
            f"env knob {name!r} is read but not declared in "
            f"analysis/env_registry.py (one line of doc, or delete the "
            f"knob)", fixable=True))

    for dotted, col in sorted(per_mod.items()):
        rel = _rel(mods[dotted].path, repo_root)
        for name, lineno in col.named:
            check_name(name, rel, lineno)
        for fn, lineno in col.dynamic:
            sites = resolved.get(fn, [])
            if sites:
                for name, site_mod, site_line in sites:
                    check_name(name, _rel(mods[site_mod].path, repo_root),
                               site_line)
            else:
                findings.append(Finding(
                    "env-dynamic", rel, lineno,
                    f"env-dynamic:{rel}:{fn}",
                    f"dynamic os.environ read in {fn}() resolves through "
                    f"no constant call site — name the knob statically "
                    f"or register the helper's call sites"))

    reg_rel = f"{package}/analysis/env_registry.py"
    for name in sorted(set(registry) - used_names):
        findings.append(Finding(
            "env-dead", reg_rel, 0, f"env-dead:{name}",
            f"registry entry {name!r} is read by no package code — a "
            f"dead knob; delete the entry (and any docs)"))
    return findings


# ---------------------------------------------------------------------------
# Named-refusal rule.

def _raise_message_text(call: ast.Call) -> str:
    parts: list[str] = []
    for node in ast.walk(call):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            parts.append(node.value)
    return "".join(parts)


def check_named_refusal(repo_root: str, package: str,
                        mods: dict[str, _Module] | None = None
                        ) -> list[Finding]:
    mods = mods if mods is not None else _load_package(repo_root, package)
    findings: list[Finding] = []
    for dotted in sorted(mods):
        mod = mods[dotted]
        rel = _rel(mod.path, repo_root)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Raise)
                    and isinstance(node.exc, ast.Call)
                    and isinstance(node.exc.func, ast.Name)
                    and node.exc.func.id == "ValueError"):
                continue
            text = _raise_message_text(node.exc)
            m = _FLAG_RE.search(text)
            if not m:
                continue
            digest = hashlib.sha256(text.encode()).hexdigest()[:8]
            findings.append(Finding(
                "named-refusal", rel, node.lineno,
                f"named-refusal:{rel}:{digest}",
                f"mode-legality refusal names {m.group(0)} but raises "
                f"bare ValueError — raise refusal.ModeRefusal so the "
                f"refusal surface stays one grep"))
    return findings


# ---------------------------------------------------------------------------
# Clock-seam rule (obs/ plus the seam-consuming control plane).

_CLOCK_FUNCS = frozenset({"time", "monotonic", "perf_counter",
                          "monotonic_ns", "time_ns"})
_NOW_FUNCS = frozenset({"now", "utcnow", "today"})
#: Modules outside obs/ that the sim's virtual clock must fully own —
#: the scheduler and remediator make every decision through
#: obs/metrics._now/_wall (their ``_sleep = time.sleep`` module seams
#: are assignments, not calls, so the rule never flags the seams
#: themselves).
_CLOCK_SEAM_EXTRA = frozenset({
    "resilience.scheduler", "resilience.remediate"})


def check_clock_seam(repo_root: str, package: str,
                     mods: dict[str, _Module] | None = None
                     ) -> list[Finding]:
    mods = mods if mods is not None else _load_package(repo_root, package)
    findings: list[Finding] = []
    for dotted in sorted(mods):
        # obs/ plus the control-plane modules sim/'s virtual clock must
        # fully own: one bare read in a decision path and two same-seed
        # simulator runs stop being bitwise-identical.
        if not (dotted == "obs" or dotted.startswith("obs.")
                or dotted in _CLOCK_SEAM_EXTRA):
            continue
        if dotted == "obs.metrics":     # the seam's home
            continue
        mod = mods[dotted]
        rel = _rel(mod.path, repo_root)
        # Aliases don't launder the clock: `import time as t` /
        # `from time import time as _t` bind local names that resolve
        # back to the module/function they came from before matching.
        mod_alias: dict[str, str] = {}      # local name -> clock module
        bound: dict[str, str] = {}          # local name -> original func
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in ("time", "datetime"):
                        mod_alias[a.asname or a.name] = a.name
            elif (isinstance(node, ast.ImportFrom)
                    and node.module in ("time", "datetime")):
                for a in node.names:
                    bound[a.asname or a.name] = a.name
        count = 0
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            parts: list[str] = []
            f = node.func
            root_bound = False
            while isinstance(f, ast.Attribute):
                parts.append(f.attr)
                f = f.value
            if isinstance(f, ast.Name):
                # Resolve the root through both alias tables: `import
                # time as t` and `from datetime import datetime as dt`
                # must match as their originals; a same-named LOCAL
                # helper (no time/datetime import behind it) must not.
                root_bound = f.id in mod_alias or f.id in bound
                parts.append(mod_alias.get(f.id) or bound.get(f.id)
                             or f.id)
            parts.reverse()
            dotted_call = ".".join(parts)
            bad = False
            if len(parts) >= 2 and parts[-2] == "time" \
                    and parts[-1] in _CLOCK_FUNCS:
                bad = True
            elif parts and parts[-1] in _NOW_FUNCS \
                    and any(p in ("datetime", "date") for p in parts[:-1]):
                bad = True
            elif len(parts) == 1 and root_bound and parts[0] in (
                    _CLOCK_FUNCS | _NOW_FUNCS):
                bad = True
            if bad:
                count += 1
                findings.append(Finding(
                    "clock-seam", rel, node.lineno,
                    f"clock-seam:{rel}:{dotted_call}:{count}",
                    f"bare {dotted_call}() in {dotted} — go through "
                    f"the obs/metrics.py seam (_now/_wall) so flight "
                    f"dumps and sim runs stay bitwise-pinnable"))
    return findings


# ---------------------------------------------------------------------------
# Keep-in-sync digest markers.

class _SyncBlock:
    def __init__(self, path: str, marker_line: int, ident: str,
                 digest: str | None):
        self.path = path                # absolute
        self.marker_line = marker_line  # 1-based line of the BEGIN marker
        self.ident = ident
        self.digest = digest
        self.body: list[str] = []
        self.closed = False


def _norm_sync_line(line: str) -> str | None:
    s = line.strip()
    for prefix in ("#", "//"):
        if s.startswith(prefix):
            s = s[len(prefix):].strip()
    return s or None


def collect_sync_blocks(repo_root: str) -> tuple[list[_SyncBlock],
                                                 list[Finding]]:
    blocks: list[_SyncBlock] = []
    findings: list[Finding] = []
    for path in _walk_files(repo_root, (".py", ".sh", ".md")):
        rel = _rel(path, repo_root)
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except (OSError, UnicodeDecodeError):
            continue
        open_block: _SyncBlock | None = None
        for i, line in enumerate(lines, 1):
            me = _MARK_END_RE.search(line)
            if me:
                if open_block is None or open_block.ident != me.group(1):
                    findings.append(Finding(
                        "keep-in-sync", rel, i,
                        f"keep-in-sync:{me.group(1)}:stray-end",
                        f"{_MARK_WORD}-END({me.group(1)}) without a "
                        f"matching begin marker"))
                else:
                    open_block.closed = True
                    blocks.append(open_block)
                    open_block = None
                continue
            mb = _MARK_BEGIN_RE.search(line)
            if mb:
                if open_block is not None:
                    findings.append(Finding(
                        "keep-in-sync", rel, open_block.marker_line,
                        f"keep-in-sync:{open_block.ident}:unterminated",
                        f"{_MARK_WORD}({open_block.ident}) never "
                        f"terminated before the next marker"))
                open_block = _SyncBlock(path, i, mb.group(1), mb.group(2))
                continue
            if open_block is not None:
                open_block.body.append(line)
        if open_block is not None:
            findings.append(Finding(
                "keep-in-sync", rel, open_block.marker_line,
                f"keep-in-sync:{open_block.ident}:unterminated",
                f"{_MARK_WORD}({open_block.ident}) never terminated"))
    return blocks, findings


def _expected_digest(group: list[_SyncBlock], repo_root: str) -> str:
    group = sorted(group, key=lambda b: (_rel(b.path, repo_root),
                                         b.marker_line))
    h = hashlib.sha256()
    for b in group:
        h.update(_rel(b.path, repo_root).encode())
        h.update(b"\x01")
        for line in b.body:
            norm = _norm_sync_line(line)
            if norm is not None:
                h.update(norm.encode())
                h.update(b"\n")
        h.update(b"\x00")
    return h.hexdigest()[:_DIGEST_LEN]


def check_keep_in_sync(repo_root: str) -> list[Finding]:
    blocks, findings = collect_sync_blocks(repo_root)
    by_id: dict[str, list[_SyncBlock]] = {}
    for b in blocks:
        by_id.setdefault(b.ident, []).append(b)
    for ident in sorted(by_id):
        group = by_id[ident]
        if len(group) < 2:
            b = group[0]
            findings.append(Finding(
                "keep-in-sync", _rel(b.path, repo_root), b.marker_line,
                f"keep-in-sync:{ident}:unpaired",
                f"{_MARK_WORD}({ident}) has no partner block — the "
                f"marker exists to pair mirrored regions across files"))
            continue
        want = _expected_digest(group, repo_root)
        for b in group:
            rel = _rel(b.path, repo_root)
            if b.digest is None:
                findings.append(Finding(
                    "keep-in-sync", rel, b.marker_line,
                    f"keep-in-sync:{ident}:{os.path.basename(rel)}",
                    f"{_MARK_WORD}({ident}) carries no digest= — run "
                    f"tools/graftlint.py --fix to stamp {want}",
                    fixable=True))
            elif b.digest != want:
                findings.append(Finding(
                    "keep-in-sync", rel, b.marker_line,
                    f"keep-in-sync:{ident}:{os.path.basename(rel)}",
                    f"{_MARK_WORD}({ident}) digest {b.digest} != current "
                    f"pair content {want}: the mirrored regions drifted "
                    f"— re-sync them, then --fix to re-stamp",
                    fixable=True))
    return findings


# ---------------------------------------------------------------------------
# Engine-owns-wiring rule (PR 19).

#: Raw step-wiring vocabulary: the ``parallel/`` step builders, the
#: async worker / bucketed-opt / ZeRO-3 state re-layout constructors,
#: and ``shard_map`` itself.  Importing or attribute-referencing any of
#: these outside ``engine/``+``parallel/`` is a fork of the Engine's
#: wiring (``make_mesh``/``create_sharded`` stay legal everywhere:
#: ``Engine.build`` accepts a caller-built mesh by design).
WIRING_NAMES = frozenset({
    "make_train_step", "make_indexed_train_step", "make_async_train_step",
    "make_indexed_async_train_step", "build_bucketed_step_fn",
    "make_worker_state", "init_bucketed_opt_state", "Zero3Layout",
    "shard_map"})

#: Standing, reviewed exceptions (repo-relative path -> why raw wiring
#: is that file's JOB, not a missed port).  Anything else that needs an
#: escape goes through the waiver budget and therefore ratchets.
WIRING_ALLOWLIST = {
    "distributedtensorflowexample_tpu/compat.py":
        "defines the shard_map version shim the ban protects",
    "distributedtensorflowexample_tpu/ops/pallas/sgd.py":
        "fused-optimizer kernel launch idiom — per-device pallas "
        "dispatch under shard_map, not trainer wiring",
    "distributedtensorflowexample_tpu/serving/sharded.py":
        "sharded decode programs declare their own HLO contracts "
        "(DESIGN.md §25) — serving's analogue of parallel/",
    "distributedtensorflowexample_tpu/serving/promote.py":
        "row promotion rides the Zero3Layout init_rows/materialize "
        "seam; the training-template re-layout already goes through "
        "engine.apply_update_layout",
    "distributedtensorflowexample_tpu/analysis/hlo_lint.py":
        "the contract checker compiles the raw builders on purpose",
    "__graft_entry__.py":
        "driver compile-check entry: exercises the raw step builders "
        "as the pre-Engine dry-run surface",
    "bench_collectives.py":
        "raw-collective microbench — measures shard_map collectives "
        "themselves, beneath any trainer",
    "bench_serving.py":
        "builds row-layout serving fixtures for the decode bench",
    "tools/faultline.py":
        "fault-injection drills drive a minimal raw step on purpose",
}


def check_engine_owns_wiring(repo_root: str, package: str,
                             mods: dict[str, _Module] | None = None
                             ) -> list[Finding]:
    """Flag imports/attribute references of :data:`WIRING_NAMES`
    outside ``engine/`` and ``parallel/`` — package modules plus
    repo-root and ``tools/`` scripts (function-level imports count:
    lazy wiring is still wiring).  Docstrings mentioning the names
    never match (AST, not grep)."""
    mods = mods if mods is not None else _load_package(repo_root, package)
    targets: list[tuple[str, ast.AST]] = []
    for dotted in sorted(mods):
        if dotted.split(".")[0] in ("engine", "parallel"):
            continue
        targets.append((_rel(mods[dotted].path, repo_root),
                        mods[dotted].tree))
    for sub in ("", "tools"):
        d = os.path.join(repo_root, sub) if sub else repo_root
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            path = os.path.join(d, name)
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError):
                continue
            targets.append((_rel(path, repo_root), tree))

    findings: list[Finding] = []
    for rel, tree in targets:
        if rel in WIRING_ALLOWLIST:
            continue
        seen: set[str] = set()

        def hit(name: str, lineno: int, rel=rel, seen=seen) -> None:
            if name in seen:
                return
            seen.add(name)
            findings.append(Finding(
                "engine-owns-wiring", rel, lineno,
                f"engine-owns-wiring:{rel}:{name}",
                f"raw step-wiring name {name!r} referenced outside "
                f"engine/ and parallel/ — declare a RunSpec and let "
                f"engine.Engine own the wiring (standing exceptions: "
                f"src_lint.WIRING_ALLOWLIST)"))

        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name.split(".")[-1] in WIRING_NAMES:
                        hit(a.name.split(".")[-1], node.lineno)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[-1] in WIRING_NAMES:
                        hit(a.name.split(".")[-1], node.lineno)
            elif isinstance(node, ast.Attribute):
                if node.attr in WIRING_NAMES:
                    hit(node.attr, node.lineno)
    return findings


# ---------------------------------------------------------------------------
# Driver + mechanical fixes.

def run_src_lint(repo_root: str,
                 package: str = "distributedtensorflowexample_tpu",
                 registry: dict[str, str] | None = None,
                 rules: tuple[str, ...] | None = None) -> list[Finding]:
    """Run the source front; returns findings sorted (rule, path, line).
    ``rules`` narrows (default: all of :data:`SRC_RULES`)."""
    active = set(rules if rules is not None else SRC_RULES)
    mods = _load_package(repo_root, package)
    findings: list[Finding] = []
    if "stdlib-only" in active:
        findings += check_stdlib_only(repo_root, package, mods)
    if active & {"env-registry", "env-dynamic", "env-dead"}:
        env = check_env_registry(repo_root, package, mods, registry)
        findings += [f for f in env if f.rule in active]
    if "named-refusal" in active:
        findings += check_named_refusal(repo_root, package, mods)
    if "clock-seam" in active:
        findings += check_clock_seam(repo_root, package, mods)
    if "keep-in-sync" in active:
        findings += check_keep_in_sync(repo_root)
    if "engine-owns-wiring" in active:
        findings += check_engine_owns_wiring(repo_root, package, mods)
    findings.sort(key=lambda f: (f.rule, f.path, f.line))
    return findings


def fix_env_registry(repo_root: str, package: str,
                     names: list[str]) -> list[str]:
    """Insert TODO-doc stubs for *names* into env_registry.py (creates
    the file if the seeded tree lacks one).  Mechanical on purpose: the
    stub lints clean so --fix converges, and the TODO text is the
    reviewer's cue to write the real one-liner."""
    if not names:
        return []
    path = os.path.join(repo_root, package, "analysis", "env_registry.py")
    if not os.path.exists(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write('"""Env-knob registry (graftlint --fix seeded)."""\n\n'
                    "ENV_REGISTRY: dict[str, str] = {\n}\n")
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines(keepends=True)
    stubs = [f'    "{n}": (\n        "TODO: document this knob '
             f'(inserted by graftlint --fix)."),\n'
             for n in sorted(names)]
    # Anchor on the ENV_REGISTRY assignment itself, not the file's
    # last bare brace: the registry may not be the file's final
    # structure, and a one-liner `= {}` form has no bare-brace line.
    start = next((i for i, ln in enumerate(lines)
                  if ln.lstrip().startswith("ENV_REGISTRY")), None)
    if start is None:
        return [f"env-registry: could not find ENV_REGISTRY in {path} "
                f"— add entries for {', '.join(sorted(names))} by hand"]
    if "{}" in lines[start]:
        lines[start] = lines[start].replace(
            "{}", "{\n" + "".join(stubs) + "}", 1)
    else:
        close = next((i for i in range(start, len(lines))
                      if lines[i].rstrip() == "}"), None)
        if close is None:
            return [f"env-registry: could not find the closing brace "
                    f"of ENV_REGISTRY in {path} — add entries for "
                    f"{', '.join(sorted(names))} by hand"]
        lines[close:close] = stubs
    with open(path, "w", encoding="utf-8") as f:
        f.write("".join(lines))
    return [f"env-registry: stubbed {n} in {package}/analysis/"
            f"env_registry.py" for n in sorted(names)]


def fix_keep_in_sync(repo_root: str) -> list[str]:
    """Re-stamp every paired marker group's digest to its current pair
    content.  Only the ``digest=`` token on the BEGIN line changes."""
    blocks, _ = collect_sync_blocks(repo_root)
    by_id: dict[str, list[_SyncBlock]] = {}
    for b in blocks:
        by_id.setdefault(b.ident, []).append(b)
    applied: list[str] = []
    by_path: dict[str, list[tuple[_SyncBlock, str]]] = {}
    for ident in sorted(by_id):
        group = by_id[ident]
        if len(group) < 2:
            continue
        want = _expected_digest(group, repo_root)
        for b in group:
            if b.digest != want:
                by_path.setdefault(b.path, []).append((b, want))
    for path, edits in by_path.items():
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines(keepends=True)
        for b, want in edits:
            i = b.marker_line - 1
            line = lines[i]
            marker = f"{_MARK_WORD}({b.ident})"
            if b.digest is not None:
                line = line.replace(f"{marker} digest={b.digest}",
                                    f"{marker} digest={want}", 1)
            else:
                line = line.replace(marker, f"{marker} digest={want}", 1)
            lines[i] = line
            applied.append(f"keep-in-sync: {b.ident} digest={want} in "
                           f"{_rel(path, repo_root)}")
        with open(path, "w", encoding="utf-8") as f:
            f.write("".join(lines))
    return applied


def apply_fixes(repo_root: str,
                package: str = "distributedtensorflowexample_tpu",
                findings: list[Finding] | None = None) -> list[str]:
    """The --fix entry point: registry stubs + marker digest refresh
    (the two mechanical rules).  Returns human-readable descriptions;
    run the lint again afterwards — the contract is that the result
    re-lints clean."""
    if findings is None:
        findings = run_src_lint(repo_root, package)
    missing = sorted({f.key.split(":", 1)[1] for f in findings
                      if f.rule == "env-registry" and f.fixable})
    out = fix_env_registry(repo_root, package, missing)
    out += fix_keep_in_sync(repo_root)
    return out
