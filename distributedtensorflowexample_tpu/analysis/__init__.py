# graftlint: stdlib-only
"""graftlint — two-front static analysis for repo invariants (PR 13).

The repo's load-bearing conventions were, until this package, enforced
by runtime probes and reviewer memory: obs/ stays importable without
jax (a subprocess probe), the ZeRO collective schedules are pinned only
by runtime golden multisets, env knobs and refusal messages and
keep-in-sync comments are folklore.  This package turns each into a
machine-checked contract:

* :mod:`.src_lint` — stdlib-only AST rules over the source tree
  (import-graph stdlib-only proof, env-var registry, named refusals,
  the obs wall-clock seam, KEEP-IN-SYNC digest markers).
* :mod:`.hlo_lint` — declarative contracts over compiled-HLO text
  (AG/RS pairing and ordering, collective op budgets, donation
  aliasing, dtype ceilings), reusing ``utils/profiling.py``'s
  ENTRY-walk.  Imported lazily: it pulls jax, this package root must
  not.
* :mod:`.env_registry` — the declared env-knob surface the env rule
  checks reads against (and dead entries out of).

Findings flow through a checked-in waiver file
(``analysis/waivers.json``, every waiver dated + reasoned) so the gate
starts green and only ratchets; ``tools/graftlint.py`` is the CLI and
tier-1 runs it via the ``lint`` marker (tests/test_analysis.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

#: Hard cap on checked-in waivers — the gate ratchets toward zero, it
#: does not accumulate exemptions (ISSUE 12 acceptance: <= 5, dated).
WAIVER_BUDGET = 5

_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}$")


@dataclasses.dataclass
class Finding:
    """One lint finding, from either front.

    ``key`` is the stable waiver-match identity — rule plus a content
    token (env name, marker id, message digest), never a line number,
    so waivers survive unrelated edits.  ``fixable`` marks findings
    ``tools/graftlint.py --fix`` can mend mechanically.
    """

    rule: str
    path: str           # repo-relative (or "<hlo:mode>" for contracts)
    line: int
    key: str
    message: str
    fixable: bool = False

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def waivers_path(repo_root: str,
                 package: str = "distributedtensorflowexample_tpu") -> str:
    return os.path.join(repo_root, package, "analysis", "waivers.json")


def load_waivers(path: str) -> tuple[list[dict], list[Finding]]:
    """Read + validate the waiver file.  Malformed waivers are
    themselves findings (rule ``waiver-invalid``) — a waiver that
    doesn't say who/when/why is exactly the folklore this gate exists
    to end.  A missing file is an empty waiver set, never an error
    (the gate must run on seeded tmp trees)."""
    findings: list[Finding] = []
    rel = os.path.basename(path)
    try:
        with open(path) as f:
            payload = json.load(f)
    except FileNotFoundError:
        return [], []
    except (OSError, json.JSONDecodeError) as e:
        return [], [Finding("waiver-invalid", rel, 0,
                            "waiver-invalid:file",
                            f"waiver file unreadable: {e}")]
    waivers = payload.get("waivers", [])
    good: list[dict] = []
    for i, w in enumerate(waivers):
        missing = [k for k in ("key", "reason", "date")
                   if not isinstance(w.get(k), str) or not w.get(k)]
        if missing:
            findings.append(Finding(
                "waiver-invalid", rel, 0, f"waiver-invalid:{i}",
                f"waiver #{i} missing {'/'.join(missing)} "
                f"(every waiver is dated + reasoned): {w!r}"))
            continue
        if not _DATE_RE.match(w["date"]):
            findings.append(Finding(
                "waiver-invalid", rel, 0, f"waiver-invalid:{i}",
                f"waiver #{i} date {w['date']!r} is not YYYY-MM-DD"))
            continue
        good.append(w)
    if len(good) > WAIVER_BUDGET:
        findings.append(Finding(
            "waiver-budget", rel, 0, "waiver-budget",
            f"{len(good)} waivers exceed the budget of {WAIVER_BUDGET} "
            f"— fix findings instead of accumulating exemptions"))
    return good, findings


def apply_waivers(findings: list[Finding], waivers: list[dict],
                  ran_rules: set[str] | None = None,
                  waiver_file: str = "waivers.json",
                  ) -> tuple[list[Finding], list[Finding], list[Finding]]:
    """Split *findings* into (unwaived, waived) and flag stale waivers.

    A waiver matches a finding by exact ``key`` equality.  A waiver
    whose key matches nothing is STALE (rule ``waiver-stale``, itself
    unwaivable) — the ratchet: once a finding is fixed its waiver must
    leave the file.  Staleness is only judged for rules that actually
    ran (``ran_rules``; None = all), so a src-only run never flags hlo
    waivers."""
    by_key = {w["key"]: w for w in waivers}
    unwaived, waived = [], []
    used: set[str] = set()
    for f in findings:
        if f.key in by_key:
            waived.append(f)
            used.add(f.key)
        else:
            unwaived.append(f)
    stale: list[Finding] = []
    for key, w in by_key.items():
        if key in used:
            continue
        rule = key.split(":", 1)[0]
        if ran_rules is not None and rule not in ran_rules:
            continue
        stale.append(Finding(
            "waiver-stale", waiver_file, 0, f"waiver-stale:{key}",
            f"waiver {key!r} ({w['date']}: {w['reason']}) matches no "
            f"current finding — delete it (the gate ratchets)"))
    return unwaived, waived, stale
