# graftlint: stdlib-only
"""The declared environment-knob surface of the package.

Every ``os.environ`` read (or write) of a named knob inside
``distributedtensorflowexample_tpu/`` must have an entry here with a
one-line doc — ``analysis/src_lint.py``'s ``env-registry`` rule proves
it, and the reverse rule (``env-dead``) flags entries no code reads any
more, so this file can neither under- nor over-state the real surface.
``tools/graftlint.py --fix`` inserts ``TODO: document`` stubs for new
knobs; replace the stub with a real one-liner before merging.

Operator-facing knobs are additionally documented in README.md;
supervisor-exported coordination variables (SUPERVISE_*/OBS_RANK/...)
are documented where they are exported.  Keys sorted alphabetically.
"""

from __future__ import annotations

ENV_REGISTRY: dict[str, str] = {
    "BUCKET_GRADS_AUTO_BYTES": (
        "Overrides --bucket_grads auto's measured-knee bucket size "
        "(bytes) without a code change after a chip re-fit "
        "(parallel/bucketing.py)."),
    "DISTTF_TPU_QUIET_SYNTHETIC": (
        "1 = suppress the loud synthetic-fallback warning when a real "
        "dataset is absent (data/synthetic.py; CI noise control)."),
    "DTFE_NATIVE_CACHE": (
        "Build/cache directory for the native C++ dataio extension "
        "(native/loader.py; default: a per-user temp dir)."),
    "FLEET_DRILL_DIE_IN_DISCARD": (
        "Drill seam: rank to SIGKILL mid-discard so the interrupted-"
        "agreement replay path stays tested (resilience/fleet.py)."),
    "FLEET_HOST_DOWN_FILE": (
        "Per-rank host-loss tombstone path (exported by the fleet "
        "supervisor): the host_loss fault writes it and the next spawn "
        "of that rank fails like a dead host (resilience/faults.py, "
        "resilience/fleet.py)."),
    "HEAL_ACTION_BUDGET": (
        "Global remediation-actions ceiling per remediator JOURNAL "
        "(WAL replay restores the spent count; a new journal resets "
        "it); exhaustion degrades to detection-only with a loud "
        "heal_budget_exhausted ledger row "
        "(resilience/remediate.py; default 8)."),
    "HEAL_CANARY_FRACTION": (
        "Share of serving requests routed to a canary candidate while "
        "it proves itself (serving/promote.py; default 0.25)."),
    "HEAL_CANARY_P99_RATIO": (
        "Canary p99 over this multiple of the baseline arm's p99 = "
        "regression, auto-rollback (serving/promote.py; default 2.0)."),
    "HEAL_CANARY_WINDOW": (
        "Canary-arm completions required before a promote/rollback "
        "verdict (serving/promote.py; default 16)."),
    "HEAL_COOLDOWN_S": (
        "Per-(kind, scope) quiet period after a remediation action — "
        "the action-storm guard (resilience/remediate.py; default 30)."),
    "HEAL_DRY_RUN": (
        "1 = remediation commissioning mode: journal heal_dry_run rows "
        "naming what WOULD fire, run no actuator "
        "(resilience/remediate.py)."),
    "HEAL_FLAP_N": (
        "Detections of one (kind, scope) inside the flap window before "
        "a remediation policy may act — a one-poll blip never reaches "
        "an actuator (resilience/remediate.py; default 2)."),
    "HEAL_FLAP_WINDOW_S": (
        "The flap-damping window in seconds "
        "(resilience/remediate.py; default 60)."),
    "HEAL_LR_DROP": (
        "1 = experimental: map loss_plateau to the LR-drop advisory "
        "stub instead of gang rollback — the actuator writes an "
        "advisory file a future trainer LR hook consumes "
        "(resilience/remediate.py)."),
    "OBS_ANOMALY_SKIP": (
        "Steps ignored at window start before the anomaly baseline "
        "arms (obs/anomaly.py; default 1 — the compile step)."),
    "OBS_ANOMALY_WARMUP": (
        "Steps used to pin the anomaly detector's step-time baseline "
        "(obs/anomaly.py; default 16)."),
    "OBS_ANOMALY_Z": (
        "EWMA z-score threshold before a step time is flagged anomalous "
        "(obs/anomaly.py; default 8.0)."),
    "OBS_COLLECTIVES": (
        "1 = pay one extra AOT compile to record the collective "
        "inventory of the live step (trainers/common.py)."),
    "OBS_DIR": (
        "Directory flight-recorder postmortems land in "
        "(obs/recorder.py; default: the system temp dir)."),
    "OBS_FLIGHT": (
        "1/true = arm the always-on flight recorder: span ring + "
        "counters + loss tail dumped on exit/signal (obs/recorder.py)."),
    "OBS_HEALTH": (
        "Path of the health heartbeat file the serve thread falls back "
        "to when HTTP is down (obs/serve.py; exported per rank by "
        "supervise_fleet)."),
    "OBS_HTTP_PORT": (
        "Port for the in-process /metrics + /health + /ledger scrape "
        "endpoint; unset/empty = no server (obs/serve.py)."),
    "OBS_LEDGER": (
        "Path of the append-only cross-run RUNS.jsonl ledger; "
        "unset/empty = no ledger (obs/ledger.py)."),
    "OBS_LEDGER_MAX_BYTES": (
        "Ledger size-rotation threshold in bytes (obs/ledger.py; "
        "default 8 MiB)."),
    "OBS_LEDGER_SAMPLE_S": (
        "Minimum seconds between sampled ledger metric rows "
        "(obs/ledger.py; default 30)."),
    "OBS_PHASE": (
        "Capture-phase label stamped on obs events/rows (exported by "
        "the supervisor's capture queue; obs/trace.py, obs/ledger.py)."),
    "OBS_PROM_DIR": (
        "Directory for node-exporter textfile-collector .prom dumps "
        "refreshed per completed supervised task "
        "(resilience/supervisor.py)."),
    "OBS_RANK": (
        "Process rank label for multi-process telemetry files/rows "
        "(exported by fleet/multi-host init; obs/*, trainers/common.py)."),
    "OBS_TRACE_FILE": (
        "Path to append per-process span events (JSONL) for the "
        "cross-rank timeline merge; unset = no trace (obs/trace.py)."),
    "SCHED_DRILL_DIE_AT": (
        "Drill seam: SIGKILL the scheduler right after it journals a "
        "matching record (substring of 'event:action:job'), so the "
        "write-ahead replay path stays tested "
        "(resilience/scheduler.py)."),
    "SCHED_QUEUE": (
        "Default queue file for tools/schedule.py when --queue is not "
        "passed (resilience/scheduler.py)."),
    "SCHED_SLO_PRIORITIES": (
        "Per-kind SLO priority overrides for the scheduler, "
        "'kind=int,...' (lower = more urgent; default serve=0 train=10 "
        "bench=20 drill=30; resilience/scheduler.py)."),
    "SCHED_TICK_S": (
        "Scheduler policy-loop cadence in seconds — the latency floor "
        "on every reap/evict/grow/admit decision "
        "(resilience/scheduler.py; default 0.25)."),
    "SERVE_LOAD_CLIENTS": (
        "Default closed-loop client thread count for serve_lm --drive "
        "and bench_serving.py sweeps (serving/loadgen.py; default 2)."),
    "SERVE_LOAD_REQUESTS": (
        "Default request count one drive/bench point issues "
        "(serving/loadgen.py; default 16)."),
    "SERVE_PORT": (
        "Request-front port for the serving worker's POST /generate + "
        "GET /stats HTTP API; 0/unset = in-process only "
        "(serving/frontend.py — distinct from OBS_HTTP_PORT, the "
        "read-only telemetry scrape)."),
    "SERVE_SLO_MS": (
        "End-to-end latency SLO in ms driving serving admission: a "
        "queued request predicted to finish past it is rejected loudly "
        "instead of admitted to miss; 0 = admit everything "
        "(serving/queue.py)."),
    "SERVE_SLOTS": (
        "Default concurrent decode slots for the serving worker "
        "(serving/engine.py; default 4)."),
    "SERVE_SNAPSHOT": (
        "Default SnapshotStore directory tools/serve_lm.py and "
        "bench_serving.py promote when --snapshot is not passed "
        "(serving/promote.py)."),
    "SIM_MAX_VIRTUAL_S": (
        "Hard ceiling on total virtual seconds one sim run may "
        "advance — a livelocked scenario (eviction ping-pong, a gate "
        "that never opens) dies loudly at the cap instead of pumping "
        "the event queue forever (sim/harness.py; default 10x the "
        "scenario horizon)."),
    "SIM_TEARDOWN_S": (
        "Default request_stop -> unanimous-143 teardown latency for "
        "simulated gangs when the scenario's per-job sim knobs don't "
        "script one — stretch it to drill slow-drain eviction windows "
        "(sim/fleet.py; default 1.0)."),
    "SNAPSHOT_DIR": (
        "Shard-redundant snapshot directory the engine wires a "
        "ShardSnapshotHook + elastic restore into when the update "
        "layout is a row layout (engine/engine.py; unset = Orbax "
        "checkpoints only)."),
    "SNAPSHOT_IO_BACKOFF_S": (
        "First retry backoff for a failed shard-payload write, "
        "doubling per retry (resilience/shardstore.py; default 0.05)."),
    "SNAPSHOT_IO_RETRIES": (
        "Bounded retries per shard-payload write before the save "
        "raises (resilience/shardstore.py; default 2)."),
    "SNAPSHOT_REDUNDANCY": (
        "Copies of every shard in a shard-redundant snapshot set: 1 "
        "own + R-1 ring mirrors, so any R-1 shard losses reconstruct "
        "and R refuse loudly (resilience/shardstore.py, mirrored by "
        "the sim's snapshot_loss world model in sim/fleet.py; "
        "default 2)."),
    "SUPERVISE_ATTEMPT": (
        "Attempt number of the supervised child, exported by the "
        "supervisor so obs rows carry retry provenance (obs/*)."),
    "SUPERVISE_HEARTBEAT": (
        "Heartbeat file path the supervised child touches per step; "
        "the watchdog kills on staleness (trainers/common.py, "
        "resilience/faults.py, obs/recorder.py)."),
    "SUPERVISE_HEARTBEAT_TIMEOUT_S": (
        "The watchdog's staleness edge in seconds, exported to "
        "children so the heartbeat_flap drill can aim at it "
        "(resilience/faults.py)."),
    "TF_CONFIG": (
        "Reference-compatible cluster topology JSON; parsed for "
        "process count/index compatibility, topology itself is "
        "jax.distributed's job (cluster.py)."),
    "XLA_FLAGS": (
        "XLA backend flags; compat.py appends version-gated CPU "
        "collective rendezvous flags in-process (read + write)."),
}
