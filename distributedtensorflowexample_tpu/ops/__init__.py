from distributedtensorflowexample_tpu.ops.losses import (
    softmax_cross_entropy, accuracy,
)

__all__ = ["softmax_cross_entropy", "accuracy"]
