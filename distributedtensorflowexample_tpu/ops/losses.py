"""Loss/metric math shared by every trainer.

The reference computed ``tf.nn.softmax_cross_entropy_with_logits`` + an
accuracy eval op per script [RECONSTRUCTED]; here they are pure jnp
functions.  The mean over the batch axis is the point where XLA inserts the
cross-replica psum under data parallelism — no explicit collective code.

Everything here runs in f32 on [B, C]-sized tensors by design: the models
upcast logits at their boundary for loss stability, and the PR-2 bytes
audit (BASELINE.md "bytes-attribution methodology") measured the whole
loss path at ~10 KB/step on the flagship workload — downcasting it to
bf16 would trade numerics for nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy_rows(logits: jnp.ndarray, labels: jnp.ndarray,
                               label_smoothing: float = 0.0) -> jnp.ndarray:
    """Per-example cross-entropy [B] from int labels; logits [B,C]."""
    num_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, num_classes, dtype=logits.dtype)
    if label_smoothing > 0.0:
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / num_classes
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(onehot * log_probs, axis=-1)


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                          label_smoothing: float = 0.0) -> jnp.ndarray:
    """Mean cross-entropy from int labels. logits [B,C] f32, labels [B] int."""
    return jnp.mean(softmax_cross_entropy_rows(logits, labels, label_smoothing))


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
