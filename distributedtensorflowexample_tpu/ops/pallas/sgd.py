"""Fused momentum-SGD update Pallas kernel (the per-step optimizer apply).

The reference's update was a native TF ``ApplyMomentum`` op per variable
(library C++, SURVEY.md §2 native-dependency table).  This kernel is the
TPU equivalent: one VMEM pass computes

    m_new = mu * m + g          (optax.sgd(momentum=mu) trace semantics)
    p_new = p - lr * m_new

over the WHOLE parameter set at once.  Every leaf is packed into a single
flat (rows, 128) f32 buffer — the momentum trace lives flat in the
optimizer state, params/grads are flattened per step — so the apply is ONE
``pallas_call`` regardless of how many parameter tensors the model has
(ResNet-20 has ~65; the round-1 per-leaf version launched ~65 kernels plus
per-leaf pad/unpad traffic per step).  ``input_output_aliases`` lets XLA
reuse the flat operands' buffers for the outputs.  ``lr`` arrives as a
traced (1, 1) SMEM scalar so LR schedules stay dynamic; ``mu`` is
compile-time static.

Segment boundaries inside the flat buffer need no masking: the pad tail's
gradient is zero, so its momentum stays zero and its params stay put.

MEASURED ON-CHIP (v5e, round 2 — BASELINE.md): 675 steps/s vs 1,543 for
the XLA apply on the same MNIST-CNN window — a 2.3x net slowdown.  The
single kernel launch is cheap; what XLA never pays is the per-step
``_flatten_leaves``/``_unflatten_like`` round-trip (~50 MB of extra HBM
traffic for a 3.3M-param model: build p_flat + g_flat, write both outputs,
then slice updates back out), because its own per-leaf apply fuses into
the gradient computation's epilogue with zero layout change.  Making this
kernel win would require the train state itself to keep params flat (model
views as slices) — not worth the intrusion for an elementwise op XLA
already fuses optimally.  The kernel stays as the opt-in
(``--fused_optimizer``) kernel-authoring reference, numbers documented.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributedtensorflowexample_tpu.ops.pallas.tiling import (
    LANES as _LANES, pick_block)

_ROW_BLOCK = 1024     # 1024x128 f32 = 512 KiB per operand block in VMEM


def _sgd_kernel(lr_ref, p_ref, m_ref, g_ref, p_out, m_out, *, mu: float):
    lr = lr_ref[0, 0]
    m_new = mu * m_ref[:] + g_ref[:]
    p_out[:] = p_ref[:] - lr * m_new
    m_out[:] = m_new


def _num_rows(n: int) -> int:
    rows = max(8, (n + _LANES - 1) // _LANES)
    return ((rows + 7) // 8) * 8


def _flatten_leaves(leaves, rows: int) -> jnp.ndarray:
    flat = jnp.concatenate(
        [jnp.ravel(x).astype(jnp.float32) for x in leaves])
    return jnp.pad(flat, (0, rows * _LANES - flat.size)).reshape(rows, _LANES)


def _unflatten_like(flat: jnp.ndarray, leaves, treedef):
    """Slice a flat buffer back into the shapes/dtypes of ``leaves``."""
    flat = flat.reshape(-1)
    out, offset = [], 0
    for leaf in leaves:
        out.append(flat[offset:offset + leaf.size]
                   .reshape(leaf.shape).astype(leaf.dtype))
        offset += leaf.size
    return treedef.unflatten(out)


def fused_sgd_flat(p_flat, m_flat, g_flat, lr, mu: float,
                   interpret: bool):
    """One momentum-SGD pass over flat (rows, 128) f32 buffers: a single
    ``pallas_call`` with a 1-D grid over row blocks."""
    rows = p_flat.shape[0]
    lr2d = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    block = pick_block(rows, _ROW_BLOCK)
    spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_sgd_kernel, mu=float(mu)),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            spec, spec, spec,
        ],
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)),
        input_output_aliases={1: 0, 2: 1},
        interpret=interpret,
    )(lr2d, p_flat, m_flat, g_flat)


def _auto_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        return jax.default_backend() not in ("tpu", "axon")
    return interpret


class FusedSgdState(NamedTuple):
    count: jnp.ndarray     # step counter for LR schedules
    trace: jnp.ndarray     # momentum, flat (rows, 128) f32


def fused_momentum_sgd(learning_rate, momentum: float = 0.9, mesh=None):
    """Optax-compatible transformation backed by the fused Pallas kernel.

    Same math as ``optax.sgd(learning_rate, momentum=momentum)``, but the
    state pytree differs (``FusedSgdState`` with a FLAT momentum buffer vs
    optax's per-leaf tuple), so a checkpoint written with one cannot be
    restored with the other — pick the flag per run, not mid-experiment.
    The optax contract returns *updates* (applied by
    ``optax.apply_updates``), so the kernel's result is expressed as
    ``p_new - p``; XLA folds the add/sub pair away.

    A ``pallas_call`` is a custom call XLA cannot auto-partition: on a
    multi-device mesh pass ``mesh`` so the kernel runs per-device under
    ``jax.shard_map`` (all operands are replicated in data parallelism, so
    every device performs the identical update).
    """
    import optax

    def init(params):
        n = sum(x.size for x in jax.tree.leaves(params))
        rows = _num_rows(n)
        return FusedSgdState(count=jnp.zeros([], jnp.int32),
                             trace=jnp.zeros((rows, _LANES), jnp.float32))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_momentum_sgd requires params")
        lr = learning_rate(state.count) if callable(learning_rate) \
            else learning_rate
        leaves_p, treedef = jax.tree.flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        rows = state.trace.shape[0]
        p_flat = _flatten_leaves(leaves_p, rows)
        g_flat = _flatten_leaves(leaves_g, rows)
        interpret = _auto_interpret(None)
        if mesh is not None and mesh.size > 1:
            from jax.sharding import PartitionSpec as P

            from distributedtensorflowexample_tpu.compat import shard_map
            apply = shard_map(
                lambda p, m, g, lr_: fused_sgd_flat(p, m, g, lr_, momentum,
                                                    interpret),
                mesh=mesh, in_specs=(P(), P(), P(), P()),
                out_specs=(P(), P()), check_vma=False)
            p_new, m_new = apply(p_flat, state.trace, g_flat,
                                 jnp.asarray(lr, jnp.float32))
        else:
            p_new, m_new = fused_sgd_flat(p_flat, state.trace, g_flat, lr,
                                          momentum, interpret)
        updates = _unflatten_like(p_new - p_flat, leaves_p, treedef)
        return updates, FusedSgdState(count=state.count + 1, trace=m_new)

    return optax.GradientTransformation(init, update)


def fused_sgd_apply(params, momentum, grads, lr, mu: float = 0.9,
                    interpret: bool | None = None):
    """Apply one momentum-SGD step to a pytree; returns (params, momentum)
    as trees (parity-test surface; the optax path keeps momentum flat).

    ``lr`` may be a traced scalar (schedule output).  ``interpret=None``
    auto-selects interpret mode off-TPU for CPU testing.
    """
    leaves_p, treedef = jax.tree.flatten(params)
    leaves_m = treedef.flatten_up_to(momentum)
    leaves_g = treedef.flatten_up_to(grads)
    rows = _num_rows(sum(x.size for x in leaves_p))
    p_new, m_new = fused_sgd_flat(
        _flatten_leaves(leaves_p, rows), _flatten_leaves(leaves_m, rows),
        _flatten_leaves(leaves_g, rows), lr, mu, _auto_interpret(interpret))
    return (_unflatten_like(p_new, leaves_p, treedef),
            _unflatten_like(m_new, leaves_p, treedef))
