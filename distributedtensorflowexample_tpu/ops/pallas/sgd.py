"""Fused momentum-SGD update Pallas kernel (the per-step optimizer apply).

The reference's update was a native TF ``ApplyMomentum`` op per variable
(library C++, SURVEY.md §2 native-dependency table).  This kernel is the
TPU equivalent: for each parameter leaf, one VMEM pass computes

    m_new = mu * m + g          (optax.sgd(momentum=mu) trace semantics)
    p_new = p - lr * m_new

in one fused pass per leaf.  ``input_output_aliases`` lets XLA reuse the
kernel operands' buffers for the outputs; note the operands here are the
padded/flattened temporaries built around the kernel, so the aliasing
saves the kernel-internal copies, not the whole-step HBM round-trip.
``lr`` arrives as a traced (1, 1) SMEM scalar so LR schedules stay
dynamic; ``mu`` is compile-time static.

Leaves are flattened and padded to (rows, 128) lanes; the pad tail is
updated too (momentum of a zero-gradient pad stays zero, params stay put),
so no masking is needed.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributedtensorflowexample_tpu.ops.pallas.tiling import (
    LANES as _LANES, pick_block)

_ROW_BLOCK = 1024     # 1024x128 f32 = 512 KiB per operand block in VMEM


def _sgd_kernel(lr_ref, p_ref, m_ref, g_ref, p_out, m_out, *, mu: float):
    lr = lr_ref[0, 0]
    m_new = mu * m_ref[:] + g_ref[:]
    p_out[:] = p_ref[:] - lr * m_new
    m_out[:] = m_new


def _pick_block(rows: int) -> int:
    return pick_block(rows, _ROW_BLOCK)


def _apply_leaf(param, mom, grad, lr2d, mu: float, interpret: bool):
    shape, dtype, n = param.shape, param.dtype, param.size
    rows = max(8, (n + _LANES - 1) // _LANES)
    rows = ((rows + 7) // 8) * 8
    padded = rows * _LANES

    def flat(x):
        x = x.astype(jnp.float32).reshape(-1)
        return jnp.pad(x, (0, padded - n)).reshape(rows, _LANES)

    block = _pick_block(rows)
    grid = (rows // block,)
    spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    p_new, m_new = pl.pallas_call(
        functools.partial(_sgd_kernel, mu=mu),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            spec, spec, spec,
        ],
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)),
        input_output_aliases={1: 0, 2: 1},
        interpret=interpret,
    )(lr2d, flat(param), flat(mom), flat(grad))
    unflat = lambda x: x.reshape(-1)[:n].reshape(shape).astype(dtype)
    return unflat(p_new), unflat(m_new)


class FusedSgdState(NamedTuple):
    count: jnp.ndarray     # step counter for LR schedules
    trace: object          # momentum tree, same structure as params


def fused_momentum_sgd(learning_rate, momentum: float = 0.9, mesh=None):
    """Optax-compatible transformation backed by the fused Pallas kernel.

    Same math as ``optax.sgd(learning_rate, momentum=momentum)``, but the
    state pytree differs (``FusedSgdState`` vs optax's tuple), so a
    checkpoint written with one cannot be restored with the other — pick
    the flag per run, not mid-experiment.  The optax contract returns
    *updates* (applied by ``optax.apply_updates``), so the kernel's result
    is expressed as ``p_new - p``; XLA folds the add/sub pair away.

    A ``pallas_call`` is a custom call XLA cannot auto-partition: on a
    multi-device mesh pass ``mesh`` so the kernel runs per-device under
    ``jax.shard_map`` (all operands are replicated in data parallelism, so
    every device performs the identical update).
    """
    import optax

    def init(params):
        return FusedSgdState(count=jnp.zeros([], jnp.int32),
                             trace=jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_momentum_sgd requires params")
        lr = learning_rate(state.count) if callable(learning_rate) \
            else learning_rate
        if mesh is not None and mesh.size > 1:
            from jax.sharding import PartitionSpec as P
            apply = jax.shard_map(
                lambda p, m, g, lr_: fused_sgd_apply(p, m, g, lr_, momentum),
                mesh=mesh, in_specs=(P(), P(), P(), P()),
                out_specs=(P(), P()), check_vma=False)
            p_new, m_new = apply(params, state.trace, grads,
                                 jnp.asarray(lr, jnp.float32))
        else:
            p_new, m_new = fused_sgd_apply(params, state.trace, grads, lr,
                                           momentum)
        updates = jax.tree.map(lambda a, b: a - b, p_new, params)
        return updates, FusedSgdState(count=state.count + 1, trace=m_new)

    return optax.GradientTransformation(init, update)


def fused_sgd_apply(params, momentum, grads, lr, mu: float = 0.9,
                    interpret: bool | None = None):
    """Apply one momentum-SGD step to every leaf; returns (params, momentum).

    ``lr`` may be a traced scalar (schedule output).  ``interpret=None``
    auto-selects interpret mode off-TPU for CPU testing.
    """
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    lr2d = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    leaves_p, treedef = jax.tree.flatten(params)
    leaves_m = treedef.flatten_up_to(momentum)
    leaves_g = treedef.flatten_up_to(grads)
    out_p, out_m = [], []
    for p, m, g in zip(leaves_p, leaves_m, leaves_g):
        np_, nm = _apply_leaf(p, m, g, lr2d, float(mu), interpret)
        out_p.append(np_)
        out_m.append(nm)
    return treedef.unflatten(out_p), treedef.unflatten(out_m)
