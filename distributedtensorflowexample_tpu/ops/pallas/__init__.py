"""Pallas TPU kernels for the framework's hot ops.

The reference's hot-path math lived in library native code (cuDNN kernels,
TF C++ executor — SURVEY.md §2 "native dependency" table).  Our TPU-native
equivalents are mostly XLA-compiled jnp, but the ops XLA's fusion touches
every step — the loss head, the optimizer update, and the input-path
row gather — also ship as hand-written Pallas kernels: single VMEM pass,
no HBM round-trips between the fused stages, selectable per run
(``RunConfig.pallas_ce`` for the loss head, ``RunConfig.fused_optimizer``
for the update, ``RunConfig.dequant_impl="pallas"`` for the fused
gather+dequant of a uint8-resident split).

All kernels run in interpret mode on CPU, so the same code path is
unit-testable without a TPU (SURVEY.md §4 test strategy).
"""

from distributedtensorflowexample_tpu.ops.pallas.cross_entropy import (
    fused_softmax_cross_entropy_rows)
from distributedtensorflowexample_tpu.ops.pallas.dequant import (
    fused_gather_dequant)
from distributedtensorflowexample_tpu.ops.pallas.sgd import (
    fused_momentum_sgd, fused_sgd_apply)

__all__ = [
    "fused_softmax_cross_entropy_rows",
    "fused_gather_dequant",
    "fused_momentum_sgd",
    "fused_sgd_apply",
]
