"""Fused softmax-cross-entropy Pallas kernel (loss head of every workload).

Replaces the reference's ``tf.nn.softmax_cross_entropy_with_logits`` native
op (SURVEY.md §2 C8/C9 loss math) with a TPU kernel: one VMEM pass computes
max, log-sum-exp and the target logit per row — the softmax is never
materialized in HBM.  The backward kernel recomputes the softmax from the
saved logits (FLOPs are free next to the HBM traffic it saves) and emits
``(softmax - target) * g`` in the same pass.

Shapes: logits [B, C] float32, labels [B] int32.  C is padded to the
128-lane tile and masked inside the kernel; rows with label < 0 contribute
zero loss and zero gradient (used by callers to pad B to the row tile).

Returns PER-ROW losses [B] so the batch mean stays an ordinary jnp op —
under data parallelism that mean is where XLA inserts the cross-chip psum,
identical to the XLA loss path (parallel/sync.py).  A ``pallas_call`` is
not auto-partitionable, so multi-device callers wrap this in
``jax.shard_map`` along the batch axis (see ``parallel.sync``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributedtensorflowexample_tpu.ops.pallas.tiling import (
    LANES as _LANES, SUBLANES, pad_rows as _pad_rows, pick_block)

_ROW_BLOCK = 512      # rows per grid step; multiple of the 8-sublane tile


def _ce_fwd_kernel(logits_ref, labels_ref, loss_ref, *, num_classes: int,
                   smoothing: float):
    logits = logits_ref[:]                      # [TB, CP] f32
    labels = labels_ref[:]                      # [TB, 1] i32
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid_col = col < num_classes
    masked = jnp.where(valid_col, logits, -jnp.inf)
    m = jnp.max(masked, axis=1, keepdims=True)
    ex = jnp.where(valid_col, jnp.exp(masked - m), 0.0)
    lse = m + jnp.log(jnp.sum(ex, axis=1, keepdims=True))      # [TB, 1]
    picked = jnp.sum(jnp.where(col == labels, logits, 0.0), axis=1,
                     keepdims=True)
    if smoothing > 0.0:
        mean_logit = jnp.sum(jnp.where(valid_col, logits, 0.0), axis=1,
                             keepdims=True) / num_classes
        target = (1.0 - smoothing) * picked + smoothing * mean_logit
    else:
        target = picked
    loss_ref[:] = jnp.where(labels >= 0, lse - target, 0.0)


def _ce_bwd_kernel(logits_ref, labels_ref, g_ref, dlogits_ref, *,
                   num_classes: int, smoothing: float):
    logits = logits_ref[:]
    labels = labels_ref[:]
    g = g_ref[:]                                # [TB, 1] upstream per-row
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid_col = col < num_classes
    masked = jnp.where(valid_col, logits, -jnp.inf)
    m = jnp.max(masked, axis=1, keepdims=True)
    ex = jnp.where(valid_col, jnp.exp(masked - m), 0.0)
    softmax = ex / jnp.sum(ex, axis=1, keepdims=True)
    onehot = jnp.where(col == labels, 1.0, 0.0)
    if smoothing > 0.0:
        target = ((1.0 - smoothing) * onehot
                  + jnp.where(valid_col, smoothing / num_classes, 0.0))
    else:
        target = onehot
    grad = (softmax - target) * g
    dlogits_ref[:] = jnp.where(valid_col & (labels >= 0), grad, 0.0)


def _pad_cols(logits: jnp.ndarray) -> jnp.ndarray:
    c = logits.shape[-1]
    cp = max(_LANES, ((c + _LANES - 1) // _LANES) * _LANES)
    if cp != c:
        logits = jnp.pad(logits, ((0, 0), (0, cp - c)))
    return logits


def _pick_block(padded_b: int) -> int:
    """Largest 8-aligned row block ≤ _ROW_BLOCK dividing the padded batch."""
    return pick_block(padded_b, _ROW_BLOCK)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ce_rows(logits, labels2d, num_classes, smoothing, interpret):
    rows, _ = _ce_fwd(logits, labels2d, num_classes, smoothing, interpret)
    return rows


def _ce_fwd(logits, labels2d, num_classes, smoothing, interpret):
    b = logits.shape[0]
    block = _pick_block(b)
    grid = (b // block,)
    rows = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, num_classes=num_classes,
                          smoothing=smoothing),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, logits.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.float32),
        interpret=interpret,
    )(logits, labels2d)
    return rows, (logits, labels2d)


def _ce_bwd(num_classes, smoothing, interpret, res, g_rows):
    logits, labels2d = res
    b = logits.shape[0]
    block = _pick_block(b)
    grid = (b // block,)
    dlogits = pl.pallas_call(
        functools.partial(_ce_bwd_kernel, num_classes=num_classes,
                          smoothing=smoothing),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, logits.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, logits.shape[1]), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(logits.shape, jnp.float32),
        interpret=interpret,
    )(logits, labels2d, g_rows)
    return dlogits, None


_ce_rows.defvjp(_ce_fwd, _ce_bwd)


def fused_softmax_cross_entropy_rows(logits: jnp.ndarray,
                                     labels: jnp.ndarray,
                                     label_smoothing: float = 0.0,
                                     interpret: bool | None = None
                                     ) -> jnp.ndarray:
    """Per-row cross-entropy losses [B] via the fused Pallas kernel.

    ``interpret=None`` auto-selects interpret mode off-TPU so CPU tests run
    the identical kernel code.  Gradients flow to ``logits`` only.
    """
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    b, c = logits.shape
    logits = _pad_cols(logits.astype(jnp.float32))
    labels2d = labels.astype(jnp.int32).reshape(b, 1)
    logits = _pad_rows(logits, SUBLANES, 0.0)
    labels2d = _pad_rows(labels2d, SUBLANES, -1)
    rows = _ce_rows(logits, labels2d, c, float(label_smoothing), interpret)
    return rows[:b, 0]
