"""Shared TPU tiling helpers for the Pallas kernels in this package.

One source of truth for the lane width and the row-block picker so the
kernels' padding behavior cannot diverge (pallas_guide.md tiling table:
float32 min tile is 8 sublanes x 128 lanes).
"""

from __future__ import annotations

import jax.numpy as jnp

LANES = 128      # last-dim tile width, all dtypes
SUBLANES = 8     # float32 second-to-last-dim tile


def pick_block(rows: int, max_block: int) -> int:
    """Largest 8-aligned power-of-two row block ≤ max_block dividing rows."""
    cand = max_block
    while cand >= SUBLANES:
        if rows % cand == 0:
            return cand
        cand //= 2
    raise ValueError(f"{rows} rows not a multiple of {SUBLANES}")


def pad_rows(x: jnp.ndarray, multiple: int, fill) -> jnp.ndarray:
    """Pad the leading dim up to a multiple, filling with ``fill``."""
    b = x.shape[0]
    bp = ((b + multiple - 1) // multiple) * multiple
    if bp != b:
        x = jnp.pad(x, ((0, bp - b),) + ((0, 0),) * (x.ndim - 1),
                    constant_values=fill)
    return x
