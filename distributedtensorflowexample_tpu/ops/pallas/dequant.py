"""Fused row-gather + affine-dequant Pallas kernel (VERDICT r4 #3 — the
profile-chosen kernel; shipped by the round-5 dequant-tax fix).

The device-resident input path reads its minibatch as ``take(split, idx)``
followed by an elementwise dequant.  XLA materializes the gathered uint8
minibatch in HBM between the two — the round-trip PROFILE_auto_r05.json
charges to the input path (82% of the ResNet-20 step, measured/roofline
0.12).  This kernel fuses the two: the scalar-prefetched index vector
drives the BlockSpec index map, so each grid step DMAs ONE uint8 source
row HBM->VMEM and writes its dequantized float32 row straight to the
output batch — uint8 bytes cross HBM exactly once, and no uint8
minibatch is ever materialized.

The dequant arithmetic is the canonical fused affine of ``data.dequant``
(``f32(u) * scale + bias``, one fused multiply-add), so the kernel's
output is bitwise-identical to the unfused affine path — asserted by the
parity tests, which run this kernel in interpret mode on CPU.

Selected via ``dequant_impl="pallas"`` (config flag / DeviceDataset /
make_device_gather); replicated resident splits only — a row-sharded
split gathers under shard_map where the plain affine form already fuses
well per shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _dequant_row_kernel(idx_ref, row_ref, scale_ref, bias_ref, out_ref):
    # idx_ref is the scalar-prefetched index vector; the BlockSpec index
    # maps already routed row_ref to source row idx[i], so the body is
    # the pure affine: one fused multiply-add per pixel.
    del idx_ref
    out_ref[...] = (row_ref[...].astype(jnp.float32) * scale_ref[...]
                    + bias_ref[...])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_gather_dequant_flat(images_flat, idx, scale_row, bias_row,
                               interpret: bool):
    n, r = images_flat.shape
    b = idx.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            # One source row per grid step, picked by the PREFETCHED
            # index — this is the gather: the index map reads idx before
            # the kernel body runs, so Pallas pipelines the row DMAs.
            pl.BlockSpec((1, r), lambda i, idx_ref: (idx_ref[i], 0)),
            pl.BlockSpec((1, r), lambda i, idx_ref: (0, 0)),
            pl.BlockSpec((1, r), lambda i, idx_ref: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, r), lambda i, idx_ref: (i, 0)),
    )
    return pl.pallas_call(
        _dequant_row_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, r), jnp.float32),
        interpret=interpret,
    )(idx, images_flat, scale_row, bias_row)


def fused_gather_dequant(images: jnp.ndarray, idx: jnp.ndarray,
                         scale: jnp.ndarray, bias: jnp.ndarray,
                         interpret: bool | None = None) -> jnp.ndarray:
    """``affine(images[idx])`` in one fused pass.

    ``images``: [N, ...] uint8 resident split; ``idx``: [B] int32 row
    ids; ``scale``/``bias``: the [1]- or [C]-shaped affine constants from
    the data pytree (``dq_scale``/``dq_bias``).  Returns the [B, ...]
    float32 batch, bitwise-identical to
    ``apply_dequant_affine(images[idx], scale, bias)``.

    ``interpret=None`` auto-selects interpret mode off-TPU so CPU tests
    run the identical kernel code (the parity gate the acceptance
    criteria name).
    """
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    if images.dtype != jnp.uint8:
        raise TypeError(f"fused_gather_dequant reads uint8 rows, got "
                        f"{images.dtype}")
    sample_shape = images.shape[1:]
    r = 1
    for d in sample_shape:
        r *= int(d)
    # Per-channel constants tiled across the flattened row (channel is
    # the fastest-varying axis), so the kernel is a pure elementwise op
    # on [1, R] blocks whatever the spec's channel count.
    scale = jnp.asarray(scale, jnp.float32).reshape(-1)
    bias = jnp.asarray(bias, jnp.float32).reshape(-1)
    reps = r // scale.shape[0]
    scale_row = jnp.tile(scale, reps).reshape(1, r)
    bias_row = jnp.tile(bias, reps).reshape(1, r)
    out = _fused_gather_dequant_flat(
        images.reshape(len(images), r), idx.astype(jnp.int32),
        scale_row, bias_row, interpret)
    return out.reshape((idx.shape[0],) + sample_shape)
