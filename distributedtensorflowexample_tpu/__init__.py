"""TPU-native distributed training framework.

A ground-up JAX/XLA rebuild of the capability surface of
``rubythonode/DistributedTensorFlowExample`` (see SURVEY.md — the reference
tree was empty at survey time, so parity is against the driver-pinned
capability contract in BASELINE.json, not file:line citations):

* local single-process MNIST softmax training          (config 1)
* async parameter-server MNIST CNN training            (config 2)
* sync-SGD (SyncReplicasOptimizer-style) MNIST CNN     (config 3)
* single-host data-parallel CIFAR-10 ResNet-20         (config 4)
* multi-host data-parallel CIFAR-10 ResNet-20          (config 5)

Design stance (BASELINE.json north star): one SPMD core replaces all four
distribution mechanisms of the reference.  Parameters are never "placed on a
parameter server" — they live replicated (or sharded) per ``NamedSharding``
on a ``jax.sharding.Mesh``; gradient combination is an XLA collective inside
a jitted step; multi-host is the same program on more processes.
"""

from distributedtensorflowexample_tpu.version import __version__

__all__ = ["__version__"]
