"""Append-only run ledger — every run on the box leaves queryable rows.

Until round 12 the repo's cross-run record was a pile of files: 20+
``BENCH_*``/``SCALING_*`` JSONs, per-run flight dumps, per-run journals.
Each is a fine *per-run* postmortem, but nothing answered "what ran on
this box, with which config, and how did it end" without a shell glob
and a human.  The ledger is that missing layer: one ``RUNS.jsonl``
(``OBS_LEDGER=<path>`` opts a process in; the fleet supervisor exports
it to every rank by default) accumulating three row kinds per run plus
fleet-level annotations:

- ``run_start`` — run id, entrypoint, the resolved config (and a crc32
  digest of it, so two runs are config-comparable without a field-by-
  field diff), platform/mesh shape, OBS_RANK / SUPERVISE_ATTEMPT;
- ``sample`` — periodic, **bounded-resolution** metric samples: the
  registry's ``delta()`` between this sample's snapshot and the last
  one, rate-limited to one row per ``OBS_LEDGER_SAMPLE_S`` (default
  30 s) no matter how hot the hook cadence is — a week-long run costs
  kilobytes, not a log-per-step flood;
- ``run_end`` — rc, final step, the loss-tail digest (cheap cross-run
  "did these two runs follow the same tape" handle), which anomaly
  flags fired, the flight path, and the final cumulative counters
  (what ``tools/obs_query.py diff`` subtracts).

Crash tolerance is the supervisor journal's, shared by construction:
appends heal a torn tail first (a record that died mid-line must not
merge with the next live one), each row is ONE write+fsync, and readers
skip unparseable lines instead of failing — a SIGKILLed run costs its
own last row, never the file.  Rotation is size-bounded
(``OBS_LEDGER_MAX_BYTES``, default 8 MiB): the full file rotates to
``<path>.1`` and readers transparently read both, so the ledger can sit
on a box for months without anyone babysitting it.

Stdlib-only like the rest of ``obs/`` (the package import guard in
tests/test_ledger.py walks every module): importing the ledger never
pulls jax, so bench's handler-before-import ordering holds.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import sys
import zlib

from distributedtensorflowexample_tpu.obs import metrics as _metrics

LEDGER_VERSION = 1

# Default bounds — env-overridable so a drill (or a test) can tighten
# them without plumbing knobs through every CLI.
DEFAULT_SAMPLE_S = 30.0
DEFAULT_MAX_BYTES = 8 * 2**20


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def ledger_path() -> str:
    """The opt-in: ``OBS_LEDGER=<path>`` — empty means no ledger (the
    one obs surface that accumulates ACROSS runs must be somewhere the
    operator chose, never a surprise file in the repo root)."""
    return os.environ.get("OBS_LEDGER", "")


def config_digest(config: dict | None) -> str | None:
    """crc32 over the canonical repr — the same cheap digest the
    multi-host config-agreement check uses (trainers/common.py), so
    "same digest" means the same thing everywhere: equal resolved
    configs, not equal argv strings."""
    if not config:
        return None
    blob = repr(sorted((str(k), str(v)) for k, v in config.items()))
    return f"{zlib.crc32(blob.encode()):08x}"


def _rotate(path: str, max_bytes: int) -> None:
    """Rotate under an exclusive sidecar lock, re-checking the size
    INSIDE it: a fleet drill has N+1 processes appending to one ledger
    by design, and two writers both observing an over-budget size would
    otherwise both run the rename — the second one renaming the
    freshly-started live file over the ``.1`` the first just rotated,
    silently unlinking the whole rotated history."""
    try:
        import fcntl
    except ImportError:         # non-POSIX: accept the (rarer) race
        os.replace(path, path + ".1")
        return
    with open(path + ".lock", "a") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            # The size this writer decided on is stale the instant
            # another writer rotated; only a re-read under the lock may
            # authorize the rename.
            if os.path.getsize(path) > max_bytes:
                os.replace(path, path + ".1")
        except OSError:
            pass
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def append_row(path: str, row: dict) -> None:
    """One ledger append: heal a torn tail, rotate when over budget,
    write the row as ONE line + fsync.  Never raises — the ledger must
    not kill the run it records (the same contract as the beat and the
    health file)."""
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        max_bytes = int(_env_float("OBS_LEDGER_MAX_BYTES",
                                   DEFAULT_MAX_BYTES))
        if max_bytes > 0 and size > max_bytes:
            # Whole-file rotation (one level): readers read .1 + live,
            # so a query spanning the rotation edge still sees both
            # halves of a run.
            _rotate(path, max_bytes)
            size = 0
        heal = False
        if size:
            # Torn-tail healing BEFORE appending (the supervisor
            # journal's rule): a row that died mid-line left no
            # trailing newline, and appending straight onto the
            # fragment would merge it with THIS row into one
            # unparseable line — losing a live record, not just the
            # dead fragment.  Inner try: a CONCURRENT writer may have
            # rotated the file away between the stat and this read —
            # that must read as "fresh file, nothing to heal", not
            # bubble to the outer swallow and silently drop THIS row.
            try:
                with open(path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    heal = f.read(1) != b"\n"
            except OSError:
                heal = False
        line = json.dumps(_metrics.json_safe(row), sort_keys=True,
                          allow_nan=False, default=str) + "\n"
        with open(path, "a") as f:
            if heal:
                f.write("\n")
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
    except Exception:
        pass


def log_event(event: str, path: str | None = None, **fields) -> None:
    """Append one loose annotation row (the fleet's ``resume_agreement``,
    the supervisor's per-attempt rows) — no-op when no ledger is
    configured."""
    path = path or ledger_path()
    if not path:
        return
    append_row(path, {"v": LEDGER_VERSION,
                      "ts": round(_metrics._wall(), 3),
                      "event": event, **fields})


class RunLedger:
    """One process's writer: a ``run_start`` at :meth:`start`, bounded
    ``sample`` rows, one ``run_end`` at :meth:`end` (or, failing that,
    at atexit with ``rc=None`` — a crash should still close its row)."""

    def __init__(self, path: str, run_id: str | None = None,
                 sample_min_s: float | None = None,
                 registry: _metrics.MetricsRegistry | None = None):
        self.path = path
        rank = os.environ.get("OBS_RANK", "")
        attempt = os.environ.get("SUPERVISE_ATTEMPT", "")
        # Readable and collision-free across ranks/attempts/restarts:
        # wall-ms + pid disambiguate two runs of the same entrypoint,
        # rank/attempt make a fleet drill's rows self-describing.
        self.run_id = run_id or "-".join(
            [f"{int(_metrics._wall() * 1000):x}", str(os.getpid())]
            + ([f"r{rank}"] if rank else [])
            + ([f"a{attempt}"] if attempt else []))
        self.sample_min_s = (
            _env_float("OBS_LEDGER_SAMPLE_S", DEFAULT_SAMPLE_S)
            if sample_min_s is None else sample_min_s)
        self._registry = registry or _metrics.registry()
        self._prev_snap: dict | None = None
        self._last_sample_t: float | None = None
        self.samples = 0
        self.ended = False

    def _row(self, event: str, **fields) -> dict:
        return {"v": LEDGER_VERSION, "ts": round(_metrics._wall(), 3),
                "event": event, "run": self.run_id, **fields}

    def start(self, entrypoint: str, config: dict | None = None,
              **fields) -> None:
        def _as_int(v):
            try:
                return int(v)
            except (TypeError, ValueError):
                return v or None
        append_row(self.path, self._row(
            "run_start", entrypoint=entrypoint,
            config=config, config_digest=config_digest(config),
            pid=os.getpid(), argv=list(sys.argv),
            rank=_as_int(os.environ.get("OBS_RANK")),
            attempt=_as_int(os.environ.get("SUPERVISE_ATTEMPT")),
            phase=os.environ.get("OBS_PHASE"), **fields))
        self._prev_snap = self._registry.snapshot()

    def sample(self, step: int | None = None, force: bool = False) -> bool:
        """One bounded-resolution sample row; returns whether a row was
        written.  The bound is TIME, not call count: callers feed this
        from whatever hook cadence they already have (MetricsHook's
        log-boundary marks) and the ledger stays kilobytes regardless."""
        now = _metrics._now()
        if (not force and self._last_sample_t is not None
                and now - self._last_sample_t < self.sample_min_s):
            return False
        self._last_sample_t = now
        snap = self._registry.snapshot()
        delta = _metrics.MetricsRegistry.delta(self._prev_snap, snap)
        self._prev_snap = snap
        self.samples += 1
        append_row(self.path, self._row("sample", step=step, delta=delta))
        return True

    def loss_tail_digest(self) -> dict | None:
        """Digest of the flight recorder's loss ring, when one is
        installed: last (step, loss) plus a sha256 over the whole tail —
        the cheap "same trajectory?" handle ``obs_query diff`` compares
        without shipping the tape itself into every run_end row."""
        from distributedtensorflowexample_tpu.obs import (
            recorder as _recorder)
        rec = _recorder.get()
        if rec is None or not rec._loss:
            return None
        tail = list(rec._loss)
        blob = json.dumps(_metrics.json_safe(tail), sort_keys=True,
                          default=str).encode()
        return {"n": len(tail), "last": tail[-1],
                "sha256": hashlib.sha256(blob).hexdigest()[:16]}

    def end(self, rc: int | None = None, final_step: int | None = None,
            **fields) -> None:
        """Terminal row (idempotent): rc, final step, loss-tail digest,
        the anomaly flags that fired, the flight path (when a recorder
        is installed), and the final cumulative counters."""
        if self.ended:
            return
        self.ended = True
        snap = self._registry.snapshot()
        flags = {k: v for k, v in snap["counters"].items()
                 if k.startswith("anomaly_flags_total") and v}
        from distributedtensorflowexample_tpu.obs import (
            recorder as _recorder)
        flight = (_recorder.flight_path()
                  if _recorder.get() is not None else None)
        append_row(self.path, self._row(
            "run_end", rc=rc, final_step=final_step,
            loss_tail=self.loss_tail_digest(),
            anomaly_flags=flags or None, flight=flight,
            counters=snap["counters"], samples=self.samples, **fields))


_GLOBAL: RunLedger | None = None


def get() -> RunLedger | None:
    return _GLOBAL


def maybe_begin(entrypoint: str, config: dict | None = None,
                **fields) -> RunLedger | None:
    """Open this process's ledger run iff ``OBS_LEDGER`` names a path —
    THE one arming predicate (the recorder's ``maybe_install`` shape),
    consulted by every entrypoint so the rule can't drift.  Idempotent:
    a second call returns the already-open run.  Arms an atexit
    ``run_end`` so a crash still closes the row (``rc=None`` marks "the
    process never reported" — distinguishable from a real rc)."""
    global _GLOBAL
    if _GLOBAL is not None:
        return _GLOBAL
    path = ledger_path()
    if not path:
        return None
    led = _GLOBAL = RunLedger(path)
    led.start(entrypoint, config=config, **fields)
    atexit.register(_atexit_end)
    return led


def end_global(rc: int | None = None, final_step: int | None = None,
               **fields) -> None:
    if _GLOBAL is not None:
        _GLOBAL.end(rc=rc, final_step=final_step, **fields)


def _atexit_end() -> None:
    if _GLOBAL is not None and not _GLOBAL.ended:
        _GLOBAL.end(rc=None)


# --- reading ---------------------------------------------------------------

def read_rows(path: str, include_rotated: bool = True
              ) -> tuple[list[dict], int]:
    """(rows, torn_count) across the rotated ``.1`` file (oldest first)
    and the live file; torn/unparseable lines are counted and skipped —
    the reader half of the crash-tolerance contract."""
    rows: list[dict] = []
    torn = 0
    paths = ([path + ".1"] if include_rotated
             and os.path.exists(path + ".1") else []) + [path]
    for p in paths:
        try:
            with open(p) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                torn += 1
                continue
            if isinstance(row, dict):
                rows.append(row)
    return rows, torn


def tail_rows(path: str, n: int,
              max_bytes: int = 256 * 1024) -> tuple[list[dict], int]:
    """(last ``n`` parsed rows, torn count) reading only a bounded tail
    chunk of the LIVE file — the ``/ledger/tail`` scrape runs inside
    the very process being observed, and re-parsing a multi-MiB ledger
    per poll would bill parse time to the run it watches.  The first
    line of a mid-file chunk is almost surely partial; it is dropped,
    not counted as torn."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            offset = max(0, size - max_bytes)
            f.seek(offset)
            blob = f.read()
    except OSError:
        return [], 0
    lines = blob.decode(errors="replace").splitlines()
    if offset > 0 and lines:
        lines = lines[1:]
    rows: list[dict] = []
    torn = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            torn += 1
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows[-max(1, n):], torn


def runs(path: str) -> dict:
    """Fold the rows into per-run groups plus the loose annotations::

        {"runs": {run_id: {"start": row|None, "samples": [...],
                           "end": row|None}},
         "order": [run_id, ...],            # first-seen order
         "events": [row, ...],              # resume_agreement etc.
         "torn": int}
    """
    rows, torn = read_rows(path)
    grouped: dict = {}
    order: list = []
    events: list = []
    for row in rows:
        run = row.get("run")
        ev = row.get("event")
        if run is None or ev not in ("run_start", "sample", "run_end"):
            events.append(row)
            continue
        if run not in grouped:
            grouped[run] = {"start": None, "samples": [], "end": None}
            order.append(run)
        if ev == "run_start":
            grouped[run]["start"] = row
        elif ev == "sample":
            grouped[run]["samples"].append(row)
        else:
            grouped[run]["end"] = row
    return {"runs": grouped, "order": order, "events": events,
            "torn": torn}


def run_table(path: str, folded: dict | None = None) -> list[dict]:
    """One summary dict per run, ledger order — the ``obs_query list``
    /``obs_report --ledger`` row shape.  Pass an already-``runs()``-
    folded dict to avoid re-reading a multi-MiB ledger for the second
    view of the same invocation."""
    folded = folded if folded is not None else runs(path)
    out = []
    for run_id in folded["order"]:
        g = folded["runs"][run_id]
        start, end = g["start"] or {}, g["end"] or {}
        flags = end.get("anomaly_flags") or {}
        out.append({
            "run": run_id,
            "entrypoint": start.get("entrypoint") or start.get("src"),
            "src": start.get("src"),
            "rank": start.get("rank"),
            "attempt": start.get("attempt"),
            "start_ts": start.get("ts"),
            "config_digest": start.get("config_digest"),
            "rc": end.get("rc") if g["end"] else None,
            # Gang rows (the fleet's) end with an explicit outcome
            # instead of an rc — honor it before classifying.
            "outcome": ("running/lost" if not g["end"] else
                        end.get("outcome") or (
                        "ok" if end.get("rc") == 0 else
                        "preempted" if end.get("rc") == 143 else
                        "unreported" if end.get("rc") is None else
                        f"rc={end.get('rc')}")),
            "final_step": end.get("final_step"),
            "samples": len(g["samples"]),
            "anomalies": sum(flags.values()) if flags else 0,
            "duration_s": (round(end["ts"] - start["ts"], 3)
                           if start.get("ts") is not None
                           and end.get("ts") is not None else None)})
    return out
