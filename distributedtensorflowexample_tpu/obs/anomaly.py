"""Online anomaly detection: step-time regression, cross-rank skew,
NaN / loss-plateau sentinels — and the ``health.json`` contract.

Until round 10 every telemetry surface was postmortem-only: flights and
journals say what a dead run did, but nothing watched a LIVE run for
the failure shapes that precede death — a step-time regression (thermal
throttle, a neighbor stealing the box, a silently-degraded backend), a
straggling rank stretching every collective rendezvous, a loss gone
NaN or flat.  This module is the watching half: stdlib-only online
detectors cheap enough to feed from the existing hook boundaries
(training/hooks.AnomalyHook, resilience/fleet.py's monitor loop), with
three surfaces per detection:

- **counters/gauges** in the shared registry (``anomaly_flags_total``
  by kind, ``anomaly_step_time_z``, ``fleet_step_skew_steps``);
- a machine-readable **``health.json``** (atomic, canonical JSON) the
  FleetSupervisor reads to annotate journal events — DETECTION ONLY,
  restart logic is unchanged by design: a false positive must cost a
  log line, never a teardown;
- **recorder triggers**: the hook/fleet dump a flight on a NEW firing,
  so the postmortem ring covers the steps AROUND the anomaly instead
  of whatever the run happened to die on later.

Detector design notes:

- :class:`EwmaRegression` pins its baseline over the first ``warmup``
  samples and never updates it — an EWMA-tracking baseline would
  absorb a slow regression (the boiled-frog failure); a pinned one
  keeps the z-score honest against the run's own healthy start.  The
  baseline sigma is floored at ``min_sigma_frac * |mean|``: warmup
  samples on a quiet box can be near-constant, and an unfloored sigma
  would turn scheduler jitter into a fired flag.
- :func:`detect_skew` separates **lag** (step-count distance behind the
  front rank — the signal when ranks run independently) from
  **straggler** (lag PLUS evidence the rank is actually slow: its own
  step-time regression flag, or a step time far above the fleet
  median).  Lag alone is not enough: a rank still compiling, or merely
  sampled at an unlucky instant, lags without being slow, and flagging
  it would name the wrong rank in the one artifact an operator trusts.
- Thresholds default from env (``OBS_ANOMALY_*``) so a drill can
  tighten warmup without new plumbing through every CLI.
"""

from __future__ import annotations

import json
import math
import os

from distributedtensorflowexample_tpu.obs import metrics as _metrics

HEALTH_VERSION = 1

# One counter family for every anomaly kind, fleet- and rank-side: a
# scraper alerts on rate(anomaly_flags_total) without enumerating kinds.
FLAGS_TOTAL = _metrics.counter(
    "anomaly_flags_total", "anomaly detections, by kind (and rank when "
    "flagged by the fleet)")
STEP_TIME_Z = _metrics.gauge(
    "anomaly_step_time_z",
    "EWMA step-time z-score against the warmup-pinned baseline")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def default_warmup() -> int:
    return int(_env_float("OBS_ANOMALY_WARMUP", 16))


def default_z_thresh() -> float:
    return _env_float("OBS_ANOMALY_Z", 8.0)


class EwmaRegression:
    """Step-time regression: EWMA-smoothed samples scored against a
    baseline PINNED over the first ``warmup`` samples (Welford mean/var,
    then frozen).  ``observe`` returns True exactly once — on the sample
    where the smoothed z-score first crosses ``z_thresh`` (the firing is
    latched; ``firing`` stays True while the z-score remains over)."""

    def __init__(self, warmup: int | None = None,
                 alpha: float = 0.3,
                 z_thresh: float | None = None,
                 min_sigma_frac: float = 0.05,
                 skip_first: int | None = None):
        self.warmup = max(2, default_warmup() if warmup is None else warmup)
        self.alpha = alpha
        self.z_thresh = default_z_thresh() if z_thresh is None else z_thresh
        self.min_sigma_frac = min_sigma_frac
        # The first call boundary's window is compile-dominated (jit
        # tracing + XLA compile: seconds against sub-ms steps — measured
        # in the faultline smoke while building this); folding it into
        # the baseline inflates mean AND sigma so far that no later
        # regression can ever score.  Skipped samples feed nothing.
        self.skip_first = (int(_env_float("OBS_ANOMALY_SKIP", 1))
                           if skip_first is None else skip_first)
        self.n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.mu0: float | None = None      # pinned once n == warmup
        self.sigma0: float | None = None
        self.ewma: float | None = None
        self.z = 0.0
        self.fired_step: int | None = None
        self.last: float | None = None

    @property
    def armed(self) -> bool:
        return self.mu0 is not None

    @property
    def firing(self) -> bool:
        return self.armed and self.z > self.z_thresh

    def observe(self, x: float, step: int | None = None) -> bool:
        """Feed one step-time sample (seconds/step); returns True on the
        FIRST firing only."""
        if self.skip_first > 0:
            self.skip_first -= 1
            return False
        self.n += 1
        self.last = x
        self.ewma = x if self.ewma is None else (
            self.ewma + self.alpha * (x - self.ewma))
        if self.mu0 is None:
            d = x - self._mean
            self._mean += d / self.n
            self._m2 += d * (x - self._mean)
            if self.n >= self.warmup:
                sigma = math.sqrt(self._m2 / max(1, self.n - 1))
                self.mu0 = self._mean
                self.sigma0 = max(sigma,
                                  self.min_sigma_frac * abs(self._mean),
                                  1e-9)
            return False
        self.z = (self.ewma - self.mu0) / self.sigma0
        if self.z > self.z_thresh and self.fired_step is None:
            self.fired_step = step if step is not None else self.n
            return True
        return False

    def payload(self) -> dict:
        r6 = lambda v: None if v is None else round(v, 6)
        return {"n": self.n, "warmup": self.warmup,
                "z_thresh": self.z_thresh,
                "baseline_mean_s": r6(self.mu0),
                "baseline_sigma_s": r6(self.sigma0),
                "ewma_s": r6(self.ewma), "last_s": r6(self.last),
                "z": round(self.z, 3),
                "firing": self.firing, "fired_step": self.fired_step}


class PlateauSentinel:
    """Loss plateau: fires when the best (lowest) loss seen in the last
    ``window`` samples fails to improve on the best BEFORE the window by
    at least ``min_delta``.  Windowed (not whole-history) so a run that
    improves, plateaus, then improves again re-arms."""

    def __init__(self, window: int = 100, min_delta: float = 1e-4):
        self.window = max(2, window)
        self.min_delta = min_delta
        self._tail: list = []           # last `window` losses
        self._best_before: float | None = None
        self.fired_step: int | None = None
        self.firing = False

    def observe(self, loss: float, step: int | None = None) -> bool:
        if not math.isfinite(loss):
            return False                # the NaN sentinel's job, not ours
        self._tail.append(loss)
        if len(self._tail) <= self.window:
            return False
        evicted = self._tail.pop(0)
        self._best_before = (evicted if self._best_before is None
                             else min(self._best_before, evicted))
        was_firing = self.firing
        self.firing = (min(self._tail)
                       > self._best_before - self.min_delta)
        # Rising-edge fire: each distinct plateau (firing False -> True)
        # fires once — improve-plateau-improve really re-arms, as the
        # windowed design promises.  fired_step keeps the FIRST plateau.
        if self.firing and not was_firing:
            if self.fired_step is None:
                self.fired_step = step
            return True
        return False

    def payload(self) -> dict:
        return {"window": self.window, "min_delta": self.min_delta,
                "firing": self.firing, "fired_step": self.fired_step,
                "best_before_window": (
                    None if self._best_before is None
                    else round(self._best_before, 6))}


class RunHealth:
    """One process's online health: step-time regression + NaN/plateau
    sentinels, serialized as the per-rank ``health.json`` the fleet
    reads.  ``observe_window``/``observe_loss`` return the list of kinds
    that NEWLY fired (the caller's cue to bump counters, emit a trace
    event, and dump a flight)."""

    def __init__(self, rank: int | None = None,
                 step_time: EwmaRegression | None = None,
                 plateau: PlateauSentinel | None = None):
        if rank is None:
            r = os.environ.get("OBS_RANK", "")
            rank = int(r) if r.lstrip("-").isdigit() else None
        self.rank = rank
        self.step_time = step_time or EwmaRegression()
        self.plateau = plateau or PlateauSentinel()
        self.nan_step: int | None = None
        self.step = 0
        self.anomalies = 0

    def observe_window(self, step: int, advanced: int,
                       window_s: float) -> list[str]:
        """Feed one call-boundary window (``advanced`` steps in
        ``window_s`` wall seconds) — the hot-path half: float math only,
        no IO."""
        self.step = step
        fired = []
        if advanced > 0 and self.step_time.observe(window_s / advanced,
                                                   step=step):
            fired.append("step_time_regression")
        self.anomalies += len(fired)
        return fired

    def observe_loss(self, step: int, loss: float) -> list[str]:
        """Feed one sampled loss (log-boundary cadence)."""
        fired = []
        if not math.isfinite(loss):
            if self.nan_step is None:
                self.nan_step = step
                fired.append("nan_loss")
        elif self.plateau.observe(loss, step=step):
            fired.append("loss_plateau")
        self.anomalies += len(fired)
        return fired

    @property
    def flags(self) -> dict:
        return {
            "step_time_regression": {
                "firing": self.step_time.firing,
                "fired_step": self.step_time.fired_step,
                "z": round(self.step_time.z, 3)},
            "nan_loss": {"firing": self.nan_step is not None,
                         "fired_step": self.nan_step},
            "loss_plateau": {"firing": self.plateau.firing,
                             "fired_step": self.plateau.fired_step}}

    def payload(self) -> dict:
        return {"version": HEALTH_VERSION, "kind": "rank",
                "rank": self.rank, "pid": os.getpid(),
                "updated_unix": round(_metrics._wall(), 3),
                "step": self.step,
                "anomalies_total": self.anomalies,
                "flags": self.flags,
                "detectors": {"step_time": self.step_time.payload(),
                              "plateau": self.plateau.payload()}}

    def write(self, path: str) -> None:
        write_health(path, self.payload())


def write_health(path: str, payload: dict) -> None:
    """Atomic canonical-JSON write; swallows OSError — health reporting
    must never kill the run it reports on (same contract as the beat)."""
    from distributedtensorflowexample_tpu.obs.recorder import atomic_write
    try:
        atomic_write(path, json.dumps(
            _metrics.json_safe(payload), sort_keys=True, indent=1,
            allow_nan=False, default=str).encode() + b"\n")
    except OSError:
        pass


def read_health(path: str) -> dict | None:
    """Tolerant read: None for missing/torn/not-yet-written files (the
    fleet polls these mid-write; atomic_write means torn should never
    happen, but a reader must not crash the supervisor either way)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return payload if isinstance(payload, dict) else None


def detect_skew(ranks: dict, lag_steps: int = 3,
                time_ratio: float = 4.0) -> dict:
    """Cross-rank skew over per-rank health reports.

    ``ranks``: rank -> {"step": int, "step_time_s": float|None (recent
    EWMA), "regression_firing": bool, "hb_age_s": float|None}.  Needs at
    least two reporting ranks (skew is a relation).

    Returns ``{"max_step", "lag_steps": {rank: lag}, "laggards": [...],
    "stragglers": [...], "why": {rank: reason}, "median_step_time_s"}``.
    A **laggard** merely trails the front rank by >= ``lag_steps``; a
    **straggler** is a laggard with evidence it is actually slow: its
    own step-time regression flag, step time > ``time_ratio`` x the
    other ranks' median, or a stalled heartbeat — the beat goes stale
    exactly when a boundary stalls, so a wedged-but-alive rank is named
    even when its last health report predates the stall.  ``hb_age_s``
    must be passed ONLY when the caller judged the span meaningful
    (FleetSupervisor._stale_beat_span gates it against the rank's OWN
    observed beat cadence — raw age at a coarse beat cadence is noise,
    not evidence); pass None otherwise.  See the module docstring for
    why lag alone must not name a straggler."""
    reporting = {r: d for r, d in ranks.items()
                 if d.get("step") is not None}
    out = {"max_step": None, "lag_steps": {}, "laggards": [],
           "stragglers": [], "why": {}, "median_step_time_s": None}
    if len(reporting) < 2:
        return out
    max_step = max(d["step"] for d in reporting.values())
    out["max_step"] = max_step
    times = sorted(d["step_time_s"] for d in reporting.values()
                   if d.get("step_time_s"))
    median = times[len(times) // 2] if times else None
    out["median_step_time_s"] = (None if median is None
                                 else round(median, 6))
    for r, d in sorted(reporting.items()):
        lag = max_step - d["step"]
        out["lag_steps"][r] = lag
        if lag < lag_steps:
            continue
        out["laggards"].append(r)
        st = d.get("step_time_s")
        # Median of the OTHER ranks: with 2 ranks the straggler's own
        # time IS the median of all, which would mask itself.
        others = sorted(v["step_time_s"] for k, v in reporting.items()
                        if k != r and v.get("step_time_s"))
        med_others = others[len(others) // 2] if others else None
        slow_vs_fleet = (st is not None and med_others
                         and st > time_ratio * med_others)
        # The caller already vetted the span (hb_age_s is passed ONLY
        # when stale vs the rank's own beat cadence) — re-gating it
        # against a step-time scale would DROP the evidence whenever
        # the peers' ewma is unavailable, naming no one.
        age = d.get("hb_age_s")
        stale_beat = age is not None and age > 0
        if d.get("regression_firing"):
            out["stragglers"].append(r)
            out["why"][r] = (f"lag {lag} steps behind rank front "
                             f"(step {d['step']} vs {max_step}) with its "
                             f"own step-time regression firing")
        elif slow_vs_fleet:
            out["stragglers"].append(r)
            out["why"][r] = (f"lag {lag} steps; step time {st:.4f}s > "
                             f"{time_ratio:.0f}x fleet median "
                             f"{med_others:.4f}s")
        elif stale_beat:
            out["stragglers"].append(r)
            out["why"][r] = (f"lag {lag} steps; heartbeat stale for "
                             f"{age:.1f}s against its own beat cadence")
        else:
            out["why"][r] = f"lagging {lag} steps (no slowness evidence)"
    return out


def spread_fraction(samples) -> float:
    """(max - min) / max over positive samples — the bench family's
    measurement-instability sentinel (a wide repeat spread marks the
    window, and the record, as noisy before a ratchet compares it)."""
    vals = [s for s in samples
            if isinstance(s, (int, float)) and s > 0]
    if len(vals) < 2:
        return 0.0
    return (max(vals) - min(vals)) / max(vals)
