"""Live telemetry over HTTP — scrape a RUNNING process, not its corpse.

Every obs surface before round 12 was file-shaped: flights land on
death, the Prometheus textfile lands after a task, health.json lands at
hook cadence.  Files are the right postmortem transport, but the north
star "serving heavy traffic" needs the live shape too: a scraper (or an
operator with curl) asking a training process how it is doing RIGHT NOW.
This module is that surface — an opt-in (``OBS_HTTP_PORT``) background
``http.server`` thread per process, read-only, loopback by default:

- ``GET /metrics``  — the registry as Prometheus text (the same bytes
  ``obs/export.py`` writes to the textfile collector, so the two
  transports can never disagree on a value's spelling);
- ``GET /health``   — the §16 ``health.json`` contract: the registered
  in-process source (``training/hooks.AnomalyHook`` registers its
  ``RunHealth.payload``) or, failing that, the ``OBS_HEALTH`` file;
- ``GET /flight``   — the installed flight recorder's payload, built
  on demand (a postmortem for a process that has not died yet);
- ``GET /ledger/tail?n=50`` — the last rows of the ``OBS_LEDGER`` run
  ledger, parsed (torn lines skipped, like every ledger reader).

The server is a daemon thread: it dies with the process and never
blocks exit.  Failures are silent-by-contract (a port collision or a
handler exception must not kill the run it observes) — ``maybe_start``
logs the refusal to stderr and returns None.  The fleet supervisor
prefers this surface for its monitor pass (HTTP scrape of each rank's
``/health``, falling back to the file) and exports a per-rank port when
launched with ``--http``.

Stdlib-only (http.server, json, threading) like the rest of ``obs/``.
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import os

from distributedtensorflowexample_tpu.obs import metrics as _metrics

# The in-process health source (AnomalyHook registers its RunHealth
# payload callable here): live detector state beats a file that is only
# as fresh as the last hook boundary.
_health_source = None


def set_health_source(fn) -> None:
    """Register ``fn() -> dict`` as this process's live health payload
    (last registration wins — one AnomalyHook per run by construction)."""
    global _health_source
    _health_source = fn


class _Handler(BaseHTTPRequestHandler):
    # Tests and drills hit this from the same box; per-request stderr
    # lines would interleave with the training logs they scrape around.
    def log_message(self, format, *args):  # noqa: A002 (stdlib signature)
        pass

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload) -> None:
        self._send(code, json.dumps(
            _metrics.json_safe(payload), sort_keys=True,
            allow_nan=False, default=str).encode() + b"\n")

    def do_GET(self):  # noqa: N802 (stdlib casing)
        try:
            url = urlparse(self.path)
            if url.path == "/metrics":
                from distributedtensorflowexample_tpu.obs import (
                    export as _export)
                self._send(200, _export.prometheus_text().encode(),
                           ctype="text/plain; version=0.0.4")
            elif url.path == "/health":
                self._health()
            elif url.path == "/flight":
                self._flight()
            elif url.path in ("/ledger/tail", "/ledger"):
                self._ledger_tail(url)
            else:
                self._send_json(404, {"error": f"unknown path {url.path}",
                                      "paths": ["/metrics", "/health",
                                                "/flight", "/ledger/tail"]})
        except BrokenPipeError:
            pass        # scraper hung up mid-response: its problem
        except Exception as e:
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:
                pass    # telemetry must never kill the run it observes

    def _health(self) -> None:
        if _health_source is not None:
            self._send_json(200, _health_source())
            return
        # File fallback: a process without an AnomalyHook (bench) may
        # still have a health file some other writer maintains.
        path = os.environ.get("OBS_HEALTH", "")
        if path:
            from distributedtensorflowexample_tpu.obs import (
                anomaly as _anomaly)
            payload = _anomaly.read_health(path)
            if payload is not None:
                self._send_json(200, payload)
                return
        self._send_json(503, {"error": "no health source in this process "
                                       "(no AnomalyHook registered, no "
                                       "readable OBS_HEALTH file)"})

    def _flight(self) -> None:
        from distributedtensorflowexample_tpu.obs import (
            recorder as _recorder)
        rec = _recorder.get()
        if rec is None:
            self._send_json(503, {"error": "no flight recorder installed "
                                           "(supervised runs and "
                                           "OBS_FLIGHT=1 arm one)"})
            return
        self._send_json(200, rec.payload("http"))

    def _ledger_tail(self, url) -> None:
        from distributedtensorflowexample_tpu.obs import ledger as _ledger
        path = _ledger.ledger_path()
        if not path or not os.path.exists(path):
            self._send_json(503, {"error": "no run ledger in this process "
                                           "(OBS_LEDGER unset or file "
                                           "missing)"})
            return
        try:
            n = int(parse_qs(url.query).get("n", ["50"])[0])
        except ValueError:
            n = 50
        # Bounded tail read: this handler runs inside the observed
        # process — a poll must not bill it a full-file re-parse.
        rows, torn = _ledger.tail_rows(path, n)
        self._send_json(200, {"path": path, "torn": torn, "rows": rows})


class ObsServer:
    """The serving thread; ``port=0`` binds an ephemeral port (the
    bound one is on ``.port`` after :meth:`start`)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._host = host
        self._port = int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return (self._httpd.server_address[1] if self._httpd is not None
                else self._port)

    def start(self) -> "ObsServer":
        self._httpd = ThreadingHTTPServer((self._host, self._port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.5},
            name="obs-serve", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


_GLOBAL: ObsServer | None = None


def get() -> ObsServer | None:
    return _GLOBAL


def maybe_start() -> ObsServer | None:
    """Start the per-process scrape endpoint iff ``OBS_HTTP_PORT`` is a
    positive port (the fleet supervisor exports one per rank under
    ``--http``; an operator exports one by hand) — THE one arming
    predicate, consulted next to ``recorder.maybe_install`` in every
    entrypoint.  Idempotent; refusals (bad value, port taken) go to
    stderr and return None: a scrape endpoint must never be the reason
    a run dies."""
    global _GLOBAL
    if _GLOBAL is not None:
        return _GLOBAL
    raw = os.environ.get("OBS_HTTP_PORT", "")
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        print(f"obs.serve: OBS_HTTP_PORT={raw!r} is not a port — not "
              f"serving", file=sys.stderr, flush=True)
        return None
    if port <= 0:
        return None
    if port > 65535:
        # Out-of-range before bind: socket raises OverflowError there,
        # which is NOT an OSError — uncaught it would break the
        # never-kill-the-run contract on an operator typo.
        print(f"obs.serve: OBS_HTTP_PORT={port} is out of range — not "
              f"serving", file=sys.stderr, flush=True)
        return None
    try:
        _GLOBAL = ObsServer(port).start()
    except (OSError, OverflowError) as e:
        print(f"obs.serve: could not bind 127.0.0.1:{port} ({e}) — not "
              f"serving", file=sys.stderr, flush=True)
        _GLOBAL = None
        return None
    return _GLOBAL
