"""obs/ — unified telemetry: metrics registry, trace spans, flight
recorder, and exporters for every run on the box.

Rounds 3-5 were one long outage diagnosed by grepping ad-hoc prints out
of watcher logs; this package is the structured replacement — one
instrumentation surface shared by trainers, the supervisor, the bench
family, and the capture queue (the TF-Replicator lesson: one monitoring
surface for every parallelism mode).

Four cooperating pieces, each usable alone:

- :mod:`.metrics` — process-wide registry of counters/gauges/histograms
  with labels, monotonic-clock timestamps, and snapshot/delta semantics.
  The hot path (one counter increment) is lock-free and microbench-
  guarded below 2 us (tests/test_obs.py).
- :mod:`.trace` — nestable span API (``with span("dispatch"): ...``)
  emitting JSONL trace events with step/attempt/phase context picked up
  from the supervisor's env (``SUPERVISE_ATTEMPT``, ``OBS_PHASE``).
- :mod:`.recorder` — bounded in-memory flight recorder (ring of recent
  spans, metric deltas, and the loss-tape tail) that dumps atomically
  to ``flight_<pid>.json`` on SIGTERM / NaN-guard trip / supervisor
  escalation, so every dead run leaves a postmortem.
- :mod:`.export` — Prometheus-textfile and JSONL exporters;
  ``tools/obs_report.py`` renders any dump as an OUTAGE_r*-style table.
- :mod:`.timeline` — cross-rank merge of flights/trace JSONL/journals
  into one wall-clock-aligned timeline (spans carry monotonic AND wall
  stamps since round 10), with a Perfetto/Chrome-trace exporter and a
  per-step anatomy decomposition (input/compute/snapshot/hook/other +
  the compiled collective schedule).
- :mod:`.anomaly` — online detectors fed from the same hooks: warmup-
  pinned EWMA step-time regression, cross-rank skew/straggler
  detection, NaN / loss-plateau sentinels; surfaced as registry
  counters, a machine-readable ``health.json``, and flight-recorder
  triggers (a detected anomaly dumps a postmortem BEFORE escalation).
- :mod:`.ledger` — the CROSS-run record: an append-only, crash-tolerant
  ``RUNS.jsonl`` (``OBS_LEDGER=<path>``) of run_start / bounded-
  resolution metric samples / run_end rows plus fleet annotations,
  queryable live and diffable after the fact (``tools/obs_query.py``).
- :mod:`.serve` — the LIVE scrape surface: an opt-in
  (``OBS_HTTP_PORT``) background HTTP thread per process exposing
  ``/metrics`` (Prometheus text), ``/health`` (the §16 contract),
  ``/flight`` (on-demand recorder dump), and ``/ledger/tail``.

Deliberately **stdlib-only**: importing obs never pulls jax, so
bench.py's record-survival contract (its SIGTERM handler must be live
before the first heavyweight import) and the supervisor's lightweight
process both instrument themselves for free.
"""

from distributedtensorflowexample_tpu.obs.anomaly import (  # noqa: F401
    EwmaRegression, PlateauSentinel, RunHealth, detect_skew, read_health,
    write_health)
from distributedtensorflowexample_tpu.obs.ledger import (  # noqa: F401
    RunLedger, run_table)
from distributedtensorflowexample_tpu.obs.serve import (  # noqa: F401
    ObsServer)
from distributedtensorflowexample_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry, counter, gauge, histogram, registry)
from distributedtensorflowexample_tpu.obs.recorder import (  # noqa: F401
    FlightRecorder, dump_global, flight_path, install, maybe_install)
from distributedtensorflowexample_tpu.obs.trace import (  # noqa: F401
    add_sink, event, remove_sink, span)
