"""Cross-rank timeline: merge per-rank telemetry into one step-aligned
view, export it as Perfetto/Chrome-trace JSON, decompose step time.

Inputs (all tolerant of gaps — a fleet postmortem is exactly the moment
some rank's file is missing or torn):

- **flight files** (``flight_<rank>_<pid>.json``, obs/recorder.py):
  the span ring plus run identity and the metrics snapshot;
- **trace JSONL** (``OBS_TRACE_FILE``, obs/trace.py): every span event,
  unbounded — the high-fidelity source when a run exported one;
- **supervisor/fleet journals** (JSON lines with wall ``ts``):
  gang/rank lifecycle + anomaly annotations, rendered as instant
  markers on the merged timeline.

Clock model (the round-10 fix that makes the merge possible): every
span event carries BOTH ``t0_s`` (monotonic — honest durations, but a
per-boot epoch incomparable across processes) and ``t0_unix`` (wall —
shared on a host, NTP-close across one).  The merge places events by
wall time and keeps monotonic durations.  Events from BEFORE the fix
carry only ``t0_s``; :func:`calibrate` recovers their wall stamps from
any sibling event in the same process that has both (one stamped event
calibrates the whole monotonic series — offset = t0_unix - t0_s is a
per-boot constant), and counts the events no sibling could place.

Stdlib-only like the rest of obs/ — tools/obs_report.py renders these
merges on a box mid-outage with nothing but a Python interpreter.
"""

from __future__ import annotations

import glob
import json
import os
import re

_FLIGHT_RANK_RE = re.compile(r"flight_(\d+)_\d+\.json$")
# The collective series-key shape MetricsHook writes (shared: tools/
# obs_report.py renders the same gauges — one parser, no drift).
COLL_SERIES_RE = re.compile(
    r'^collective_(ops|bytes)_per_step\{op="([^"]+)"\}$')

# Span names that are per-step anatomy categories (see step_anatomy):
# checkpoint/snapshot both mean "serialize state" (CheckpointHook vs
# resilience SnapshotStore) — one column.
_SNAPSHOT_SPANS = ("snapshot", "checkpoint")


def _rank_key(rank):
    """Type-stable sort key: OBS_RANK need not be numeric (trace._context
    and the flight writer both keep e.g. "chief" as-is), so ranks of
    mixed int/str must sort without a TypeError mid-outage — ints first
    in numeric order, then strings, None last."""
    if rank is None:
        return (2, "", 0)
    if isinstance(rank, str):
        return (1, rank, 0)
    return (0, "", rank)


# --- loading ---------------------------------------------------------------

def _norm(ev: dict, src: str, rank=None, attempt=None, pid=None) -> dict:
    """Normalize one span event: identity fields resolved (event-level
    context wins over source-level — a trace file may interleave
    attempts), source recorded for provenance."""
    out = dict(ev)
    out["rank"] = ev.get("rank", rank)
    out["attempt"] = ev.get("attempt", attempt)
    out["pid"] = ev.get("pid", pid)
    out["src"] = src
    return out


def events_from_flight(flight: dict, src: str = "") -> list[dict]:
    return [_norm(ev, src or f"flight:{flight.get('pid')}",
                  rank=flight.get("rank"), attempt=flight.get("attempt"),
                  pid=flight.get("pid"))
            for ev in flight.get("spans") or [] if isinstance(ev, dict)]


def events_from_trace_file(path: str) -> tuple[list[dict], int]:
    """(events, torn_lines) — a trace JSONL whose writer died mid-line
    loses that line, not the file."""
    events, torn = [], 0
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return [], 0
    for line in lines:
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            torn += 1
            continue
        if isinstance(ev, dict) and "name" in ev:
            events.append(_norm(ev, f"trace:{os.path.basename(path)}"))
    return events, torn


def journal_records(path: str) -> tuple[list[dict], int]:
    """(records, torn) — same tolerant JSONL read the journal's own
    replay uses."""
    records, torn = [], 0
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return [], 0
    for line in lines:
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            torn += 1
    return records, torn


def per_rank_collectives(flight: dict) -> dict:
    """{op: {"ops": n, "bytes": b}} from the flight's per-step
    collective gauges (OBS_COLLECTIVES=1 runs) — the anatomy table's
    collective column."""
    out: dict = {}
    for key, g in (flight.get("metrics") or {}).get("gauges", {}).items():
        m = COLL_SERIES_RE.match(key)
        if m:
            out.setdefault(m.group(2), {})[
                "ops" if m.group(1) == "ops" else "bytes"] = g.get("value")
    return out


# --- calibration -----------------------------------------------------------

def calibrate(events: list[dict]) -> int:
    """Fill missing ``t0_unix`` in place from per-process monotonic->wall
    offsets (keyed by (src, pid): one boot epoch per process).  Returns
    how many events NO sibling could place — the merge reports them
    instead of silently dropping lanes."""
    offsets: dict = {}
    for ev in events:
        if ev.get("t0_unix") is not None and ev.get("t0_s") is not None:
            offsets.setdefault((ev["src"], ev.get("pid")),
                               ev["t0_unix"] - ev["t0_s"])
    unplaced = 0
    for ev in events:
        if ev.get("t0_unix") is None:
            off = offsets.get((ev["src"], ev.get("pid")))
            if off is not None and ev.get("t0_s") is not None:
                ev["t0_unix"] = round(ev["t0_s"] + off, 6)
            else:
                unplaced += 1
    return unplaced


# --- the merge -------------------------------------------------------------

def merge(flight_paths=(), trace_paths=(), journal_paths=(),
          health_paths=()) -> dict:
    """Merge every readable source into one timeline dict::

        {"events":   [span events, wall-ordered, rank/attempt labeled],
         "markers":  [journal records with wall ts],
         "health":   [health.json payloads],
         "collectives": {rank: {op: {"ops", "bytes"}}},
         "coverage": {"ranks_present", "ranks_expected", "ranks_missing",
                      "unreadable": {path: error}, "torn_lines": n,
                      "uncalibrated_events": n}}

    Tolerant by contract (the ISSUE's torn-flight satellite): an
    unreadable flight costs ITS lane plus a coverage entry, never the
    report."""
    events: list[dict] = []
    markers: list[dict] = []
    health: list[dict] = []
    collectives: dict = {}
    unreadable: dict = {}
    torn_lines = 0
    present: set = set()
    expected: set = set()

    for path in flight_paths:
        m = _FLIGHT_RANK_RE.search(os.path.basename(path))
        if m:
            expected.add(int(m.group(1)))
        try:
            with open(path) as f:
                flight = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            unreadable[path] = str(e)
            continue
        events.extend(events_from_flight(
            flight, src=f"flight:{os.path.basename(path)}"))
        rank = flight.get("rank")
        if rank is not None:
            present.add(rank)
            coll = per_rank_collectives(flight)
            if coll:
                collectives[rank] = coll
    for path in trace_paths:
        evs, torn = events_from_trace_file(path)
        events.extend(evs)
        torn_lines += torn
        present.update(ev["rank"] for ev in evs
                       if ev.get("rank") is not None)
    for path in journal_paths:
        records, torn = journal_records(path)
        torn_lines += torn
        for rec in records:
            if rec.get("ts") is not None:
                markers.append(rec)
            if rec.get("event") == "gang_start":
                expected.update(rec.get("ranks") or [])
    for path in health_paths:
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            unreadable[path] = str(e)
            continue
        if isinstance(payload, dict):
            payload["src"] = os.path.basename(path)
            health.append(payload)

    # Dedup across sources: a run with both OBS_DIR and OBS_TRACE_FILE
    # writes every span close to the flight ring AND the trace JSONL —
    # the same close must land on the timeline once, or anatomy totals
    # double and the tie-out against loop_*_seconds_total breaks.  The
    # identity tuple is everything a close stamps that BOTH writers
    # carry (pid lands only in flight payloads, so it can't be part of
    # identity); at µs monotonic precision two distinct spans of one
    # rank/attempt cannot collide on it.  First occurrence wins —
    # flights load first, so the pid-carrying copy is the one kept.
    seen: set = set()
    unique = []
    for ev in events:
        key = (ev.get("rank"), ev.get("attempt"), ev.get("name"),
               ev.get("t0_s"), ev.get("dur_s"), ev.get("step"))
        if key in seen:
            continue
        seen.add(key)
        unique.append(ev)
    events = unique
    uncalibrated = calibrate(events)
    events.sort(key=lambda ev: (ev.get("t0_unix") is None,
                                ev.get("t0_unix") or 0.0,
                                ev.get("t0_s") or 0.0))
    markers.sort(key=lambda r: r.get("ts") or 0.0)
    return {"events": events, "markers": markers, "health": health,
            "collectives": collectives,
            "coverage": {
                "ranks_present": sorted(present, key=_rank_key),
                "ranks_expected": sorted(expected | present,
                                         key=_rank_key),
                "ranks_missing": sorted(expected - present,
                                        key=_rank_key),
                "unreadable": unreadable,
                "torn_lines": torn_lines,
                "uncalibrated_events": uncalibrated}}


def fleet_dir_sources(flight_dir: str = "", journal: str = "",
                      trace_glob: str = "") -> dict:
    """Discover a fleet run's sources: flights + per-rank/fleet
    health.json next to the flight dir and the journal."""
    flights = (sorted(glob.glob(os.path.join(flight_dir, "flight_*.json")))
               if flight_dir else [])
    health: list[str] = []
    for base in {flight_dir, os.path.dirname(journal)} - {""}:
        health += sorted(glob.glob(os.path.join(base, "health*.json")))
    base_name = os.path.basename(flight_dir.rstrip(os.sep))
    if base_name == "flight" or base_name.endswith("_flight"):
        # ONLY the documented layouts reach one level up: the fleet
        # puts health files in the WORKDIR with flights in
        # <workdir>/flight, and supervise --capture archives flights in
        # <journal>_flight/ next to the journal.  An arbitrary --dir
        # (or the journal's parent) must never widen the glob — a
        # flight dir directly under /tmp would merge some other
        # process's /tmp/health*.json into this report.
        parent = os.path.dirname(flight_dir.rstrip(os.sep))
        if parent:
            health += sorted(glob.glob(os.path.join(parent,
                                                    "health*.json")))
    traces = sorted(glob.glob(trace_glob)) if trace_glob else []
    return {"flight_paths": flights, "trace_paths": traces,
            "journal_paths": [journal] if journal else [],
            "health_paths": sorted(set(health))}


# --- Perfetto / Chrome-trace export ---------------------------------------

_FLEET_LANE = 9999      # pid lane for rank-less events (fleet, bench)
_SLOT_TRACK_BASE = 1000  # tid offset for serving decode-slot tracks


def chrome_trace(merged: dict) -> dict:
    """Chrome-trace JSON (the dialect Perfetto and chrome://tracing both
    load): one process lane per rank, complete events for spans, instant
    events for journal markers.  ``ts`` is microseconds from the
    earliest wall stamp so the numbers stay readable."""
    events = [ev for ev in merged["events"]
              if ev.get("t0_unix") is not None]
    stamps = ([ev["t0_unix"] for ev in events]
              + [r["ts"] for r in merged["markers"]
                 if r.get("ts") is not None])
    base = min(stamps) if stamps else 0.0
    lanes: dict = {}
    out: list = []
    # Non-numeric ranks (OBS_RANK="chief" is legal everywhere upstream)
    # need int pids for Perfetto: deterministic lanes above the fleet
    # lane, in sorted order over every rank this merge carries.
    named = sorted({r for r in
                    ([ev.get("rank") for ev in events]
                     + [m.get("rank") for m in merged["markers"]])
                    if isinstance(r, str)})
    named_pid = {r: _FLEET_LANE + 1 + i for i, r in enumerate(named)}

    def _lane(rank, label: str):
        pid = (_FLEET_LANE if rank is None
               else named_pid[rank] if isinstance(rank, str)
               else int(rank))
        if pid not in lanes:
            lanes[pid] = True
            out.append({"ph": "M", "pid": pid, "name": "process_name",
                        "args": {"name": label}})
            out.append({"ph": "M", "pid": pid, "name": "process_sort_index",
                        "args": {"sort_index": pid}})
        return pid

    slot_tids: set = set()
    for ev in events:
        rank = ev.get("rank")
        pid = _lane(rank, "fleet / unranked" if rank is None
                    else f"rank {rank}")
        attempt = ev.get("attempt") or 0
        args = {k: v for k, v in ev.items()
                if k not in ("name", "t0_s", "t0_unix", "dur_s", "depth",
                             "parent", "pid", "src", "rank")}
        # Serving events carry a decode-slot attr: one Perfetto lane
        # PER SLOT (tid offset past the attempt tracks), so a worker's
        # request lifecycle (queue → prefill → decode) renders as slot
        # occupancy over time instead of interleaving on one row.
        slot = ev.get("slot")
        if isinstance(slot, int) and slot >= 0:
            tid = _SLOT_TRACK_BASE + slot
            if (pid, tid) not in slot_tids:
                slot_tids.add((pid, tid))
                out.append({"ph": "M", "pid": pid, "tid": tid,
                            "name": "thread_name",
                            "args": {"name": f"slot {slot}"}})
                out.append({"ph": "M", "pid": pid, "tid": tid,
                            "name": "thread_sort_index",
                            "args": {"sort_index": tid}})
        else:
            tid = int(attempt) if str(attempt).isdigit() else 0
        out.append({"ph": "X", "pid": pid,
                    # One track per attempt: restarts render as separate
                    # rows instead of interleaving with the run they
                    # replaced.  Same-track nesting comes from span
                    # containment, which the thread-local span stack
                    # guarantees within one attempt.
                    "tid": tid,
                    "name": str(ev.get("name")),
                    "ts": round((ev["t0_unix"] - base) * 1e6, 1),
                    "dur": round((ev.get("dur_s") or 0.0) * 1e6, 1),
                    "args": args})
    for rec in merged["markers"]:
        if rec.get("ts") is None:
            continue
        rank = rec.get("rank")
        pid = _lane(rank, "fleet / unranked" if rank is None
                    else f"rank {rank}")
        out.append({"ph": "i", "pid": pid, "tid": 0, "s": "p",
                    "name": str(rec.get("event")),
                    "ts": round((rec["ts"] - base) * 1e6, 1),
                    "args": {k: v for k, v in rec.items()
                             if k not in ("ts", "event")}})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"base_unix": base,
                          "coverage": merged["coverage"]}}


# --- step anatomy ----------------------------------------------------------

def step_anatomy(merged: dict) -> list[dict]:
    """Per-window step-time decomposition from the ``steps`` span events
    (training/hooks.MetricsHook emits one per log boundary, carrying the
    TrainLoop category counters' deltas) plus snapshot/checkpoint spans
    contained in each window.

    Row: {rank, attempt, step_from, step_to, n, window_s, input_s,
    compute_s, hook_s, snapshot_s, other_s, collective_ops,
    collective_bytes}.  Category semantics (DESIGN.md §16): ``input`` =
    host batch fetch, ``compute`` = the train-step call (dispatch +
    compute + collective wait — XLA fuses them; the collective columns
    carry the compiled schedule's per-step op/byte counts instead of a
    time this pin cannot separate), ``hook`` = after_step hooks minus
    the snapshot spans broken out, ``other`` = logging + loop
    bookkeeping (the window remainder).  Totals tie out against the
    ``loop_*_seconds_total`` counters — gated in tests."""
    spans = [ev for ev in merged["events"] if ev.get("name") == "steps"
             and ev.get("dur_s") is not None]
    snap_spans = [ev for ev in merged["events"]
                  if ev.get("name") in _SNAPSHOT_SPANS
                  and ev.get("t0_unix") is not None]
    rows = []
    for ev in spans:
        rank, attempt = ev.get("rank"), ev.get("attempt")
        n = ev.get("n") or 0
        window = ev["dur_s"]
        t0, t1 = ev.get("t0_unix"), None
        if t0 is not None:
            t1 = t0 + window
        snapshot_s = sum(
            s.get("dur_s") or 0.0 for s in snap_spans
            if s.get("rank") == rank and s.get("attempt") == attempt
            and t0 is not None
            and t0 - 1e-6 <= s["t0_unix"] <= t1 + 1e-6)
        input_s = ev.get("input_s")
        compute_s = ev.get("compute_s")
        hook_s = ev.get("hook_s")
        other_s = None
        if None not in (input_s, compute_s, hook_s):
            other_s = max(0.0, window - input_s - compute_s - hook_s)
        coll = merged["collectives"].get(rank) or {}
        ops = sum(d.get("ops") or 0 for d in coll.values())
        nbytes = sum(d.get("bytes") or 0 for d in coll.values())
        rows.append({
            "rank": rank, "attempt": attempt,
            "step_from": (ev.get("step") - n if ev.get("step") is not None
                          else None),
            "step_to": ev.get("step"), "n": n,
            "t0_unix": t0,
            "window_s": round(window, 6),
            "input_s": input_s, "compute_s": compute_s,
            "hook_s": (None if hook_s is None
                       else round(max(0.0, hook_s - snapshot_s), 6)),
            "snapshot_s": round(snapshot_s, 6),
            "other_s": None if other_s is None else round(other_s, 6),
            "collective_ops": ops * n if coll else None,
            "collective_bytes": nbytes * n if coll else None})
    rows.sort(key=lambda r: (_rank_key(r["rank"]),
                             r["attempt"] or 0,
                             r["t0_unix"] or 0.0))
    return rows


def anatomy_totals(rows: list[dict]) -> dict:
    """Per-category sums over anatomy rows (the tie-out side: compare
    against the flight's ``loop_*_seconds_total`` counters)."""
    tot = {"window_s": 0.0, "input_s": 0.0, "compute_s": 0.0,
           "hook_s": 0.0, "snapshot_s": 0.0, "other_s": 0.0,
           "collective_ops": 0, "collective_bytes": 0, "n": 0}
    for row in rows:
        for k in tot:
            v = row.get(k)
            if v is not None:
                tot[k] = round(tot[k] + v, 6)
    return tot
