"""Bounded in-memory flight recorder — every dead run leaves a postmortem.

The recorder rings the last N trace spans, metric-registry deltas, and
the loss-tape tail, and dumps the lot — plus a full registry snapshot —
atomically (tmp, fsync, rename) to ``flight_<pid>.json`` in ``OBS_DIR``
(default: the system temp dir).  Dump triggers, mirroring how runs on
this box actually die:

- **SIGTERM** (``install(sigterm=True)``): chained ONLY when the
  process has no handler of its own (disposition is SIG_DFL) — a
  cooperative trainer's ``sigterm_flag`` takes precedence inside its
  scope, and those paths dump explicitly (``dump_global("preempted")``)
  before exiting 143.
- **NaN-guard / fault trip**: ``NaNGuardHook`` dumps before raising, so
  the poisoned-loss evidence survives the process it kills.
- **Supervisor escalation**: the supervisor dumps its OWN flight when
  it kills a child group (wall/heartbeat) — the one process that still
  can when the child is wedged in a dead dispatch.
- **atexit**: any exit without a prior dump (crash with a traceback,
  clean finish) writes one with reason ``exit``.

The dump is canonical JSON (sorted keys, fixed indent): re-serializing
the parsed content reproduces the exact bytes, and every RING field
(spans, deltas, loss tail, notes, identity) is captured at record time
— so dumps are reproducible up to the one dump-time field, the registry
snapshot's monotonic clock stamp (tests pin full bitwise stability
under a pinned clock).  That is what makes flight files diffable
across attempts: everything that differs is a real difference or a
timestamp, never dict-ordering noise.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import sys
import tempfile
import threading

from distributedtensorflowexample_tpu.obs import metrics as _metrics
from distributedtensorflowexample_tpu.obs import trace as _trace

FLIGHT_VERSION = 1


def atomic_write(path: str, data: bytes) -> None:
    """tmp/fsync/rename: the file either exists complete or not at all.
    THE one implementation for the obs formats (flight dumps, exporter
    textfiles; resilience snapshots delegate here too) — a torn-write
    fix must not need applying twice.  A FAILED write unlinks its tmp
    before re-raising: the disk-full-survival path retries every
    snapshot interval, and leaking one partial tmp per retry onto the
    already-full filesystem would guarantee it never saves again."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def env_opted_in() -> bool:
    """OBS_FLIGHT truthiness — one parse shared by every entrypoint, so
    the same value can't arm the recorder in one CLI and silently not
    in another."""
    return os.environ.get("OBS_FLIGHT", "").lower() in (
        "1", "true", "t", "yes", "y")


def flight_dir() -> str:
    return os.environ.get("OBS_DIR") or tempfile.gettempdir()


def flight_path(pid: int | None = None) -> str:
    """``flight_<pid>.json`` — or ``flight_<rank>_<pid>.json`` when the
    process has a rank (``OBS_RANK``, exported by the fleet supervisor
    and by distributed trainers from their resolved ``ClusterInfo``):
    N ranks of one gang attempt may recycle pids across restarts, and a
    multi-process postmortem must never have two ranks' flights collide
    on (or be attributed by) pid alone."""
    pid = os.getpid() if pid is None else pid
    rank = os.environ.get("OBS_RANK", "")
    name = f"flight_{rank}_{pid}.json" if rank else f"flight_{pid}.json"
    return os.path.join(flight_dir(), name)


class FlightRecorder:
    def __init__(self, max_spans: int = 256, max_deltas: int = 64,
                 max_loss: int = 256,
                 registry: _metrics.MetricsRegistry | None = None):
        self._spans = collections.deque(maxlen=max_spans)
        self._deltas = collections.deque(maxlen=max_deltas)
        self._loss = collections.deque(maxlen=max_loss)
        self._registry = registry or _metrics.registry()
        self._notes: dict = {}
        # Through the _wall seam (not time.time directly): a test that
        # pins both clocks gets bitwise-stable dumps INCLUDING the
        # wall-stamped span events the satellite fix added.
        self._start_unix = round(_metrics._wall(), 3)
        self._attempt = os.environ.get("SUPERVISE_ATTEMPT")
        self._phase = os.environ.get("OBS_PHASE")
        self._rank = os.environ.get("OBS_RANK")
        self.dumped = False

    # --- record (ring) ----------------------------------------------------
    def record_span(self, event: dict) -> None:
        self._spans.append(event)

    def record_loss(self, step: int, loss: float) -> None:
        self._loss.append([int(step), float(loss)])

    def record_delta(self, delta: dict) -> None:
        self._deltas.append(delta)

    def note(self, **fields) -> None:
        """Attach run facts (model, workdir, ...) to the postmortem."""
        self._notes.update(fields)

    # --- dump -------------------------------------------------------------
    def payload(self, reason: str) -> dict:
        def _as_int(v):
            if v is None:
                return None
            try:
                return int(v)
            except ValueError:
                return v

        return {"version": FLIGHT_VERSION,
                "reason": reason,
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "start_unix": self._start_unix,
                "attempt": _as_int(self._attempt),
                "rank": _as_int(self._rank),
                "phase": self._phase,
                "notes": dict(self._notes),
                "spans": list(self._spans),
                "loss_tail": list(self._loss),
                "metric_deltas": list(self._deltas),
                "metrics": self._registry.snapshot()}

    def dump(self, reason: str = "manual", path: str | None = None,
             final: bool = True) -> str:
        """Atomic: a postmortem format must not have its own torn-write
        failure mode.  ``final=False`` is for MID-RUN dumps (supervisor
        escalations between attempts): the file is written but the
        recorder is not marked terminally dumped, so the atexit dump
        still refreshes it with the process's true final state — a
        flight that stopped at attempt 1 of 3 would contradict the very
        journal it exists to cross-check."""
        path = path or flight_path()
        # The dump dir may not exist yet (a fleet child inherits an
        # OBS_DIR its supervisor named but never had to create): a
        # postmortem silently lost to ENOENT — dump_global swallows the
        # OSError — is the one failure mode this module must not have.
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # default=str: a foreign scalar (numpy/jax) in a span attr or
        # note serializes as its string form — one forgotten cast must
        # not cost the whole postmortem (dump_global would swallow the
        # TypeError and the run would die with no flight at all).
        atomic_write(path,
                     json.dumps(_metrics.json_safe(self.payload(reason)),
                                sort_keys=True, indent=1,
                                allow_nan=False, default=str
                                ).encode() + b"\n")
        if final:
            self.dumped = True
        return path


_GLOBAL: FlightRecorder | None = None


def get() -> FlightRecorder | None:
    return _GLOBAL


def install(sigterm: bool = True) -> FlightRecorder:
    """Create (idempotently) the process-wide recorder: subscribe it to
    trace events, arm the atexit dump, and — when ``sigterm`` and no
    handler is installed — chain a dump onto SIGTERM before dying by
    the signal's default disposition (so the wait-status stays honest)."""
    global _GLOBAL
    if _GLOBAL is not None:
        return _GLOBAL
    rec = _GLOBAL = FlightRecorder()
    _trace.add_sink(rec.record_span)
    atexit.register(_atexit_dump)
    if (sigterm
            and threading.current_thread() is threading.main_thread()
            and signal.getsignal(signal.SIGTERM) == signal.SIG_DFL):
        signal.signal(signal.SIGTERM, _sigterm_dump_and_die)
    return rec


def maybe_install(sigterm: bool = True) -> FlightRecorder | None:
    """Arm the recorder iff this run should leave postmortems: under a
    supervisor (SUPERVISE_ATTEMPT / SUPERVISE_HEARTBEAT exported) or an
    explicit OBS_FLIGHT opt-in.  THE one arming predicate — every CLI
    entrypoint (trainers, bench family, faultline, supervise) consults
    it, so the rule can't drift per entrypoint."""
    if (os.environ.get("SUPERVISE_ATTEMPT")
            or os.environ.get("SUPERVISE_HEARTBEAT")
            or env_opted_in()):
        return install(sigterm=sigterm)
    return None


def dump_global(reason: str, final: bool = True) -> str | None:
    """Dump the installed recorder; None (never a raise) when there is
    none or the write fails — telemetry must not kill the run."""
    if _GLOBAL is None:
        return None
    try:
        return _GLOBAL.dump(reason, final=final)
    except Exception:
        return None


def _atexit_dump() -> None:
    if _GLOBAL is not None and not _GLOBAL.dumped:
        dump_global("exit")


def _sigterm_dump_and_die(signum, frame) -> None:
    dump_global("sigterm")
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)
