"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints, in priority order:

1. **Hot-path cost**: an increment on the train-loop boundary must be
   invisible next to even a CPU step.  A child (one labeled series) is a
   ``__slots__`` object and ``inc`` is a single attribute ``+=`` — no
   lock, no dict lookup, no allocation.  Under the GIL that is effectively
   atomic; under free-threading a torn increment costs one tick of
   accuracy, never a deadlock — the right trade for telemetry.  The
   guard lives in tests/test_obs.py: < 2 us per increment on CPU.
2. **Snapshot/delta semantics**: ``snapshot()`` is a plain JSON-able
   dict stamped with a monotonic-clock timestamp; ``delta(prev, cur)``
   turns two snapshots into rates-ready differences (counters diff,
   gauges take the newer value).  The flight recorder rings deltas; the
   exporters serialize snapshots.
3. **Labels**: ``family.labels(k=v)`` returns the child for that label
   set; the series key is canonical (labels sorted), so
   ``labels(a=1, b=2)`` and ``labels(b=2, a=1)`` are the same series.

Registration (``registry().counter(name)``) takes a lock and is
idempotent — calling it again with the same name returns the same
family, so module-level and ad-hoc call sites can share series without
coordinating.  Stdlib-only on purpose (see the package docstring).
"""

from __future__ import annotations

import bisect
import math
import threading
import time

# Patchable seams: tests monkeypatch these to pin timestamps so flight
# dumps are bitwise-reproducible.  ``_now`` is the monotonic clock every
# in-process duration/age uses; ``_wall`` is the unix clock that lets
# events from DIFFERENT processes line up on one timeline (monotonic
# epochs are per-boot/per-namespace, wall clocks are shared on a host
# and NTP-close across one) — the cross-rank merge in obs/timeline.py
# aligns on wall stamps and keeps durations monotonic.
_now = time.monotonic
_wall = time.time

# Span histogram defaults: wall seconds from sub-ms dispatch boundaries
# to multi-minute capture phases.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0, 600.0)


def json_safe(obj):
    """Replace non-finite floats with their string names ("nan"/"inf")
    so every obs writer (flight dumps, JSONL exporter, trace-file sink)
    emits STRICT JSON even — especially — when recording the NaN loss
    a drill exists to document: a bare ``NaN`` token (json.dumps's
    permissive default) breaks jq and every non-Python consumer."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return str(obj)
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def series_key(name: str, label_items: tuple = ()) -> str:
    """Canonical Prometheus-style series key: ``name{a="1",b="2"}``."""
    if not label_items:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in label_items)
    return f"{name}{{{inner}}}"


class _CounterChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, amount=1) -> None:
        self.value += amount


class _GaugeChild:
    __slots__ = ("value", "monotonic_ts")

    def __init__(self):
        self.value = 0.0
        self.monotonic_ts = None    # never set

    def set(self, value) -> None:
        self.value = value
        self.monotonic_ts = _now()

    def inc(self, amount=1) -> None:
        self.set(self.value + amount)


class _HistogramChild:
    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: tuple):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # last slot: > max bound
        self.sum = 0.0
        self.count = 0

    def observe(self, value) -> None:
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1


class _Family:
    """One metric name; children are its labeled series (the unlabeled
    series is the ``()`` child, resolved once at construction so the
    bare ``inc()``/``set()`` path skips the dict entirely)."""

    kind = ""
    _child_cls: type = None

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._children: dict[tuple, object] = {}
        # RLock, not Lock: the SIGTERM-chained flight dump runs in the
        # MAIN thread and may interrupt it mid-registration — snapshot()
        # re-acquiring a plain Lock there would deadlock the dying
        # process past its kill grace with no postmortem written.
        self._lock = threading.RLock()
        self._bare = self._resolve(())

    def _new_child(self):
        return self._child_cls()

    def _resolve(self, items: tuple):
        child = self._children.get(items)
        if child is None:
            with self._lock:
                child = self._children.get(items)
                if child is None:
                    child = self._children[items] = self._new_child()
        return child

    def labels(self, **labels):
        return self._resolve(tuple(sorted(
            (k, str(v)) for k, v in labels.items())))

    def _touched(self, child) -> bool:
        if isinstance(child, _CounterChild):
            return bool(child.value)
        if isinstance(child, _GaugeChild):
            return child.monotonic_ts is not None
        return bool(child.count)

    def series(self):
        """(series_key, child) pairs, canonically sorted.  The key set
        is copied UNDER the lock: a snapshot may run on another thread
        (bench's watchdog dumping a flight) while the observed thread
        registers a new labeled series, and iterating the live dict
        there would raise mid-dump and silently cost the postmortem.
        The eager unlabeled child (the lock-free bare-op fast path) is
        elided while untouched in a family that only ever uses labels —
        a labeled-only export must not grow a phantom zero series."""
        with self._lock:
            snapshot = sorted(self._children.items())
        for items, child in snapshot:
            if (not items and len(snapshot) > 1
                    and not self._touched(child)):
                continue
            yield series_key(self.name, items), child


class Counter(_Family):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount=1) -> None:
        self._bare.inc(amount)

    @property
    def value(self):
        return self._bare.value


class Gauge(_Family):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value) -> None:
        self._bare.set(value)

    def inc(self, amount=1) -> None:
        self._bare.inc(amount)

    @property
    def value(self):
        return self._bare.value


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS):
        self._bounds = tuple(sorted(buckets))
        super().__init__(name, help)

    def _new_child(self):
        return _HistogramChild(self._bounds)

    def observe(self, value) -> None:
        self._bare.observe(value)


class MetricsRegistry:
    """Name -> family map with idempotent registration."""

    def __init__(self):
        self._families: dict[str, _Family] = {}
        self._lock = threading.RLock()   # see _Family: signal-safe re-entry

    def _register(self, cls, name: str, help: str, **kw) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = self._families[name] = cls(name, help, **kw)
        if not isinstance(fam, cls):
            raise ValueError(f"metric {name!r} already registered as a "
                             f"{fam.kind}, not a {cls.kind}")
        return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    def families(self):
        # Keys copied under the lock — same cross-thread-snapshot
        # reasoning as _Family.series().
        with self._lock:
            fams = sorted(self._families.items())
        for _, fam in fams:
            yield fam

    def snapshot(self) -> dict:
        """Point-in-time JSON-able view, stamped with the monotonic
        clock (wall time is a different axis — the flight recorder
        carries its own start_unix for that)."""
        snap = {"monotonic_ts": round(_now(), 6),
                "counters": {}, "gauges": {}, "histograms": {}}
        for fam in self.families():
            for key, child in fam.series():
                if fam.kind == "counter":
                    snap["counters"][key] = child.value
                elif fam.kind == "gauge":
                    snap["gauges"][key] = {
                        "value": child.value,
                        "monotonic_ts": (None if child.monotonic_ts is None
                                         else round(child.monotonic_ts, 6))}
                else:
                    # One copy of the bucket counts serves every derived
                    # field: reading child.count at a later instant than
                    # the counts (while another thread observes) could
                    # yield +Inf < a finite bucket's cumulative — a
                    # structurally invalid histogram, worse than the
                    # one-tick skew the lock-free design accepts.
                    counts = list(child.counts)
                    cum, buckets = 0, {}
                    for bound, n in zip(child.bounds, counts):
                        cum += n
                        buckets[str(bound)] = cum
                    total = sum(counts)
                    buckets["+Inf"] = total
                    snap["histograms"][key] = {
                        "count": total,
                        "sum": round(child.sum, 6),
                        "buckets": buckets}
        return snap

    @staticmethod
    def delta(prev: dict | None, cur: dict) -> dict:
        """Counter differences (a series absent from ``prev`` counts
        from zero), newest gauge values, and the monotonic span between
        the two snapshots — the rate denominator."""
        prev = prev or {}
        out = {"span_s": (None if "monotonic_ts" not in prev else round(
                   cur["monotonic_ts"] - prev["monotonic_ts"], 6)),
               "counters": {}, "gauges": {}}
        prev_c = prev.get("counters", {})
        for key, value in cur.get("counters", {}).items():
            d = value - prev_c.get(key, 0)
            if d:
                out["counters"][key] = d
        for key, g in cur.get("gauges", {}).items():
            out["gauges"][key] = g["value"]
        return out


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every wired seam shares."""
    return _REGISTRY


def counter(name: str, help: str = "") -> Counter:
    return _REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
    return _REGISTRY.histogram(name, help, buckets=buckets)
