"""Exporters: Prometheus textfile format and JSONL snapshots.

Both read the registry, neither mutates it.  The Prometheus text is the
node-exporter *textfile collector* dialect (write the file into its
watched directory and the fleet scraper picks it up — no HTTP server to
babysit on a box whose processes die by design); the JSONL exporter is
the greppable local form (one snapshot+delta per line, same spirit as
the scalars.jsonl the MetricsLogger already writes).

Output is canonically ordered (families and series sorted), so golden
tests pin the exact bytes and a diff between two exports is a diff
between two states — not between two dict orderings.
"""

from __future__ import annotations

import json

from distributedtensorflowexample_tpu.obs import metrics as _metrics
from distributedtensorflowexample_tpu.obs import recorder as _recorder


def _fmt(value) -> str:
    if isinstance(value, bool):
        return str(int(value))
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def _series_with_label(key: str, extra: str) -> str:
    """Append one label to a series key that may or may not already
    carry a label set (``h{a="1"}`` + ``le="5"`` -> ``h{a="1",le="5"}``)."""
    if key.endswith("}"):
        return f'{key[:-1]},{extra}}}'
    return f"{key}{{{extra}}}"


def prometheus_text(registry: _metrics.MetricsRegistry | None = None) -> str:
    reg = registry or _metrics.registry()
    lines: list[str] = []
    for fam in reg.families():
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for key, child in fam.series():
            if fam.kind == "histogram":
                # One copy of the counts backs every derived line (see
                # MetricsRegistry.snapshot: a later read of child.count
                # under concurrent observes could break the +Inf >=
                # finite-bucket monotonicity Prometheus requires).
                counts = list(child.counts)
                total = sum(counts)
                cum = 0
                base, labels = key, ""
                if key.endswith("}"):
                    base = key[:key.index("{")]
                    labels = key[key.index("{"):]
                for bound, n in zip(child.bounds, counts):
                    cum += n
                    lines.append(_series_with_label(
                        f"{base}_bucket{labels}", f'le="{bound}"')
                        + f" {cum}")
                lines.append(_series_with_label(
                    f"{base}_bucket{labels}", 'le="+Inf"')
                    + f" {total}")
                lines.append(f"{base}_sum{labels} {_fmt(child.sum)}")
                lines.append(f"{base}_count{labels} {total}")
            else:
                lines.append(f"{key} {_fmt(child.value)}")
    return "\n".join(lines) + "\n"


def write_prometheus_textfile(
        path: str,
        registry: _metrics.MetricsRegistry | None = None) -> str:
    """Atomic write — the textfile collector may read at any instant
    and a torn scrape half-counts everything."""
    _recorder.atomic_write(path, prometheus_text(registry).encode())
    return path


class JsonlExporter:
    """Append one ``{"unix_ts", "snapshot", "delta"}`` line per export;
    the delta is against this exporter's previous snapshot (None on the
    first line), so consumers get rates without re-deriving them."""

    def __init__(self, path: str):
        self._path = path
        self._prev: dict | None = None

    def export(self,
               registry: _metrics.MetricsRegistry | None = None) -> dict:
        reg = registry or _metrics.registry()
        snap = reg.snapshot()
        # Through the _wall seam (not time.time directly): the PR-13
        # clock-seam rule — a test that pins the seam must pin THIS
        # stamp too, or JSONL exports are not bitwise-reproducible.
        rec = {"unix_ts": round(_metrics._wall(), 3),
               "snapshot": snap,
               "delta": (_metrics.MetricsRegistry.delta(self._prev, snap)
                         if self._prev is not None else None)}
        with open(self._path, "a") as f:
            f.write(json.dumps(_metrics.json_safe(rec), sort_keys=True,
                               allow_nan=False, default=str) + "\n")
        self._prev = snap
        return rec
