"""Nestable trace spans with supervisor context, emitted as JSONL events.

One event per closed span::

    {"name": "snapshot", "t0_s": 12.345678, "t0_unix": 1753900000.123456,
     "dur_s": 0.004321, "depth": 1, "parent": "steps", "step": 40,
     "attempt": 1, "phase": "full_bench"}

- ``t0_s``/``dur_s`` are monotonic-clock seconds (same clock as the
  metrics registry, so spans and metric snapshots line up);
  ``t0_unix`` is the SAME instant on the wall clock.  Both are
  deliberate: monotonic is the honest duration/ordering axis inside one
  process, but its epoch is per-boot — two ranks' monotonic stamps are
  incomparable, which made cross-process alignment impossible before
  round 10.  The wall stamp is what obs/timeline.py merges N ranks'
  events on (derived once at close from the shared ``_wall`` seam, so a
  pinned-clock test still gets bitwise-stable dumps).
- ``attempt``/``phase`` are propagated from the environment the
  supervisor exports (``SUPERVISE_ATTEMPT``; ``OBS_PHASE`` is set per
  capture-queue task), read at span close — a child never has to thread
  supervisor identity through its own call stack, which is exactly how
  the capture journal and the telemetry stay in agreement.
- Nesting is a thread-local stack: ``depth``/``parent`` come from the
  enclosing ``span`` on the same thread.

Sinks: every event goes to each registered sink (the flight recorder
registers itself on install) and, when ``OBS_TRACE_FILE`` names a path,
is appended there as one JSON line.  Span close is NOT a hot path —
spans wrap phases, snapshot writes, and log-boundary windows, never the
per-step dispatch — so the per-event env lookups and the append-open
are deliberate simplicity, not an oversight.  Sink exceptions are
swallowed: telemetry must never kill the run it observes.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading

from distributedtensorflowexample_tpu.obs import metrics as _metrics

_tls = threading.local()
_sinks: list = []
_SPAN_SECONDS = _metrics.histogram(
    "span_seconds", "wall seconds per closed trace span")


def add_sink(sink) -> None:
    """Register ``sink(event: dict)`` for every future event."""
    if sink not in _sinks:
        _sinks.append(sink)


def remove_sink(sink) -> None:
    if sink in _sinks:
        _sinks.remove(sink)


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _context() -> dict:
    ctx = {}
    attempt = os.environ.get("SUPERVISE_ATTEMPT")
    if attempt:
        try:
            ctx["attempt"] = int(attempt)
        except ValueError:
            ctx["attempt"] = attempt
    phase = os.environ.get("OBS_PHASE")
    if phase:
        ctx["phase"] = phase
    # Rank context (OBS_RANK: fleet supervisor / distributed trainers):
    # spans from N ranks of one gang land in N flight files, and the
    # per-rank timeline obs_report renders needs each event to say
    # whose it is without joining on pid.
    rank = os.environ.get("OBS_RANK")
    if rank:
        try:
            ctx["rank"] = int(rank)
        except ValueError:
            ctx["rank"] = rank
    return ctx


def event(name: str, dur_s: float, t0_s: float | None = None,
          **attrs) -> dict:
    """Emit one span event without the context manager (hooks that
    measure a boundary-to-boundary window synthesize events this way).
    Returns the event dict (tests and callers may inspect it)."""
    stack = _stack()
    now = _metrics._now()
    if t0_s is None:
        t0_s = now - dur_s
    rec = {"name": name,
           "t0_s": round(t0_s, 6),
           # The same open instant on the wall clock: wall-now minus the
           # monotonic elapsed-since-open.  Computed at CLOSE (not open)
           # so the synthesized-event path (hooks that only know a
           # duration) gets the identical stamp semantics for free.
           "t0_unix": round(_metrics._wall() - (now - t0_s), 6),
           "dur_s": round(dur_s, 6),
           "depth": len(stack),
           "parent": stack[-1] if stack else None,
           **_context(), **attrs}
    _SPAN_SECONDS.labels(name=name).observe(dur_s)
    for sink in list(_sinks):
        try:
            sink(rec)
        except Exception:
            pass
    path = os.environ.get("OBS_TRACE_FILE")
    if path:
        try:
            # default=str: a span attr the caller forgot to convert (a
            # numpy/jax scalar in the yielded attrs dict) serializes as
            # its string form instead of raising TypeError out of
            # span.__exit__ — and the broad except keeps the module
            # contract: telemetry must never kill the run it observes.
            with open(path, "a") as f:
                f.write(json.dumps(_metrics.json_safe(rec), sort_keys=True,
                                   allow_nan=False, default=str) + "\n")
        except Exception:
            pass
    return rec


@contextlib.contextmanager
def span(name: str, **attrs):
    """``with span("dispatch", step=7) as a: ...`` — yields the attr
    dict so the body can add results post-hoc (``a["rc"] = 0``)."""
    stack = _stack()
    stack.append(name)
    t0 = _metrics._now()
    try:
        yield attrs
    finally:
        stack.pop()
        event(name, _metrics._now() - t0, t0_s=t0, **attrs)
