"""Native (C++) runtime components.

The reference's runtime work below the Python layer was TensorFlow library
C++ (SURVEY.md §2: tf.data input kernels, gRPC runtime, NCCL).  On TPU the
compute/collective side of that is XLA+libtpu; the host-side input stack is
ours, and lives here as a C++ shared library with ctypes bindings
(``dataio.cc`` + ``loader.py``): dataset parsing, parallel batch gather,
and fused gather+augmentation.  Pure-numpy fallbacks keep every feature
working when the toolchain is absent.
"""

from distributedtensorflowexample_tpu.native.loader import (
    augment_crop_flip, available, gather, gather_augment, omp_threads,
    parse_cifar, parse_idx_images, parse_idx_labels)

__all__ = [
    "augment_crop_flip",
    "available",
    "gather",
    "gather_augment",
    "omp_threads",
    "parse_cifar",
    "parse_idx_images",
    "parse_idx_labels",
]
